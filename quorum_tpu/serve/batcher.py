"""Dynamic batching: a bounded request queue feeding one dispatcher
thread that coalesces small requests into full device batches.

The device is efficient at `--max-batch` reads per step and terrible
at one; the batcher closes that gap the way inference servers do.
`submit()` enqueues a request (a list of FASTQ records + a Future)
under admission control — a full queue raises `QueueFull`, which the
HTTP front end maps to 429 + Retry-After, so overload sheds at the
door instead of growing an unbounded backlog (the bounded
jflib::pool discipline of the reference, applied to requests). The
dispatcher pops the queue, waits up to `max_wait_ms` for more work to
coalesce (first-request arrival starts the clock), drops requests
whose deadline already passed, packs up to `max_batch` reads into one
engine step, and demuxes each request's slice of the results back
through its Future.

Priority lanes (ISSUE 7): requests are admitted into one of two FIFO
lanes — `interactive` or `bulk` (the `X-Quorum-Priority` header at
the HTTP layer). The dispatcher pops them with a weighted scheme:
when both lanes hold work, `interactive_weight` interactive pops are
taken for every bulk pop, so a bulk backlog cannot starve interactive
traffic while bulk still drains at a guaranteed floor. One capacity
bound (`queue_requests`) covers both lanes.

Telemetry mirrors the host pipeline's vocabulary: a `queue_depth`
high-water gauge (set_max), a `queue_wait_us` histogram
(admission -> dispatch), `batch_reads` + the dispatch/wait split from
the engine, and request outcome counters
(`requests_accepted/_rejected_queue_full/_deadline_exceeded/_failed`
/`_completed`).

Fault containment (ISSUEs 4 + 7):

* A device-step exception fails ONLY that batch's futures (the HTTP
  layer maps them to 500) while the dispatcher keeps running.
* A failed multi-request batch is bisect-retried once
  (`batch_bisections`); a half that fails AGAIN with more than one
  request aboard is *hedged* — its requests re-run solo, bounded by
  `max_hedges` per failed batch (`hedges_total`), so an innocent
  batchmate never eats a 500 for a poisoned neighbor and its answer
  stays byte-identical to the offline CLI.
* The engine-step **watchdog** (`step_timeout_ms`): each device step
  runs under a monitor thread; a step that exceeds the budget — a
  wedged compile or hung device — is abandoned (`EngineStepTimeout`
  fails only that batch), and the dispatcher rebuilds a warm engine
  through `engine_factory` (DB reload + per-bucket recompile,
  `engine_restarts_total`) instead of wedging the process forever.
* After `max_consecutive_failures` engine-step failures in a row the
  batcher reports unhealthy and `/healthz` answers 503, so a load
  balancer ejects the replica (`engine_step_failures`,
  `consecutive_failures`); any success heals the streak.
* ANY dispatcher exit path — clean drain or a bug in the dispatch
  loop itself — fails every queued future immediately instead of
  stranding clients until their deadline.

Engine swaps (`swap_engine`) are how both the watchdog restart and
the server's hot `POST /reload` take effect: the dispatcher captures
the engine once per step attempt, so a batch already on the device
finishes on the OLD engine while every later step uses the new one;
the `engine_generation` gauge stamps which generation is serving.
"""

from __future__ import annotations

import collections
import threading
import time
from concurrent.futures import Future

from ..telemetry import NULL, flight, labeled
from ..utils.vlog import vlog

PRIORITIES = ("interactive", "bulk")


class QueueFull(Exception):
    """Admission refused: the request queue is at capacity. The HTTP
    layer maps this to 429 with `retry_after` seconds."""

    def __init__(self, retry_after: float = 1.0):
        super().__init__("request queue full")
        self.retry_after = retry_after


class Draining(Exception):
    """Admission refused: the server is quiescing (503)."""


class DeadlineExceeded(Exception):
    """The request's deadline passed before its batch dispatched
    (504)."""


class EngineStepTimeout(RuntimeError):
    """The watchdog abandoned a device step that exceeded
    `step_timeout_ms` (the HTTP layer maps it to 500; the engine is
    rebuilt underneath)."""


def _deliver_exception(fut: Future, err: BaseException) -> bool:
    """Fail a future that may or may not already be running/resolved
    (the watchdog paths can race a normal resolution): True if this
    call delivered the exception."""
    try:
        if not fut.set_running_or_notify_cancel():
            return False  # cancelled by an abandoned waiter
    except RuntimeError:
        pass  # already marked running
    try:
        fut.set_exception(err)
        return True
    except Exception:
        return False  # already resolved


class _Request:
    """One admitted request plus its phase ledger (ISSUE 10): the
    dispatcher thread stamps lane wait at pop and accumulates device /
    hedge step time per attempt; the HTTP layer reads the ledger off
    the Future (`fut.request`) to build the response's
    `X-Quorum-Phases` header and the request lifecycle event. Only
    the dispatcher thread writes the phase fields after admission."""

    __slots__ = ("records", "future", "t_enq", "deadline", "rid",
                 "lane", "lane_wait_us", "device_us", "hedge_us",
                 "bisected", "hedged")

    def __init__(self, records, future, deadline, rid=None,
                 lane="interactive"):
        self.records = records
        self.future = future
        self.t_enq = time.perf_counter()
        self.deadline = deadline  # absolute perf_counter, or None
        self.rid = rid            # X-Quorum-Request-Id (or None)
        self.lane = lane
        self.lane_wait_us = 0     # admission -> dispatch pop
        self.device_us = 0        # engine step time (incl. bisect)
        self.hedge_us = 0         # solo re-run time after a bisect
        self.bisected = False
        self.hedged = False


class DynamicBatcher:
    """One dispatcher thread over two bounded priority lanes.

    `max_batch` is also the engine's fixed row capacity; requests
    larger than `max_batch` reads are corrected across several device
    steps within one dispatch (their Future still resolves once, with
    the full result). `queue_requests` bounds ADMITTED requests not
    yet dispatched, across both lanes — in-flight device work doesn't
    count against it.
    """

    def __init__(self, engine, max_batch: int | None = None,
                 max_wait_ms: float = 5.0, queue_requests: int = 64,
                 max_consecutive_failures: int = 0,
                 step_timeout_ms: float | None = None,
                 engine_factory=None, max_hedges: int = 8,
                 interactive_weight: int = 4,
                 registry=NULL):
        self.engine = engine
        self.max_batch = int(max_batch or engine.rows)
        if self.max_batch > engine.rows:
            raise ValueError(
                f"max_batch {self.max_batch} exceeds engine rows "
                f"{engine.rows}")
        self.max_wait_s = max(0.0, float(max_wait_ms)) / 1000.0
        self.queue_requests = int(queue_requests)
        # 0 = never flip unhealthy (the CLI default is 5)
        self.max_consecutive_failures = int(max_consecutive_failures)
        self.step_timeout_s = (float(step_timeout_ms) / 1000.0
                               if step_timeout_ms else None)
        # the watchdog's rebuild gets its own (larger) budget: DB
        # reload + per-bucket recompile is legitimately slower than
        # one step, but a wedged rebuild must not re-wedge the
        # dispatcher (tests shrink this)
        self.rebuild_timeout_s = (max(4 * self.step_timeout_s, 60.0)
                                  if self.step_timeout_s else None)
        # called as engine_factory(hung_engine) after a watchdog fire;
        # must return a fresh warm engine (the CLI rebuilds from the
        # same flags and re-pays the hung engine's length buckets)
        self.engine_factory = engine_factory
        self.max_hedges = max(0, int(max_hedges))
        self.interactive_weight = max(1, int(interactive_weight))
        self.registry = registry
        self._lanes: dict[str, collections.deque[_Request]] = {
            p: collections.deque() for p in PRIORITIES}
        self._pop_seq = 0
        self._generation = 0
        self._lock = threading.Lock()
        self._work = threading.Condition(self._lock)
        self._draining = False
        self._closed = False
        self._dead = False  # dispatcher exited (drain or death)
        # the batch the dispatcher is running RIGHT NOW (empty between
        # steps): drain forensics read it for meta.drained_ids
        self._inflight: list[_Request] = []
        self._consecutive_failures = 0
        # feature counters exist from setup (value 0 counts): a serve
        # metrics document must show the watchdog/hedging surface even
        # before the first fault (tools/metrics_check.py requires the
        # names when meta declares the feature)
        if self.max_hedges > 0:
            registry.counter("hedges_total")
        if self.step_timeout_s is not None:
            registry.counter("engine_restarts_total")
            registry.counter("engine_step_timeouts")
        # per-lane depth/wait series (ISSUE 10): the summed
        # `queue_depth` gauge stays for dashboard compatibility, but
        # one number over two lanes hides interactive starvation —
        # these exist from setup so a zero-traffic lane still shows
        for p in PRIORITIES:
            registry.gauge(labeled("queue_depth", lane=p))
            registry.histogram(labeled("lane_wait_us", lane=p))
        self._thread = threading.Thread(target=self._dispatch_loop,
                                        name="quorum-serve-dispatch",
                                        daemon=True)
        self._thread.start()

    # -- admission --------------------------------------------------------
    def submit(self, records, deadline_s: float | None = None,
               priority: str = "interactive",
               request_id: str | None = None) -> Future:
        """Enqueue one request (list of (header, seq, qual) records)
        into the `priority` lane. Returns a Future resolving to the
        per-read (fa, log) list, with the request's phase ledger
        attached as `fut.request` (the HTTP layer reads it for the
        response's phase header + lifecycle event). Raises QueueFull
        (429) or Draining (503) at admission; an expired deadline
        resolves the Future with DeadlineExceeded. `request_id` is
        the X-Quorum-Request-Id threaded through hedge/bisect
        telemetry."""
        if priority not in self._lanes:
            raise ValueError(f"unknown priority {priority!r} "
                             f"(one of {PRIORITIES})")
        fut: Future = Future()
        deadline = (time.perf_counter() + deadline_s
                    if deadline_s is not None else None)
        req = _Request(list(records), fut, deadline, rid=request_id,
                       lane=priority)
        fut.request = req
        reg = self.registry
        with self._lock:
            if self._draining or self._dead:
                reg.counter("requests_rejected_draining").inc()
                raise Draining()
            if self._qlen_locked() >= self.queue_requests:
                reg.counter("requests_rejected_queue_full").inc()
                raise QueueFull(retry_after=self._retry_after_locked())
            reg.counter("requests_accepted").inc()
            if req.records:
                self._lanes[priority].append(req)
                reg.gauge("queue_depth").set_max(self._qlen_locked())
                reg.gauge(labeled("queue_depth", lane=priority)) \
                    .set_max(len(self._lanes[priority]))
                self._work.notify()
        if not req.records:
            # nothing to correct: resolve immediately (never
            # enqueued), but AFTER admission control so an empty
            # probe still honors drain and backpressure; completed
            # here so accepted == completed + failed + deadline holds
            reg.counter("requests_completed").inc()
            fut.set_result([])
        return fut

    def _retry_after_locked(self) -> float:
        """Suggested Retry-After: one full queue's worth of batches at
        the coalescing wait, floored at 1 s. Deliberately coarse — the
        point is a hint that backs clients off, not a promise."""
        batches = max(1, self.queue_requests)
        return max(1.0, round(batches * self.max_wait_s, 1))

    def _qlen_locked(self) -> int:
        return sum(len(q) for q in self._lanes.values())

    def _reads_locked(self) -> int:
        return sum(len(r.records) for q in self._lanes.values()
                   for r in q)

    def _first_enq_locked(self) -> float:
        return min(q[0].t_enq for q in self._lanes.values() if q)

    @property
    def depth(self) -> int:
        with self._lock:
            return self._qlen_locked()

    # -- engine swap ------------------------------------------------------
    def current_engine(self):
        with self._lock:
            return self.engine

    @property
    def generation(self) -> int:
        """How many engine swaps (watchdog restarts + hot reloads)
        this batcher has served across; 0 = the boot engine."""
        with self._lock:
            return self._generation

    def swap_engine(self, new_engine,
                    expected_generation: int | None = None) -> int:
        """Atomically install `new_engine` for every step dispatched
        from now on; a step already in flight finishes on the old
        engine (the dispatcher captured its reference). Returns the
        new generation number (also the `engine_generation` gauge).

        `expected_generation` makes the swap conditional: if another
        swap landed since the caller captured that generation, this
        one is dropped and -1 returned — the watchdog's rebuild uses
        it so a concurrent /reload's fresher engine is never
        clobbered by a stale-config replacement."""
        rows = int(getattr(new_engine, "rows", self.max_batch))
        if rows < self.max_batch:
            raise ValueError(
                f"replacement engine rows {rows} below max_batch "
                f"{self.max_batch}")
        with self._lock:
            if (expected_generation is not None
                    and self._generation != expected_generation):
                return -1
            self.engine = new_engine
            self._generation += 1
            gen = self._generation
        self.registry.gauge("engine_generation").set(gen)
        return gen

    # -- health -----------------------------------------------------------
    @property
    def consecutive_failures(self) -> int:
        with self._lock:
            return self._consecutive_failures

    @property
    def healthy(self) -> bool:
        """False once the dispatcher is gone or
        `max_consecutive_failures` engine steps failed in a row —
        the `/healthz` 503 signal load balancers eject on."""
        with self._lock:
            if self._dead:
                return False
            return (self.max_consecutive_failures <= 0
                    or self._consecutive_failures
                    < self.max_consecutive_failures)

    def pending_rids(self) -> list[str]:
        """Request ids admitted but not yet resolved — the batch on
        the device right now plus both lane backlogs, in dispatch
        order. The server's drain path stamps this as
        `meta.drained_ids` so a postmortem can name exactly which
        requests a SIGTERM caught in flight."""
        with self._lock:
            reqs = list(self._inflight)
            reqs += [r for q in self._lanes.values() for r in q]
        return [r.rid for r in reqs if r.rid]

    # -- drain / shutdown -------------------------------------------------
    def drain(self, timeout: float | None = None) -> bool:
        """Stop admitting, flush everything already admitted, stop the
        dispatcher. Idempotent. Returns True if the dispatcher thread
        exited within `timeout`."""
        with self._lock:
            self._draining = True
            self._work.notify_all()
        self._thread.join(timeout)
        return not self._thread.is_alive()

    # -- dispatch ---------------------------------------------------------
    def _next_lane_locked(self) -> str | None:
        """The weighted pop: interactive unless it is empty, or the
        pop sequence owes bulk its guaranteed slot (one of every
        `interactive_weight + 1` pops while both lanes hold work)."""
        inter = self._lanes["interactive"]
        bulk = self._lanes["bulk"]
        if not inter and not bulk:
            return None
        if not inter:
            return "bulk"
        if not bulk:
            return "interactive"
        w = self.interactive_weight
        return "bulk" if self._pop_seq % (w + 1) == w else "interactive"

    def _take_locked(self) -> list[_Request]:
        """Pop admitted requests up to max_batch reads, in weighted
        lane order. Always pops at least one request (an oversize
        request dispatches alone and is chunked across device
        steps)."""
        taken: list[_Request] = []
        reads = 0
        while True:
            lane = self._next_lane_locked()
            if lane is None:
                break
            nxt = len(self._lanes[lane][0].records)
            if taken and reads + nxt > self.max_batch:
                break
            req = self._lanes[lane].popleft()
            self._pop_seq += 1
            taken.append(req)
            reads += nxt
        return taken

    def _dispatch_loop(self) -> None:
        try:
            self._dispatch_loop_inner()
        except BaseException as e:  # noqa: BLE001 - loop bug
            # a bug in the dispatch loop itself (not an engine step —
            # those are contained below): count it and fall through to
            # the shutdown; re-raising from a daemon thread would only
            # print a traceback nobody handles while clients hang
            self.registry.counter("dispatcher_crashes").inc()
            vlog("quorum-serve dispatcher died: ", e)
            try:
                flight.try_dump("dispatcher_crash", detail=repr(e))
            except Exception:  # noqa: BLE001 - never mask the crash  # qlint: disable=thread-swallowed-exception - best-effort forensics; the crash is already counted (dispatcher_crashes) above
                pass
        finally:
            # EVERY dispatcher exit path — clean drain or a bug in the
            # loop itself — must fail the queued futures immediately:
            # a stranded future means a client hung until its deadline
            # for work that can never run
            self._shutdown_pending()

    def _dispatch_loop_inner(self) -> None:
        reg = self.registry
        while True:
            with self._work:
                while not self._qlen_locked() and not self._draining:
                    self._work.wait(timeout=0.1)
                if not self._qlen_locked():
                    if self._draining:
                        self._closed = True
                        return
                    continue
                # coalescing window: the FIRST waiter's arrival starts
                # the clock; stop early once a full batch is waiting
                if self.max_wait_s > 0:
                    give_up = self._first_enq_locked() + self.max_wait_s
                    while (not self._draining
                           and self._reads_locked() < self.max_batch):
                        left = give_up - time.perf_counter()
                        if left <= 0:
                            break
                        self._work.wait(timeout=left)
                        if not self._qlen_locked():
                            break
                    if not self._qlen_locked():
                        continue
                taken = self._take_locked()
                self._inflight = taken
            try:
                self._run_batch(taken, reg)
            except BaseException as e:  # noqa: BLE001 - watchdog
                # _run_batch contains engine failures itself; anything
                # escaping is a bug in the dispatch path — fail THIS
                # batch's futures and keep the dispatcher alive
                self._record_step(reg, ok=False)
                n = 0
                for req in taken:
                    if _deliver_exception(req.future, e):
                        n += 1
                if n:
                    reg.counter("requests_failed").inc(n)
            finally:
                with self._lock:
                    self._inflight = []

    def _shutdown_pending(self) -> None:
        err = RuntimeError("quorum-serve dispatcher exited")
        with self._lock:
            self._dead = True
            stranded = [r for q in self._lanes.values() for r in q]
            for q in self._lanes.values():
                q.clear()
        n = 0
        for req in stranded:
            if _deliver_exception(req.future, err):
                n += 1
        if n:
            self.registry.counter("requests_failed").inc(n)

    def _record_step(self, reg, ok: bool) -> None:
        """Track engine-step health: consecutive failures drive the
        unhealthy flip; any success resets the streak."""
        with self._lock:
            if ok:
                self._consecutive_failures = 0
            else:
                self._consecutive_failures += 1
            n = self._consecutive_failures
        if not ok:
            reg.counter("engine_step_failures").inc()
        reg.gauge("consecutive_failures").set(n)

    # -- the watchdog -----------------------------------------------------
    def _timed_step(self, eng, records) -> list:
        """One engine step under the watchdog. Without a timeout this
        is a direct call; with one, the step runs on a monitor thread
        and a budget overrun abandons it (the hung thread is daemon
        and holds only the OLD engine's lock), rebuilds the engine,
        and raises EngineStepTimeout for this batch."""
        if self.step_timeout_s is None:
            return eng.step(records)
        box: dict = {}
        done = threading.Event()

        def run():
            try:
                box["res"] = eng.step(records)
            except BaseException as e:  # noqa: BLE001 - relayed below
                box["err"] = e
            finally:
                done.set()

        t = threading.Thread(target=run, name="quorum-serve-step",
                             daemon=True)
        t.start()
        if not done.wait(self.step_timeout_s):
            self._handle_step_timeout(eng)
            raise EngineStepTimeout(
                f"engine step exceeded {self.step_timeout_s * 1e3:.0f}"
                " ms (watchdog)")
        err = box.get("err")
        if err is not None:
            raise err
        return box["res"]

    def _handle_step_timeout(self, hung_engine) -> None:
        """A step blew its budget: count it and rebuild a warm engine
        so the NEXT step runs on a live one. The rebuild ITSELF runs
        under a (larger) budget — if the device/compiler is wedged
        enough that even a fresh engine's warmup hangs, the dispatcher
        must not re-wedge on the cure: the rebuild thread is abandoned
        too, the old engine stays, every later step times out, the
        failure streak grows, and /healthz flips — the correct signal
        when a rebuild cannot save the replica."""
        reg = self.registry
        reg.counter("engine_step_timeouts").inc()
        vlog("quorum-serve watchdog: abandoning engine step after ",
             self.step_timeout_s, " s")
        # the black-box moment: the hung `quorum-serve-step` thread is
        # still alive (daemon, abandoned), so the dump's all-thread
        # stacks show exactly WHERE the engine step wedged
        try:
            flight.try_dump(
                "watchdog", site="serve.engine.step",
                detail=("engine step exceeded "
                        f"{self.step_timeout_s * 1e3:.0f} ms; hung "
                        "thread quorum-serve-step abandoned"))
        except Exception:  # noqa: BLE001 - never mask the timeout
            pass
        if self.engine_factory is None:
            return
        gen_at_timeout = self.generation
        box: dict = {}
        done = threading.Event()

        def build():
            try:
                box["eng"] = self.engine_factory(hung_engine)
            except BaseException as e:  # noqa: BLE001 - relayed below
                box["err"] = e
            finally:
                done.set()

        t = threading.Thread(target=build, name="quorum-serve-rebuild",
                             daemon=True)
        t.start()
        if not done.wait(self.rebuild_timeout_s):
            reg.counter("engine_rebuild_failures").inc()
            vlog("quorum-serve watchdog: engine rebuild itself wedged;"
                 " keeping the old engine")
            return
        try:
            if "err" in box:
                raise box["err"]
            # conditional on the generation seen at timeout: a
            # /reload that landed while this rebuild ran installed a
            # FRESHER engine (possibly a new config) — never clobber
            # it with this stale-config replacement
            gen = self.swap_engine(box["eng"],
                                   expected_generation=gen_at_timeout)
        except BaseException as e:  # noqa: BLE001 - best-effort
            reg.counter("engine_rebuild_failures").inc()
            vlog("quorum-serve watchdog: engine rebuild failed: ", e)
            return
        if gen < 0:
            vlog("quorum-serve watchdog: rebuild superseded by a "
                 "concurrent engine swap; dropping it")
            return
        reg.counter("engine_restarts_total").inc()
        reg.event("engine_restart", generation=gen)
        vlog("quorum-serve watchdog: warm engine rebuilt "
             "(generation ", gen, ")")

    def _step_requests(self, reqs: list[_Request],
                       ledger: str = "device_us") -> list[list]:
        """One coalesced engine pass over `reqs`: flatten, step in
        max_batch chunks, return each request's slice of results.
        Captures the CURRENT engine once per attempt — a bisect or
        hedge retry after a watchdog restart runs on the rebuilt
        engine, while a batch already stepping finishes on the old
        one. The attempt's wall time lands on every rider's phase
        ledger (`device_us`, or `hedge_us` for a solo hedge re-run) —
        attempts are disjoint wall intervals, so a bisected request's
        ledger sums its failed and retried passes. Accumulated even
        when the step raises: the failed attempt's time is exactly
        what the 500's lifecycle event should attribute."""
        eng = self.current_engine()
        flat: list = []
        slices: list[tuple[int, int]] = []
        for req in reqs:
            slices.append((len(flat), len(flat) + len(req.records)))
            flat.extend(req.records)
        t0 = time.perf_counter()
        try:
            results: list = []
            for off in range(0, len(flat), self.max_batch):
                results.extend(
                    self._timed_step(eng,
                                     flat[off:off + self.max_batch]))
        finally:
            spent = int((time.perf_counter() - t0) * 1e6)
            for req in reqs:
                setattr(req, ledger, getattr(req, ledger) + spent)
        return [results[s:e] for s, e in slices]

    def _resolve(self, reqs: list[_Request], per_req: list[list],
                 reg) -> None:
        reg.counter("requests_completed").inc(len(reqs))
        for req, res in zip(reqs, per_req):
            try:
                if req.future.set_running_or_notify_cancel():
                    req.future.set_result(res)
            except Exception:  # pragma: no cover - abandoned future
                pass

    def _run_batch(self, taken: list[_Request], reg) -> None:
        now = time.perf_counter()
        live: list[_Request] = []
        for req in taken:
            # the pop stamps the request's lane-wait phase (admission
            # -> dispatch, or -> expiry for a 504): the per-lane
            # histogram is the starvation signal one summed
            # queue_wait_us hides, and the worst waits are exactly the
            # expired ones — omitting them would bias it low when
            # starvation actually happens
            req.lane_wait_us = int((now - req.t_enq) * 1e6)
            if reg.enabled:
                reg.histogram(labeled("lane_wait_us",
                                      lane=req.lane)).observe(
                    req.lane_wait_us)
            if req.deadline is not None and now > req.deadline:
                reg.counter("requests_deadline_exceeded").inc()
                if req.future.set_running_or_notify_cancel():
                    req.future.set_exception(DeadlineExceeded())
            else:
                # the summed series keeps its seed semantics: waits of
                # DISPATCHED requests only
                if reg.enabled:
                    reg.histogram("queue_wait_us").observe(
                        req.lane_wait_us)
                live.append(req)
        if not live:
            return
        try:
            per_req = self._step_requests(live)
        except BaseException as e:  # noqa: BLE001 - isolated per batch
            self._record_step(reg, ok=False)
            if len(live) > 1:
                self._bisect_retry(live, reg)
            else:
                reg.counter("requests_failed").inc(1)
                _deliver_exception(live[0].future, e)
            return
        self._record_step(reg, ok=True)
        self._resolve(live, per_req, reg)

    def _bisect_retry(self, live: list[_Request], reg) -> None:
        """A failed multi-request batch is bisect-retried ONCE: each
        half runs its own engine pass, so a poisoned request drags
        down at most its half. A half that fails AGAIN with several
        requests aboard is ambiguous — those requests are *hedged*:
        re-run solo (bounded by `max_hedges` per failed batch,
        `hedges_total`), so an innocent batchmate never eats a 500 and
        its response stays byte-identical to the offline CLI. A half
        or hedge succeeding also proves the device is alive, resetting
        the consecutive-failure streak."""
        reg.counter("batch_bisections").inc()
        # the victims' request ids ride the event (ISSUE 10), so a
        # fleet operator can answer "whose batch bisected?" from the
        # JSONL stream alone
        reg.event("batch_bisect", requests=len(live),
                  request_ids=",".join(r.rid or "-" for r in live))
        for req in live:
            req.bisected = True
        budget = self.max_hedges
        mid = (len(live) + 1) // 2
        for half in (live[:mid], live[mid:]):
            if not half:
                continue
            try:
                per_req = self._step_requests(half)
            except BaseException as e:  # noqa: BLE001 - per half
                self._record_step(reg, ok=False)
                # no solo hedging after a watchdog timeout: each hedge
                # of a deterministically-hanging request would cost a
                # FULL step-timeout + engine rebuild with the
                # dispatcher blocked — fail the ambiguous half fast
                # and let the health flip handle a truly wedged device
                if (len(half) > 1 and budget > 0
                        and not isinstance(e, EngineStepTimeout)):
                    budget = self._hedge_solo(half, e, reg, budget)
                else:
                    reg.counter("requests_failed").inc(len(half))
                    for req in half:
                        _deliver_exception(req.future, e)
                continue
            self._record_step(reg, ok=True)
            self._resolve(half, per_req, reg)

    def _hedge_solo(self, half: list[_Request], err: BaseException,
                    reg, budget: int) -> int:
        """Re-run each request of an ambiguously-failed half alone,
        spending one hedge per solo step; requests past the budget
        fail with the half's original error. Returns the remaining
        budget."""
        for i, req in enumerate(half):
            if budget <= 0:
                rest = half[i:]
                reg.counter("requests_failed").inc(len(rest))
                for r in rest:
                    _deliver_exception(r.future, err)
                return 0
            budget -= 1
            reg.counter("hedges_total").inc()
            reg.event("hedge", request_id=req.rid or "-",
                      reads=len(req.records))
            req.hedged = True
            try:
                per_req = self._step_requests([req], ledger="hedge_us")
            except BaseException as e:  # noqa: BLE001 - per request
                self._record_step(reg, ok=False)
                reg.counter("requests_failed").inc(1)
                _deliver_exception(req.future, e)
                continue
            self._record_step(reg, ok=True)
            self._resolve([req], per_req, reg)
        return budget
