"""Dynamic batching: a bounded request queue feeding one dispatcher
thread that coalesces small requests into full device batches.

The device is efficient at `--max-batch` reads per step and terrible
at one; the batcher closes that gap the way inference servers do.
`submit()` enqueues a request (a list of FASTQ records + a Future)
under admission control — a full queue raises `QueueFull`, which the
HTTP front end maps to 429 + Retry-After, so overload sheds at the
door instead of growing an unbounded backlog (the bounded
jflib::pool discipline of the reference, applied to requests). The
dispatcher pops the queue, waits up to `max_wait_ms` for more work to
coalesce (first-request arrival starts the clock), drops requests
whose deadline already passed, packs up to `max_batch` reads into one
engine step, and demuxes each request's slice of the results back
through its Future.

Telemetry mirrors the host pipeline's vocabulary: a `queue_depth`
high-water gauge (set_max), a `queue_wait_us` histogram
(admission -> dispatch), `batch_reads` + the dispatch/wait split from
the engine, and request outcome counters
(`requests_accepted/_rejected_queue_full/_deadline_exceeded/_failed`
/`_completed`).

Fault isolation (ISSUE 4): a device-step exception fails ONLY that
batch's futures (the HTTP layer maps them to 500) while the
dispatcher keeps running; a failed multi-request batch is
bisect-retried once so a single poisoned request doesn't take its
batchmates down with it (`batch_bisections`); after
`max_consecutive_failures` engine-step failures in a row the batcher
reports unhealthy and `/healthz` answers 503, so a load balancer
ejects the replica instead of the process dying silently
(`engine_step_failures`, `consecutive_failures`). And ANY dispatcher
exit path — clean drain or a bug in the dispatch loop itself — fails
every queued future immediately instead of stranding clients until
their deadline.
"""

from __future__ import annotations

import collections
import threading
import time
from concurrent.futures import Future

from ..telemetry import NULL
from ..utils.vlog import vlog


class QueueFull(Exception):
    """Admission refused: the request queue is at capacity. The HTTP
    layer maps this to 429 with `retry_after` seconds."""

    def __init__(self, retry_after: float = 1.0):
        super().__init__("request queue full")
        self.retry_after = retry_after


class Draining(Exception):
    """Admission refused: the server is quiescing (503)."""


class DeadlineExceeded(Exception):
    """The request's deadline passed before its batch dispatched
    (504)."""


def _deliver_exception(fut: Future, err: BaseException) -> bool:
    """Fail a future that may or may not already be running/resolved
    (the watchdog paths can race a normal resolution): True if this
    call delivered the exception."""
    try:
        if not fut.set_running_or_notify_cancel():
            return False  # cancelled by an abandoned waiter
    except RuntimeError:
        pass  # already marked running
    try:
        fut.set_exception(err)
        return True
    except Exception:
        return False  # already resolved


class _Request:
    __slots__ = ("records", "future", "t_enq", "deadline")

    def __init__(self, records, future, deadline):
        self.records = records
        self.future = future
        self.t_enq = time.perf_counter()
        self.deadline = deadline  # absolute perf_counter, or None


class DynamicBatcher:
    """One dispatcher thread over a bounded deque of requests.

    `max_batch` is also the engine's fixed row capacity; requests
    larger than `max_batch` reads are corrected across several device
    steps within one dispatch (their Future still resolves once, with
    the full result). `queue_requests` bounds ADMITTED requests not
    yet dispatched — in-flight device work doesn't count against it.
    """

    def __init__(self, engine, max_batch: int | None = None,
                 max_wait_ms: float = 5.0, queue_requests: int = 64,
                 max_consecutive_failures: int = 0,
                 registry=NULL):
        self.engine = engine
        self.max_batch = int(max_batch or engine.rows)
        if self.max_batch > engine.rows:
            raise ValueError(
                f"max_batch {self.max_batch} exceeds engine rows "
                f"{engine.rows}")
        self.max_wait_s = max(0.0, float(max_wait_ms)) / 1000.0
        self.queue_requests = int(queue_requests)
        # 0 = never flip unhealthy (the CLI default is 5)
        self.max_consecutive_failures = int(max_consecutive_failures)
        self.registry = registry
        self._q: collections.deque[_Request] = collections.deque()
        self._lock = threading.Lock()
        self._work = threading.Condition(self._lock)
        self._draining = False
        self._closed = False
        self._dead = False  # dispatcher exited (drain or death)
        self._consecutive_failures = 0
        self._thread = threading.Thread(target=self._dispatch_loop,
                                        name="quorum-serve-dispatch",
                                        daemon=True)
        self._thread.start()

    # -- admission --------------------------------------------------------
    def submit(self, records, deadline_s: float | None = None) -> Future:
        """Enqueue one request (list of (header, seq, qual) records).
        Returns a Future resolving to the per-read (fa, log) list.
        Raises QueueFull (429) or Draining (503) at admission; an
        expired deadline resolves the Future with DeadlineExceeded."""
        fut: Future = Future()
        deadline = (time.perf_counter() + deadline_s
                    if deadline_s is not None else None)
        req = _Request(list(records), fut, deadline)
        reg = self.registry
        with self._lock:
            if self._draining or self._dead:
                reg.counter("requests_rejected_draining").inc()
                raise Draining()
            if len(self._q) >= self.queue_requests:
                reg.counter("requests_rejected_queue_full").inc()
                raise QueueFull(retry_after=self._retry_after_locked())
            reg.counter("requests_accepted").inc()
            if req.records:
                self._q.append(req)
                reg.gauge("queue_depth").set_max(len(self._q))
                self._work.notify()
        if not req.records:
            # nothing to correct: resolve immediately (never
            # enqueued), but AFTER admission control so an empty
            # probe still honors drain and backpressure; completed
            # here so accepted == completed + failed + deadline holds
            reg.counter("requests_completed").inc()
            fut.set_result([])
        return fut

    def _retry_after_locked(self) -> float:
        """Suggested Retry-After: one full queue's worth of batches at
        the coalescing wait, floored at 1 s. Deliberately coarse — the
        point is a hint that backs clients off, not a promise."""
        batches = max(1, self.queue_requests)
        return max(1.0, round(batches * self.max_wait_s, 1))

    @property
    def depth(self) -> int:
        with self._lock:
            return len(self._q)

    # -- health -----------------------------------------------------------
    @property
    def consecutive_failures(self) -> int:
        with self._lock:
            return self._consecutive_failures

    @property
    def healthy(self) -> bool:
        """False once the dispatcher is gone or
        `max_consecutive_failures` engine steps failed in a row —
        the `/healthz` 503 signal load balancers eject on."""
        with self._lock:
            if self._dead:
                return False
            return (self.max_consecutive_failures <= 0
                    or self._consecutive_failures
                    < self.max_consecutive_failures)

    # -- drain / shutdown -------------------------------------------------
    def drain(self, timeout: float | None = None) -> bool:
        """Stop admitting, flush everything already admitted, stop the
        dispatcher. Idempotent. Returns True if the dispatcher thread
        exited within `timeout`."""
        with self._lock:
            self._draining = True
            self._work.notify_all()
        self._thread.join(timeout)
        return not self._thread.is_alive()

    # -- dispatch ---------------------------------------------------------
    def _take_locked(self) -> list[_Request]:
        """Pop admitted requests up to max_batch reads. Always pops at
        least one request (an oversize request dispatches alone and is
        chunked across device steps)."""
        taken: list[_Request] = []
        reads = 0
        while self._q:
            nxt = len(self._q[0].records)
            if taken and reads + nxt > self.max_batch:
                break
            req = self._q.popleft()
            taken.append(req)
            reads += nxt
        return taken

    def _dispatch_loop(self) -> None:
        try:
            self._dispatch_loop_inner()
        except BaseException as e:  # noqa: BLE001 - loop bug
            # a bug in the dispatch loop itself (not an engine step —
            # those are contained below): count it and fall through to
            # the shutdown; re-raising from a daemon thread would only
            # print a traceback nobody handles while clients hang
            self.registry.counter("dispatcher_crashes").inc()
            vlog("quorum-serve dispatcher died: ", e)
        finally:
            # EVERY dispatcher exit path — clean drain or a bug in the
            # loop itself — must fail the queued futures immediately:
            # a stranded future means a client hung until its deadline
            # for work that can never run
            self._shutdown_pending()

    def _dispatch_loop_inner(self) -> None:
        reg = self.registry
        while True:
            with self._work:
                while not self._q and not self._draining:
                    self._work.wait(timeout=0.1)
                if not self._q:
                    if self._draining:
                        self._closed = True
                        return
                    continue
                # coalescing window: the FIRST waiter's arrival starts
                # the clock; stop early once a full batch is waiting
                if self.max_wait_s > 0:
                    first = self._q[0]
                    give_up = first.t_enq + self.max_wait_s
                    while (not self._draining
                           and sum(len(r.records) for r in self._q)
                           < self.max_batch):
                        left = give_up - time.perf_counter()
                        if left <= 0:
                            break
                        self._work.wait(timeout=left)
                        if not self._q:
                            break
                    if not self._q:
                        continue
                taken = self._take_locked()
            try:
                self._run_batch(taken, reg)
            except BaseException as e:  # noqa: BLE001 - watchdog
                # _run_batch contains engine failures itself; anything
                # escaping is a bug in the dispatch path — fail THIS
                # batch's futures and keep the dispatcher alive
                self._record_step(reg, ok=False)
                n = 0
                for req in taken:
                    if _deliver_exception(req.future, e):
                        n += 1
                if n:
                    reg.counter("requests_failed").inc(n)

    def _shutdown_pending(self) -> None:
        err = RuntimeError("quorum-serve dispatcher exited")
        with self._lock:
            self._dead = True
            stranded = list(self._q)
            self._q.clear()
        n = 0
        for req in stranded:
            if _deliver_exception(req.future, err):
                n += 1
        if n:
            self.registry.counter("requests_failed").inc(n)

    def _record_step(self, reg, ok: bool) -> None:
        """Track engine-step health: consecutive failures drive the
        unhealthy flip; any success resets the streak."""
        with self._lock:
            if ok:
                self._consecutive_failures = 0
            else:
                self._consecutive_failures += 1
            n = self._consecutive_failures
        if not ok:
            reg.counter("engine_step_failures").inc()
        reg.gauge("consecutive_failures").set(n)

    def _step_requests(self, reqs: list[_Request]) -> list[list]:
        """One coalesced engine pass over `reqs`: flatten, step in
        max_batch chunks, return each request's slice of results."""
        flat: list = []
        slices: list[tuple[int, int]] = []
        for req in reqs:
            slices.append((len(flat), len(flat) + len(req.records)))
            flat.extend(req.records)
        results: list = []
        for off in range(0, len(flat), self.max_batch):
            results.extend(
                self.engine.step(flat[off:off + self.max_batch]))
        return [results[s:e] for s, e in slices]

    def _resolve(self, reqs: list[_Request], per_req: list[list],
                 reg) -> None:
        reg.counter("requests_completed").inc(len(reqs))
        for req, res in zip(reqs, per_req):
            try:
                if req.future.set_running_or_notify_cancel():
                    req.future.set_result(res)
            except Exception:  # pragma: no cover - abandoned future
                pass

    def _run_batch(self, taken: list[_Request], reg) -> None:
        now = time.perf_counter()
        live: list[_Request] = []
        for req in taken:
            if req.deadline is not None and now > req.deadline:
                reg.counter("requests_deadline_exceeded").inc()
                if req.future.set_running_or_notify_cancel():
                    req.future.set_exception(DeadlineExceeded())
            else:
                if reg.enabled:
                    reg.histogram("queue_wait_us").observe(
                        int((now - req.t_enq) * 1e6))
                live.append(req)
        if not live:
            return
        try:
            per_req = self._step_requests(live)
        except BaseException as e:  # noqa: BLE001 - isolated per batch
            self._record_step(reg, ok=False)
            if len(live) > 1:
                self._bisect_retry(live, reg)
            else:
                reg.counter("requests_failed").inc(1)
                _deliver_exception(live[0].future, e)
            return
        self._record_step(reg, ok=True)
        self._resolve(live, per_req, reg)

    def _bisect_retry(self, live: list[_Request], reg) -> None:
        """A failed multi-request batch is bisect-retried ONCE: each
        half runs its own engine pass, so a single poisoned request
        fails only its half's futures (with one more split it would
        be exactly isolated; one level keeps worst-case extra device
        steps at two) while innocent batchmates still get answers. A
        half succeeding also proves the device is alive, resetting
        the consecutive-failure streak."""
        reg.counter("batch_bisections").inc()
        mid = (len(live) + 1) // 2
        for half in (live[:mid], live[mid:]):
            if not half:
                continue
            try:
                per_req = self._step_requests(half)
            except BaseException as e:  # noqa: BLE001 - per half
                self._record_step(reg, ok=False)
                reg.counter("requests_failed").inc(len(half))
                for req in half:
                    _deliver_exception(req.future, e)
                continue
            self._record_step(reg, ok=True)
            self._resolve(half, per_req, reg)
