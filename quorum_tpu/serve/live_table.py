"""Mutable counting table for the live ingestion tier (ISSUE 18).

The batch pipeline's stage 1 assumes the whole input exists before
counting starts; a sequencer doesn't work that way — reads arrive for
hours. `LiveTable` is the build-side tile table (ops/ctable.TBuildState)
kept OPEN: `ingest_records` pushes FASTQ records through the exact
stage-1 insert wire (fastq.batch_records → packing.pack_reads →
tile_insert_reads_packed, grow via tile_grow_build) in fixed-shape
batches, and `seal()` produces an immutable epoch snapshot WITHOUT
closing the build planes (tile_seal never donates its inputs), so
ingestion continues while the snapshot is exported, verified, and
swapped into the correction path.

Three pieces live here, the ingest dispatcher (serve/ingest.py) owns
the threading around them:

* **LiveTable** — the open build table + running stats. Batch rows are
  fixed (`QUORUM_INGEST_BATCH` lever) so the fused insert executable
  compiles once per (geometry, length-bucket), not per chunk size.
* **epoch_floor** — the time-varying presence floor: the PR 13 floor
  machinery generalized from a build-time constant to a ramp. Early
  epochs see thin coverage where a once-seen k-mer is as likely error
  as signal, so the floor starts at `initial`; as mean HQ coverage
  approaches `ramp`, the floor steps down linearly to `final`. The
  policy is declared in every epoch header (`live_epoch.floor_policy`)
  so a snapshot is self-describing.
* **LiveTableCheckpoint** — durability, mirroring Stage1Checkpoint
  byte-for-byte in idiom: one file, sealed JSON header line + raw
  planes, incremental CRC32C payload digest, streamed tmp-then-rename,
  `checkpoint.commit` fault site. The cursor it carries is the ingest
  CHUNK sequence number, not a batch index: a killed service resumes
  the table at the last committed chunk and acknowledges re-sent
  chunks at-or-below that cursor as duplicates — exactly-once inserts
  without re-ingesting.
"""

from __future__ import annotations

import json
import math
import os

import jax.numpy as jnp
import numpy as np

from ..io import fastq, integrity, packing
from ..io.checkpoint import CheckpointError
from ..ops import ctable
from ..utils import faults, levers, resources

LIVE_CKPT_FORMAT = "quorum_tpu_live_ckpt/1"


def epoch_floor(initial: int, final: int, ramp: float,
                coverage: float) -> int:
    """The presence floor for an epoch sealed at mean HQ `coverage`
    (total_hq / distinct_hq). Linear ramp from `initial` at coverage 0
    down to `final` at coverage >= `ramp`; degenerate policies
    (initial <= final, or no ramp) pin at `final`."""
    initial = int(initial)
    final = int(final)
    if initial <= final or ramp <= 0:
        return final
    if coverage >= ramp:
        return final
    frac = max(0.0, 1.0 - float(coverage) / float(ramp))
    return final + int(math.ceil((initial - final) * frac))


class LiveStats:
    """Running ingest totals (the checkpoint persists them, healthz
    reports them)."""

    def __init__(self):
        self.reads = 0
        self.bases = 0
        self.batches = 0
        self.grows = 0

    def as_dict(self) -> dict:
        return {"reads": self.reads, "bases": self.bases,
                "batches": self.batches, "grows": self.grows}


class LiveTable:
    """An open stage-1 build table that accepts reads forever.

    NOT thread-safe: the ingest dispatcher thread is the sole owner of
    the build planes; HTTP threads hand it records through a queue
    (serve/ingest.py) and only ever touch sealed snapshots."""

    def __init__(self, k: int, bits: int, size: int, qual_thresh: int,
                 *, batch_rows: int | None = None, max_grows: int = 8):
        if batch_rows is None:
            batch_rows = int(levers.raw("QUORUM_INGEST_BATCH")
                             or "256")
        if batch_rows <= 0:
            raise ValueError(f"batch_rows must be > 0, got {batch_rows}")
        self.k = int(k)
        self.bits = int(bits)
        self.size = int(size)
        self.qual_thresh = int(qual_thresh)
        self.batch_rows = int(batch_rows)
        self.max_grows = int(max_grows)
        self.meta = ctable.TileMeta(
            self.k, self.bits,
            ctable.tile_rb_for(self.size, self.k, self.bits))
        self.bstate = ctable.make_tile_build(self.meta)
        self.stats = LiveStats()

    # -- ingest -----------------------------------------------------------
    def ingest_records(self, records) -> int:
        """Insert `records` ((header, seq, qual) tuples) and return the
        number inserted. Slices into fixed `batch_rows`-row batches —
        the padding keeps the fused insert executable's signature set
        to one per length bucket, so a stream of odd-sized chunks
        never recompiles."""
        n_in = 0
        for batch in fastq.batch_records(iter(records),
                                         self.batch_rows):
            self._insert_batch(batch)
            n_in += batch.n
        return n_in

    def _insert_batch(self, batch) -> None:
        pk = packing.pack_reads(batch.codes, batch.quals,
                                batch.lengths,
                                thresholds=(self.qual_thresh,))
        bstate, meta = self.bstate, self.meta
        bstate, full, (chi, clo, q, valid, placed) = \
            ctable.tile_insert_reads_packed(bstate, meta, pk,
                                            self.qual_thresh)
        full = bool(full)
        if full:
            pending = jnp.logical_and(valid, jnp.logical_not(placed))
        for _ in range(self.max_grows + 1):
            if not full:
                break
            # the existing geometry-restart machinery: double the rows
            # and re-drive only the observations that missed
            bstate, meta = ctable.tile_grow_build(bstate, meta)
            self.stats.grows += 1
            bstate, full, placed = ctable.tile_insert_observations(
                bstate, meta, chi, clo, q, pending)
            full = bool(full)
            pending = jnp.logical_and(pending,
                                      jnp.logical_not(placed))
        else:
            if full:
                raise RuntimeError("Hash is full")
        self.bstate, self.meta = bstate, meta
        self.stats.batches += 1
        self.stats.reads += int(batch.n)
        self.stats.bases += int(batch.lengths.sum())

    # -- epoch snapshot ---------------------------------------------------
    def seal(self):
        """Non-destructively seal the current contents: returns
        (TileState, n_occupied, distinct_hq, total_hq). The build
        planes stay valid — tile_seal reads them without donation, so
        the next chunk inserts into the same table the snapshot was
        cut from."""
        state, dup, occ, distinct, total = ctable.tile_seal(
            self.bstate, self.meta)
        if bool(dup):
            raise RuntimeError(
                "live table sealed with duplicate keys in one bucket "
                "(corrupted build state)")
        return state, int(occ), int(distinct), int(total)

    def coverage(self, distinct: int, total: int) -> float:
        """Mean HQ multiplicity of the sealed snapshot — the ramp
        signal epoch_floor consumes."""
        return (float(total) / float(distinct)) if distinct > 0 else 0.0


# ---------------------------------------------------------------------------
# Durability: the live-table snapshot (Stage1Checkpoint's idiom, with
# a chunk cursor instead of a batch cursor)
# ---------------------------------------------------------------------------


class LiveSnapshot:
    """A loaded live-table snapshot: host planes + the ingest cursor."""

    def __init__(self, header: dict, tag: np.ndarray, hq: np.ndarray,
                 lq: np.ndarray):
        self.header = header
        self.tag = tag
        self.hq = hq
        self.lq = lq

    @property
    def cursor(self) -> int:
        return int(self.header["cursor"])

    def check_config(self, k: int, bits: int, qual_thresh: int,
                     batch_rows: int) -> None:
        h = self.header
        want = {"k": k, "bits": bits, "qual_thresh": qual_thresh,
                "batch_rows": batch_rows}
        for key, val in want.items():
            if int(h.get(key, -1)) != int(val):
                raise CheckpointError(
                    f"live-table checkpoint was written with {key}="
                    f"{h.get(key)}, this service uses {val}; refusing "
                    "to resume (delete the checkpoint to start over)")


class LiveTableCheckpoint:
    """Atomic snapshot file `<live-dir>/live.ckpt`: the open build
    planes plus the last fully-ingested chunk sequence number."""

    def __init__(self, directory: str):
        self.dir = directory
        self.path = os.path.join(directory, "live.ckpt")

    def save(self, table: LiveTable, cursor: int) -> None:
        """Snapshot after chunk `cursor` is fully inserted. D2H
        happens here (np.asarray) — the checkpoint is a sync point,
        which is why `--live-checkpoint-every` is a cadence knob.
        Rides the degradation ladder as a stage-1 checkpoint
        (ISSUE 19): ENOSPC disables snapshots, ingest keeps going."""
        if resources.degraded("stage1.checkpoint"):
            return
        with resources.guard("stage1.checkpoint", path=self.path):
            self._save_guarded(table, cursor)

    def _save_guarded(self, table: LiveTable, cursor: int) -> None:
        os.makedirs(self.dir, exist_ok=True)
        bstate, meta = table.bstate, table.meta
        tag = np.ascontiguousarray(np.asarray(bstate.tag,
                                              dtype=np.uint32))
        hq = np.ascontiguousarray(np.asarray(bstate.hq,
                                             dtype=np.uint32))
        lq = np.ascontiguousarray(np.asarray(bstate.lq,
                                             dtype=np.uint32))
        pcrc = integrity.crc32c(tag)
        pcrc = integrity.crc32c(hq, pcrc)
        pcrc = integrity.crc32c(lq, pcrc)
        header = integrity.seal({
            "format": LIVE_CKPT_FORMAT,
            "k": meta.k,
            "bits": meta.bits,
            "rb_log2": meta.rb_log2,
            "cursor": int(cursor),
            "reads": int(table.stats.reads),
            "bases": int(table.stats.bases),
            "batches": int(table.stats.batches),
            "grows": int(table.stats.grows),
            "qual_thresh": int(table.qual_thresh),
            "batch_rows": int(table.batch_rows),
            "tag_shape": list(tag.shape),
            "acc_len": int(hq.shape[0]),
            "payload_crc32c": pcrc,
        })
        tmp = self.path + ".tmp"
        with open(tmp, "wb") as f:
            f.write(json.dumps(header).encode() + b"\n")
            f.write(tag.tobytes())
            f.write(hq.tobytes())
            f.write(lq.tobytes())
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, self.path)
        integrity.fsync_dir(self.path)
        faults.inject("checkpoint.commit", path=self.path)

    def load(self) -> LiveSnapshot | None:
        """The last committed snapshot, or None when there is none. A
        truncated/corrupt file raises CheckpointError — resuming from
        garbage must not look like a fresh start."""
        if not os.path.exists(self.path):
            return None
        with open(self.path, "rb") as f:
            line = f.readline(1 << 20)
            try:
                header = json.loads(line)
            except ValueError:
                raise CheckpointError(
                    f"corrupt live-table checkpoint '{self.path}' "
                    "(bad header)") from None
            if header.get("format") != LIVE_CKPT_FORMAT:
                raise CheckpointError(
                    f"'{self.path}' is not a live-table checkpoint "
                    f"(format={header.get('format')!r})")
            try:
                integrity.check_seal(header, "live-table checkpoint",
                                     self.path)
            except integrity.IntegrityError as e:
                raise CheckpointError(str(e)) from None
            rows, tile = header["tag_shape"]
            acc = header["acc_len"]
            want = (rows * tile + 2 * acc) * 4
            payload = f.read()
        if len(payload) != want:
            raise CheckpointError(
                f"corrupt live-table checkpoint '{self.path}': "
                f"payload {len(payload)} bytes, want {want}")
        got = integrity.crc32c(payload)
        if got != int(header["payload_crc32c"]):
            integrity.record_error(
                f"live-table checkpoint '{self.path}': payload digest "
                f"mismatch (crc32c {got:#010x} != recorded "
                f"{int(header['payload_crc32c']):#010x})",
                path=self.path, section="payload")
            raise CheckpointError(
                f"live-table checkpoint '{self.path}' failed its "
                "payload digest; the snapshot is silently corrupted — "
                "refusing to resume from it (delete it to start over)")
        integrity.record_verified(len(payload))
        arr = np.frombuffer(payload, dtype=np.uint32)
        tag = arr[:rows * tile].reshape(rows, tile)
        hq = arr[rows * tile:rows * tile + acc]
        lq = arr[rows * tile + acc:]
        return LiveSnapshot(header, tag, hq, lq)

    def cursor(self) -> int | None:
        """Header-only peek at the committed chunk cursor; None when
        no usable snapshot."""
        try:
            if not os.path.exists(self.path):
                return None
            with open(self.path, "rb") as f:
                header = json.loads(f.readline(1 << 20))
            return int(header["cursor"])
        except (OSError, ValueError, KeyError):
            return None

    def clear(self) -> None:
        try:
            os.remove(self.path)
        except FileNotFoundError:
            pass


def load_or_create(ckpt: LiveTableCheckpoint, k: int, bits: int,
                   size: int, qual_thresh: int,
                   *, batch_rows: int | None = None,
                   max_grows: int = 8) -> tuple[LiveTable, int]:
    """Resume the live table from `ckpt` when a snapshot exists (the
    killed-service path), else start fresh. Returns (table, cursor);
    cursor is -1 for a fresh table (no chunk ingested yet)."""
    table = LiveTable(k, bits, size, qual_thresh,
                      batch_rows=batch_rows, max_grows=max_grows)
    snap = ckpt.load()
    if snap is None:
        return table, -1
    snap.check_config(table.k, table.bits, table.qual_thresh,
                      table.batch_rows)
    meta = ctable.TileMeta(table.k, table.bits,
                           int(snap.header["rb_log2"]))
    table.meta = meta
    table.bstate = ctable.TBuildState(
        jnp.asarray(snap.tag), jnp.asarray(snap.hq),
        jnp.asarray(snap.lq))
    table.stats.reads = int(snap.header.get("reads", 0))
    table.stats.bases = int(snap.header.get("bases", 0))
    table.stats.batches = int(snap.header.get("batches", 0))
    table.stats.grows = int(snap.header.get("grows", 0))
    return table, snap.cursor
