"""Lock discipline rules (ISSUE 12 rule 5): the static half of the
concurrency sanitizer.

The serve tier and the telemetry exporters are the two places the
repo runs real thread concurrency (dispatcher/watchdog/HTTP handlers;
heartbeat/ticker/push/scrape), and PRs 7, 10, and 11 each hand-fixed
a race here — the lock-free warm_lengths snapshot, the receiver
writing its fleet doc outside its lock, the straggler event after
write(). ROADMAP items 1 and 4 (multi-host fleet, online counting
fused into the threaded serve engine) multiply the hazard. Two
passes:

* ``lock-unguarded-write`` — a lockset pass per class (and per
  module-level lock) over the nine lock-bearing modules: an attribute
  that is mutated under ``with self._lock`` somewhere is a
  lock-guarded attribute, so mutating it WITHOUT the lock elsewhere
  is a finding. Convention honored: methods named ``*_locked`` assert
  the caller holds the lock; ``__init__`` constructs before the
  object escapes. A deliberate lock-free snapshot (serve/engine's
  warm_lengths) carries an inline disable with its reason.
* ``lock-order-inversion`` — cross-module acquisition edges (a
  ``with``-lock block that calls into a method known to take another
  catalogued lock, or lexically nests one) checked against
  :data:`LOCK_ORDER`, the declared global order. An edge from a
  later-ranked lock into an earlier-ranked one is an inversion — the
  static mirror of what analysis/tsan.py detects at runtime.

The declared order (outermost first). Telemetry locks rank below
serve locks because exporters/alert evaluation are CALLED FROM serve
paths holding serve locks, never the reverse; the registry lock is
the innermost of all — metric increments happen under everything.
"""

from __future__ import annotations

import ast

from .core import Finding, call_name, dotted, rule

SCOPE = (
    "quorum_tpu/serve/batcher.py",
    "quorum_tpu/serve/server.py",
    "quorum_tpu/serve/ingest.py",
    "quorum_tpu/serve/admission.py",
    "quorum_tpu/telemetry/export.py",
    "quorum_tpu/telemetry/alerts.py",
    "quorum_tpu/telemetry/spans.py",
    "quorum_tpu/telemetry/flight.py",
    "quorum_tpu/telemetry/registry.py",
    "quorum_tpu/utils/faults.py",
    "quorum_tpu/utils/resources.py",
    "quorum_tpu/ops/tuning.py",
    "quorum_tpu/parallel/fleet.py",
)

# Lock keys are "<module-stem>.<Class>.<attr>" or "<module-stem>.<name>"
# for module-level locks. Outermost (acquired first) ranks first.
LOCK_ORDER = (
    "server.CorrectionHTTPServer._reload_lock",
    "server.CorrectionHTTPServer._req_lock",
    # the ingest dispatcher's queue lock: HTTP handlers enqueue under
    # it, and the worker calls swap_engine (batcher lock) from its
    # epoch path — so it ranks outside the batcher, never inside
    "ingest.IngestDispatcher._lock",
    "batcher.Batcher._lock",
    "admission.TokenBucketQuota._lock",
    "alerts.AlertEngine._lock",
    # the resource frame lock: guards the degraded-writer set and the
    # watchdog beat cursor; degrade()/beat() are called from writer
    # paths that may hold serve/alert locks, and every registry/
    # flight call it triggers happens after release — so it ranks
    # between the feeders above and the telemetry sinks below
    "resources._lock",
    "export._LIVE_LOCK",
    "spans.SpanTracer._lock",
    # the flight ring: its taps run at the TOP of event()/_record(),
    # OUTSIDE the registry/tracer locks, and dump() (which reads the
    # registry under its lock) is reached from alert evaluation
    # holding alerts._lock — so the ring ranks between the feeders
    # above it and the registry below it
    "flight.FlightRecorder._lock",
    "registry.MetricsRegistry._lock",
    # the fleet state lock: guards the bring-up singleton, the
    # exchange epoch counters, and the host-run sanction depth; the
    # exchange path calls faults.inject (FaultPlan._lock) AFTER
    # releasing it, and it is never held across a barrier or a
    # blocking KV get — so it ranks just outside the fault plan
    "fleet._lock",
    "faults.FaultPlan._lock",
    "tuning._lock",
)

_LOCK_CTORS = ("threading.Lock", "threading.RLock",
               "threading.Condition", "Lock", "RLock", "Condition")


def _stem(rel: str) -> str:
    return rel.rsplit("/", 1)[-1][:-3]


class _ClassLocks:
    """Lock attributes of one class, with Condition aliases folded
    onto the lock they wrap."""

    def __init__(self, cls: ast.ClassDef, stem: str):
        self.cls = cls
        self.stem = stem
        self.attrs: dict[str, str] = {}  # attr -> canonical attr
        for node in ast.walk(cls):
            if not isinstance(node, ast.Assign) or not isinstance(
                    node.value, ast.Call):
                continue
            ctor = call_name(node.value)
            if ctor not in _LOCK_CTORS:
                continue
            for tgt in node.targets:
                if (isinstance(tgt, ast.Attribute)
                        and isinstance(tgt.value, ast.Name)
                        and tgt.value.id == "self"):
                    canonical = tgt.attr
                    if "Condition" in ctor and node.value.args:
                        wrapped = node.value.args[0]
                        if (isinstance(wrapped, ast.Attribute)
                                and isinstance(wrapped.value, ast.Name)
                                and wrapped.value.id == "self"):
                            canonical = wrapped.attr
                    self.attrs[tgt.attr] = canonical

    def key(self, attr: str) -> str:
        return f"{self.stem}.{self.cls.name}.{self.attrs[attr]}"


def _module_locks(tree: ast.Module, stem: str) -> dict[str, str]:
    """Module-global lock names -> key."""
    locks = {}
    for node in tree.body:
        if isinstance(node, ast.Assign) and isinstance(
                node.value, ast.Call) and call_name(
                    node.value) in _LOCK_CTORS:
            for tgt in node.targets:
                if isinstance(tgt, ast.Name):
                    locks[tgt.id] = f"{stem}.{tgt.id}"
    return locks


def _with_lock_items(node: ast.With, cl: _ClassLocks | None,
                     mod_locks: dict[str, str]) -> list[str]:
    """Lock keys this `with` statement acquires."""
    keys = []
    for item in node.items:
        ce = item.context_expr
        if (isinstance(ce, ast.Attribute)
                and isinstance(ce.value, ast.Name)
                and ce.value.id == "self"
                and cl is not None and ce.attr in cl.attrs):
            keys.append(cl.key(ce.attr))
        elif isinstance(ce, ast.Name) and ce.id in mod_locks:
            keys.append(mod_locks[ce.id])
    return keys


def _collect(project):
    """Per scoped module: (tree, stem, classes, mod_locks)."""
    out = []
    for rel in SCOPE:
        src = project.get(rel)
        if src is None or src.tree is None:
            continue
        stem = _stem(rel)
        classes = {cls.name: _ClassLocks(cls, stem)
                   for cls in src.tree.body
                   if isinstance(cls, ast.ClassDef)}
        classes = {name: cl for name, cl in classes.items()
                   if cl.attrs}
        out.append((src, stem, classes, _module_locks(src.tree, stem)))
    return out


def _store_attrs(node: ast.AST) -> list[tuple[str, int]]:
    """self.X stores (plain or augmented) in the subtree."""
    stores = []
    for n in ast.walk(node):
        tgts = []
        if isinstance(n, ast.Assign):
            tgts = n.targets
        elif isinstance(n, (ast.AugAssign, ast.AnnAssign)):
            tgts = [n.target]
        for tgt in tgts:
            if (isinstance(tgt, ast.Attribute)
                    and isinstance(tgt.value, ast.Name)
                    and tgt.value.id == "self"):
                stores.append((tgt.attr, tgt.lineno))
            elif (isinstance(tgt, ast.Subscript)
                    and isinstance(tgt.value, ast.Attribute)
                    and isinstance(tgt.value.value, ast.Name)
                    and tgt.value.value.id == "self"):
                stores.append((tgt.value.attr, tgt.lineno))
    return stores


@rule("lock-unguarded-write",
      "mutation of a lock-guarded attribute without the lock")
def lock_unguarded_write(project):
    findings = []
    for src, stem, classes, mod_locks in _collect(project):
        for cls_node in src.tree.body:
            if not isinstance(cls_node, ast.ClassDef):
                continue
            cl = classes.get(cls_node.name)
            if cl is None:
                continue
            methods = [m for m in cls_node.body if isinstance(
                m, (ast.FunctionDef, ast.AsyncFunctionDef))]
            # pass 1: attrs mutated under the lock anywhere
            guarded: set[str] = set()
            locked_spans: list[tuple[int, int]] = []
            for m in methods:
                for w in ast.walk(m):
                    if isinstance(w, ast.With) and _with_lock_items(
                            w, cl, mod_locks):
                        locked_spans.append(
                            (w.lineno, w.end_lineno or w.lineno))
                        guarded.update(
                            a for a, _ in _store_attrs(w))
            guarded -= set(cl.attrs)  # the locks themselves
            if not guarded:
                continue

            def under_lock(line: int) -> bool:
                return any(lo <= line <= hi for lo, hi in locked_spans)

            # pass 2: the same attrs mutated outside any locked span
            for m in methods:
                if m.name in ("__init__", "__del__", "__enter__",
                              "__exit__") or m.name.endswith("_locked"):
                    continue
                for attr, line in _store_attrs(m):
                    if attr not in guarded or under_lock(line):
                        continue
                    findings.append(Finding(
                        "lock-unguarded-write", src.rel, line,
                        f"self.{attr} is mutated under "
                        f"{cls_node.name}'s lock elsewhere but "
                        f"written here in {m.name}() without it — "
                        "a concurrent reader can observe the torn "
                        "update",
                        "take the lock (or rename the method "
                        "*_locked if every caller already holds it); "
                        "a deliberate lock-free snapshot takes "
                        "# qlint: disable=lock-unguarded-write "
                        "with its reason"))
    return findings


# method names too generic to resolve by name across modules: a
# `.close()` on a file object must not resolve to AlertEngine.close.
# Cross-module edges only come from DISTINCTIVE method names.
_GENERIC_METHODS = frozenset((
    "close", "open", "write", "read", "get", "put", "set", "add",
    "start", "stop", "run", "flush", "clear", "pop", "update",
    "event", "inc", "observe", "append", "wait", "notify", "send",
))


def _lock_taking_methods(collected):
    """(class name, method name) -> lock key, for resolving calls
    made while holding a lock into acquisition edges."""
    out: dict[str, str] = {}
    for src, stem, classes, mod_locks in collected:
        for cls_name, cl in classes.items():
            cls_node = cl.cls
            for m in cls_node.body:
                if not isinstance(m, (ast.FunctionDef,
                                      ast.AsyncFunctionDef)):
                    continue
                if m.name in _GENERIC_METHODS:
                    continue
                for w in ast.walk(m):
                    if isinstance(w, ast.With):
                        for key in _with_lock_items(w, cl, mod_locks):
                            out[f"{cls_name}.{m.name}"] = key
    return out


@rule("lock-order-inversion",
      "lock acquisition order contradicting the declared LOCK_ORDER")
def lock_order_inversion(project):
    collected = _collect(project)
    rank = {key: i for i, key in enumerate(LOCK_ORDER)}
    takers = _lock_taking_methods(collected)
    # method-name -> candidate lock keys (cross-module resolution is
    # by name; collisions produce multiple candidates and we only
    # report when EVERY candidate inverts — precision over recall)
    by_method: dict[str, set[str]] = {}
    for qual, key in takers.items():
        by_method.setdefault(qual.rsplit(".", 1)[-1], set()).add(key)

    findings = []
    seen: set[tuple] = set()
    for src, stem, classes, mod_locks in _collect(project):
        for cls_node in [n for n in src.tree.body
                         if isinstance(n, ast.ClassDef)] + [None]:
            cl = classes.get(cls_node.name) if cls_node else None
            scope_node = cls_node if cls_node else src.tree
            # the module pass (cls_node None) must not re-walk class
            # bodies — a module-lock acquisition inside a method is
            # already covered by its class pass
            class_spans = [] if cls_node else [
                (n.lineno, n.end_lineno or n.lineno)
                for n in src.tree.body if isinstance(n, ast.ClassDef)]
            for w in ast.walk(scope_node):
                if not isinstance(w, ast.With):
                    continue
                if any(lo <= w.lineno <= hi for lo, hi in class_spans):
                    continue
                held = _with_lock_items(w, cl, mod_locks)
                if not held:
                    continue
                outer = held[0]
                if outer not in rank:
                    continue
                inner_keys: list[tuple[str, int, str]] = []
                for n in ast.walk(w):
                    if isinstance(n, ast.With) and n is not w:
                        for k in _with_lock_items(n, cl, mod_locks):
                            inner_keys.append(
                                (k, n.lineno, "nested with"))
                    elif isinstance(n, ast.Call):
                        name = call_name(n).rsplit(".", 1)[-1]
                        cands = by_method.get(name, ())
                        if cands and all(
                                k in rank
                                and rank[k] < rank[outer]
                                for k in cands):
                            inner_keys.append((
                                sorted(cands)[0], n.lineno,
                                f"call to {dotted(n.func)}() which "
                                "acquires it"))
                for inner, line, how in inner_keys:
                    if inner == outer or inner not in rank:
                        continue
                    key = (src.rel, line, outer, inner)
                    if key in seen:
                        continue
                    if rank[inner] < rank[outer]:
                        seen.add(key)
                        findings.append(Finding(
                            "lock-order-inversion", src.rel, line,
                            f"{outer} is held while acquiring "
                            f"{inner} ({how}) but LOCK_ORDER ranks "
                            f"{inner} OUTER — the reverse nesting "
                            "elsewhere deadlocks",
                            "acquire in declared order (analysis/"
                            "rules_locks.LOCK_ORDER), or re-rank the "
                            "order if this direction is the designed "
                            "one everywhere"))
    return findings
