"""Unused-definition rule (ISSUE 12 satellite): dead-code detection
tuned for THIS repo's layout.

Twelve PRs of refactors leave orphans — a helper whose last caller
was folded into a shared idiom, an import kept from a deleted code
path. Dead code is not free: it gets read, maintained, and (worst)
trusted as load-bearing by the next refactor. The rule:

* module-level functions and classes in ``quorum_tpu/`` whose name is
  referenced in NO other scanned file and nowhere else in their own
  module (tests count as references — a test-only helper is alive);
* imports a module never references (``__init__.py`` re-exports and
  conventional-alias imports are exempt).

Findings in ``tools/`` are INFO severity (report-only, per the
issue): the smoke tools are invoked by ci/tier1.sh with their whole
surface, and deleting there is a human call.

Usage detection is identifier-boundary text search across every
scanned file including strings and comments — ``getattr``-style
dynamic dispatch and doc references keep a symbol alive. The rule
errs toward NOT flagging; what it does flag really has zero textual
referents anywhere.
"""

from __future__ import annotations

import ast

from .core import SEV_ERROR, SEV_INFO, Finding, rule

# names with implicit callers: entry points (pyproject scripts),
# pytest hooks/fixtures, dunder machinery
_IMPLICIT = {"main", "bench_main"}

# conventional side-effect / namespace imports that exist to be
# re-exported or to register something at import time
_ALIAS_OK = {"annotations"}


def _module_defs(tree: ast.Module):
    for node in tree.body:
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.ClassDef)):
            yield node


def _decorated_implicit(node) -> bool:
    for dec in node.decorator_list:
        text = ast.unparse(dec)
        if "fixture" in text or "register" in text or "rule" in text:
            return True
    return False


@rule("unused-definition",
      "module-level def/class or import nothing references")
def unused_definition(project):
    findings = []
    for src in project.files.values():
        if src.tree is None or src.in_tests:
            continue
        if not (src.in_package or src.in_tools):
            continue
        severity = SEV_INFO if src.in_tools else SEV_ERROR
        is_init = src.rel.endswith("__init__.py")

        # --- defs and classes -----------------------------------------
        for node in _module_defs(src.tree):
            name = node.name
            if (name.startswith("__") or name in _IMPLICIT
                    or _decorated_implicit(node)):
                continue
            # own-module references beyond the def line itself: a
            # local caller or a docstring pointer — alive either way
            if _mentions_beyond_def(src, name):
                continue
            if project.usage_count(name, exclude_rel=src.rel) > 0:
                continue
            kind = ("class" if isinstance(node, ast.ClassDef)
                    else "function")
            findings.append(Finding(
                "unused-definition", src.rel, node.lineno,
                f"{kind} {name} has no reference anywhere in the "
                "scanned tree (package, tools, tests, bench)",
                "delete it (git remembers), or wire up the caller "
                "it was written for",
                severity=severity))

        # --- imports --------------------------------------------------
        if is_init:
            continue  # __init__ imports ARE the public surface
        for node in src.tree.body:
            imported: list[tuple[str, int]] = []
            if isinstance(node, ast.Import):
                for alias in node.names:
                    name = alias.asname or alias.name.split(".")[0]
                    imported.append((name, node.lineno))
            elif isinstance(node, ast.ImportFrom):
                if node.module == "__future__":
                    continue
                for alias in node.names:
                    if alias.name == "*":
                        continue
                    name = alias.asname or alias.name
                    imported.append((name, node.lineno))
            for name, line in imported:
                if name in _ALIAS_OK or name.startswith("_"):
                    continue
                # `# noqa` on the import line: a declared side-effect
                # or registration import (the rule modules themselves)
                if line <= len(src.lines) and "noqa" in \
                        src.lines[line - 1]:
                    continue
                if _mentions_beyond_import(src, name):
                    continue
                findings.append(Finding(
                    "unused-definition", src.rel, line,
                    f"import {name} is never used in this module",
                    "remove the import",
                    severity=severity))
    return findings


def _mentions_beyond_def(src, name: str) -> bool:
    """Does `name` appear on any line that is not its own def/class
    line or a decorator line directly above one?"""
    hits = 0
    for line in src.lines:
        if f"def {name}" in line or f"class {name}" in line:
            continue
        if _word_in(line, name):
            hits += 1
    return hits > 0


def _mentions_beyond_import(src, name: str) -> bool:
    for line in src.lines:
        stripped = line.strip()
        if stripped.startswith(("import ", "from ")) and \
                _word_in(line, name):
            continue
        if _word_in(line, name):
            return True
    return False


def _word_in(line: str, name: str) -> bool:
    i = 0
    while True:
        i = line.find(name, i)
        if i < 0:
            return False
        before = line[i - 1] if i else " "
        after_idx = i + len(name)
        after = line[after_idx] if after_idx < len(line) else " "
        if not (before.isalnum() or before == "_") and not (
                after.isalnum() or after == "_"):
            return True
        i += 1
