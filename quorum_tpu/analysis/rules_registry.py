"""Declared-registry consistency rules (ISSUE 12 rule 2).

Three registries exist precisely because their members kept drifting
from their consumers: the env-lever catalog (utils/levers.py), the
fault-site catalog (utils/faults.SITES), and the required-counter
contract (telemetry/contract.py). Each rule checks BOTH directions —
an undeclared use is a finding (it bypasses the registry) and an
unused declaration is a finding (the registry is lying about the
system's surface).
"""

from __future__ import annotations

import ast

from .core import Finding, call_name, const_str, rule

# the modules that ARE the registries: reads/declarations inside them
# are the mechanism, not a bypass
_LEVERS_MODULE = "quorum_tpu/utils/levers.py"
_FAULTS_MODULE = "quorum_tpu/utils/faults.py"

_ENV_READ_FUNCS = ("os.environ.get", "os.getenv", "environ.get")
_LEVER_FUNCS = ("levers.raw", "levers.get_bool")


def _lever_catalog() -> dict:
    from ..utils.levers import CATALOG
    return CATALOG


def _fault_sites() -> dict:
    from ..utils.faults import SITES
    return SITES


def _env_read_name(call: ast.Call) -> str | None:
    """The constant env-var name of an os.environ read, or None."""
    if call_name(call) in _ENV_READ_FUNCS and call.args:
        return const_str(call.args[0])
    return None


def _iter_env_reads(tree: ast.AST):
    """(node, name) for every constant-name environ read: .get/getenv
    calls plus `os.environ["X"]` subscripts in load context."""
    for node in ast.walk(tree):
        if isinstance(node, ast.Call):
            name = _env_read_name(node)
            if name is not None:
                yield node, name
        elif isinstance(node, ast.Subscript) and isinstance(
                node.ctx, ast.Load):
            base = ast.unparse(node.value)
            if base in ("os.environ", "environ"):
                name = const_str(node.slice)
                if name is not None:
                    yield node, name


@rule("lever-raw-env-read",
      "QUORUM_* env read in quorum_tpu/ bypassing utils.levers")
def lever_raw_env_read(project):
    findings = []
    for src in project.package_files():
        if src.tree is None or src.rel == _LEVERS_MODULE:
            continue
        for node, name in _iter_env_reads(src.tree):
            if not name.startswith("QUORUM_"):
                continue
            findings.append(Finding(
                "lever-raw-env-read", src.rel, node.lineno,
                f"direct environ read of {name!r} bypasses the lever "
                "catalog — a renamed or undeclared lever would "
                "silently steer nothing",
                "read it via quorum_tpu.utils.levers.raw(name) (or "
                "the typed getters); declare the lever in "
                "levers.CATALOG if it is new"))
    return findings


@rule("lever-undeclared",
      "QUORUM_* name read anywhere but missing from levers.CATALOG")
def lever_undeclared(project):
    catalog = _lever_catalog()
    findings = []
    for src in project.files.values():
        if src.tree is None or src.rel == _LEVERS_MODULE:
            continue
        if src.in_tests:
            # tests may fabricate lever names to probe the catalog
            # check itself; the package and tools must not
            continue
        seen: set[str] = set()
        for node, name in _iter_env_reads(src.tree):
            if not name.startswith("QUORUM_") or name in catalog:
                continue
            if name in seen:
                continue
            seen.add(name)
            findings.append(Finding(
                "lever-undeclared", src.rel, node.lineno,
                f"{name!r} is read here but not declared in "
                "utils/levers.py — undocumented, untyped, invisible "
                "to --emit-docs",
                "add a _declare(...) entry (name, type, default, one-"
                "line doc) to quorum_tpu/utils/levers.py"))
        for node in ast.walk(src.tree):
            if not isinstance(node, ast.Call):
                continue
            if call_name(node) not in _LEVER_FUNCS or not node.args:
                continue
            name = const_str(node.args[0])
            if (name is None or not name.startswith("QUORUM_")
                    or name in catalog or name in seen):
                continue
            seen.add(name)
            findings.append(Finding(
                "lever-undeclared", src.rel, node.lineno,
                f"levers read of undeclared {name!r} (would raise "
                "KeyError at runtime)",
                "declare it in quorum_tpu/utils/levers.py"))
    return findings


@rule("lever-unused",
      "levers.CATALOG entry nothing in the repo reads")
def lever_unused(project):
    catalog = _lever_catalog()
    findings = []
    levers_src = project.get(_LEVERS_MODULE)
    for name in sorted(catalog):
        if project.usage_count(name, exclude_rel=_LEVERS_MODULE) == 0:
            line = 1
            if levers_src is not None:
                for i, text in enumerate(levers_src.lines, 1):
                    if f'"{name}"' in text:
                        line = i
                        break
            findings.append(Finding(
                "lever-unused", _LEVERS_MODULE, line,
                f"catalog declares {name!r} but nothing in the repo "
                "reads it — the published lever table would lie",
                "wire the lever up or delete the declaration"))
    return findings


@rule("fault-site-undeclared",
      "faults.inject() site string missing from faults.SITES")
def fault_site_undeclared(project):
    sites = _fault_sites()
    findings = []
    for src in project.package_files():
        if src.tree is None or src.rel == _FAULTS_MODULE:
            continue
        for node in ast.walk(src.tree):
            if not isinstance(node, ast.Call):
                continue
            fn = call_name(node)
            if not (fn == "faults.inject" or fn.endswith(".inject")
                    and "faults" in fn):
                continue
            if not node.args:
                continue
            name = const_str(node.args[0])
            if name is None:
                continue
            # the shorthand "site@batch=N" never appears at inject
            # call sites, but normalize anyway
            base = name.partition("@")[0]
            if base in sites:
                continue
            findings.append(Finding(
                "fault-site-undeclared", src.rel, node.lineno,
                f"inject site {name!r} is not declared in "
                "utils/faults.SITES — plans targeting it work by "
                "accident and the site list in the module doc lies",
                "declare the site (name -> where it fires) in "
                "quorum_tpu/utils/faults.py SITES"))
    return findings


@rule("fault-site-unused",
      "faults.SITES entry with no live inject() call")
def fault_site_unused(project):
    sites = _fault_sites()
    live: set[str] = set()
    for src in project.package_files():
        if src.tree is None or src.rel == _FAULTS_MODULE:
            continue
        for node in ast.walk(src.tree):
            if isinstance(node, ast.Call) and call_name(
                    node) == "faults.inject" and node.args:
                name = const_str(node.args[0])
                if name:
                    live.add(name.partition("@")[0])
    findings = []
    faults_src = project.get(_FAULTS_MODULE)
    for name in sorted(sites):
        if name in live:
            continue
        line = 1
        if faults_src is not None:
            for i, text in enumerate(faults_src.lines, 1):
                if f'"{name}"' in text:
                    line = i
                    break
        findings.append(Finding(
            "fault-site-unused", _FAULTS_MODULE, line,
            f"SITES declares {name!r} but no faults.inject() call "
            "carries it — plans naming the site silently never fire",
            "remove the declaration or restore the inject() call"))
    return findings


@rule("counter-not-precreated",
      "contract-required counter with no literal .counter() creation")
def counter_not_precreated(project):
    """The PR-7 SERVE_FEATURE_COUNTERS lesson: a counter the contract
    requires (telemetry/contract.py) only appears in documents if the
    code CREATES it — at setup, so a zero value still lands. This
    pass proves every required name has a literal `.counter("name")`
    call (directly, or through a module-level NAME = "literal"
    constant) somewhere in quorum_tpu/."""
    from ..telemetry.contract import precreated_counter_names
    created: set[str] = set()
    for src in project.package_files():
        if src.tree is None:
            continue
        # module-level string constants, for the
        # COUNTER_X = "name"; reg.counter(COUNTER_X) indirection
        consts: dict[str, str] = {}
        for node in src.tree.body:
            if (isinstance(node, ast.Assign)
                    and len(node.targets) == 1
                    and isinstance(node.targets[0], ast.Name)):
                val = const_str(node.value)
                if val is not None:
                    consts[node.targets[0].id] = val
        for node in ast.walk(src.tree):
            if not isinstance(node, ast.Call) or not node.args:
                continue
            fn = call_name(node)
            if not fn.endswith(".counter") and fn != "counter":
                continue
            arg = node.args[0]
            name = const_str(arg)
            if name is None and isinstance(arg, ast.Name):
                name = consts.get(arg.id)
            if name:
                created.add(name)
    findings = []
    contract_rel = "quorum_tpu/telemetry/contract.py"
    contract_src = project.get(contract_rel)
    for name in precreated_counter_names():
        if name in created:
            continue
        line = 1
        if contract_src is not None:
            for i, text in enumerate(contract_src.lines, 1):
                if f'"{name}"' in text:
                    line = i
                    break
        findings.append(Finding(
            "counter-not-precreated", contract_rel, line,
            f"contract requires counter {name!r} but no "
            '.counter("...") literal in quorum_tpu/ creates it — '
            "metrics_check would fail every document that declares "
            "the feature",
            "create the counter at feature setup (value 0 counts) "
            "with the literal name, or drop it from the contract"))
    return findings
