"""Runtime compile sentinel: the dynamic half of the trace-contract
tier (ISSUE 15), opt-in via ``QUORUM_COMPILE_SENTINEL=1`` — the
compile-count twin of the ``QUORUM_TSAN`` lock sanitizer.

The static rules (rules_compile.py) prove every jit site is declared
in the COMPILE_BUDGET catalog; this module proves the declared
executable counts HOLD while code actually runs. :func:`install`
replaces ``jax.jit`` with a recording factory: every jitted function
whose target (or creation site) lives in ``quorum_tpu/`` is wrapped
so a jit-cache miss — detected as growth of the function's own
dispatch cache (``_cache_size``), which jax guarantees grows exactly
once per distinct abstract signature — lands in a ledger with the
site key, the abstract shapes, and the acquisition stack. Cache HITS
cost one C++ attribute call; functions defined outside the package
(tests, jax internals) are returned unwrapped, zero overhead.

Each recorded compile is checked against the catalog:

* an **unbudgeted** site compiling (a jit added without a catalog
  entry — belt to the lint's suspenders, for jits constructed via
  paths the AST can't see) is a violation;
* a site exceeding its ``allow`` of distinct signatures within one
  cache epoch (``jax.clear_caches`` starts a new epoch) is a
  **budget overrun** — the "engine compiles once per length bucket"
  class of regression;
* the same ``(site, signature)`` compiling twice in one epoch is a
  **duplicate compile** — the re-jit-per-call / blown-cache class —
  unless the site is declared ``recreated`` (mesh closures that are
  legitimately re-jitted per build).

The conftest autouse gate (tests/conftest.py) fails the test during
which a violation was first observed, stacks attached — which makes
"a warm serve answers a second request with zero compiles" and "a
resumed run re-pays exactly the compiles of its torn partitions"
enforced invariants rather than docstring comments. Ledger totals
export into every final metrics document (``compile_events`` counter,
per-site ``compiles{site=...}`` counters, ``meta.compile_sites``) so
``tools/perf_diff.py`` gates compile-count regressions against
``PERF_BASELINE.json`` the same way it gates wall clock.

Like the tsan twin: modules that bound the real ``jax.jit`` before
:func:`install` keep it (partial coverage is the documented cost of a
pure-Python sentinel), which is why ``quorum_tpu/__init__`` installs
at package import when the lever is set — before any jit-bearing
submodule is imported.
"""

from __future__ import annotations

import os
import threading
import traceback
import weakref

_BOOK = threading.Lock()          # guards the ledger and epoch state
_EVENTS: list[dict] = []          # every recorded compile, in order
_VIOLATIONS: list[dict] = []
_EPOCH = 0                        # budget epoch: _SITE_SIGS lifetime
_CACHE_GEN = 0                    # bumped ONLY on a real cache clear
_SITE_SIGS: dict[str, set] = {}   # per-epoch distinct signatures
_SITE_TOTALS: dict[str, int] = {}  # process-lifetime compile counts
_INSTANCES: weakref.WeakSet = weakref.WeakSet()  # live wrappers
_INSTALLED = False
_REAL_JIT = None
_REAL_CLEAR = None

_PKG_DIR = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
_THIS_FILE = os.path.abspath(__file__)


def _budget():
    from .compile_budget import COMPILE_BUDGET
    return COMPILE_BUDGET


def _rel(path: str) -> str:
    return "quorum_tpu/" + os.path.relpath(
        path, _PKG_DIR).replace(os.sep, "/")


def _site_for(fun, creation_stack) -> str | None:
    """The ledger key for a jitted callable: ``<relpath>:<qualname>``
    when the function's code lives in the package, else the first
    package frame of the creation stack as ``<relpath>:<fn>.<jit>``
    (shard_map products carry jax-internal code objects), else None —
    an external jit the sentinel leaves untouched."""
    code = getattr(fun, "__code__", None)
    path = getattr(code, "co_filename", "")
    if path.startswith(_PKG_DIR + os.sep):
        return f"{_rel(path)}:{fun.__qualname__}"
    for frame in creation_stack:
        if frame.filename == _THIS_FILE:
            continue
        if frame.filename.startswith(_PKG_DIR + os.sep):
            return f"{_rel(frame.filename)}:{frame.name}.<jit>"
    return None


def _describe_leaf(leaf) -> str:
    shape = getattr(leaf, "shape", None)
    dtype = getattr(leaf, "dtype", None)
    if shape is not None and dtype is not None:
        desc = f"{dtype}[{','.join(str(d) for d in shape)}]"
        # the jit cache keys on more than (dtype, shape): a weakly
        # typed scalar and a committed sharding each compile their
        # own executable, so the ledger signature must carry them or
        # legitimate recompiles read as duplicates
        if getattr(leaf, "weak_type", False):
            desc += "~"
        sharding = getattr(leaf, "sharding", None)
        if sharding is not None:
            desc += f"@{sharding}"
            # an explicitly placed (committed) array and an
            # uncommitted one with the same sharding are distinct
            # cache entries — observed on the --devices N gather
            # path, where the sharded build's device_put state
            # re-pays the export executable
            if getattr(leaf, "_committed", None) is False:
                desc += "?"
        return desc
    if isinstance(leaf, (bool, int, float, str, bytes)) or leaf is None:
        return f"{type(leaf).__name__}:{leaf!r}"[:48]
    # a non-array leaf is a static argument (a frozen geometry/config
    # dataclass): the jit cache keys on its VALUE (hash/eq), so the
    # ledger signature must too — the repr carries the fields; long
    # ones compress to a digest so distinct configs never collide on
    # a truncation boundary
    r = repr(leaf)
    if len(r) > 120:
        import hashlib
        r = r[:80] + "#" + hashlib.sha1(r.encode()).hexdigest()[:12]
    return f"{type(leaf).__name__}:{r}"


def _signature(args, kwargs) -> tuple:
    """Abstract shapes of one call: array leaves by (dtype, shape),
    everything else (static args, config NamedTuples) by value repr —
    the same facets the jit cache keys on, flattened."""
    import jax
    try:
        leaves = jax.tree_util.tree_leaves((args, kwargs))
    except Exception:  # noqa: BLE001 - exotic pytrees stay opaque
        return ("<unflattenable>",)
    return tuple(_describe_leaf(v) for v in leaves)


class _SentinelJit:
    """Transparent wrapper around one jitted function: delegates the
    call, then compares the pjit dispatch-cache size against the last
    observed value — growth is exactly the set of fresh executables
    this call compiled."""

    __slots__ = ("_inner", "_site", "_gen", "_size", "_lock",
                 "__weakref__")

    def __init__(self, inner, site: str):
        self._inner = inner
        self._site = site
        self._gen = _CACHE_GEN
        self._size = 0
        # per-instance floor updates are a read-modify-write;
        # concurrent dispatches through ONE wrapper (serve handler vs
        # watchdog warmup share the module-level jits) must not
        # double-record a compile or misattribute one signature's
        # compile to another's call
        self._lock = threading.Lock()
        _INSTANCES.add(self)

    def __call__(self, *args, **kwargs):
        try:
            return self._inner(*args, **kwargs)
        finally:
            self._observe(args, kwargs)

    def __getattr__(self, name):
        return getattr(self._inner, name)

    def _observe(self, args, kwargs) -> None:
        try:
            n = self._inner._cache_size()
        except Exception:  # noqa: BLE001 - private API drift: degrade
            return
        with self._lock:
            if self._gen != _CACHE_GEN:
                # the real jit caches were cleared since our last
                # look: restart the floor so post-clear compiles
                # count fresh (a ledger reset() does NOT zero the
                # floor — the warm cache is still warm, and a hit
                # must not replay the prior cache size as phantom
                # compiles)
                self._gen = _CACHE_GEN
                self._size = 0
            if n <= self._size:
                self._size = n  # hit (or concurrent clear): no event
                return
            count = n - self._size
            self._size = n
        _record(self._site, _signature(args, kwargs), count)

    def _resync(self) -> None:
        """Align the floor with the live cache (ledger reset): past
        compiles are forgotten, not re-reported."""
        try:
            n = self._inner._cache_size()
        except Exception:  # noqa: BLE001 - private API drift
            return
        with self._lock:
            self._gen = _CACHE_GEN
            self._size = n


def _record(site: str, sig: tuple, count: int) -> None:
    stack = "".join(traceback.format_stack(limit=14)[:-2])
    budget = _budget().get(site)
    with _BOOK:
        _EVENTS.append({"site": site, "signature": sig,
                        "count": count, "epoch": _EPOCH})
        _SITE_TOTALS[site] = _SITE_TOTALS.get(site, 0) + count
        if budget is None:
            _VIOLATIONS.append({
                "kind": "unbudgeted", "site": site, "signature": sig,
                "stack": stack,
                "detail": "site has no COMPILE_BUDGET entry"})
            return
        sigs = _SITE_SIGS.setdefault(site, set())
        if sig in sigs:
            if not budget.recreated:
                _VIOLATIONS.append({
                    "kind": "duplicate", "site": site,
                    "signature": sig, "stack": stack,
                    "detail": "identical abstract signature compiled "
                              "twice in one cache epoch — the jit "
                              "cache was bypassed or the function is "
                              "re-jitted per call"})
            return
        sigs.add(sig)
        if len(sigs) > budget.allow:
            _VIOLATIONS.append({
                "kind": "overrun", "site": site, "signature": sig,
                "stack": stack,
                "detail": f"{len(sigs)} distinct executables this "
                          f"epoch exceeds the declared allowance of "
                          f"{budget.allow} (one per {budget.per})"})


def _sentinel_jit(fun=None, **kwargs):
    """The replacement ``jax.jit``: wraps jits the PACKAGE creates
    with a recording shim, hands everything else straight back. The
    budget is about the package's own jit sites — a test ad-hoc
    jitting a package helper is not a contract violation, so
    attribution keys on who CALLED jax.jit (the creation frame), not
    on where the function's code lives."""
    if fun is None:
        return lambda f: _sentinel_jit(f, **kwargs)
    jitted = _REAL_JIT(fun, **kwargs)
    stack = [f for f in reversed(traceback.extract_stack(limit=12))
             if f.filename != _THIS_FILE]
    if not (stack and stack[0].filename.startswith(
            _PKG_DIR + os.sep)):
        return jitted  # created outside quorum_tpu/: external
    site = _site_for(fun, stack)
    if site is None:
        return jitted
    return _SentinelJit(jitted, site)


def _sentinel_clear_caches(*args, **kwargs):
    global _CACHE_GEN
    out = _REAL_CLEAR(*args, **kwargs)
    with _BOOK:
        _CACHE_GEN += 1  # instances re-floor at 0: caches ARE empty
    new_epoch()
    return out


# -- public surface -------------------------------------------------------

def install() -> None:
    """Patch ``jax.jit`` (and ``jax.clear_caches``, which starts a
    new budget epoch) with the recording factory. Must run before the
    jit-bearing modules are imported — their module-level
    ``functools.partial(jax.jit, ...)`` decorators bind whatever
    ``jax.jit`` is at import time (quorum_tpu/__init__ does this when
    the lever is set)."""
    global _INSTALLED, _REAL_JIT, _REAL_CLEAR
    if _INSTALLED:
        return
    import jax
    _REAL_JIT = jax.jit
    _REAL_CLEAR = jax.clear_caches
    jax.jit = _sentinel_jit
    jax.clear_caches = _sentinel_clear_caches
    _INSTALLED = True


def uninstall() -> None:
    global _INSTALLED
    if not _INSTALLED:
        return
    import jax
    jax.jit = _REAL_JIT
    jax.clear_caches = _REAL_CLEAR
    _INSTALLED = False


def installed() -> bool:
    return _INSTALLED


def enabled_by_env() -> bool:
    from ..utils import levers
    return levers.get_bool("QUORUM_COMPILE_SENTINEL")


def new_epoch() -> None:
    """Start a fresh budget epoch (the wrapped ``jax.clear_caches``
    calls this): per-epoch signature sets reset, lifetime totals and
    the ledger survive."""
    global _EPOCH
    with _BOOK:
        _EPOCH += 1
        _SITE_SIGS.clear()


def events() -> list[dict]:
    with _BOOK:
        return list(_EVENTS)


def violations() -> list[dict]:
    with _BOOK:
        return list(_VIOLATIONS)


def site_totals() -> dict[str, int]:
    """Process-lifetime compile count per site (ledger export)."""
    with _BOOK:
        return dict(_SITE_TOTALS)


def reset() -> None:
    """Forget everything (test isolation): ledger, violations,
    totals, and the per-epoch sets. Live wrappers re-anchor their
    floors to the CURRENT cache size — the jit caches are still
    warm, so a post-reset cache hit must record nothing (a zeroed
    floor would replay the whole prior cache as phantom events)."""
    global _EPOCH
    with _BOOK:
        _EPOCH += 1
        _SITE_SIGS.clear()
        _EVENTS.clear()
        _VIOLATIONS.clear()
        _SITE_TOTALS.clear()
    for inst in list(_INSTANCES):
        inst._resync()


def format_violation(v: dict) -> str:
    sig = ", ".join(v["signature"][:8])
    if len(v["signature"]) > 8:
        sig += ", ..."
    return (f"compile-budget violation [{v['kind']}] at {v['site']}: "
            f"{v['detail']}\n    signature: ({sig})\n"
            f"-- compiling call --\n{v['stack']}")


def export(reg) -> None:
    """Stamp the ledger into a metrics registry before its final
    write: the ``compile_events`` total, one ``compiles{site=...}``
    counter per site, and ``meta.compile_sites`` — the surface
    ``tools/perf_diff.py`` gates against PERF_BASELINE.json. Counters
    are set by delta so a second final write stays idempotent."""
    if not getattr(reg, "enabled", False):
        return
    from ..telemetry.registry import labeled
    totals = site_totals()
    total = sum(totals.values())
    c = reg.counter("compile_events")
    if total > c.value:
        c.inc(total - c.value)
    for site, n in sorted(totals.items()):
        sc = reg.counter(labeled("compiles", site=site))
        if n > sc.value:
            sc.inc(n - sc.value)
    reg.set_meta(compile_sentinel=1, compile_sites=totals)
