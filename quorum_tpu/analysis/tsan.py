"""Runtime lock-order sanitizer: the dynamic half of the concurrency
sanitizer (ISSUE 12), opt-in via ``QUORUM_TSAN=1``.

The static lockset pass (rules_locks.py) sees the acquisitions the
AST names; this sees the ones that actually HAPPEN — watchdog
rebuilds racing /reload, exporters called from handler threads,
whatever shape tomorrow's streaming-ingest tier (ROADMAP item 4)
takes. :func:`install` replaces ``threading.Lock``/``RLock`` with
wrapping factories; every wrapper records, per thread, the stack of
wrapped locks currently held, keyed by the lock's CONSTRUCTION SITE
(file:line) so the thousands of per-metric Counter locks collapse to
one key. Acquiring B while holding A records the edge A->B; a later
acquisition of A while holding B is an **observed inversion** — two
threads interleaving those paths deadlock — and lands in
:func:`violations` with both stacks.

Design constraints, in order:

* **No false positives.** Same-site self-edges are ignored (many
  instances share a construction-site key; ordering among them is
  invisible at this granularity). Reentrant RLock re-acquisition is
  not an edge. An inversion is only reported for an exact reversed
  pair of construction-site keys.
* **Never deadlock the run.** The sanitizer's own bookkeeping lock is
  only ever taken with no wrapped lock's internal state touched
  under it; wrapped acquire/release happen OUTSIDE it.
* **Cheap.** Per acquire: one thread-local list append and one dict
  probe; the stack walk for diagnostics happens only when a NEW edge
  is first seen.

The conftest opt-in (``QUORUM_TSAN=1``, on in ci/tier1.sh) installs
this before tests import the serve/telemetry stack and FAILS the
test on any violation observed during it — the runtime analogue of a
lint finding. ``threading.Condition(lock)`` works unchanged: the
wrapper exposes only acquire/release/locked, so Condition uses its
portable fallback path through exactly those methods.
"""

from __future__ import annotations

import os
import threading
import traceback

_BOOK = threading.Lock()          # guards _EDGES/_VIOLATIONS only
_EDGES: dict = {}                 # (site_a, site_b) -> acquire stack
_VIOLATIONS: list[dict] = []
_VIOLATION_PAIRS: set = set()     # (a, b) already reported
_INSTALLED = False
_REAL_LOCK = threading.Lock
_REAL_RLOCK = threading.RLock
_TLS = threading.local()
# flight-recorder tap (ISSUE 16): when a FlightRecorder is installed
# under QUORUM_TSAN=1, every non-reentrant acquisition's construction
# site feeds its ring — the lock-acquisition timeline of a wedged run
# lands in the postmortem dump. One global read per acquire when off.
_FLIGHT_HOOK = None


def set_flight_hook(fn):
    """Install (or clear, fn=None) the per-acquisition flight tap.
    Returns the previous hook so nested observability sessions can
    restore it."""
    global _FLIGHT_HOOK
    prev = _FLIGHT_HOOK
    _FLIGHT_HOOK = fn
    return prev


def _held() -> list:
    stack = getattr(_TLS, "stack", None)
    if stack is None:
        stack = _TLS.stack = []
    return stack


def _site() -> str:
    """file:line of the wrapper's construction, excluding this module
    and the threading module — the allocation-site key."""
    for frame in reversed(traceback.extract_stack(limit=12)[:-2]):
        fn = frame.filename
        if fn.endswith(("analysis/tsan.py", "threading.py")):
            continue
        return f"{os.path.basename(fn)}:{frame.lineno}"
    return "<unknown>"


class _SanitizedLock:
    """A threading.Lock/RLock wrapper recording acquisition order.
    Reentrant re-acquisition (RLock) is tracked via the per-thread
    held stack — re-entries append a no-edge marker so the matching
    release pops cleanly."""

    __slots__ = ("_inner", "_sitekey")

    def __init__(self, inner, sitekey: str):
        self._inner = inner
        self._sitekey = sitekey

    def acquire(self, blocking=True, timeout=-1):
        got = self._inner.acquire(blocking, timeout)
        if got:
            self._record_acquire()
        return got

    def release(self):
        self._record_release()
        self._inner.release()

    def locked(self):
        return self._inner.locked()

    def _at_fork_reinit(self):
        # concurrent.futures registers this as an at-fork hook on the
        # module lock it creates at import; per-thread held stacks
        # are thread-local, so the child starts clean anyway
        self._inner._at_fork_reinit()

    __enter__ = acquire

    def __exit__(self, *exc):
        self.release()

    # -- Condition compatibility -------------------------------------
    # threading.Condition binds these if present; the RLock fast
    # paths delegate to the real lock (full release/restore across a
    # wait()) while keeping the held stack truthful. On a plain Lock
    # the inner has none of them, so fall back to acquire/release —
    # exactly Condition's own portable fallback.
    def _release_save(self):
        save = getattr(self._inner, "_release_save", None)
        if save is None:
            self.release()
            return None
        state = save()
        stack = _held()
        for i in range(len(stack) - 1, -1, -1):
            if stack[i][0] is self:
                del stack[i]
        return state

    def _acquire_restore(self, state):
        restore = getattr(self._inner, "_acquire_restore", None)
        if restore is None:
            self.acquire()
            return
        restore(state)
        self._record_acquire()

    def _is_owned(self):
        owned = getattr(self._inner, "_is_owned", None)
        if owned is not None:
            return owned()
        if self._inner.acquire(False):
            self._inner.release()
            return False
        return True

    def _record_acquire(self) -> None:
        stack = _held()
        if any(w is self for w, _ in stack):
            stack.append((self, None))  # reentrant: no edge
            return
        site = self._sitekey
        candidates = []
        with _BOOK:
            for _, held_site in stack:
                if held_site is None or held_site == site:
                    continue
                edge = (held_site, site)
                if edge not in _EDGES or (site, held_site) in _EDGES:
                    candidates.append(edge)
        if candidates:
            # the stack walk is the expensive part — do it unlocked,
            # then RE-CHECK for the reverse edge inside the same
            # critical section that publishes ours: two threads
            # racing the reversed acquisitions (the exact deadlock
            # interleaving) each see the other's edge from whichever
            # publish lands second
            here = "".join(traceback.format_stack(limit=8)[:-2])
            with _BOOK:
                for edge in candidates:
                    rev = (edge[1], edge[0])
                    if rev in _EDGES and edge not in _VIOLATION_PAIRS:
                        _VIOLATION_PAIRS.add(edge)
                        _VIOLATIONS.append({
                            "held": edge[0], "acquiring": edge[1],
                            "thread": threading.current_thread().name,
                            "stack": here,
                            "reverse_stack": _EDGES[rev],
                        })
                    _EDGES.setdefault(edge, here)
        stack.append((self, site))
        hook = _FLIGHT_HOOK
        if hook is not None:
            try:
                hook(site)
            except Exception:  # noqa: BLE001 - the tap never breaks locking
                pass

    def _record_release(self) -> None:
        stack = _held()
        for i in range(len(stack) - 1, -1, -1):
            if stack[i][0] is self:
                del stack[i]
                return


def _make_factory(real_ctor):
    def factory(*a, **kw):
        return _SanitizedLock(real_ctor(*a, **kw), _site())
    return factory


def install() -> None:
    """Patch threading.Lock/RLock with sanitizing factories. Modules
    that bound the real factory at import time keep it (partial
    coverage is the documented cost of a pure-Python sanitizer);
    everything constructed via `threading.Lock()` after this point is
    tracked."""
    global _INSTALLED
    if _INSTALLED:
        return
    threading.Lock = _make_factory(_REAL_LOCK)
    threading.RLock = _make_factory(_REAL_RLOCK)
    _INSTALLED = True


def uninstall() -> None:
    global _INSTALLED
    if not _INSTALLED:
        return
    threading.Lock = _REAL_LOCK
    threading.RLock = _REAL_RLOCK
    _INSTALLED = False


def installed() -> bool:
    return _INSTALLED


def violations() -> list[dict]:
    with _BOOK:
        return list(_VIOLATIONS)


def reset() -> None:
    """Forget observed edges and violations (test isolation)."""
    with _BOOK:
        _EDGES.clear()
        _VIOLATIONS.clear()
        _VIOLATION_PAIRS.clear()


def format_violation(v: dict) -> str:
    return (f"lock-order inversion: thread {v['thread']!r} acquired "
            f"{v['acquiring']} while holding {v['held']}, but the "
            f"reverse order was observed earlier.\n"
            f"-- this acquisition --\n{v['stack']}"
            f"-- earlier reverse acquisition --\n{v['reverse_stack']}")


def enabled_by_env() -> bool:
    from ..utils import levers
    return levers.get_bool("QUORUM_TSAN")
