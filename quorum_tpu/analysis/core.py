"""quorum-lint core: project loading, findings, suppressions,
baseline (ISSUE 12).

The suite is AST-based and repo-aware: every rule encodes a bug class
a past hardening PR actually fixed by hand (the `"wb"` re-open that
truncated the event JSONL, the copied non-atomic tmp+rename writes,
the swallowed HTTPException that silently killed the push daemon, the
lock-free-snapshot races in serve), so the next instance fails CI
instead of waiting for the next hand audit. Rules register with
:func:`rule`; the CLI (analysis/cli.py) loads the whole repo once
into a :class:`Project` and hands it to each rule.

Suppression and exception handling:

* ``# qlint: disable=RULE[,RULE...]`` on the finding's line (or on
  the opening line of its statement) suppresses it — used for the
  genuinely-intended cases (streaming outputs that cannot be atomic,
  a lock-free snapshot that is the documented design);
* a committed ``qlint_baseline.json`` grandfathers known findings
  (kept EMPTY on main — the fix sweep is part of the deal; the
  baseline exists so a red lint can land in an emergency without
  deleting the gate);
* ``--strict`` (what ci/tier1.sh runs) additionally fails when the
  baseline is non-empty or the generated docs drifted.
"""

from __future__ import annotations

import ast
import json
import os
import re


# -- findings -------------------------------------------------------------

SEV_ERROR = "error"
SEV_INFO = "info"


class Finding:
    """One lint result: where, which rule, what, and how to fix it."""

    __slots__ = ("rule", "path", "line", "message", "hint", "severity")

    def __init__(self, rule: str, path: str, line: int, message: str,
                 hint: str = "", severity: str = SEV_ERROR):
        self.rule = rule
        self.path = path
        self.line = int(line)
        self.message = message
        self.hint = hint
        self.severity = severity

    def render(self) -> str:
        out = f"{self.path}:{self.line}: [{self.rule}] {self.message}"
        if self.hint:
            out += f"\n    fix: {self.hint}"
        return out

    def as_dict(self) -> dict:
        return {"rule": self.rule, "file": self.path, "line": self.line,
                "message": self.message, "severity": self.severity}


# -- rule registry --------------------------------------------------------

RULES: dict[str, "Rule"] = {}


class Rule:
    __slots__ = ("id", "doc", "fn")

    def __init__(self, id_: str, doc: str, fn):
        self.id = id_
        self.doc = doc
        self.fn = fn


def rule(id_: str, doc: str):
    """Register a rule: `fn(project) -> list[Finding]`."""
    def deco(fn):
        RULES[id_] = Rule(id_, doc, fn)
        return fn
    return deco


# -- source files ---------------------------------------------------------

_SUPPRESS_RE = re.compile(r"#\s*qlint:\s*disable=([\w,-]+)")


class SourceFile:
    """One parsed file: text, AST, per-line suppressions."""

    def __init__(self, rel: str, text: str):
        self.rel = rel
        self.text = text
        self.lines = text.splitlines()
        self.tree: ast.Module | None = None
        self.parse_error: str | None = None
        try:
            self.tree = ast.parse(text)
        except SyntaxError as e:  # pragma: no cover - repo parses
            self.parse_error = str(e)
        # line -> set of rule ids disabled on that line
        self.suppressed: dict[int, set[str]] = {}
        for i, line in enumerate(self.lines, 1):
            m = _SUPPRESS_RE.search(line)
            if m:
                self.suppressed[i] = {
                    r.strip() for r in m.group(1).split(",") if r.strip()}

    def is_suppressed(self, rule_id: str, line: int) -> bool:
        return rule_id in self.suppressed.get(line, ())

    @property
    def in_package(self) -> bool:
        return self.rel.startswith("quorum_tpu/")

    @property
    def in_tools(self) -> bool:
        return self.rel.startswith("tools/")

    @property
    def in_tests(self) -> bool:
        return self.rel.startswith("tests/")


# -- the project ----------------------------------------------------------

# what a default lint walks: the package, the tools shims, the bench
# harness, and the tests (tests are scanned for *references* — usage
# of a lever or a helper from a test keeps it alive — but rules that
# report findings restrict themselves to package/tools scopes).
DEFAULT_ROOTS = ("quorum_tpu", "tools", "tests", "bench.py")

_SKIP_DIRS = {"__pycache__", ".git", "golden", ".claude"}


class Project:
    """The loaded repo: every scanned file, parsed once, plus the
    helpers rules share (identifier usage index, function walker)."""

    def __init__(self, root: str, roots=DEFAULT_ROOTS):
        self.root = os.path.abspath(root)
        self.files: dict[str, SourceFile] = {}
        self._word_cache: dict[str, set[str]] = {}
        for entry in roots:
            full = os.path.join(self.root, entry)
            if os.path.isfile(full):
                self._load(entry)
            elif os.path.isdir(full):
                for dirpath, dirnames, filenames in os.walk(full):
                    dirnames[:] = [d for d in dirnames
                                   if d not in _SKIP_DIRS]
                    for fn in sorted(filenames):
                        if fn.endswith(".py"):
                            rel = os.path.relpath(
                                os.path.join(dirpath, fn), self.root)
                            self._load(rel.replace(os.sep, "/"))

    def _load(self, rel: str) -> None:
        try:
            with open(os.path.join(self.root, rel),
                      encoding="utf-8") as f:
                self.files[rel] = SourceFile(rel, f.read())
        except OSError:  # pragma: no cover - racing deletes
            pass

    def package_files(self):
        return [f for f in self.files.values() if f.in_package]

    def get(self, rel: str) -> SourceFile | None:
        return self.files.get(rel)

    # -- cross-file identifier usage (deadcode, lever-unused) ------------
    _WORD_RE = re.compile(r"[A-Za-z_][A-Za-z0-9_]*")

    def words_in(self, rel: str) -> set[str]:
        """All identifier-shaped tokens in one file (string literals
        and comments included — a name mentioned in a docstring table
        or built via getattr stays 'used'; this rule errs alive)."""
        cached = self._word_cache.get(rel)
        if cached is None:
            cached = set(self._WORD_RE.findall(self.files[rel].text))
            self._word_cache[rel] = cached
        return cached

    def usage_count(self, name: str, exclude_rel: str | None = None
                    ) -> int:
        """How many files mention `name` (identifier-boundary match),
        optionally excluding one file (the definition's own)."""
        n = 0
        for rel in self.files:
            if rel == exclude_rel:
                continue
            if name in self.words_in(rel):
                n += 1
        return n


# -- AST helpers shared by the rules --------------------------------------

def walk_functions(tree: ast.AST):
    """Yield every FunctionDef/AsyncFunctionDef with its qualname
    ("Class.method" / "outer.<locals>.inner")."""
    def visit(node, prefix):
        for child in ast.iter_child_nodes(node):
            if isinstance(child, (ast.FunctionDef,
                                  ast.AsyncFunctionDef)):
                qual = f"{prefix}{child.name}"
                yield child, qual
                yield from visit(child, f"{qual}.<locals>.")
            elif isinstance(child, ast.ClassDef):
                yield from visit(child, f"{prefix}{child.name}.")
            else:
                yield from visit(child, prefix)
    yield from visit(tree, "")


def call_name(call: ast.Call) -> str:
    """Dotted best-effort name of a call target: "os.replace",
    "self._work.notify", "open"."""
    return dotted(call.func)


def dotted(node: ast.AST) -> str:
    parts = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
    elif isinstance(node, ast.Call):
        parts.append(dotted(node.func) + "()")
    else:
        parts.append("?")
    return ".".join(reversed(parts))


def const_str(node) -> str | None:
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return node.value
    return None


# -- baseline -------------------------------------------------------------

BASELINE_NAME = "qlint_baseline.json"


def load_baseline(path: str) -> list[dict]:
    """The committed exception list: [{"rule", "file", "line"?}, ...].
    A missing file is an empty baseline; a malformed one is a loud
    error (a silently ignored baseline would un-gate CI)."""
    if not os.path.exists(path):
        return []
    with open(path) as f:
        doc = json.load(f)
    entries = doc.get("findings", doc) if isinstance(doc, dict) else doc
    if not isinstance(entries, list) or not all(
            isinstance(e, dict) and "rule" in e and "file" in e
            for e in entries):
        raise ValueError(
            f"{path}: baseline must be a list of "
            "{{rule, file[, line]}} objects")
    return entries


def baseline_matches(entry: dict, finding: Finding) -> bool:
    if entry["rule"] != finding.rule or entry["file"] != finding.path:
        return False
    return "line" not in entry or int(entry["line"]) == finding.line


def apply_baseline(findings: list[Finding], entries: list[dict]
                   ) -> tuple[list[Finding], list[dict]]:
    """Split findings into (surviving, matched-entry list). An entry
    can absorb any number of findings (file-wide when no line)."""
    used: list[dict] = []
    live: list[Finding] = []
    for f in findings:
        hit = next((e for e in entries if baseline_matches(e, f)), None)
        if hit is None:
            live.append(f)
        elif hit not in used:
            used.append(hit)
    return live, used


# -- driver ---------------------------------------------------------------

def run_rules(project: Project, rule_ids=None) -> list[Finding]:
    """Run the selected rules (default: all), drop suppressed
    findings, return the rest sorted by location."""
    ids = sorted(RULES) if rule_ids is None else list(rule_ids)
    findings: list[Finding] = []
    for rid in ids:
        r = RULES.get(rid)
        if r is None:
            raise KeyError(f"unknown rule {rid!r} "
                           f"(known: {', '.join(sorted(RULES))})")
        for f in r.fn(project):
            src = project.get(f.path)
            if src is not None and src.is_suppressed(f.rule, f.line):
                continue
            findings.append(f)
    findings.sort(key=lambda f: (f.path, f.line, f.rule))
    return findings
