"""Hot-path hygiene rule (ISSUE 12 rule 3).

The per-batch dispatch loops are the performance contract of this
repo: stage 1/stage 2 throughput comes from keeping the device fed,
and every host sync the loop takes OUTSIDE the measured dispatch/wait
window is invisible stall time — it neither shows up in the
`*_dispatch_us`/`*_wait_us` attribution (PR 2) nor in the devtrace
idle split (PR 10), it just makes the run slower and the telemetry
wrong. PERF_NOTES round 6 measured exactly this shape binding
multi-device scaling before the host pipeline was sharded.

``hot-path-sync`` scans every package module for dispatch regions —
the scope is DERIVED, not declared: a *dispatch region* is the body
of any function that calls ``observe_dispatch_wait`` or dispatches
under ``tracer.step(...)``, wherever it lives. (The rule used to
scan a hardcoded 4-tuple of modules, which is how the PR-13
``ops/sketch.py`` sketch loop and the ``parallel/tile_sharded.py``
shard-step loop went unscanned until ISSUE 15: a new dispatch loop
joined the perf contract without joining the lint's scope.) Inside a
region, these force or risk a host sync:

* ``jax.block_until_ready`` / ``jax.device_get`` / ``.item()``
* ``np.asarray(x)`` and ``bool/int/float(x)`` where ``x`` is a name
  produced by the traced device step

and each is a finding unless it sits in a **recognized timer
section**:

* between ``time.perf_counter()`` stamps that feed
  ``observe_dispatch_wait`` (the measured window — where the ONE
  deliberate sync point belongs), or
* inside a ``with timer.stage(...)`` block (grow/checkpoint/seal
  phases measure their own sync), or
* a ready-data copy: the argument names a traced step output and an
  earlier, timed sync in the same function already awaited that step
  (pulling an already-materialized flag D2H is a copy, not a stall).
"""

from __future__ import annotations

import ast

from .core import Finding, call_name, rule, walk_functions


def scope(project) -> list[str]:
    """The modules the rule scans: every package file whose AST
    carries a dispatch-region signal (an ``observe_dispatch_wait``
    call or a ``with tracer.step(...)`` block). Derived per run so a
    new dispatch loop is in scope the commit it appears."""
    rels = []
    for src in project.package_files():
        if src.tree is None:
            continue
        has_signal = any(
            (isinstance(n, ast.Call)
             and call_name(n).endswith("observe_dispatch_wait"))
            for n in ast.walk(src.tree)) or any(
            isinstance(n, ast.With) and any(
                isinstance(item.context_expr, ast.Call)
                and call_name(item.context_expr).endswith(
                    "tracer.step")
                for item in n.items)
            for n in ast.walk(src.tree))
        if has_signal:
            rels.append(src.rel)
    return sorted(rels)


_ALWAYS_SYNC = ("jax.block_until_ready", "block_until_ready",
                "jax.device_get", "device_get")
_CAST_FUNCS = ("bool", "int", "float", "np.asarray", "numpy.asarray")


def _walk_no_defs(fn: ast.AST):
    """Walk a function body without descending into nested function/
    class definitions (their statements execute elsewhere)."""
    stack = list(ast.iter_child_nodes(fn))
    while stack:
        node = stack.pop()
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.ClassDef, ast.Lambda)):
            continue
        yield node
        stack.extend(ast.iter_child_nodes(node))


def _is_perf_counter_assign(node: ast.AST) -> bool:
    return (isinstance(node, ast.Assign)
            and isinstance(node.value, ast.Call)
            and call_name(node.value) in ("time.perf_counter",
                                          "perf_counter"))


def _tracer_step_withs(fn: ast.AST):
    """`with tracer.step(...)` / `with self.tracer.step(...)` blocks
    directly in this function (not nested defs)."""
    for node in _walk_no_defs(fn):
        if isinstance(node, ast.With):
            for item in node.items:
                if isinstance(item.context_expr, ast.Call) and \
                        call_name(item.context_expr).endswith(
                            "tracer.step"):
                    yield node


def _step_result_names(fn: ast.AST) -> set[str]:
    """Names assigned inside `with tracer.step(...)` blocks — the
    device step's outputs (tuple targets included)."""
    names: set[str] = set()
    for w in _tracer_step_withs(fn):
        for node in ast.walk(w):
            if isinstance(node, ast.Assign):
                for tgt in node.targets:
                    for leaf in ast.walk(tgt):
                        if isinstance(leaf, ast.Name):
                            names.add(leaf.id)
    return names


def _timer_stage_spans(fn: ast.AST) -> list[tuple[int, int]]:
    spans = []
    for node in _walk_no_defs(fn):
        if not isinstance(node, ast.With):
            continue
        for item in node.items:
            ce = item.context_expr
            if isinstance(ce, ast.Call) and (
                    call_name(ce).endswith("timer.stage")
                    or call_name(ce) == "timer"):
                spans.append((node.lineno, node.end_lineno or
                              node.lineno))
    return spans


def _sync_calls(fn: ast.AST, step_names: set[str]):
    """(node, why) for every potential host sync in this function
    (not descending into nested defs)."""
    for node in _walk_no_defs(fn):
        if not isinstance(node, ast.Call):
            continue
        fname = call_name(node)
        if fname in _ALWAYS_SYNC:
            yield node, f"{fname}() blocks on the device"
            continue
        if fname.endswith(".item") and not node.args:
            yield node, ".item() forces a D2H sync"
            continue
        if fname in _CAST_FUNCS and node.args:
            arg = node.args[0]
            if isinstance(arg, ast.Name) and arg.id in step_names:
                yield node, (f"{fname}({arg.id}) syncs on a device-"
                             "step output")


def _find_regions(tree: ast.Module):
    """Functions whose body is a dispatch region."""
    for fn, qual in walk_functions(tree):
        has_observe = any(
            isinstance(n, ast.Call)
            and call_name(n).endswith("observe_dispatch_wait")
            for n in _walk_no_defs(fn))
        has_step = any(True for _ in _tracer_step_withs(fn))
        if has_observe or has_step:
            yield fn, qual


@rule("hot-path-sync",
      "host sync in a per-batch dispatch loop outside a timer section")
def hot_path_sync(project):
    findings = []
    for rel in scope(project):
        src = project.get(rel)
        if src is None or src.tree is None:
            continue
        for fn, qual in _find_regions(src.tree):
            perf_lines = sorted(
                n.lineno for n in _walk_no_defs(fn)
                if _is_perf_counter_assign(n))
            observe_lines = sorted(
                n.lineno for n in _walk_no_defs(fn)
                if isinstance(n, ast.Call)
                and call_name(n).endswith("observe_dispatch_wait"))
            timer_spans = _timer_stage_spans(fn)
            step_names = _step_result_names(fn)

            def timed(line: int) -> bool:
                # the measured window: a perf_counter stamp before
                # AND a later stamp or the observe call after — the
                # sync is exactly what the wait histogram measures
                before = any(p < line for p in perf_lines)
                after = any(p > line for p in perf_lines) or any(
                    o >= line for o in observe_lines)
                return before and after

            def in_timer_stage(line: int) -> bool:
                return any(lo <= line <= hi for lo, hi in timer_spans)

            exempt_lines: list[int] = []
            for node, why in sorted(
                    _sync_calls(fn, step_names),
                    key=lambda p: p[0].lineno):
                line = node.lineno
                if timed(line) or in_timer_stage(line):
                    exempt_lines.append(line)
                    continue
                # ready-data copy: this step's outputs were already
                # awaited by an earlier, timed sync
                arg = node.args[0] if node.args else None
                if (isinstance(arg, ast.Name)
                        and arg.id in step_names
                        and any(e < line for e in exempt_lines)):
                    exempt_lines.append(line)
                    continue
                findings.append(Finding(
                    "hot-path-sync", rel, line,
                    f"{why} inside dispatch region {qual} but "
                    "outside any recognized timer section — stall "
                    "time invisible to the dispatch/wait attribution",
                    "move it inside the perf_counter window feeding "
                    "observe_dispatch_wait (or a timer.stage block), "
                    "or defer the host read out of the loop"))
    return findings
