"""COMPILE_BUDGET: the declared executable-count catalog — every
``jax.jit`` site in ``quorum_tpu/``, its entry point, and how many
distinct executables it is allowed to compile (ISSUE 15).

The compilation contracts used to live in docstrings: the serve
engine promises at most one executable per distinct length bucket
(serve/engine.py, "Compilation discipline"), the stage-2 extension
loop one per lane-drain level (models/corrector.py), stage-1 insert
one per (geometry, wire shape). This catalog is the machine-checked
form, enforced in both directions like the lever catalog:

* ``quorum-lint``'s ``jit-unbudgeted`` rule fails CI on any jit site
  missing here, and on any entry whose site is gone;
* the runtime compile sentinel (``QUORUM_COMPILE_SENTINEL=1``,
  analysis/compile_sentinel.py) records every jit-cache miss against
  these keys and fails the observing test when a site exceeds its
  ``allow`` or compiles the same abstract signature twice without a
  cache clear (``recreated`` sites — closures re-jitted per
  build/mesh — are exempt from the duplicate check only);
* ``quorum-lint --emit-docs`` renders :func:`render_docs` into the
  README between the ``qlint:budget`` markers.

Keys are ``<relpath>:<qualname>`` of the jitted function — stable
across line churn. An opaque jit argument (a ``shard_map`` product)
keys as ``<relpath>:<creating-fn>.<jit>``.

``allow`` bounds DISTINCT abstract signatures per cache epoch (a
``jax.clear_caches()`` starts a new epoch). The numbers were measured
over the full tier-1 suite — the worst legitimate test-module epoch —
then given ~2x headroom; production epochs (one process, one
geometry) sit far below them. They are regression tripwires, not
targets.
"""

from __future__ import annotations


class Budget:
    """One declared jit site: the catalog row."""

    __slots__ = ("site", "entry", "per", "allow", "recreated")

    def __init__(self, site: str, entry: str, per: str, allow: int,
                 recreated: bool = False):
        self.site = site
        self.entry = entry
        self.per = per
        self.allow = int(allow)
        self.recreated = recreated


COMPILE_BUDGET: dict[str, Budget] = {}


def _declare(site: str, entry: str, per: str, allow: int,
             recreated: bool = False) -> None:
    COMPILE_BUDGET[site] = Budget(site, entry, per, allow, recreated)


# -- the catalog ----------------------------------------------------------
# Grouped by module; keep each group alphabetical by qualname.

# ops/ctable.py — flat-table (stage-1 v0) kernels
_declare(
    "quorum_tpu/ops/ctable.py:_bucket_rem_jit",
    "ctable.bucket_rem", "geometry x key-batch shape", 48)
_declare(
    "quorum_tpu/ops/ctable.py:_build_round",
    "ctable.insert_observations claim rounds",
    "geometry x observation-batch shape", 64)
_declare(
    "quorum_tpu/ops/ctable.py:_finish_obs",
    "ctable.insert_observations epilogue", "observation-batch shape",
    24)
_declare(
    "quorum_tpu/ops/ctable.py:_grow_prep",
    "ctable.grow re-insert walk", "geometry x chunk length", 24)
_declare(
    "quorum_tpu/ops/ctable.py:_prep_obs",
    "ctable.insert_observations prologue", "observation-batch shape",
    16)
_declare(
    "quorum_tpu/ops/ctable.py:extract_observations_impl",
    "models/create_database.extract_observations (module-level jit "
    "of the ctable kernel)", "k x read-batch shape", 8)
_declare(
    "quorum_tpu/ops/ctable.py:finalize_build",
    "ctable.finalize_build", "geometry", 16)
_declare(
    "quorum_tpu/ops/ctable.py:lookup",
    "ctable.lookup", "geometry x key-batch shape", 24)
_declare(
    "quorum_tpu/ops/ctable.py:table_stats",
    "ctable.table_stats", "geometry", 8)

# ops/ctable.py — tile-table (stage-1/2 production) kernels
_declare(
    "quorum_tpu/ops/ctable.py:_tile_compact_rounds",
    "ctable.tile_insert retry path",
    "geometry x batch shape x (rounds, cap)", 16)
_declare(
    "quorum_tpu/ops/ctable.py:_tile_floor_jit",
    "ctable.tile_floor (presence floor, ISSUE 14)",
    "geometry x floor value", 8)
_declare(
    "quorum_tpu/ops/ctable.py:_tile_grow_prep",
    "ctable.tile_grow re-insert walk", "geometry x chunk length", 8)
_declare(
    "quorum_tpu/ops/ctable.py:_tile_insert_fused",
    "ctable.tile_insert (pre-extracted observations)",
    "geometry x batch shape x (rounds, cap, agg_cap)", 24)
_declare(
    "quorum_tpu/ops/ctable.py:_tile_insert_reads_fused",
    "ctable.tile_insert_reads (unpacked read batch)",
    "geometry x read-batch shape x lever caps", 8)
_declare(
    "quorum_tpu/ops/ctable.py:_tile_insert_reads_fused_packed",
    "ctable.tile_insert_reads (packed wire, the hot stage-1 step)",
    "geometry x wire shape x lever caps — the ONE per-batch stage-1 "
    "executable", 24)
_declare(
    "quorum_tpu/ops/ctable.py:_tile_parts_jit",
    "ctable.tile_lookup_prepared / sketch gating / engine warmup",
    "geometry x key-batch shape", 16)
_declare(
    "quorum_tpu/ops/ctable.py:_tile_round1",
    "ctable.tile_insert first claim round",
    "geometry x batch shape", 8)
_declare(
    "quorum_tpu/ops/ctable.py:tile_compact_device",
    "ctable.tile_compact_device (sharded export)",
    "geometry x cap", 8)
_declare(
    "quorum_tpu/ops/ctable.py:tile_departition_rows",
    "ctable.tile_departition_rows (--partitions reassembly)",
    "local geometry x (g, part)", 24)
_declare(
    "quorum_tpu/ops/ctable.py:tile_export_v4",
    "io/db_format v4 export", "geometry x cap", 12)
_declare(
    "quorum_tpu/ops/ctable.py:tile_finalize",
    "ctable.tile_finalize", "geometry", 12)
_declare(
    "quorum_tpu/ops/ctable.py:tile_lookup",
    "ctable.tile_lookup (stage-2 count fetch)",
    "geometry x key-batch shape", 32)
_declare(
    "quorum_tpu/ops/ctable.py:tile_rows_device_from_compact",
    "ctable.tile_rows_device_from_compact (sharded import)",
    "geometry x compact shape", 16)
_declare(
    "quorum_tpu/ops/ctable.py:tile_seal",
    "ctable.tile_seal (build -> query handoff)", "geometry", 8)
_declare(
    "quorum_tpu/ops/ctable.py:tile_stats",
    "ctable.tile_stats", "geometry", 8)

# ops/sketch.py — count-min prefilter kernels (ISSUE 14)
_declare(
    "quorum_tpu/ops/sketch.py:_gated_insert_wire",
    "sketch.gated_insert_wire (stage-2 of the two-pass prefilter / "
    "khmer-style inline)", "sketch+table geometry x wire shape x "
    "mode", 12)
_declare(
    "quorum_tpu/ops/sketch.py:_sketch_pass_wire",
    "sketch.sketch_pass_wire (pass-1 count-min update)",
    "sketch geometry x wire shape", 8)
_declare(
    "quorum_tpu/ops/sketch.py:singleton_entries",
    "sketch.singleton_entries (prefilter audit)", "table geometry",
    4)

# models/corrector.py — the stage-2 device program
_declare(
    "quorum_tpu/models/corrector.py:_bwd_epilogue",
    "corrector.correct_batch backward-pass merge",
    "batch shape x uniform flag", 8)
_declare(
    "quorum_tpu/models/corrector.py:_correct_device",
    "corrector.correct_batch (unpacked) — compiles one executable "
    "per (geometry, batch shape, drain levels); the extension "
    "loop's lane-drain levels are static by design",
    "geometry x batch shape x static lever tuple", 32)
_declare(
    "quorum_tpu/models/corrector.py:_correct_device_packed",
    "corrector.correct_batch_packed (the hot serve/offline step; "
    "serve/engine.py promises at most ONE of these per length "
    "bucket)", "geometry x wire shape x static lever tuple", 16)
_declare(
    "quorum_tpu/models/corrector.py:_pack_finish",
    "corrector.fetch_finish (full-width result pack)",
    "batch shape x width", 32)
_declare(
    "quorum_tpu/models/corrector.py:_pack_finish_lean",
    "corrector.fetch_finish (event-driven lean pack)",
    "batch shape x event cap", 8)
_declare(
    "quorum_tpu/models/corrector.py:_rc_prologue",
    "corrector.correct_batch reverse-complement prologue",
    "batch shape x uniform flag", 8)

# parallel/tile_sharded.py — mesh closures, re-jitted per build/mesh
_declare(
    "quorum_tpu/parallel/tile_sharded.py:_try_place_all.<jit>",
    "tile_sharded grow re-route placement", "mesh x overflow shape",
    8, recreated=True)
_declare(
    "quorum_tpu/parallel/tile_sharded.py:build_step.<locals>.step",
    "tile_sharded.build_step (unpacked sharded insert)",
    "mesh x geometry x batch shape", 24, recreated=True)
_declare(
    "quorum_tpu/parallel/tile_sharded.py:"
    "build_step_wire.<locals>.step",
    "tile_sharded.build_step_wire (packed sharded insert — the hot "
    "--devices N stage-1 step)", "mesh x geometry x wire shape", 8,
    recreated=True)
_declare(
    "quorum_tpu/parallel/tile_sharded.py:"
    "correct_step.<locals>.step",
    "tile_sharded.correct_step (replicated-table stage 2)",
    "mesh x geometry x batch shape", 8, recreated=True)
_declare(
    "quorum_tpu/parallel/tile_sharded.py:"
    "correct_step_routed.<locals>.step",
    "tile_sharded.correct_step_routed (row-sharded stage 2)",
    "mesh x geometry x batch shape", 8, recreated=True)
_declare(
    "quorum_tpu/parallel/tile_sharded.py:"
    "correct_step_wire.<locals>.step",
    "tile_sharded.correct_step_wire (packed sharded stage 2)",
    "mesh x geometry x wire shape", 8, recreated=True)
_declare(
    "quorum_tpu/parallel/tile_sharded.py:finalize.<jit>",
    "tile_sharded.finalize (per-shard counter fold)",
    "mesh x geometry", 16, recreated=True)
_declare(
    "quorum_tpu/parallel/tile_sharded.py:query_step.<locals>.step",
    "tile_sharded.query_step (sharded lookup)",
    "mesh x geometry x key-batch shape", 8, recreated=True)
_declare(
    "quorum_tpu/parallel/tile_sharded.py:"
    "shard_occupancy.<locals>.occ",
    "tile_sharded.shard_occupancy (load-balance telemetry)",
    "mesh x geometry", 4, recreated=True)



def names() -> list[str]:
    return sorted(COMPILE_BUDGET)


def render_docs() -> str:
    """The README compile-budget table, generated from the catalog
    (the `quorum-lint --emit-docs` payload)."""
    lines = [
        "| Site | Entry point | One executable per | Allowance |",
        "|---|---|---|---|",
    ]
    for key in names():
        b = COMPILE_BUDGET[key]
        site = b.site.replace("quorum_tpu/", "")
        allow = str(b.allow) + (" (re-jitted)" if b.recreated else "")
        lines.append(f"| `{site}` | {b.entry} | {b.per} | {allow} |")
    return "\n".join(lines) + "\n"
