"""Trace-contract rules: enforce the JAX compilation boundary
statically (ISSUE 15).

The repo's throughput story rests on a compilation contract that was,
until this tier, stated only in docstrings: the serve engine promises
"at most one executable per distinct length bucket"
(serve/engine.py, "Compilation discipline"), the extension loop
compiles one executable per drain level (models/corrector.py), and
stage-1 insert one per (geometry, shape). Nothing caught a recompile
regression except latency on hardware CI doesn't have, and nothing
caught trace-time hazards until they silently doubled compile counts.
These rules make the contract lexical; the runtime twin
(analysis/compile_sentinel.py) makes it observable.

Four rules over every ``jax.jit`` site in ``quorum_tpu/``:

* ``trace-lever-read`` — a ``levers.raw``/``levers.get_bool``, env
  read, or ``global`` statement inside a jitted body runs at TRACE
  time: the value is baked into the executable, so flipping the lever
  later silently steers nothing (and un-keyed trace state is how
  compile counts double). Resolution belongs in the host wrapper,
  passed in as a static argument.
* ``trace-python-branch`` — an ``if``/``while`` (or ternary) on a
  traced-array-derived name inside a jitted body: either a
  ``TracerBoolConversionError`` at first trace or, via
  ``static_argnums`` promotion, a fresh executable per distinct
  value. Structural tests (``is None``, ``isinstance``, ``.shape``/
  ``.ndim``/``len()``) are static and exempt.
* ``jit-unbudgeted`` — every jit site must be declared in the
  ``COMPILE_BUDGET`` catalog (analysis/compile_budget.py) with its
  entry point and allowed executable count, checked in BOTH
  directions like the lever catalog: an undeclared site bypasses the
  budget, a stale declaration means the table lies.
* ``static-argnum-hazard`` — a static argument that is a ``float``
  (cache fragments on bit-identical noise: 0.1 vs 0.1000001 is two
  executables) or unhashable (``TypeError`` at call time), or a
  ``static_argnums`` index out of range.

Site keys are ``<relpath>:<qualname>`` — stable across line-number
churn. A ``jax.jit(expr)`` whose argument is not a local function or
lambda (e.g. a ``shard_map`` product) keys as
``<relpath>:<enclosing-fn>.<jit>``, matching what the runtime
sentinel derives from the creation stack.
"""

from __future__ import annotations

import ast

from .core import Finding, call_name, dotted, rule, walk_functions

_JIT_NAMES = ("jax.jit", "jit")
_PARTIAL_NAMES = ("functools.partial", "partial")

# attribute reads on a traced value that are static at trace time
_STATIC_ATTRS = ("shape", "ndim", "dtype", "size")
# calls whose result over a traced value is a static python value
_STATIC_CALLS = ("len", "isinstance", "type", "id")

_UNHASHABLE_ANNOS = ("list", "dict", "set", "bytearray",
                     "np.ndarray", "numpy.ndarray", "jnp.ndarray",
                     "jax.Array")


def _parse_static(kw_nodes) -> tuple[list[int], list[str]]:
    """(static_argnums, static_argnames) literals from jit keywords;
    non-literal specs come back empty (nothing to check)."""
    nums: list[int] = []
    names: list[str] = []
    for k in kw_nodes:
        if k.arg == "static_argnums":
            for n in ast.walk(k.value):
                if isinstance(n, ast.Constant) and isinstance(
                        n.value, int):
                    nums.append(n.value)
        elif k.arg == "static_argnames":
            for n in ast.walk(k.value):
                if isinstance(n, ast.Constant) and isinstance(
                        n.value, str):
                    names.append(n.value)
    return nums, names


def _jit_decorator(dec) -> tuple[bool, list, int]:
    """(is_jit, keyword_nodes, lineno) for one decorator node —
    handles ``@jax.jit``, ``@jax.jit(...)`` and
    ``@functools.partial(jax.jit, ...)``."""
    if dotted(dec) in _JIT_NAMES:
        return True, [], dec.lineno
    if isinstance(dec, ast.Call):
        f = dotted(dec.func)
        if f in _JIT_NAMES:
            return True, dec.keywords, dec.lineno
        if f in _PARTIAL_NAMES and dec.args and \
                dotted(dec.args[0]) in _JIT_NAMES:
            return True, dec.keywords, dec.lineno
    return False, [], 0


class JitSite:
    """One discovered jit site: where, what function body it traces
    (None when the argument is an opaque expression), and which
    parameter names are static."""

    __slots__ = ("rel", "line", "key", "qual", "fn", "static_nums",
                 "static_names")

    def __init__(self, rel, line, key, qual, fn, static_nums,
                 static_names):
        self.rel = rel
        self.line = line
        self.key = key
        self.qual = qual
        self.fn = fn
        self.static_nums = static_nums
        self.static_names = static_names

    def params(self) -> list[str]:
        if self.fn is None:
            return []
        return [a.arg for a in self.fn.args.args]

    def traced_params(self) -> set[str]:
        """Parameter names whose values are tracers inside the body
        (everything not promoted static)."""
        ps = self.params()
        static = {ps[i] for i in self.static_nums if 0 <= i < len(ps)}
        static.update(self.static_names)
        return {p for p in ps if p not in static}


def _enclosing_map(tree):
    """node-id -> (qualname, bare function name) of the innermost
    enclosing function, for attributing call-form jit sites."""
    encl: dict[int, tuple[str, str]] = {}

    def visit(node, qual, name):
        for child in ast.iter_child_nodes(node):
            if isinstance(child, (ast.FunctionDef,
                                  ast.AsyncFunctionDef)):
                cq = (qual + child.name) if qual else child.name
                for n in ast.walk(child):
                    encl.setdefault(id(n), (cq, child.name))
                visit(child, cq + ".<locals>.", child.name)
            elif isinstance(child, ast.ClassDef):
                visit(child, (qual or "") + child.name + ".", name)
            else:
                visit(child, qual, name)

    visit(tree, "", "<module>")
    return encl


def jit_sites(src, global_defs: dict | None = None) -> list[JitSite]:
    """Every jit site in one SourceFile, keyed the way the runtime
    sentinel keys its ledger. `global_defs` (name -> (rel, qual,
    fn-node)) resolves re-exported callables jitted away from their
    defining module — the sentinel keys those on the def's file, so
    the static key must too."""
    if src.tree is None:
        return []
    sites: list[JitSite] = []
    defs = list(walk_functions(src.tree))
    decorated_ids = set()

    for fn, qual in defs:
        for dec in fn.decorator_list:
            is_jit, kws, line = _jit_decorator(dec)
            if not is_jit:
                continue
            nums, names = _parse_static(kws)
            sites.append(JitSite(src.rel, fn.lineno,
                                 f"{src.rel}:{qual}", qual, fn,
                                 nums, names))
            decorated_ids.add(id(fn))

    encl = _enclosing_map(src.tree)
    by_name: dict[str, list[tuple[ast.AST, str]]] = {}
    for fn, qual in defs:
        by_name.setdefault(fn.name, []).append((fn, qual))

    for node in ast.walk(src.tree):
        if not isinstance(node, ast.Call) or \
                dotted(node.func) not in _JIT_NAMES:
            continue
        nums, names = _parse_static(node.keywords)
        encl_qual, encl_name = encl.get(id(node), ("", "<module>"))
        arg = node.args[0] if node.args else None
        if isinstance(arg, ast.Lambda):
            prefix = f"{encl_qual}.<locals>." if encl_qual else ""
            qual = f"{prefix}<lambda>"
            sites.append(JitSite(src.rel, node.lineno,
                                 f"{src.rel}:{qual}", qual, arg,
                                 nums, names))
            continue
        if isinstance(arg, ast.Name):
            cands = by_name.get(arg.id, [])
            # nearest scope first: a def local to the enclosing
            # function, else a module-level def of that name
            local = [(f, q) for f, q in cands
                     if encl_qual and q.startswith(
                         encl_qual + ".<locals>.")]
            pick = local or [(f, q) for f, q in cands
                             if "." not in q]
            if pick:
                fn, qual = pick[0]
                if id(fn) in decorated_ids:
                    continue  # jit-of-already-jitted: one site
                sites.append(JitSite(src.rel, node.lineno,
                                     f"{src.rel}:{qual}", qual, fn,
                                     nums, names))
                continue
            hit = (global_defs or {}).get(arg.id)
            if hit is not None:
                def_rel, def_qual, def_fn = hit
                sites.append(JitSite(src.rel, node.lineno,
                                     f"{def_rel}:{def_qual}",
                                     def_qual, def_fn, nums, names))
                continue
        # opaque argument (shard_map product, imported callable):
        # key on the creating function, like the runtime sentinel
        qual = f"{encl_name}.<jit>"
        sites.append(JitSite(src.rel, node.lineno,
                             f"{src.rel}:{qual}", qual, None,
                             nums, names))
    return sites


def project_jit_sites(project) -> list[JitSite]:
    # pure function of the loaded sources, asked for by all four
    # rules — computed once per Project
    cached = getattr(project, "_jit_sites_cache", None)
    if cached is not None:
        return cached
    # module-level defs across the package, for re-exported callables
    # jitted away from home; ambiguous names stay unresolved (the
    # opaque fallback keys on the creating function instead)
    global_defs: dict[str, tuple | None] = {}
    for src in project.package_files():
        if src.tree is None:
            continue
        for fn, qual in walk_functions(src.tree):
            if "." in qual:
                continue
            if fn.name in global_defs:
                global_defs[fn.name] = None  # ambiguous
            else:
                global_defs[fn.name] = (src.rel, qual, fn)
    global_defs = {k: v for k, v in global_defs.items()
                   if v is not None}
    sites = []
    for src in project.package_files():
        sites.extend(jit_sites(src, global_defs))
    project._jit_sites_cache = sites
    return sites


def _budget_catalog() -> dict:
    from .compile_budget import COMPILE_BUDGET
    return COMPILE_BUDGET


# -- trace-lever-read ------------------------------------------------------

_LEVER_CALLS = ("levers.raw", "levers.get_bool")
_ENV_CALLS = ("os.environ.get", "os.getenv", "environ.get",
              "getenv")


@rule("trace-lever-read",
      "lever/env read or `global` inside a jitted body (trace-time "
      "state baked into the executable)")
def trace_lever_read(project):
    findings = []
    for site in project_jit_sites(project):
        if site.fn is None:
            continue
        for node in ast.walk(site.fn):
            if isinstance(node, ast.Call):
                fname = call_name(node)
                if fname in _LEVER_CALLS or fname in _ENV_CALLS:
                    findings.append(Finding(
                        "trace-lever-read", site.rel, node.lineno,
                        f"{fname}(...) inside jitted {site.qual} runs "
                        "at TRACE time — the value is baked into the "
                        "executable and later env changes silently "
                        "steer nothing",
                        "resolve the lever in the host wrapper and "
                        "pass the value in as a static argument"))
            elif isinstance(node, ast.Subscript) and isinstance(
                    node.ctx, ast.Load):
                if dotted(node.value) in ("os.environ", "environ"):
                    findings.append(Finding(
                        "trace-lever-read", site.rel, node.lineno,
                        f"os.environ[...] inside jitted {site.qual} "
                        "is a trace-time read baked into the "
                        "executable",
                        "resolve at wrapper level, pass as a static "
                        "argument"))
            elif isinstance(node, ast.Global):
                findings.append(Finding(
                    "trace-lever-read", site.rel, node.lineno,
                    f"`global {', '.join(node.names)}` inside jitted "
                    f"{site.qual}: mutable-global state read at trace "
                    "time is invisible to the jit cache key",
                    "thread the value through the call signature "
                    "(static if it selects code paths)"))
    return findings


# -- trace-python-branch ---------------------------------------------------

def _tainted_name(expr: ast.AST, traced: set[str]) -> str | None:
    """The first traced Name referenced in a tracer-value-bearing
    position inside `expr`, or None. Static projections are exempt:
    `.shape`/`.ndim`/`.dtype`/`.size`, `len()`/`isinstance()`, and
    `is`/`is not` comparisons (all resolve to python values at trace
    time)."""
    parent: dict[int, ast.AST] = {}
    for node in ast.walk(expr):
        for child in ast.iter_child_nodes(node):
            parent.setdefault(id(child), node)
    for node in ast.walk(expr):
        if not (isinstance(node, ast.Name)
                and isinstance(node.ctx, ast.Load)
                and node.id in traced):
            continue
        cur, exempt = node, False
        while True:
            p = parent.get(id(cur))
            if p is None:
                break
            if isinstance(p, ast.Attribute) and \
                    p.attr in _STATIC_ATTRS:
                exempt = True
                break
            if isinstance(p, ast.Call) and cur is not p.func and \
                    call_name(p) in _STATIC_CALLS:
                exempt = True
                break
            if isinstance(p, ast.Compare) and all(
                    isinstance(op, (ast.Is, ast.IsNot))
                    for op in p.ops):
                exempt = True
                break
            cur = p
        if not exempt:
            return node.id
    return None


def _assign_targets(node) -> list[str]:
    names = []
    targets = []
    if isinstance(node, ast.Assign):
        targets = node.targets
    elif isinstance(node, (ast.AugAssign, ast.AnnAssign)):
        targets = [node.target]
    for tgt in targets:
        for leaf in ast.walk(tgt):
            if isinstance(leaf, ast.Name):
                names.append(leaf.id)
    return names


def _scan_branches(fn, traced: set[str], rel: str, qual: str,
                   findings: list) -> None:
    """Taint-propagate assignments then flag if/while/ternary tests
    on traced names, recursing into nested defs with their parameters
    shadowed out."""
    traced = set(traced)
    own: list[ast.stmt] = []
    nested: list[ast.AST] = []
    stack = list(ast.iter_child_nodes(fn)) if not isinstance(
        fn, ast.Lambda) else [fn.body]
    while stack:
        node = stack.pop()
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.Lambda)):
            nested.append(node)
            continue
        own.append(node)
        stack.extend(ast.iter_child_nodes(node))

    assigns = [n for n in own
               if isinstance(n, (ast.Assign, ast.AugAssign,
                                 ast.AnnAssign))]
    for _ in range(len(assigns) + 1):
        grew = False
        for a in assigns:
            if a.value is None:
                continue
            if _tainted_name(a.value, traced):
                for t in _assign_targets(a):
                    if t not in traced:
                        traced.add(t)
                        grew = True
        if not grew:
            break

    for node in own:
        test = None
        if isinstance(node, (ast.If, ast.While, ast.IfExp)):
            test = node.test
        if test is None:
            continue
        name = _tainted_name(test, traced)
        if name is not None:
            kind = ("while" if isinstance(node, ast.While) else "if")
            findings.append(Finding(
                "trace-python-branch", rel, node.lineno,
                f"python `{kind}` on traced value {name!r} inside "
                f"jitted {qual}: TracerBoolConversionError at trace "
                "time, or one fresh executable per distinct value if "
                "promoted static",
                "use lax.cond/jnp.where for data-dependent control "
                "flow, or hoist the decision to the host wrapper as "
                "a static argument"))

    for sub in nested:
        params = {a.arg for a in sub.args.args}
        params.update(a.arg for a in sub.args.kwonlyargs)
        sub_qual = qual + ".<locals>." + getattr(sub, "name",
                                                 "<lambda>")
        _scan_branches(sub, traced - params, rel, sub_qual, findings)


@rule("trace-python-branch",
      "python if/while on a traced-array-derived name inside a "
      "jitted body")
def trace_python_branch(project):
    findings: list[Finding] = []
    for site in project_jit_sites(project):
        if site.fn is None:
            continue
        _scan_branches(site.fn, site.traced_params(), site.rel,
                       site.qual, findings)
    return findings


# -- jit-unbudgeted --------------------------------------------------------

_BUDGET_MODULE = "quorum_tpu/analysis/compile_budget.py"


@rule("jit-unbudgeted",
      "jax.jit site missing from COMPILE_BUDGET (or a stale budget "
      "entry with no live site)")
def jit_unbudgeted(project):
    budget = _budget_catalog()
    findings = []
    live_keys: set[str] = set()
    for site in project_jit_sites(project):
        live_keys.add(site.key)
        if site.key in budget:
            continue
        findings.append(Finding(
            "jit-unbudgeted", site.rel, site.line,
            f"jit site {site.key!r} is not declared in the "
            "COMPILE_BUDGET catalog — its executable count is "
            "invisible to the compile sentinel and the README table",
            "declare it (entry point, compile unit, allowed "
            "executables) in quorum_tpu/analysis/compile_budget.py"))
    budget_src = project.get(_BUDGET_MODULE)
    for key in sorted(budget):
        if key in live_keys:
            continue
        line = 1
        if budget_src is not None:
            # the key renders as "<file>.py:<qual>" — find the qual
            # fragment (declarations split the string across lines)
            frag = key.rsplit(":", 1)[1]
            for i, text in enumerate(budget_src.lines, 1):
                if f'"{frag}"' in text or f"{frag}\"" in text:
                    line = i
                    break
        findings.append(Finding(
            "jit-unbudgeted", _BUDGET_MODULE, line,
            f"COMPILE_BUDGET declares {key!r} but no live jax.jit "
            "site matches — the published budget table lies",
            "remove the stale entry or restore the jit site"))
    return findings


# -- static-argnum-hazard --------------------------------------------------

def _anno_name(node) -> str:
    if node is None:
        return ""
    return dotted(node) if isinstance(
        node, (ast.Name, ast.Attribute)) else ""


@rule("static-argnum-hazard",
      "float or unhashable static jit argument (cache fragmentation "
      "/ TypeError)")
def static_argnum_hazard(project):
    findings = []
    for site in project_jit_sites(project):
        if site.fn is None or isinstance(site.fn, ast.Lambda):
            if site.static_nums or site.static_names:
                # nothing to inspect: statics on an opaque callable
                # can't be validated — that itself is the hazard
                if site.fn is None:
                    findings.append(Finding(
                        "static-argnum-hazard", site.rel, site.line,
                        f"static arguments on opaque jit site "
                        f"{site.key!r} cannot be checked against a "
                        "signature",
                        "jit a named local function instead"))
            continue
        args = site.fn.args
        params = args.args
        defaults = list(args.defaults)
        # right-align defaults onto the positional params
        dmap: dict[str, ast.AST] = {}
        for p, d in zip(params[len(params) - len(defaults):],
                        defaults):
            dmap[p.arg] = d
        for i in site.static_nums:
            if i >= len(params) and not args.vararg:
                findings.append(Finding(
                    "static-argnum-hazard", site.rel, site.line,
                    f"static_argnums index {i} is out of range for "
                    f"jitted {site.qual} ({len(params)} positional "
                    "parameter(s))",
                    "fix the index list — a misaligned static "
                    "promotes the wrong argument"))
        static_params = [params[i] for i in site.static_nums
                         if 0 <= i < len(params)]
        static_params += [p for p in params
                          if p.arg in site.static_names]
        for p in static_params:
            anno = _anno_name(p.annotation)
            default = dmap.get(p.arg)
            if anno == "float" or (isinstance(default, ast.Constant)
                                   and isinstance(default.value,
                                                  float)):
                findings.append(Finding(
                    "static-argnum-hazard", site.rel, p.lineno,
                    f"float static argument {p.arg!r} on jitted "
                    f"{site.qual}: the jit cache keys on exact bits, "
                    "so near-equal floats compile fresh executables",
                    "quantize to an int/bool at the wrapper, or make "
                    "the value traced"))
            elif anno in _UNHASHABLE_ANNOS or isinstance(
                    default, (ast.List, ast.Dict, ast.Set)):
                findings.append(Finding(
                    "static-argnum-hazard", site.rel, p.lineno,
                    f"unhashable static argument {p.arg!r} "
                    f"({anno or 'mutable default'}) on jitted "
                    f"{site.qual}: TypeError at the first call",
                    "pass a hashable (tuple/NamedTuple) or make the "
                    "argument traced"))
    return findings
