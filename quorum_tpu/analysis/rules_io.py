"""Durable-write discipline rules (ISSUE 12 rule 1).

PR 2 found FOUR hand-copied non-atomic tmp+rename writes and folded
them into `telemetry.registry.atomic_write`; PR 8 added fsync-the-
directory durability to that one place; PR 11's hardening pass found
the events JSONL being lazily re-opened `"wb"` — a truncation of the
stream it meant to append to. Both classes are mechanical, so both
are rules now:

* ``raw-artifact-write`` — an ``open(path, "w"/"wb"/"a"/...)``
  landing a run artifact must either be part of the atomic idiom
  (the enclosing function also calls ``os.replace`` — which is what
  ``atomic_write``, ``_atomic_db_write`` and the checkpoint writers
  look like) or be a recognized stream (``.partial`` outputs, the
  quarantine FASTQ — paths whose expression says so), or carry an
  explicit ``# qlint: disable=raw-artifact-write`` with its
  justification. Anything else is a torn-file-on-crash waiting for a
  reader.
* ``append-truncation`` — the PR-11 class: a truncating re-open of an
  instance-held path (``self.<attr>``) from more than one call site
  in a module. The second open destroys what the first wrote; streams
  must open once (guarded) and append thereafter.
"""

from __future__ import annotations

import ast

from .core import (Finding, call_name, const_str, dotted, rule,
                   walk_functions)

_WRITE_MODES = ("w", "a", "x")

# substrings in the PATH EXPRESSION that mark a genuine stream (the
# allowlist the issue calls for): .partial outputs are journaled and
# committed by rename at finalize, quarantine files are append-streams
# of rejected raw records. Deliberately NOT "tmp": a .tmp write is
# only fine when the enclosing function also os.replace()s it (the
# separate atomic-idiom check) — exempting the substring would waive
# exactly the write-the-tmp-but-forget-the-replace case.
_STREAM_MARKERS = ("partial", "quarantine")


def _open_mode(call: ast.Call) -> str | None:
    """The constant mode string of a builtin open() call, or None
    when it isn't a literal-mode builtin open."""
    if call_name(call) != "open":
        return None
    mode = None
    if len(call.args) >= 2:
        mode = const_str(call.args[1])
    for kw in call.keywords:
        if kw.arg == "mode":
            mode = const_str(kw.value)
    return mode


def _is_write_mode(mode: str) -> bool:
    # "r+b" (in-place patching, the corrupt fault action) is not a
    # create/truncate/append — only w/a/x modes land new artifacts
    return any(m in mode for m in _WRITE_MODES)


def _path_expr(call: ast.Call) -> str:
    if call.args:
        return ast.unparse(call.args[0])
    for kw in call.keywords:
        if kw.arg == "file":
            return ast.unparse(kw.value)
    return ""


@rule("raw-artifact-write",
      "open() with a write mode outside the atomic-replace idiom")
def raw_artifact_write(project):
    findings = []
    for src in project.package_files():
        if src.tree is None:
            continue
        # map every call to its innermost enclosing function (outer
        # functions yield before nested ones, so the last write wins)
        # — module-level calls fall back to the module region
        owner: dict[int, tuple[ast.AST, str]] = {}
        for node, qual in walk_functions(src.tree):
            for call in ast.walk(node):
                if isinstance(call, ast.Call):
                    owner[id(call)] = (node, qual)
        for call in ast.walk(src.tree):
            if not isinstance(call, ast.Call):
                continue
            mode = _open_mode(call)
            if mode is None or not _is_write_mode(mode):
                continue
            region, qual = owner.get(id(call), (src.tree, "<module>"))
            # the atomic idiom: the same function later os.replace()s
            # the tmp file into place (atomic_write, _atomic_db_write,
            # and the checkpoint writers all look like this)
            replaces = any(
                call_name(c) in ("os.replace", "os.rename")
                for c in ast.walk(region) if isinstance(c, ast.Call))
            if replaces:
                continue
            path_src = _path_expr(call)
            if any(m in path_src.lower() for m in _STREAM_MARKERS):
                continue
            findings.append(Finding(
                "raw-artifact-write", src.rel, call.lineno,
                f"open({path_src!r}, {mode!r}) in {qual} lands an "
                "artifact without the atomic-replace idiom (crash = "
                "torn file for every later reader)",
                "use telemetry.registry.atomic_write / "
                "io.db_format._atomic_db_write, or write a sibling "
                ".tmp and os.replace it; a genuine stream takes "
                "# qlint: disable=raw-artifact-write with its reason"))
    return findings


@rule("append-truncation",
      "truncating re-open of an instance-held path (PR-11 JSONL class)")
def append_truncation(project):
    findings = []
    for src in project.package_files():
        if src.tree is None:
            continue
        sites: dict[str, list[ast.Call]] = {}
        for call in ast.walk(src.tree):
            if not isinstance(call, ast.Call):
                continue
            mode = _open_mode(call)
            if mode is None or "w" not in mode:
                continue
            if not call.args:
                continue
            path = call.args[0]
            # only instance-held paths: the bug class is a long-lived
            # object lazily re-opening ITS OWN stream (locals named
            # `tmp` in two writer functions are unrelated files)
            if not (isinstance(path, ast.Attribute)
                    and isinstance(path.value, ast.Name)
                    and path.value.id == "self"):
                continue
            sites.setdefault(dotted(path), []).append(call)
        for path_src, calls in sorted(sites.items()):
            if len(calls) < 2:
                continue
            for call in calls:
                findings.append(Finding(
                    "append-truncation", src.rel, call.lineno,
                    f"{path_src} is opened with a truncating mode at "
                    f"{len(calls)} call sites in this module — a "
                    "re-open destroys the stream the first open was "
                    "building (the PR-11 events-JSONL truncation)",
                    "open the stream once behind a guard (if self._f "
                    "is None) and seal it on close; a second writer "
                    "must append or go through the guard"))
    return findings
