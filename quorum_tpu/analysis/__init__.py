"""quorum_tpu.analysis: the repo-aware static-analysis suite and
concurrency sanitizer behind `quorum-lint` (ISSUE 12).

Each rule encodes a bug class a past hardening PR fixed by hand, so
the next instance fails CI instead of waiting for a reviewer:

=========================  ============================================
rule                       bug class (origin)
=========================  ============================================
raw-artifact-write         non-atomic tmp+rename copies (PR 2/8)
append-truncation          "wb" re-open truncating a stream (PR 11)
lever-raw-env-read         env reads bypassing the catalog
lever-undeclared /         QUORUM_* surface drifting from docs
lever-unused
fault-site-undeclared /    fault plans naming dead sites (PR 4)
fault-site-unused
counter-not-precreated     SERVE_FEATURE_COUNTERS lesson (PR 7)
hot-path-sync              untimed host syncs in dispatch loops (PR 6/9)
thread-swallowed-exception silent push-daemon death (PR 10)
lock-unguarded-write       serve snapshot races (PR 7)
lock-order-inversion       + runtime twin in analysis/tsan.py
unused-definition          refactor orphans
trace-lever-read           trace-time state baked into executables
trace-python-branch        TracerBoolConversion / silent recompiles
jit-unbudgeted             COMPILE_BUDGET drift, both directions
                           + runtime twin in compile_sentinel.py
static-argnum-hazard       float/unhashable static args
=========================  ============================================

Import surface: `run_lint` for tests/tools, `tsan` /
`compile_sentinel` for the runtime sanitizers, `cli.main` for the
entry point.
"""

from . import compile_sentinel, tsan  # noqa: F401
from .core import Finding, Project, run_rules  # noqa: F401


def run_lint(root: str, rule_ids=None):
    """Lint the repo at `root` with the full rule set (or a subset);
    returns the surviving findings. The programmatic twin of the CLI
    used by tests and tools."""
    from . import (rules_compile, rules_deadcode,  # noqa: F401
                   rules_hotpath, rules_io, rules_locks,
                   rules_registry, rules_threads)
    return run_rules(Project(root), rule_ids)
