"""Daemon-thread exception hygiene rule (ISSUE 12 rule 4).

PR 10's review found the metrics push daemon dying SILENTLY: a
``BadStatusLine`` from a non-HTTP peer raised
``http.client.HTTPException``, which the loop's ``except`` net did
not cover — the thread unwound, the run kept going, and pushes just
stopped, uncounted. The fix was one counter increment. The class is
mechanical: a background thread has no caller to propagate into, so
an ``except`` that neither re-raises nor counts is a failure mode
with NO observable signal — precisely what the telemetry tier exists
to prevent.

``thread-swallowed-exception`` finds every function used as a
``threading.Thread(target=...)`` in quorum_tpu/ (by name, resolved
against the defs in the same module — methods, module functions, and
closure ``def loop():`` targets alike), then requires every
``except`` handler in those functions (nested defs included: they run
on the same thread) to do at least one of:

* re-raise (any ``raise``),
* increment a counter (``....inc(...)``) — the push-daemon fix,
* hard-exit (``os._exit``) or call a ``fail``-named helper.

Anything else is a silent swallow. A deliberate best-effort pass
(teardown paths where even counting could throw) takes
``# qlint: disable=thread-swallowed-exception`` with its reason.
"""

from __future__ import annotations

import ast

from .core import Finding, call_name, rule, walk_functions


def _thread_target_names(tree: ast.Module) -> set[str]:
    """Bare function/method names passed as Thread(target=...)."""
    names: set[str] = set()
    for node in ast.walk(tree):
        if not isinstance(node, ast.Call):
            continue
        fn = call_name(node)
        if not fn.endswith("Thread"):
            continue
        for kw in node.keywords:
            if kw.arg != "target":
                continue
            v = kw.value
            if isinstance(v, ast.Name):
                names.add(v.id)
            elif isinstance(v, ast.Attribute):
                # self._loop / batcher._dispatch_loop: resolve by
                # method name; library targets (httpd.serve_forever)
                # simply won't match a local def
                names.add(v.attr)
    return names


_LOG_ONLY = ("vlog", "print", "warn", "warning", "debug", "info",
             "error", "exception", "log")


def _handler_is_loud(handler: ast.ExceptHandler) -> bool:
    """Does this handler produce a signal? Loud =
    * re-raise, hard-exit, or a fail-named helper;
    * a counter increment (`.inc(...)`) or tally (`x[0] += 1`);
    * relaying the bound exception through an error CHANNEL — stored
      (`box["err"] = e`, `self.err = e`) or passed to a non-logging
      call (`q.put(("__err__", e))`): the waiting side re-raises it.
    A handler that only logs (vlog/print) — or does nothing — is the
    silent-death class."""
    bound = handler.name  # `except X as e:` -> "e", else None
    for node in ast.walk(handler):
        if isinstance(node, ast.Raise):
            return True
        if isinstance(node, ast.AugAssign):
            return True  # errors[0] += 1: a tally is a counter
        if isinstance(node, ast.Assign) and bound and any(
                isinstance(n, ast.Name) and n.id == bound
                for n in ast.walk(node.value)):
            return True  # exception stored into a relay channel
        if isinstance(node, ast.Call):
            fn = call_name(node)
            if fn.endswith(".inc"):
                return True
            if fn in ("os._exit", "_exit"):
                return True
            last = fn.rsplit(".", 1)[-1]
            if "fail" in last:
                return True
            if bound and last not in _LOG_ONLY and any(
                    isinstance(n, ast.Name) and n.id == bound
                    for a in list(node.args)
                    + [kw.value for kw in node.keywords]
                    for n in ast.walk(a)):
                return True  # exception forwarded through a call
    return False


@rule("thread-swallowed-exception",
      "except in a thread-target function with no raise/counter")
def thread_swallowed_exception(project):
    findings = []
    for src in project.package_files():
        if src.tree is None:
            continue
        targets = _thread_target_names(src.tree)
        if not targets:
            continue
        for fn, qual in walk_functions(src.tree):
            if fn.name not in targets:
                continue
            # the whole subtree, nested defs included — everything
            # here executes on the daemon thread
            for node in ast.walk(fn):
                if not isinstance(node, ast.ExceptHandler):
                    continue
                if _handler_is_loud(node):
                    continue
                caught = (ast.unparse(node.type)
                          if node.type is not None else "BaseException")
                findings.append(Finding(
                    "thread-swallowed-exception", src.rel, node.lineno,
                    f"thread target {qual} swallows {caught} with "
                    "neither a re-raise nor a counter — the thread "
                    "(or its work item) degrades with zero signal, "
                    "the PR-10 silent-push-death class",
                    "count it (reg.counter(...).inc()) and/or "
                    "re-raise; a deliberate best-effort teardown "
                    "takes # qlint: disable=thread-swallowed-"
                    "exception with a reason"))
    return findings
