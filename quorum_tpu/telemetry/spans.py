"""Hierarchical span tracing: the live/deep half of the telemetry
subsystem (ISSUE 2 tentpole).

`with tracer.span("correct_batch", reads=n):` records start, duration,
parent (per-thread stack) and scalar attributes for one region of host
work. Each span is mirrored into `jax.profiler.TraceAnnotation` (and
`tracer.step(...)` into `StepTraceAnnotation`) so that under
`--profile` the host spans line up with the XLA device timeline in
TensorBoard/Perfetto — the host-side counterpart of the GPU-counter
per-phase breakdowns Gerbil reports (PAPERS.md, arxiv 1607.06618).

Two artifacts per run, from one `--trace-spans PATH` flag:

* `PATH` — span JSONL, one object per line, streamed as spans close
  (schema: `validate_span_line` in schema.py); survives crashes.
* chrome trace (`PATH` with `.jsonl` swapped for `.trace.json`) — the
  same spans in Chrome `trace_event` format (`{"traceEvents": [...]}`,
  "X" complete events, microsecond timestamps), written at `close()`;
  loads directly in Perfetto / `chrome://tracing`.

Zero-cost when disabled: `tracer_for(None)` returns the NULL singleton
whose `span`/`step` are re-entrant no-op context managers and whose
`enabled` flag lets hot paths skip attribute derivation.

Thread model: the parent stack is thread-local (the prefetch, render
and writer threads each get their own lineage); the JSONL sink and the
retained-span list share one lock. Costs are per-span (per-batch at
the call sites), never per-base.
"""

from __future__ import annotations

import contextlib
import itertools
import json
import os
import threading
import time

from ..utils import resources
from .registry import _scalar, atomic_write


def chrome_trace_path(path: str) -> str:
    """The Chrome trace twin of a span-JSONL path: `.jsonl` (or
    `.json`) swapped for `.trace.json`, else appended."""
    for ext in (".jsonl", ".json"):
        if path.endswith(ext):
            return path[: -len(ext)] + ".trace.json"
    return path + ".trace.json"


@contextlib.contextmanager
def _annotation(kind: str, name: str, step=None):
    """Best-effort jax.profiler annotation context: TraceAnnotation for
    plain spans, StepTraceAnnotation for device steps. A no-op when jax
    (or the annotation API) is unavailable — the tracer's own record
    never depends on it."""
    ctx = None
    try:
        from jax import profiler as _prof
        if kind == "step":
            ctx = _prof.StepTraceAnnotation(name, step_num=step)
        else:
            ctx = _prof.TraceAnnotation(name)
    except Exception:  # noqa: BLE001 - jax absent / API drift
        ctx = None
    if ctx is None:
        yield
        return
    with ctx:
        yield


class SpanTracer:
    """One per instrumented run (`--trace-spans PATH`)."""

    enabled = True

    # retained-span cap for the Chrome export: the JSONL stream is
    # unbounded (it goes to disk as spans close); the in-memory list
    # backing close()'s trace_event dump is not. Past the cap the
    # Chrome trace is truncated (and says so in its metadata) while
    # the JSONL keeps every span.
    MAX_RETAINED = 100_000

    def __init__(self, path: str | None, chrome_path: str | None = None):
        self.path = path
        self.chrome_path = chrome_path or (
            chrome_trace_path(path) if path else None)
        self._t0 = time.perf_counter()
        self._lock = threading.Lock()
        self._f = None
        self._ids = itertools.count(1)
        self._local = threading.local()
        self._spans: list[dict] = []
        self._dropped = 0
        self._tids: dict[int, int] = {}
        self._closed = False
        # flight-recorder tap (ISSUE 16): open/close edges feed the
        # forensic ring, outside self._lock (see registry.py)
        self.flight = None

    # -- internals --------------------------------------------------------
    def _stack(self) -> list:
        st = getattr(self._local, "stack", None)
        if st is None:
            st = self._local.stack = []
        return st

    def _tid(self) -> int:
        """Small stable per-thread id (Chrome tid / JSONL `tid`)."""
        ident = threading.get_ident()
        with self._lock:
            t = self._tids.get(ident)
            if t is None:
                t = self._tids[ident] = len(self._tids)
            return t

    def _record(self, name: str, sid: int, parent: int | None,
                ts: float, dur: float, attrs: dict) -> None:
        fl = self.flight
        if fl is not None:
            fl.record("span", name, sid=sid, dur=round(dur, 6))
        obj = {"span": name, "id": sid, "parent": parent,
               "tid": self._tid(),
               "ts": round(ts, 6), "dur": round(dur, 6)}
        for k, v in attrs.items():
            obj[k] = _scalar(v)
        line = json.dumps(obj) + "\n"
        enospc = None
        with self._lock:
            if self._closed:
                # a straggler (producer/render thread) outliving
                # close(): reopening the JSONL in "w" here would
                # truncate every streamed span — drop it instead
                self._dropped += 1
                return
            if len(self._spans) < self.MAX_RETAINED:
                self._spans.append(obj)
            else:
                self._dropped += 1
            if self.path and not resources.degraded("trace.spans"):
                try:
                    if self._f is None:
                        # streaming span JSONL: one line per closed
                        # span all run long — atomic replace cannot
                        # apply to a stream; opened once behind the
                        # None guard
                        self._f = open(self.path, "w")  # qlint: disable=raw-artifact-write
                    self._f.write(line)
                    self._f.flush()
                except OSError as e:
                    # traces are an optional writer (ISSUE 19): a
                    # full disk drops the trace, never the run. The
                    # ladder call happens OUTSIDE self._lock (it logs
                    # + counts into the registry).
                    if not resources.is_enospc(e):
                        raise
                    enospc = e
                    if self._f is not None:
                        try:
                            self._f.close()
                        except OSError:
                            pass
                        self._f = None
        if enospc is not None:
            resources.degrade("trace.spans", enospc, path=self.path)

    @contextlib.contextmanager
    def _span(self, kind: str, name: str, step, attrs: dict):
        stack = self._stack()
        sid = next(self._ids)
        parent = stack[-1] if stack else None
        stack.append(sid)
        fl = self.flight
        if fl is not None:
            fl.record("span_open", name, sid=sid)
        if step is not None:
            attrs = dict(attrs, step=step)
        t0 = time.perf_counter()
        try:
            with _annotation(kind, name, step):
                yield
        finally:
            dur = time.perf_counter() - t0
            stack.pop()
            self._record(name, sid, parent, t0 - self._t0, dur, attrs)

    # -- public surface ---------------------------------------------------
    def span(self, name: str, **attrs):
        """Record a host region; nests via the per-thread stack and
        mirrors into jax.profiler.TraceAnnotation."""
        return self._span("span", name, None, attrs)

    def step(self, name: str, step: int, **attrs):
        """Record a device-dispatch region tagged with a step number;
        mirrors into jax.profiler.StepTraceAnnotation so per-batch
        device time is attributable in the XLA trace."""
        return self._span("step", name, int(step), attrs)

    def elapsed(self) -> float:
        return time.perf_counter() - self._t0

    def as_chrome_trace(self) -> dict:
        """The retained spans as a Chrome trace_event document
        (Perfetto / chrome://tracing 'X' complete events, µs units)."""
        pid = os.getpid()
        with self._lock:
            events = [
                {"name": s["span"], "ph": "X", "pid": pid,
                 "tid": s["tid"],
                 "ts": round(s["ts"] * 1e6, 3),
                 "dur": round(s["dur"] * 1e6, 3),
                 "args": {k: v for k, v in s.items()
                          if k not in ("span", "ts", "dur", "tid")}}
                for s in self._spans
            ]
            dropped = self._dropped
        doc = {"traceEvents": events, "displayTimeUnit": "ms"}
        if dropped:
            doc["metadata"] = {"dropped_spans": dropped}
        return doc

    def write_chrome_trace(self, path: str | None = None) -> str | None:
        """Write the Chrome trace JSON (atomic replace). Returns the
        path written."""
        path = path or self.chrome_path
        if not path or resources.degraded("trace.spans"):
            return None
        with resources.guard("trace.spans", path=path):
            atomic_write(path,
                         json.dumps(self.as_chrome_trace()) + "\n")
            return path
        return None  # guard swallowed an ENOSPC: trace degraded

    def close(self) -> None:
        """Flush + close the JSONL sink and write the Chrome trace.
        Idempotent (the CLIs call it from finally blocks)."""
        with self._lock:
            if self._closed:
                return
            self._closed = True
            if self._f is not None:
                self._f.close()
                self._f = None
        self.write_chrome_trace()


class NullTracer:
    """The disabled tracer: every surface is a no-op."""

    enabled = False
    path = None
    chrome_path = None

    @contextlib.contextmanager
    def _noop(self):
        yield

    def span(self, name, **attrs):
        return self._noop()

    def step(self, name, step, **attrs):
        return self._noop()

    def elapsed(self):
        return 0.0

    def as_chrome_trace(self):
        return {"traceEvents": [], "displayTimeUnit": "ms"}

    def write_chrome_trace(self, path=None):
        return None

    def close(self):
        pass


NULL_TRACER = NullTracer()


def tracer_for(path: str | None) -> SpanTracer | NullTracer:
    """The one constructor call sites use: a real tracer when a
    `--trace-spans PATH` was given, the no-op singleton when not."""
    if not path:
        return NULL_TRACER
    return SpanTracer(path)
