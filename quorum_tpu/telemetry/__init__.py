"""Structured telemetry: metrics registry + JSONL run events.

`registry_for(path, heartbeat_s)` is the entry point the CLIs use for
their `--metrics PATH` option; it returns the no-op NULL singleton
when no path is given, so instrumentation is zero-cost when disabled.
See registry.py for the model and schema.py for the document format.
"""

from .registry import (Counter, Gauge, Histogram, MetricsRegistry,
                       NULL, NullRegistry, registry_for,
                       track_jax_compile_cache)
from .schema import (SCHEMA_VERSION, check_file, metric_line,
                     validate_bench_line, validate_events_line,
                     validate_metrics)

__all__ = [
    "Counter", "Gauge", "Histogram", "MetricsRegistry", "NULL",
    "NullRegistry", "registry_for", "track_jax_compile_cache",
    "SCHEMA_VERSION", "check_file", "metric_line",
    "validate_bench_line", "validate_events_line", "validate_metrics",
]
