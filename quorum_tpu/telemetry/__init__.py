"""Structured telemetry: metrics registry, JSONL run events, span
tracing, and live Prometheus exposition.

`registry_for(path, heartbeat_s)` is the entry point the CLIs use for
their `--metrics PATH` option; it returns the no-op NULL singleton
when no path is given, so instrumentation is zero-cost when disabled.
`tracer_for(path)` is the same contract for `--trace-spans`
(spans.py); export.py drives `--metrics-port`/`--metrics-textfile`.
See registry.py for the model and schema.py for the document formats.
"""

from . import flight
from . import quality
from .alerts import (AlertEngine, DEFAULT_QUALITY_RULES,
                     DEFAULT_RESOURCE_RULES, DEFAULT_RULES,
                     DEFAULT_SERVE_RULES, load_rules, merge_rules)
from .quality import QualityScorecard
from .registry import (Counter, Gauge, Histogram, MetricsRegistry,
                       NULL, NullRegistry, labeled,
                       observe_dispatch_wait, registry_for,
                       track_jax_compile_cache)
from .schema import (SCHEMA_VERSION, check_file, metric_line,
                     validate_bench_line, validate_chrome_trace,
                     validate_events_line, validate_metrics,
                     validate_quality, validate_span_line)
from .spans import NULL_TRACER, NullTracer, SpanTracer, tracer_for

__all__ = [
    "flight", "quality",
    "AlertEngine", "DEFAULT_QUALITY_RULES", "DEFAULT_RESOURCE_RULES",
    "DEFAULT_RULES", "DEFAULT_SERVE_RULES", "load_rules",
    "merge_rules",
    "QualityScorecard",
    "Counter", "Gauge", "Histogram", "MetricsRegistry", "NULL",
    "NullRegistry", "labeled", "observe_dispatch_wait", "registry_for",
    "track_jax_compile_cache",
    "SCHEMA_VERSION", "check_file", "metric_line",
    "validate_bench_line", "validate_chrome_trace",
    "validate_events_line", "validate_metrics", "validate_quality",
    "validate_span_line",
    "NULL_TRACER", "NullTracer", "SpanTracer", "tracer_for",
]
