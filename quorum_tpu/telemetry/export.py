"""Live metrics exposition (ISSUE 2 tentpole): Prometheus text
rendering, an atomic textfile writer, and an optional stdlib HTTP
endpoint serving `/metrics` + `/healthz` DURING a run.

PR 1 made metrics machine-readable but post-hoc only (one JSON at
exit). Operators of a Gbases/hour pipeline need to scrape progress
mid-run — the queryable-stats model of KMC 3 (PAPERS.md). Two
transports, both driven from the same registries:

* **Textfile** (`--metrics-textfile PATH`): the Prometheus
  node-exporter textfile-collector pattern. Every registry heartbeat
  re-renders ALL live registries and atomically replaces PATH
  (tmp + os.replace), so a scraper never observes a torn file.
* **HTTP** (`--metrics-port PORT`): a daemon-thread
  `http.server` serving the same rendering at `/metrics` (Prometheus
  text exposition format 0.0.4) and a liveness JSON at `/healthz`.
  PORT 0 binds an ephemeral port (reported via vlog and
  `meta.metrics_port`).

Every enabled registry created through `registry_for` registers into
the module-level LIVE set (weak — finished runs drop out), labelled by
its `meta.stage`/`meta.driver`; the in-process `quorum` driver plus
both stage registries therefore appear in ONE exposition with
`stage=...` labels, no cross-wiring needed.

`lint_prometheus_text` is the shared linter behind
`tools/metrics_check.py --prom` — hand-rolled like schema.py, no
dependency beyond the standard library.
"""

from __future__ import annotations

import json
import re
import threading
import time
import weakref

from ..utils import resources
from .registry import atomic_write

PREFIX = "quorum_tpu_"

# enabled registries in this process, weakly held: label -> doc comes
# from each registry's own meta at render time. The lock serializes
# adds (main thread, mid-run) against snapshots (HTTP handler
# threads) — WeakSet iteration concurrent with add raises
# RuntimeError, which would fail a scrape. A finished registry's
# FINAL rendering is retained strongly by label (_FINAL): without it,
# stage 1's series would vanish from the shared driver endpoint and
# textfile the moment the stage returns and its registry is freed —
# the exposition must keep carrying every stage the process ran.
_LIVE: weakref.WeakSet = weakref.WeakSet()
_FINAL: dict[str, tuple[dict, float]] = {}  # label -> (doc, elapsed)
_TEXTFILE_PATHS: set[str] = set()  # textfile targets seen this job
_LIVE_LOCK = threading.Lock()
_SERVER_REF: weakref.ref | None = None


def _retain_final(reg, final: bool = False) -> None:
    """write()-time exporter: snapshot the registry's last document
    so the exposition outlives the registry object."""
    if final:
        with _LIVE_LOCK:
            _FINAL[_reg_label(reg)] = (reg.as_dict(), reg.elapsed())


def register_live(reg) -> None:
    """Expose `reg` through the live endpoints (weak while running;
    its final document is retained by stage label after write())."""
    if not getattr(reg, "enabled", False):
        return
    with _LIVE_LOCK:
        if reg in _LIVE:
            return
        _LIVE.add(reg)
    reg.add_exporter(_retain_final)


def live_registries() -> list:
    with _LIVE_LOCK:
        return list(_LIVE)


def reset_exposition() -> None:
    """Forget the retained final documents of earlier runs in this
    process (still-live registries are unaffected). serve() calls
    this so a NEW endpoint never reports a previous job's counters;
    long-lived embedders sharing one process across jobs can call it
    between runs."""
    with _LIVE_LOCK:
        _FINAL.clear()
        _TEXTFILE_PATHS.clear()


def _metric_name(name: str) -> str:
    """Prometheus-legal metric name component."""
    name = re.sub(r"[^a-zA-Z0-9_]", "_", name)
    if name and name[0].isdigit():
        name = "_" + name
    return name


def split_labeled_name(name: str) -> tuple[str, str | None]:
    """Registry metric names may carry an embedded label set —
    `lane_wait_us{lane="interactive"}` (telemetry.labeled) — so flat
    name->value registries can express labelled series without a
    label-aware metric model. Returns (base_name, label_text or
    None); the label text is rendered verbatim inside the sample's
    braces (the producer writes valid `k="v"` pairs; the exposition
    linter still checks the rendered output)."""
    if name.endswith("}") and "{" in name:
        base, _, rest = name.partition("{")
        return base, rest[:-1]
    return name, None


def _label_value(v: str) -> str:
    return (str(v).replace("\\", "\\\\").replace('"', '\\"')
            .replace("\n", "\\n"))


def _reg_label(reg) -> str:
    meta = getattr(reg, "meta", {}) or {}
    return str(meta.get("stage") or meta.get("driver") or "run")


def prometheus_text(docs: dict[str, dict],
                    elapsed: dict[str, float] | None = None) -> str:
    """Render {stage_label: metrics_doc} (MetricsRegistry.as_dict
    shapes) as Prometheus text exposition format. Counters become
    `<prefix><name>_total` (TYPE counter), gauges `<prefix><name>`
    (TYPE gauge), exact-count histograms cumulative `_bucket{le=...}`
    series plus `_sum`/`_count` (TYPE histogram). Every sample carries
    a `stage` label so the driver and both stages coexist in one
    exposition."""
    # name -> (type, [lines]) keeps each # TYPE header emitted once
    # even when several stages share a metric name
    out: dict[str, tuple[str, list[str]]] = {}

    def add(name: str, mtype: str, line: str) -> None:
        if name not in out:
            out[name] = (mtype, [])
        out[name][1].append(line)

    for label, doc in sorted(docs.items()):
        stage_lab = f'stage="{_label_value(label)}"'

        def labs(k: str) -> tuple[str, str]:
            """(prometheus base name, full label text) for a registry
            key that may carry an embedded label set."""
            base, extra = split_labeled_name(k)
            name = PREFIX + _metric_name(base)
            return name, (stage_lab if extra is None
                          else f"{stage_lab},{extra}")

        for k, v in doc.get("counters", {}).items():
            name, lab = labs(k)
            name += "_total"
            add(name, "counter", f"{name}{{{lab}}} {v}")
        for k, v in doc.get("gauges", {}).items():
            name, lab = labs(k)
            add(name, "gauge", f"{name}{{{lab}}} {v}")
        if elapsed and label in elapsed:
            name = PREFIX + "elapsed_seconds"
            add(name, "gauge",
                f"{name}{{{stage_lab}}} {round(elapsed[label], 3)}")
        for k, h in doc.get("histograms", {}).items():
            name, lab = labs(k)
            # exact per-value counts -> cumulative le buckets; the
            # cardinality-guard "overflow" key lands in +Inf only
            numeric = sorted(int(b) for b in h.get("counts", {})
                             if str(b).lstrip("-").isdigit())
            cum = 0
            for b in numeric:
                cum += h["counts"][str(b)]
                add(name, "histogram",
                    f'{name}_bucket{{{lab},le="{b}"}} {cum}')
            add(name, "histogram",
                f'{name}_bucket{{{lab},le="+Inf"}} {h.get("count", 0)}')
            add(name, "histogram", f"{name}_sum{{{lab}}} {h.get('sum', 0)}")
            add(name, "histogram",
                f"{name}_count{{{lab}}} {h.get('count', 0)}")

    lines: list[str] = []
    for name in sorted(out):
        mtype, samples = out[name]
        lines.append(f"# TYPE {name} {mtype}")
        lines.extend(samples)
    return "\n".join(lines) + ("\n" if lines else "")


def render_live() -> str:
    """Prometheus text for every live registry in this process, plus
    the retained final documents of registries that already finished
    (so one scrape/textfile carries every stage the run touched)."""
    with _LIVE_LOCK:
        finals = dict(_FINAL)
        regs = list(_LIVE)
    docs: dict[str, dict] = {}
    elapsed: dict[str, float] = {}
    for label, (doc, el) in finals.items():
        docs[label] = doc
        elapsed[label] = el
    from_final = set(docs)
    for reg in regs:
        label = _reg_label(reg)
        if label in from_final:
            from_final.discard(label)  # live registry supersedes its
            # own (or a predecessor's) retained snapshot
        elif label in docs:  # two LIVE regs sharing a label: the
            label = f"{label}_{len(docs)}"  # later wins its own slot
        docs[label] = reg.as_dict()
        elapsed[label] = reg.elapsed()
    return prometheus_text(docs, elapsed)


def write_textfile(path: str, text: str | None = None) -> str:
    """Atomically replace `path` with the current live rendering: a
    reader at the rename target can never observe a half-written
    file. An optional writer on the degradation ladder (ISSUE 19):
    ENOSPC disables the textfile for the rest of the run — scraping
    goes stale, the run keeps going."""
    if resources.degraded("metrics.textfile"):
        return path
    with resources.guard("metrics.textfile", path=path):
        if text is None:
            text = render_live()
        atomic_write(path, text)
    return path


def attach_textfile(reg, path: str, period: float = 1.0) -> None:
    """Refresh the Prometheus textfile from `reg`'s heartbeats (each
    write renders ALL live registries, so one file serves a whole
    driver run), rate-limited to `period`, plus one final write when
    the registry writes its JSON.

    Attaching a path this process has not written before marks a NEW
    job: retained finals from earlier runs are dropped so the new
    textfile never reports a previous job's counters. Re-attaching a
    known path (the driver's stages sharing one file) retains them —
    that sharing is the point. Back-to-back jobs reusing one path in
    one process should call `reset_exposition()` between runs."""
    with _LIVE_LOCK:
        if path not in _TEXTFILE_PATHS:
            _TEXTFILE_PATHS.add(path)
            _FINAL.clear()
    register_live(reg)
    last = [-1e18]

    def export(reg_, final: bool = False) -> None:
        now = time.perf_counter()
        if not final and now - last[0] < period:
            return
        last[0] = now
        try:
            write_textfile(path)
        except OSError:  # pragma: no cover - exposition must not kill runs
            pass

    reg.add_exporter(export)


# ---------------------------------------------------------------------------
# HTTP endpoint
# ---------------------------------------------------------------------------

class MetricsHTTPServer:
    """`/metrics` + `/healthz` on a daemon thread (stdlib
    http.server). `close()` (idempotent) shuts the socket down; the
    CLIs call it from their finally blocks so the port frees even on
    error exits. Binds loopback by default: the exposition is
    unauthenticated and carries run metadata (input paths, cmdline) —
    pass host="0.0.0.0" explicitly to scrape from off-machine."""

    def __init__(self, port: int, host: str = "127.0.0.1"):
        import http.server

        t0 = time.perf_counter()

        class Handler(http.server.BaseHTTPRequestHandler):
            def do_GET(self):  # noqa: N802 - http.server API
                if self.path.split("?")[0] == "/metrics":
                    body = render_live().encode()
                    ctype = "text/plain; version=0.0.4; charset=utf-8"
                elif self.path.split("?")[0] == "/healthz":
                    body = (json.dumps(
                        {"status": "ok",
                         "uptime_s": round(time.perf_counter() - t0, 3),
                         "registries": len(live_registries())})
                        + "\n").encode()
                    ctype = "application/json"
                else:
                    self.send_error(404)
                    return
                self.send_response(200)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def log_message(self, *a):  # quiet: scrapes are periodic
                pass

        self._httpd = http.server.ThreadingHTTPServer((host, port), Handler)
        self._httpd.daemon_threads = True
        self.port = self._httpd.server_address[1]
        self._thread = threading.Thread(target=self._httpd.serve_forever,
                                        kwargs={"poll_interval": 0.1},
                                        daemon=True)
        self._thread.start()
        self._open = True

    def close(self) -> None:
        global _SERVER_REF
        if not self._open:
            return
        self._open = False
        if _SERVER_REF is not None and _SERVER_REF() is self:
            _SERVER_REF = None  # current_server() -> None immediately,
            # not only after this object is garbage-collected
        self._httpd.shutdown()
        self._httpd.server_close()
        self._thread.join(timeout=5)


def serve(port: int, host: str = "127.0.0.1") -> MetricsHTTPServer:
    """Start the live endpoint; port 0 binds an ephemeral port (read
    it back from `.port`)."""
    global _SERVER_REF
    reset_exposition()  # a fresh endpoint = a fresh job
    srv = MetricsHTTPServer(port, host=host)
    _SERVER_REF = weakref.ref(srv)
    return srv


def start_exposition(reg, port: int | None, textfile: str | None,
                     period: float = 0.0):
    """The one start sequence every CLI shares: serve `/metrics` when
    a port is given (recording `meta.metrics_port`), attach the
    textfile writer when a path is given (refreshed at `period`
    seconds when > 0, else 1 Hz). Returns the server (or None) for
    the caller's teardown path — call this INSIDE the same umbrella
    that stamps status=error, so a busy port still lands the error
    document."""
    server = None
    if port is not None:
        server = serve(port)
        reg.set_meta(metrics_port=server.port)
        from ..utils.vlog import vlog
        vlog("Serving live /metrics on port ", server.port)
    if textfile:
        attach_textfile(reg, textfile,
                        period=period if period and period > 0 else 1.0)
        reg.set_meta(metrics_textfile=textfile)
    return server


def current_server() -> MetricsHTTPServer | None:
    """The most recently started (still-alive) server in this process
    — lets tests and in-process tooling discover the ephemeral port."""
    return _SERVER_REF() if _SERVER_REF is not None else None


# ---------------------------------------------------------------------------
# Prometheus text linter (tools/metrics_check.py --prom)
# ---------------------------------------------------------------------------

_SAMPLE_RE = re.compile(
    r"^(?P<name>[a-zA-Z_:][a-zA-Z0-9_:]*)"
    r"(?P<labels>\{[^{}]*\})?"
    r" (?P<value>[-+]?(?:[0-9]*\.?[0-9]+(?:[eE][-+]?[0-9]+)?|Inf|NaN))"
    r"(?: [0-9]+)?$")
_LABEL_RE = re.compile(
    r'^[a-zA-Z_][a-zA-Z0-9_]*="(?:[^"\\]|\\.)*"$')
_TYPE_RE = re.compile(
    r"^# TYPE [a-zA-Z_:][a-zA-Z0-9_:]* "
    r"(counter|gauge|histogram|summary|untyped)$")


def lint_prometheus_text(text: str) -> list[str]:
    """Validate Prometheus text exposition format (the shape the
    textfile collector and scrapers parse). Returns problems (empty =
    valid): malformed sample/TYPE lines, bad label syntax, counters
    not ending in _total, and non-monotonic histogram buckets."""
    errs: list[str] = []
    types: dict[str, str] = {}
    buckets: dict[tuple, list[tuple[float, float]]] = {}
    any_sample = False
    for i, line in enumerate(text.splitlines(), 1):
        if not line.strip():
            continue
        if line.startswith("#"):
            if line.startswith("# TYPE") and not _TYPE_RE.match(line):
                errs.append(f"line {i}: malformed TYPE line")
            elif _TYPE_RE.match(line):
                _, _, name, mtype = line.split(" ")
                types[name] = mtype
            continue
        m = _SAMPLE_RE.match(line)
        if not m:
            errs.append(f"line {i}: not a valid sample line")
            continue
        any_sample = True
        name = m.group("name")
        labels = m.group("labels")
        lab_map: dict[str, str] = {}
        if labels:
            for part in _split_labels(labels[1:-1]):
                if not _LABEL_RE.match(part):
                    errs.append(f"line {i}: bad label {part!r}")
                else:
                    k, v = part.split("=", 1)
                    lab_map[k] = v[1:-1]
        base = name
        for suf in ("_bucket", "_sum", "_count", "_total"):
            if name.endswith(suf):
                base = name[: -len(suf)]
                break
        mtype = types.get(name) or types.get(base)
        if mtype == "counter" and not name.endswith("_total"):
            errs.append(f"line {i}: counter {name!r} missing _total")
        if name.endswith("_bucket"):
            le = lab_map.get("le")
            if le is None:
                errs.append(f"line {i}: histogram bucket without le=")
            else:
                try:
                    le_f = float("inf") if le == "+Inf" else float(le)
                except ValueError:
                    errs.append(f"line {i}: non-numeric le={le!r}")
                    continue
                key = (base, tuple(sorted(
                    (k, v) for k, v in lab_map.items() if k != "le")))
                buckets.setdefault(key, []).append(
                    (le_f, float(m.group("value"))))
    for (base, _lab), bs in buckets.items():
        bs.sort()
        vals = [v for _le, v in bs]
        if vals != sorted(vals):
            errs.append(f"histogram {base!r}: buckets not cumulative")
    if not any_sample:
        errs.append("no samples found")
    return errs


def _split_labels(s: str) -> list[str]:
    """Split `a="x",b="y"` on commas outside quotes."""
    parts, cur, in_q, esc = [], [], False, False
    for ch in s:
        if esc:
            cur.append(ch)
            esc = False
            continue
        if ch == "\\":
            cur.append(ch)
            esc = True
            continue
        if ch == '"':
            in_q = not in_q
        if ch == "," and not in_q:
            parts.append("".join(cur))
            cur = []
        else:
            cur.append(ch)
    if cur:
        parts.append("".join(cur))
    return parts
