"""The metrics document schema (version `quorum-tpu-metrics/1`) and
its validator — shared by `tools/metrics_check.py`, the tests, and
bench.py's line emitter, so every machine-readable artifact the
pipeline produces stays mutually comparable.

Final metrics JSON (MetricsRegistry.as_dict):

    {
      "schema":     "quorum-tpu-metrics/1",
      "meta":       {str: scalar | [scalar] | {str: scalar}},
      "counters":   {str: int >= 0},
      "gauges":     {str: number},
      "histograms": {str: {"count": int, "sum": number,
                           "counts": {str: int}}},
      "timers":     {str: {"total_seconds": number,
                           "stages": {str: {"seconds": number,
                                            "calls": int,
                                            "units": int}}}}
    }

Events JSONL (one JSON object per line): `event` (str) and `t`
(seconds since registry creation, number) are required; all other
values must be scalars. `heartbeat` events carry progress fields
(reads/bases so far, a monotonic `elapsed_s`, derived `gb_per_h`).

A multi-host aggregated document (parallel/multihost.
aggregate_metrics) additionally carries a `hosts` section: one
complete per-host metrics document per process index, with the
top-level counters equal to the per-host sums.

Span JSONL (telemetry/spans.py, one object per line): `span` (str),
`id` (int), `ts`/`dur` (seconds, numbers) required; `parent` is an
int or null, `tid` an int; all other values scalars. The Chrome-trace
twin (`{"traceEvents": [...]}`, "X" complete events) is validated by
`validate_chrome_trace`.

No dependency on jsonschema: the checks are hand-rolled and return a
list of human-readable problem strings (empty = valid).
"""

from __future__ import annotations

import json

SCHEMA_VERSION = "quorum-tpu-metrics/1"

_SCALAR = (str, int, float, bool, type(None))


def _is_scalar(v) -> bool:
    return isinstance(v, _SCALAR)


def _is_number(v) -> bool:
    return isinstance(v, (int, float)) and not isinstance(v, bool)


def _validate_fleet_shape(doc: dict) -> list[str]:
    """Structural fleet consistency (ISSUE 20): a document whose meta
    declares `host_process_count > 1` was aggregated from a multi-host
    fleet run — its `hosts` section must carry exactly one shard per
    process, and every shard's own meta.host_process_index must be a
    distinct in-range process id (two shards claiming one index means
    a host's document was overwritten; a missing index means one was
    never collected). Name-level requirements (resource gauges,
    compile ledgers) live in tools/metrics_check.py."""
    errs: list[str] = []
    meta = doc.get("meta", {})
    pc = meta.get("host_process_count")
    if pc is None:
        return errs
    if not isinstance(pc, int) or isinstance(pc, bool) or pc < 1:
        return [f"meta.host_process_count must be a positive "
                f"integer, got {pc!r}"]
    if pc <= 1:
        return errs
    hosts = doc.get("hosts", {})
    if len(hosts) != pc:
        errs.append(f"meta.host_process_count={pc} but {len(hosts)} "
                    "host shard(s) present")
    indices = []
    for hk in sorted(hosts):
        hmeta = hosts[hk].get("meta", {}) if isinstance(
            hosts[hk], dict) else {}
        idx = hmeta.get("host_process_index")
        if not isinstance(idx, int) or isinstance(idx, bool) \
                or not 0 <= idx < pc:
            errs.append(f"hosts[{hk!r}]: meta.host_process_index "
                        f"{idx!r} is not a process id in [0, {pc})")
        else:
            indices.append(idx)
    if len(set(indices)) != len(indices):
        errs.append("duplicate meta.host_process_index across host "
                    "shards (one host's document overwrote another's)")
    return errs


def validate_metrics(doc, _nested: bool = False) -> list[str]:
    """Validate a final metrics document (optionally carrying a
    multi-host `hosts` section of per-host shard documents). Returns
    problems (empty = valid)."""
    errs: list[str] = []
    if not isinstance(doc, dict):
        return ["document is not a JSON object"]
    if doc.get("schema") != SCHEMA_VERSION:
        errs.append(f"schema is {doc.get('schema')!r}, "
                    f"expected {SCHEMA_VERSION!r}")
    for key in ("meta", "counters", "gauges", "histograms", "timers"):
        if not isinstance(doc.get(key), dict):
            errs.append(f"missing or non-object section {key!r}")
    allowed = {"schema", "meta", "counters", "gauges",
               "histograms", "timers"}
    # the correction-quality section (ISSUE 17): derived by
    # MetricsRegistry.as_dict from the document's own counters when a
    # QualityScorecard is installed — per-host shard documents carry
    # their own, so it is allowed nested too
    allowed.add("quality")
    if not _nested:
        allowed.add("hosts")
        # fleet documents (tools/push_receiver.py) may carry receiver-
        # side lifecycle events — staleness alerts a silent host
        # cannot write into its own (absent) document (ISSUE 16)
        allowed.add("events")
    unknown = set(doc) - allowed
    if unknown:
        errs.append(f"unknown top-level keys {sorted(unknown)}")
    if errs:
        return errs
    if not _nested and "hosts" in doc:
        if not isinstance(doc["hosts"], dict):
            errs.append("hosts is not an object")
        else:
            for hk, hdoc in doc["hosts"].items():
                errs.extend(f"hosts[{hk!r}]: {e}" for e in
                            validate_metrics(hdoc, _nested=True))
            errs.extend(_validate_fleet_shape(doc))
    if not _nested and "events" in doc:
        if not isinstance(doc["events"], list):
            errs.append("events is not a list")
        else:
            for i, ev in enumerate(doc["events"]):
                errs.extend(f"events[{i}]: {e}" for e in
                            validate_events_line(ev))
    if "quality" in doc:
        errs.extend(f"quality: {e}" for e in
                    validate_quality(doc["quality"]))

    for k, v in doc["meta"].items():
        ok = (_is_scalar(v)
              or (isinstance(v, list) and all(_is_scalar(x) for x in v))
              or (isinstance(v, dict)
                  and all(_is_scalar(x) for x in v.values())))
        if not ok:
            errs.append(f"meta[{k!r}] is not scalar/list/flat-object")
    for k, v in doc["counters"].items():
        if not (isinstance(v, int) and not isinstance(v, bool) and v >= 0):
            errs.append(f"counters[{k!r}] = {v!r} is not a non-negative int")
    for k, v in doc["gauges"].items():
        if not _is_number(v):
            errs.append(f"gauges[{k!r}] = {v!r} is not a number")
    for k, h in doc["histograms"].items():
        if not isinstance(h, dict):
            errs.append(f"histograms[{k!r}] is not an object")
            continue
        if not (isinstance(h.get("count"), int)
                and _is_number(h.get("sum"))
                and isinstance(h.get("counts"), dict)):
            errs.append(f"histograms[{k!r}] needs count/sum/counts")
            continue
        total = 0
        for bk, bn in h["counts"].items():
            if not isinstance(bk, str) or not isinstance(bn, int):
                errs.append(f"histograms[{k!r}].counts[{bk!r}] malformed")
            else:
                total += bn
        if total != h["count"]:
            errs.append(f"histograms[{k!r}]: counts sum {total} != "
                        f"count {h['count']}")
    for k, t in doc["timers"].items():
        if not isinstance(t, dict) or not _is_number(
                t.get("total_seconds")):
            errs.append(f"timers[{k!r}] needs numeric total_seconds")
            continue
        stages = t.get("stages", {})
        if not isinstance(stages, dict):
            errs.append(f"timers[{k!r}].stages is not an object")
            continue
        for sk, sv in stages.items():
            if not (isinstance(sv, dict) and _is_number(sv.get("seconds"))
                    and isinstance(sv.get("calls"), int)):
                errs.append(f"timers[{k!r}].stages[{sk!r}] malformed")
    return errs


# the correction-quality section (telemetry/quality.py, ISSUE 17):
# what MetricsRegistry.as_dict derives from the document's own
# counters/histograms when a QualityScorecard is installed
QUALITY_SCHEMA = "quorum-tpu-quality/1"

# the quality-section count maps (histogram `counts` re-keyed
# deterministically by quality._sorted_counts)
_QUALITY_COUNT_MAPS = ("sub_pos_spectrum", "substitutions_per_read",
                       "trunc_cycle_3p", "trunc_cycle_5p",
                       "skip_reasons")
_QUALITY_COUNTS = ("reads", "corrected", "skipped", "substitutions",
                   "truncations_3p", "truncations_5p")
_QUALITY_RATES = ("anchor_rate", "contam_rate",
                  "corrections_per_read", "skip_rate",
                  "trunc_rate_3p", "trunc_rate_5p")


def validate_quality(q) -> list[str]:
    """Validate a `quality` section (quality.section_from_doc):
    schema stamp, non-negative counts, the full rate set as numbers
    in sane ranges, count maps of non-negative ints, and — when the
    producing run knew its DB coverage — a coherent `coverage`
    sub-object."""
    errs: list[str] = []
    if not isinstance(q, dict):
        return ["quality section is not a JSON object"]
    if q.get("schema") != QUALITY_SCHEMA:
        errs.append(f"schema is {q.get('schema')!r}, "
                    f"expected {QUALITY_SCHEMA!r}")
    for k in _QUALITY_COUNTS:
        v = q.get(k)
        if not isinstance(v, int) or isinstance(v, bool) or v < 0:
            errs.append(f"{k!r} must be a non-negative int, got {v!r}")
    rates = q.get("rates")
    if not isinstance(rates, dict):
        errs.append("missing/non-object 'rates'")
    else:
        for k in _QUALITY_RATES:
            v = rates.get(k)
            if not _is_number(v) or v < 0:
                errs.append(f"rates[{k!r}] must be a non-negative "
                            f"number, got {v!r}")
    if not (isinstance(q.get("spectrum_cycles_per_bucket"), int)
            and q.get("spectrum_cycles_per_bucket", 0) > 0):
        errs.append("'spectrum_cycles_per_bucket' must be a positive "
                    "int")
    for mk in _QUALITY_COUNT_MAPS:
        m = q.get(mk)
        if not isinstance(m, dict):
            errs.append(f"missing/non-object {mk!r}")
            continue
        for bk, bn in m.items():
            if not isinstance(bk, str) or not isinstance(bn, int) \
                    or isinstance(bn, bool) or bn < 0:
                errs.append(f"{mk}[{bk!r}] malformed")
    cov = q.get("coverage")
    if cov is not None:
        if not isinstance(cov, dict):
            errs.append("'coverage' is not an object")
        else:
            for k in ("predicted_mean", "predicted_anchor_rate"):
                if not _is_number(cov.get(k)) or cov.get(k, -1) < 0:
                    errs.append(f"coverage[{k!r}] must be a "
                                "non-negative number")
    return errs


# the serve request lifecycle event (ISSUE 10): one per terminal
# status, with disjoint phase durations in microseconds
REQUEST_EVENT_PHASES = ("admission_us", "queue_us", "device_us",
                        "hedge_us", "render_us", "total_us")

# per-request quality tallies (ISSUE 17): optional on a request event
# (the 200 path stamps them; error paths have no render output), but
# when present they must be non-negative ints — the ledger's quality
# phases reconcile against the final document's outcome counters
REQUEST_EVENT_QUALITY = ("q_corrected", "q_skipped", "q_subs",
                         "q_t3", "q_t5")

# the alert lifecycle event (telemetry/alerts.py, ISSUE 11): one per
# firing->healed transition of a rule
ALERT_EVENT_STATES = ("firing", "healed")


def _validate_alert_event(obj) -> list[str]:
    """The `alert` event's contract on top of the generic event
    shape: a named rule and a firing/healed state (value/detail/
    severity ride along as ordinary scalars)."""
    errs: list[str] = []
    if not isinstance(obj.get("rule"), str) or not obj.get("rule"):
        errs.append("alert event missing/empty 'rule'")
    if obj.get("state") not in ALERT_EVENT_STATES:
        errs.append(f"alert event 'state' must be one of "
                    f"{ALERT_EVENT_STATES}, got {obj.get('state')!r}")
    return errs


def _validate_request_event(obj) -> list[str]:
    """The `request` lifecycle event's extra contract on top of the
    generic event shape: a non-empty trace id, an HTTP status, a
    lane, and every phase duration present and non-negative."""
    errs: list[str] = []
    if not isinstance(obj.get("request_id"), str) \
            or not obj.get("request_id"):
        errs.append("request event missing/empty 'request_id'")
    if not isinstance(obj.get("status"), int) \
            or isinstance(obj.get("status"), bool):
        errs.append("request event missing/non-int 'status'")
    if not isinstance(obj.get("lane"), str) or not obj.get("lane"):
        errs.append("request event missing/empty 'lane'")
    for k in REQUEST_EVENT_PHASES:
        v = obj.get(k)
        if not _is_number(v):
            errs.append(f"request event missing/non-numeric {k!r}")
        elif v < 0:
            errs.append(f"request event {k!r} is negative")
    for k in REQUEST_EVENT_QUALITY:
        if k in obj:
            v = obj[k]
            if not isinstance(v, int) or isinstance(v, bool) or v < 0:
                errs.append(f"request event {k!r} must be a "
                            "non-negative int when present")
    return errs


def validate_events_line(obj) -> list[str]:
    """Validate one parsed events-JSONL object. `request` lifecycle
    events (serve request tracing, ISSUE 10) are additionally held to
    their richer contract."""
    errs: list[str] = []
    if not isinstance(obj, dict):
        return ["event line is not a JSON object"]
    if not isinstance(obj.get("event"), str) or not obj.get("event"):
        errs.append("missing/empty 'event' field")
    if not _is_number(obj.get("t")):
        errs.append("missing/non-numeric 't' field")
    for k, v in obj.items():
        if not _is_scalar(v):
            errs.append(f"event field {k!r} is not scalar")
    if obj.get("event") == "request":
        errs.extend(_validate_request_event(obj))
    if obj.get("event") == "alert":
        errs.extend(_validate_alert_event(obj))
    return errs


def validate_span_line(obj) -> list[str]:
    """Validate one parsed span-JSONL object (telemetry/spans.py)."""
    errs: list[str] = []
    if not isinstance(obj, dict):
        return ["span line is not a JSON object"]
    if not isinstance(obj.get("span"), str) or not obj.get("span"):
        errs.append("missing/empty 'span' field")
    if not isinstance(obj.get("id"), int) or isinstance(obj.get("id"), bool):
        errs.append("missing/non-int 'id' field")
    if not (obj.get("parent") is None or isinstance(obj.get("parent"), int)):
        errs.append("'parent' must be an int or null")
    if not isinstance(obj.get("tid"), int):
        errs.append("missing/non-int 'tid' field")
    for k in ("ts", "dur"):
        if not _is_number(obj.get(k)):
            errs.append(f"missing/non-numeric {k!r} field")
        elif obj[k] < 0:
            errs.append(f"{k!r} is negative")
    for k, v in obj.items():
        if not _is_scalar(v):
            errs.append(f"span field {k!r} is not scalar")
    return errs


def validate_chrome_trace(doc) -> list[str]:
    """Validate a Chrome trace_event document (the loadable-in-
    Perfetto twin of the span JSONL): {"traceEvents": [...]} of "X"
    complete events with numeric µs ts/dur and pid/tid."""
    errs: list[str] = []
    if not isinstance(doc, dict) or not isinstance(
            doc.get("traceEvents"), list):
        return ["not a Chrome trace object (no traceEvents list)"]
    for i, ev in enumerate(doc["traceEvents"]):
        if not isinstance(ev, dict):
            errs.append(f"traceEvents[{i}] is not an object")
            continue
        if not isinstance(ev.get("name"), str) or not ev.get("name"):
            errs.append(f"traceEvents[{i}]: missing name")
        if ev.get("ph") not in ("X", "B", "E", "i", "M"):
            errs.append(f"traceEvents[{i}]: unsupported ph "
                        f"{ev.get('ph')!r}")
        if not _is_number(ev.get("ts")):
            errs.append(f"traceEvents[{i}]: missing/non-numeric ts")
        if ev.get("ph") == "X" and not _is_number(ev.get("dur")):
            errs.append(f"traceEvents[{i}]: X event without dur")
        for k in ("pid", "tid"):
            if not isinstance(ev.get(k), int):
                errs.append(f"traceEvents[{i}]: missing/non-int {k}")
    return errs


# the perf-regression verdict document (tools/perf_diff.py, ISSUE 11)
PERF_DIFF_SCHEMA = "quorum-tpu-perf-diff/1"
# the accuracy-regression verdict document (tools/quality_diff.py,
# ISSUE 17) — same diff-verdict shape, its own schema stamp
QUALITY_DIFF_SCHEMA = "quorum-tpu-quality-diff/1"


def validate_perf_diff(doc, schema: str = PERF_DIFF_SCHEMA) -> list[str]:
    """Validate a diff verdict document (perf_diff and, via the
    `schema` arg, quality_diff — both tools share the shape):
    verdict/checked/regressions coherent, per-metric entries carrying
    ok flags. The verdict must AGREE with the regression list — a
    'pass' document listing regressions (or vice versa) means the
    gate's output was hand-altered or the tool broke."""
    errs: list[str] = []
    if not isinstance(doc, dict):
        return ["diff-verdict document is not a JSON object"]
    if doc.get("schema") != schema:
        errs.append(f"schema is {doc.get('schema')!r}, expected "
                    f"{schema!r}")
    if doc.get("verdict") not in ("pass", "regression"):
        errs.append(f"verdict must be pass|regression, got "
                    f"{doc.get('verdict')!r}")
    regs = doc.get("regressions")
    if not isinstance(regs, list) or not all(
            isinstance(r, str) for r in regs):
        errs.append("regressions must be a list of strings")
        regs = []
    if not isinstance(doc.get("checked"), int) \
            or isinstance(doc.get("checked"), bool) \
            or doc.get("checked", -1) < 0:
        errs.append("checked must be a non-negative int")
    if doc.get("verdict") == "pass" and regs:
        errs.append("verdict 'pass' but regressions listed")
    if doc.get("verdict") == "regression" and not regs:
        errs.append("verdict 'regression' with no regressions listed")
    docs = doc.get("docs")
    if not isinstance(docs, dict):
        errs.append("missing/non-object 'docs' section")
        return errs
    n_bad = 0
    for dk, dv in docs.items():
        if not isinstance(dv, dict):
            errs.append(f"docs[{dk!r}] is not an object")
            continue
        for mk, mv in dv.get("metrics", {}).items():
            if not isinstance(mv, dict) or not isinstance(
                    mv.get("ok"), bool):
                errs.append(f"docs[{dk!r}].metrics[{mk!r}] needs a "
                            "boolean 'ok'")
            elif not mv["ok"]:
                n_bad += 1
    if doc.get("verdict") == "pass" and n_bad:
        errs.append(f"verdict 'pass' but {n_bad} metric entr"
                    f"{'y' if n_bad == 1 else 'ies'} report ok=false")
    return errs


# the mer-count histogram sidecar (cli/histo_mer_database --json,
# ISSUE 17): the machine-readable twin of the textual spectrum, so
# the scorecard's coverage-model fit and operators consume it without
# parsing stdout
HISTO_SCHEMA = "quorum-tpu-histo/1"


def validate_histo(doc) -> list[str]:
    """Validate a mer-histogram sidecar document: schema stamp, a
    `bins` list of `[count, n_lowqual, n_highqual]` int rows in
    strictly increasing count order, and summary stats consistent
    with the rows."""
    errs: list[str] = []
    if not isinstance(doc, dict):
        return ["histo document is not a JSON object"]
    if doc.get("schema") != HISTO_SCHEMA:
        errs.append(f"schema is {doc.get('schema')!r}, "
                    f"expected {HISTO_SCHEMA!r}")
    bins = doc.get("bins")
    if not isinstance(bins, list):
        errs.append("missing/non-list 'bins' section")
        return errs
    prev = -1
    for i, row in enumerate(bins):
        if not (isinstance(row, list) and len(row) == 3 and all(
                isinstance(v, int) and not isinstance(v, bool)
                and v >= 0 for v in row)):
            errs.append(f"bins[{i}] must be [count, n_lowqual, "
                        f"n_highqual] non-negative ints, got {row!r}")
            continue
        if row[0] <= prev:
            errs.append(f"bins[{i}]: count {row[0]} not strictly "
                        "increasing")
        prev = row[0]
    stats = doc.get("stats")
    if not isinstance(stats, dict):
        errs.append("missing/non-object 'stats' section")
    else:
        for k in ("distinct_total", "distinct_nonempty", "max_count"):
            v = stats.get(k)
            if not isinstance(v, int) or isinstance(v, bool) or v < 0:
                errs.append(f"stats[{k!r}] must be a non-negative int")
        if not _is_number(stats.get("coverage_mode")) \
                or stats.get("coverage_mode", -1) < 0:
            errs.append("stats['coverage_mode'] must be a "
                        "non-negative number")
    return errs


# the flight-recorder dump document (telemetry/flight.py, ISSUE 16)
# and the quorum-debug-bundle manifest that packages one
FLIGHT_SCHEMA = "quorum-tpu-flight/1"
DEBUG_BUNDLE_SCHEMA = "quorum-tpu-debug-bundle/1"

# what a bundle entry can be; "other" keeps the manifest open to
# operator-supplied extras without a schema bump
BUNDLE_FILE_KINDS = ("flight", "metrics", "events", "spans", "trace",
                     "fsck", "config", "other")


def _flight_seal_errors(doc) -> list[str]:
    """A flight dump MUST be sealed (unlike pre-v5 metrics artifacts,
    where the seal is optional): the dump is the black box an operator
    reads AFTER the process died, so an unsealed or altered one is
    exactly the artifact that cannot be trusted."""
    from ..io.integrity import SEAL_FIELD, crc32c
    want = doc.get(SEAL_FIELD)
    if not isinstance(want, int) or isinstance(want, bool):
        return [f"missing/non-int seal field {SEAL_FIELD!r} "
                "(flight dumps are always sealed)"]
    body = json.dumps({k: v for k, v in doc.items()
                       if k != SEAL_FIELD}, sort_keys=True).encode()
    got = crc32c(body)
    if got != want:
        return [f"seal mismatch: computed crc32c {got:#010x} != "
                f"recorded {want:#010x} — the dump was altered after "
                "it was written"]
    return []


def validate_flight_dump(doc) -> list[str]:
    """Validate a flight-recorder crash dump (FlightRecorder.dump):
    trigger identity (kind/thread/tid), ring entries as scalar-valued
    timeline records, all-thread stacks, the embedded registry
    snapshot as a well-formed metrics document, and the mandatory
    integrity seal (recomputed, not just present)."""
    errs: list[str] = []
    if not isinstance(doc, dict):
        return ["flight dump is not a JSON object"]
    if doc.get("schema") != FLIGHT_SCHEMA:
        errs.append(f"schema is {doc.get('schema')!r}, "
                    f"expected {FLIGHT_SCHEMA!r}")
    meta = doc.get("meta")
    if not isinstance(meta, dict):
        errs.append("missing/non-object 'meta' section")
    else:
        if not isinstance(meta.get("pid"), int):
            errs.append("meta.pid missing/non-int")
        if not (isinstance(meta.get("argv"), list) and all(
                isinstance(a, str) for a in meta["argv"])):
            errs.append("meta.argv must be a list of strings")
        if not isinstance(meta.get("capacity"), int) \
                or meta.get("capacity", 0) < 1:
            errs.append("meta.capacity missing/non-positive")
    trig = doc.get("trigger")
    if not isinstance(trig, dict):
        errs.append("missing/non-object 'trigger' section")
    else:
        if not isinstance(trig.get("kind"), str) or not trig.get("kind"):
            errs.append("trigger.kind missing/empty")
        if not isinstance(trig.get("thread"), str) \
                or not trig.get("thread"):
            errs.append("trigger.thread missing/empty (the dump must "
                        "name the triggering thread)")
        if not isinstance(trig.get("tid"), int):
            errs.append("trigger.tid missing/non-int")
        if not _is_number(trig.get("t")):
            errs.append("trigger.t missing/non-numeric")
        for k in ("site", "detail", "exception"):
            if k in trig and not isinstance(trig[k], str):
                errs.append(f"trigger.{k} is not a string")
    ring = doc.get("ring")
    if not isinstance(ring, list):
        errs.append("missing/non-list 'ring' section")
    else:
        for i, e in enumerate(ring):
            if not isinstance(e, dict):
                errs.append(f"ring[{i}] is not an object")
                continue
            if not _is_number(e.get("t")):
                errs.append(f"ring[{i}].t missing/non-numeric")
            for k in ("kind", "name"):
                if not isinstance(e.get(k), str) or not e.get(k):
                    errs.append(f"ring[{i}].{k} missing/empty")
            if not isinstance(e.get("tid"), int):
                errs.append(f"ring[{i}].tid missing/non-int")
            for k, v in e.items():
                if not _is_scalar(v):
                    errs.append(f"ring[{i}].{k} is not scalar")
    if not (isinstance(doc.get("dropped"), int)
            and not isinstance(doc.get("dropped"), bool)
            and doc.get("dropped", -1) >= 0):
        errs.append("'dropped' must be a non-negative int")
    threads = doc.get("threads")
    if not isinstance(threads, list):
        errs.append("missing/non-list 'threads' section")
    else:
        for i, t in enumerate(threads):
            if not isinstance(t, dict):
                errs.append(f"threads[{i}] is not an object")
                continue
            if not isinstance(t.get("name"), str):
                errs.append(f"threads[{i}].name missing")
            if not isinstance(t.get("tid"), int):
                errs.append(f"threads[{i}].tid missing/non-int")
            if not (isinstance(t.get("stack"), list) and all(
                    isinstance(s, str) for s in t["stack"])):
                errs.append(f"threads[{i}].stack must be a list of "
                            "strings")
    if not isinstance(doc.get("levers"), dict):
        errs.append("missing/non-object 'levers' section")
    if not isinstance(doc.get("autotune"), dict):
        errs.append("missing/non-object 'autotune' section")
    reg = doc.get("registry")
    if not isinstance(reg, dict):
        errs.append("missing/non-object 'registry' section")
    else:
        errs.extend(f"registry: {e}" for e in validate_metrics(reg))
    errs.extend(_flight_seal_errors(doc))
    return errs


def validate_debug_bundle_manifest(doc) -> list[str]:
    """Validate a quorum-debug-bundle manifest: what the tarball
    holds, each entry typed, sized, and digest-stamped, so a bundle
    shipped across machines self-describes what made it into the
    postmortem (and what was missing at collection time)."""
    errs: list[str] = []
    if not isinstance(doc, dict):
        return ["bundle manifest is not a JSON object"]
    if doc.get("schema") != DEBUG_BUNDLE_SCHEMA:
        errs.append(f"schema is {doc.get('schema')!r}, "
                    f"expected {DEBUG_BUNDLE_SCHEMA!r}")
    meta = doc.get("meta")
    if not isinstance(meta, dict):
        errs.append("missing/non-object 'meta' section")
    else:
        for k, v in meta.items():
            ok = (_is_scalar(v)
                  or (isinstance(v, list)
                      and all(_is_scalar(x) for x in v)))
            if not ok:
                errs.append(f"meta[{k!r}] is not scalar/list")
    files = doc.get("files")
    if not isinstance(files, list):
        errs.append("missing/non-list 'files' section")
        return errs
    if not files:
        errs.append("'files' is empty — a bundle must hold at least "
                    "the artifact that motivated it")
    for i, f in enumerate(files):
        if not isinstance(f, dict):
            errs.append(f"files[{i}] is not an object")
            continue
        if not isinstance(f.get("name"), str) or not f.get("name"):
            errs.append(f"files[{i}].name missing/empty")
        if f.get("kind") not in BUNDLE_FILE_KINDS:
            errs.append(f"files[{i}].kind must be one of "
                        f"{BUNDLE_FILE_KINDS}, got {f.get('kind')!r}")
        if not (isinstance(f.get("bytes"), int)
                and not isinstance(f.get("bytes"), bool)
                and f.get("bytes", -1) >= 0):
            errs.append(f"files[{i}].bytes must be a non-negative int")
        if not isinstance(f.get("crc32c"), int) \
                or isinstance(f.get("crc32c"), bool):
            errs.append(f"files[{i}].crc32c missing/non-int")
        if "problems" in f and not (
                isinstance(f["problems"], int)
                and not isinstance(f["problems"], bool)
                and f["problems"] >= 0):
            errs.append(f"files[{i}].problems must be a non-negative "
                        "int")
    return errs


def validate_bench_line(obj) -> list[str]:
    """Validate one parsed bench-style metric line (the `metric_line`
    output format: `metric` (str) plus scalar fields)."""
    errs: list[str] = []
    if not isinstance(obj, dict):
        return ["bench line is not a JSON object"]
    if not isinstance(obj.get("metric"), str) or not obj.get("metric"):
        errs.append("missing/empty 'metric' field")
    for k, v in obj.items():
        if not _is_scalar(v):
            errs.append(f"bench field {k!r} is not scalar")
    return errs


def check_file(path: str) -> list[str]:
    """Validate any metrics artifact by path, dispatching on content:
    a whole-document metrics JSON (MetricsRegistry.write), a Chrome
    trace (SpanTracer.write_chrome_trace), an events or span .jsonl
    stream, or a bench-style metric-line file (one `{"metric": ...}`
    object per line, as bench.py emits)."""
    errs: list[str] = []
    try:
        with open(path) as f:
            text = f.read()
    except OSError as e:
        return [str(e)]
    try:
        doc = json.loads(text)
    except ValueError:
        doc = None
    if isinstance(doc, dict) and doc.get("schema") == PERF_DIFF_SCHEMA:
        return validate_perf_diff(doc)
    if isinstance(doc, dict) and doc.get("schema") == QUALITY_DIFF_SCHEMA:
        return validate_perf_diff(doc, schema=QUALITY_DIFF_SCHEMA)
    if isinstance(doc, dict) and doc.get("schema") == HISTO_SCHEMA:
        return validate_histo(doc)
    if isinstance(doc, dict) and doc.get("schema") == FLIGHT_SCHEMA:
        return validate_flight_dump(doc)
    if isinstance(doc, dict) and doc.get("schema") == DEBUG_BUNDLE_SCHEMA:
        return validate_debug_bundle_manifest(doc)
    if (isinstance(doc, dict)
            and ("schema" in doc or "counters" in doc)
            and "metric" not in doc and "event" not in doc):
        return validate_metrics(doc)
    if isinstance(doc, dict) and "traceEvents" in doc:
        return validate_chrome_trace(doc)
    # line-oriented: events JSONL, span JSONL, and/or bench metric
    # lines (a bench run interleaves kinds through one stdout)
    any_line = False
    for i, line in enumerate(text.splitlines(), 1):
        line = line.strip()
        if not line or line.startswith("#"):
            continue
        any_line = True
        try:
            obj = json.loads(line)
        except ValueError as e:
            errs.append(f"line {i}: invalid JSON ({e})")
            continue
        if isinstance(obj, dict) and "metric" in obj:
            check = validate_bench_line
        elif isinstance(obj, dict) and "span" in obj:
            check = validate_span_line
        else:
            check = validate_events_line
        errs.extend(f"line {i}: {e}" for e in check(obj))
    if not any_line:
        errs.append("no metrics content found")
    return errs


def metric_line(metric: str, **fields) -> str:
    """One bench-style JSON line (`{"metric": ..., ...}`) with the
    field types checked — bench.py emits through this so BENCH_*.json
    stays schema-consistent across rounds. Values must be scalars."""
    if not metric or not isinstance(metric, str):
        raise ValueError("metric name must be a non-empty string")
    obj = {"metric": metric}
    for k, v in fields.items():
        if not _is_scalar(v):
            raise ValueError(
                f"metric_line field {k!r} is not a scalar: {type(v)}")
        obj[k] = v
    return json.dumps(obj)
