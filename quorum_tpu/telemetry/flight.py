"""Flight recorder: a black box for wedged and dying runs (ISSUE 16).

The telemetry stack observes runs through final documents and
heartbeats — but the moments that matter most are exactly the ones
where neither arrives: a wedged dispatch loop, a watchdog-killed
engine step, an uncaught exception mid-batch. Dapper-style always-on
bounded-overhead tracing (PAPERS.md) is the blueprint: keep the last
window of truth resident at near-zero cost, dump it only when
something goes wrong.

:class:`FlightRecorder` is a lock-light ring buffer (fixed-capacity
deque) of recent telemetry: every registry event (run manifest,
heartbeats, checkpoint cursors, serve request-phase transitions,
fault injections), span open/close edges, per-batch dispatch/wait
samples, and — under ``QUORUM_TSAN=1`` — lock acquisitions. It is
installed by ``cli/observability.observability()`` in every entry
point and fed by taps inside the existing event sink and span tracer
(``MetricsRegistry.event`` / ``SpanTracer._record``), so instrumented
code needs no new call sites.

On a trigger — an uncaught exception in the observability umbrella, a
serve watchdog ``EngineStepTimeout`` or dispatcher crash, an alert
rule with ``dump: true``, or ``SIGUSR1`` — :meth:`FlightRecorder.dump`
writes an atomic, sealed (io/integrity crc32c), self-describing dump
document (schema ``quorum-tpu-flight/1``): the ring contents,
all-thread Python stacks (``sys._current_frames``), resolved lever
values, the active autotune profile, and a registry snapshot. Exactly
one dump lands per incident (the first trigger wins; ``SIGUSR1``
forces). ``quorum-serve`` additionally snapshots a live replica via
loopback-only ``GET /debug/flight``; ``tools/trace_summary.py
--flight`` renders a dump as a timeline with the triggering thread
highlighted; ``quorum-debug-bundle`` collects dump + metrics + fsck
verdicts into one postmortem tarball.

Levers: ``QUORUM_FLIGHT`` (0 disables the recorder entirely),
``QUORUM_FLIGHT_RING`` (ring capacity), ``QUORUM_FLIGHT_DIR`` (dump
directory override). Contract counters: ``flight_dumps_total`` /
``flight_events_dropped_total`` (telemetry/contract.py). The ring
lock is ranked in analysis/rules_locks.LOCK_ORDER; taps run OUTSIDE
the registry/tracer locks so the ring lock never nests inside them.
"""

from __future__ import annotations

import contextlib
import json
import os
import signal
import sys
import threading
import time
import traceback
from collections import deque

from ..utils import faults, levers

DUMP_SCHEMA = "quorum-tpu-flight/1"
BUNDLE_SCHEMA = "quorum-tpu-debug-bundle/1"
DEFAULT_RING = 4096


def default_out_path(metrics_path: str | None) -> str | None:
    """Where a dump lands: ``QUORUM_FLIGHT_DIR`` when set (one file
    per pid, so fleet hosts sharing a directory never collide), else
    the `--metrics` sibling ``<base>.flight.json``, else None — a run
    with no metrics path and no explicit directory has nowhere
    durable to dump, so triggers only feed the in-memory ring (still
    served by ``GET /debug/flight``)."""
    d = levers.raw("QUORUM_FLIGHT_DIR")
    if d:
        return os.path.join(d, f"flight-{os.getpid()}.json")
    if metrics_path:
        base = (metrics_path[:-5] if metrics_path.endswith(".json")
                else metrics_path)
        return base + ".flight.json"
    return None


class FlightRecorder:
    """One per observability session. `record()` is the only hot
    surface: a TLS re-entrancy check, one small dict build, and one
    deque append under `_lock` — per-event/per-span/per-batch cost,
    never per-base. Everything expensive (stack walks, lever
    resolution, sealing, IO) happens only in `dump()`."""

    def __init__(self, registry, out_path: str | None = None,
                 capacity: int | None = None):
        self.enabled = levers.get_bool("QUORUM_FLIGHT", True)
        if capacity is None:
            try:
                capacity = int(levers.raw("QUORUM_FLIGHT_RING")
                               or DEFAULT_RING)
            except ValueError:
                capacity = DEFAULT_RING
        self.capacity = max(16, capacity)
        self.out_path = out_path
        self.registry = registry
        self._t0 = time.perf_counter()
        self._lock = threading.Lock()
        self._ring: deque = deque(maxlen=self.capacity)
        self._seq = 0               # total records offered
        self._dropped_flushed = 0   # drops already counted
        self._dumped = False
        self.last_dump_path: str | None = None
        self._tls = threading.local()
        # contract counters pre-created so a clean run's final
        # document carries them at 0 (telemetry/contract.py)
        self._dumps = registry.counter("flight_dumps_total")
        self._drops = registry.counter("flight_events_dropped_total")

    @contextlib.contextmanager
    def _held(self):
        """Take the ring lock with the TLS re-entrancy flag raised:
        under QUORUM_TSAN=1 the lock hook observes this very
        acquisition and re-enters :meth:`record` on the same thread,
        which must bail out (the record() guard), never block on the
        lock it is reporting. EVERY internal acquisition of
        ``_lock`` goes through here or through record() itself."""
        tls = self._tls
        prev = getattr(tls, "busy", False)
        tls.busy = True
        try:
            with self._lock:
                yield
        finally:
            tls.busy = prev

    # -- the hot surface ---------------------------------------------------
    def record(self, kind: str, name: str, **fields) -> None:
        """Append one ring entry. Values are stored by reference and
        sanitized to scalars only at dump time. Re-entrancy (a tap
        firing while a record is already in flight on this thread —
        the TSAN hook observing the ring lock's own acquisition) is a
        silent drop, never a deadlock."""
        if not self.enabled:
            return
        tls = self._tls
        if getattr(tls, "busy", False):
            return
        tls.busy = True
        try:
            obj = {"t": round(time.perf_counter() - self._t0, 6),
                   "kind": kind, "name": name,
                   "tid": threading.get_ident()}
            if fields:
                obj.update(fields)
            with self._lock:
                self._seq += 1
                self._ring.append(obj)
        finally:
            tls.busy = False

    # -- snapshots ---------------------------------------------------------
    def _sanitize(self, entries: list) -> list:
        from .registry import _scalar
        return [{k: _scalar(v) for k, v in e.items()} for e in entries]

    def _thread_stacks(self) -> list[dict]:
        names = {t.ident: t.name for t in threading.enumerate()}
        out = []
        for ident, frame in sys._current_frames().items():
            out.append({
                "name": names.get(ident, "<unknown>"),
                "tid": ident,
                "stack": [ln.rstrip("\n") for ln in
                          traceback.format_stack(frame)],
            })
        return out

    def _lever_values(self) -> dict:
        vals = {}
        for name in levers.names():
            lv = levers.CATALOG[name]
            env = levers.raw(name)
            vals[name] = {"value": env, "default": lv.default}
        return vals

    def _autotune_profile(self) -> dict:
        prof: dict = {}
        try:
            from ..ops import tuning
            path = tuning.active_profile_path()
            if path:
                prof["path"] = path
                with open(path) as f:
                    prof["profile"] = json.load(f)
        except Exception:  # noqa: BLE001 - forensics never kill dumps
            pass
        return prof

    def snapshot(self, trigger: dict | None = None) -> dict:
        """The full (unsealed) dump document — also what
        ``GET /debug/flight`` serves from a live replica."""
        with self._held():
            ring = list(self._ring)
            seq = self._seq
        doc = {
            "schema": DUMP_SCHEMA,
            "meta": {
                "pid": os.getpid(),
                "argv": list(sys.argv),
                "capacity": self.capacity,
                "stage": self.registry.as_dict().get(
                    "meta", {}).get("stage"),
            },
            "trigger": trigger or {"kind": "snapshot",
                                   "thread": threading.current_thread().name,
                                   "tid": threading.get_ident(),
                                   "t": round(time.perf_counter()
                                              - self._t0, 6)},
            "ring": self._sanitize(ring),
            "dropped": max(0, seq - len(ring)),
            "threads": self._thread_stacks(),
            "levers": self._lever_values(),
            "autotune": self._autotune_profile(),
            "registry": self.registry.as_dict(),
        }
        return doc

    def _make_trigger(self, kind: str, detail: str,
                      site: str | None) -> dict:
        trig = {
            "kind": kind,
            "detail": detail,
            "thread": threading.current_thread().name,
            "tid": threading.get_ident(),
            "t": round(time.perf_counter() - self._t0, 6),
        }
        if site:
            trig["site"] = site
        exc = sys.exc_info()[1]
        if exc is not None:
            trig["exception"] = repr(exc)
            trig["exc_stack"] = [
                ln.rstrip("\n")
                for ln in traceback.format_exception(exc)]
        return trig

    # -- the cold surface --------------------------------------------------
    def dump(self, kind: str, detail: str = "",
             site: str | None = None, force: bool = False,
             out_path: str | None = None) -> str | None:
        """Write the sealed dump document (atomic replace). Exactly
        one dump lands per incident: the first trigger wins and later
        ones return the existing path — an operator `SIGUSR1`
        (`force=True`) overrides. Returns the path written, or None
        when the recorder is disabled or has nowhere to write."""
        if not self.enabled:
            return None
        out = out_path or self.out_path
        if not out:
            # still note the trigger in the ring: a later /debug/flight
            # snapshot of a pathless replica shows what fired
            self.record("trigger", kind, detail=detail, site=site)
            return None
        with self._held():
            if self._dumped and not force:
                return self.last_dump_path
            self._dumped = True
        from ..io import integrity
        from .registry import atomic_write
        doc = self.snapshot(self._make_trigger(kind, detail, site))
        doc = integrity.seal(doc)
        atomic_write(out, json.dumps(doc, indent=1) + "\n")
        self.last_dump_path = out
        self._dumps.inc()
        self.flush_drop_counter()
        self.registry.event("flight_dump", path=out, trigger=kind,
                            site=site or "")
        faults.inject("flight.dump", path=out)
        return out

    def flush_drop_counter(self) -> None:
        """Land ring evictions in `flight_events_dropped_total` —
        called at dump time and at session teardown, so a clean run's
        final document says how much history the window forgot."""
        with self._held():
            dropped = max(0, self._seq - len(self._ring))
            delta = dropped - self._dropped_flushed
            self._dropped_flushed = dropped
        if delta > 0:
            self._drops.inc(delta)


# -- ambient installation --------------------------------------------------
# One recorder is "current" per process (nested observability blocks —
# the driver's stage children — stack and restore, like
# io/integrity.install_registry). Serve internals (watchdog,
# dispatcher-crash handler, /debug/flight, alert dump rules) reach it
# through current() so no constructor threading is needed.

_CURRENT: FlightRecorder | None = None


def current() -> FlightRecorder | None:
    return _CURRENT


def try_dump(kind: str, detail: str = "", site: str | None = None,
             force: bool = False) -> str | None:
    """Dump via the current recorder; IO/forensics failures never
    propagate into the triggering path (a dying run must keep dying
    for its real reason). A seeded `flight.dump` fault does propagate
    — that is the point of the site."""
    rec = _CURRENT
    if rec is None:
        return None
    try:
        return rec.dump(kind, detail=detail, site=site, force=force)
    except faults.FaultError:
        raise
    except Exception:  # noqa: BLE001 - forensics never kill runs
        return None


def _sigusr1(_signum, _frame) -> None:
    try:
        try_dump("sigusr1", detail="operator SIGUSR1", force=True)
    except Exception:  # noqa: BLE001 - signal handlers never raise
        pass


def install(rec: FlightRecorder):
    """Make `rec` the process-current recorder: SIGUSR1 dumps it and,
    under QUORUM_TSAN=1, lock acquisitions feed its ring. Returns an
    opaque token for :func:`uninstall` (nest/restore)."""
    global _CURRENT
    prev = _CURRENT
    _CURRENT = rec
    prev_handler = None
    if rec.enabled and hasattr(signal, "SIGUSR1"):
        try:
            prev_handler = signal.getsignal(signal.SIGUSR1)
            signal.signal(signal.SIGUSR1, _sigusr1)
        except (ValueError, OSError):
            prev_handler = None  # not the main thread
    prev_hook = None
    if rec.enabled:
        try:
            from ..analysis import tsan
            if tsan.installed():
                prev_hook = tsan.set_flight_hook(
                    lambda site: rec.record("lock", site))
        except Exception:  # noqa: BLE001 - sanitizer hook is best-effort
            prev_hook = None
    return (prev, prev_handler, prev_hook)


def uninstall(token) -> None:
    global _CURRENT
    prev, prev_handler, prev_hook = token
    rec = _CURRENT
    if rec is not None:
        try:
            rec.flush_drop_counter()
        except Exception:  # noqa: BLE001 - teardown never raises
            pass
    _CURRENT = prev
    if prev_handler is not None and hasattr(signal, "SIGUSR1"):
        try:
            signal.signal(signal.SIGUSR1, prev_handler)
        except (ValueError, OSError):
            pass
    try:
        from ..analysis import tsan
        if tsan.installed():
            tsan.set_flight_hook(prev_hook)
    except Exception:  # noqa: BLE001 - sanitizer hook is best-effort
        pass
