"""Alert rules over the live registry: the layer that WATCHES the
signals (ISSUE 11).

PRs 1/2/10 made every layer of the pipeline report what it is doing —
counters, heartbeats, push export, device-truth kernel attribution —
but nothing acted on the reports: a stalled pipeline or a burning
serve SLO looked exactly like a healthy run to everything except a
human reading JSONL. This module closes that loop with a small
declarative rule engine evaluated periodically against the run's own
`MetricsRegistry`, on the same heartbeat cadence the exporters already
use (plus a ticker thread, because a STALLED run is precisely the one
that stops heartbeating).

Rule kinds (JSON objects, loaded from `--alert-rules FILE` on top of
built-in defaults):

* ``threshold`` — compare a metric to a bound every evaluation::

      {"name": "integrity_errors", "type": "threshold",
       "metric": "counters.integrity_errors_total",
       "op": ">", "value": 0}

  Metric addresses are ``counters.NAME``, ``gauges.NAME``, or
  ``histograms.NAME.count|sum|mean``. A metric that has not appeared
  yet simply keeps the rule quiet (and can never crash the
  evaluation thread — a bad address is counted in
  ``alert_rule_errors_total`` instead of raised).

* ``rate`` — the per-second increase of a counter over a sliding
  window::

      {"name": "push_failing", "type": "rate",
       "metric": "counters.metrics_push_failures_total",
       "window_s": 300, "op": ">", "value": 0.2}

* ``absence`` — no sign of life for ``for_s`` seconds. Without a
  ``metric`` the sign of life is the registry heartbeat itself
  (every ``heartbeat()`` call notifies the engine through the
  exporter hook); with one, the metric's value must CHANGE within
  the window. This is the stalled-pipeline rule: the batch loops
  heartbeat per batch, so a wedged device step goes quiet and the
  ticker fires the alert mid-stall — and the next completed batch
  heals it. Heartbeat-absence ARMS on the first beat: a registry
  that never heartbeats at all (the quorum driver's manifest
  registry idles while its stages do the heartbeating in their own
  registries) is out of scope rather than a guaranteed false page
  at ``for_s`` — its stages' engines carry the stall watch.

* ``burn_rate`` — multi-window SLO burn (the Google SRE workbook
  shape): the error ratio over each window, divided by the SLO's
  error budget, must exceed the window's factor in EVERY window for
  the rule to fire (long window = real burn, short window = still
  burning). Error ratios come from counters
  (``bad``/``total`` lists) or from a latency histogram
  (``hist`` + ``above_us`` — use a LOW-CARDINALITY histogram like
  the log-quantized ``request_e2e_bucket_us`` the serve layer
  records via ``latency_bucket_us``; a raw exact-microsecond
  histogram like ``request_us`` trips Histogram's 512-key guard and
  its overflowed observations cannot be budget-attributed)::

      {"name": "serve_slo_availability", "type": "burn_rate",
       "objective": 0.999,
       "bad": ["requests_failed", "requests_deadline_exceeded"],
       "total": ["requests_completed", "requests_failed",
                 "requests_deadline_exceeded"],
       "windows": [[3600, 1.0], [300, 6.0]]}

Firing rules land a structured ``alert`` event in the JSONL stream
(``rule``/``state``/``value``/``detail``), flip the
``alerts_firing{rule=...}`` gauge to 1 (back to 0 on heal — the
gauges are pre-created at 0 so every document carries the surface),
and count ``alerts_fired_total``. The serve layer additionally
surfaces `summary()`/`slo_status()` in ``/healthz`` detail WITHOUT
touching liveness: a burning SLO needs attention, not ejection.

Everything here is best-effort by construction: rule evaluation never
raises out of the engine, and a closed engine goes inert (so no event
can land after the registry's event sink closed).
"""

from __future__ import annotations

import json
import threading
import time

from .registry import labeled

# the built-in rule set every instrumented run evaluates; a
# `--alert-rules` file overrides by name (or removes with
# {"name": ..., "disable": true})
DEFAULT_RULES = [
    # no heartbeat for 5 minutes AFTER the first one: the pipeline
    # stalled (a wedged device step, a hung producer) — the batch
    # loops heartbeat per batch, so silence IS the signal (and a
    # registry that never heartbeats, like the driver manifest, never
    # arms — no false page on long multi-stage runs)
    {"name": "pipeline_stalled", "type": "absence", "for_s": 300.0,
     "severity": "page"},
    # any artifact failed its digests (ISSUE 8) — never routine
    {"name": "integrity_errors", "type": "threshold",
     "metric": "counters.integrity_errors_total", "op": ">",
     "value": 0, "severity": "page"},
    # the driver is retrying stages: the run is limping
    {"name": "stage_retries", "type": "threshold",
     "metric": "counters.stage_retries_total", "op": ">", "value": 0,
     "severity": "warn"},
    # the push transport is failing faster than its retry absorbs
    {"name": "push_failing", "type": "rate",
     "metric": "counters.metrics_push_failures_total",
     "window_s": 300.0, "op": ">", "value": 0.2, "severity": "warn"},
]

# the serve SLO surface (appended when meta.stage == "serve"): a
# multi-window availability burn over the batcher's terminal-status
# counters, and a deadline-budget burn over the request ledger's
# end-to-end latency (ISSUE 10). The latency rule reads the
# QUANTIZED `request_e2e_bucket_us` histogram the server records per
# 200 (serve/server.py via latency_bucket_us below) — the exact-count
# `request_us` histogram blows Histogram's 512-key cardinality guard
# within a few hundred requests, after which over-budget observations
# vanish into the "overflow" key and a rule reading it goes blind.
DEFAULT_SERVE_RULES = [
    # a serve replica heartbeats per served BATCH, so silence is the
    # normal idle state, not a stall — the generic absence page would
    # fire on every quiet replica 5 minutes after its last request.
    # Serve health is the SLO rules' + the engine watchdog's job; a
    # rules file can re-add an absence rule deliberately.
    {"name": "pipeline_stalled", "disable": True},
    {"name": "serve_slo_availability", "type": "burn_rate",
     "objective": 0.999,
     "bad": ["requests_failed", "requests_deadline_exceeded"],
     "total": ["requests_completed", "requests_failed",
               "requests_deadline_exceeded"],
     "windows": [[3600.0, 1.0], [300.0, 6.0]], "severity": "page"},
    {"name": "serve_slo_latency", "type": "burn_rate",
     "objective": 0.99, "hist": "request_e2e_bucket_us",
     "above_us": 2_000_000,
     "windows": [[3600.0, 1.0], [300.0, 6.0]], "severity": "warn"},
]


# the input-drift surface (appended on EVERY instrumented run, ISSUE
# 17): threshold rules over the windowed `quality_*` gauges a
# QualityScorecard refreshes per batch window. The scorecard
# pre-creates every gauge at its QUIET value (rates 0, ratios 1.0)
# and stage-1 builds close no data windows, so the rules cost
# nothing where they cannot apply; on a registry with no scorecard
# at all the metrics are absent, which also keeps threshold rules
# quiet. All three dump: a quality regression mid-run is exactly the
# trajectory the flight ring should preserve (ISSUE 16).
DEFAULT_QUALITY_RULES = [
    # the worst normalized deviation of any windowed rate from its
    # EWMA baseline — 4.0 means "this window sits 4 baselines away",
    # loose enough for shot noise on small windows, tight enough that
    # a chemistry change or bad flowcell tile pages within a window
    {"name": "quality_drift", "type": "threshold",
     "metric": "gauges.quality_drift_score", "op": ">", "value": 4.0,
     "severity": "warn", "dump": True},
    # more than 20% of a window's reads hitting the contaminant
    # screen is a library-prep or sample-swap event, not noise
    {"name": "contam_spike", "type": "threshold",
     "metric": "gauges.quality_contam_rate", "op": ">", "value": 0.2,
     "severity": "page", "dump": True},
    # observed trusted-anchor rate below half of what the DB header's
    # poisson_stats predict: the reads do not match the database
    # (wrong reference DB, or coverage collapsed)
    {"name": "coverage_drop", "type": "threshold",
     "metric": "gauges.quality_coverage_ratio", "op": "<",
     "value": 0.5, "severity": "page", "dump": True},
]


# the resource-exhaustion surface (appended whenever the resource
# guard's monitor is live, ISSUE 19): standing rules over the
# monitor's disk gauges and the degradation ladder's counter. The
# scalar `disk_free_bytes_min` is the minimum across every watched
# mount (threshold rules are exact-name lookups; the per-path
# `disk_free_bytes{path=}` gauges are for humans and dashboards).
# Thresholds are deliberately generic floors, not per-run estimates —
# the per-run sizing question is preflight's job before work starts.
DEFAULT_RESOURCE_RULES = [
    # under ~2 GiB free on some watched mount: the operator still has
    # time to clean up or move the checkpoint dir before writers fail
    {"name": "disk_low", "type": "threshold",
     "metric": "gauges.disk_free_bytes_min", "op": "<",
     "value": float(2 << 30), "severity": "warn"},
    # under ~256 MiB: exhaustion is imminent — page, and seal the
    # flight ring while the process can still write somewhere
    {"name": "disk_exhausted", "type": "threshold",
     "metric": "gauges.disk_free_bytes_min", "op": "<",
     "value": float(256 << 20), "severity": "page", "dump": True},
    # the degradation ladder disabled an optional writer: the run is
    # still producing byte-identical primary output, but its
    # checkpoints/traces/caches are silently gone — never routine
    {"name": "writer_degraded", "type": "threshold",
     "metric": "counters.writer_degraded_total", "op": ">", "value": 0,
     "severity": "warn"},
]


def latency_bucket_us(us) -> int:
    """Quarter-octave log quantization for latency histograms: four
    buckets per power of two, <= ~160 distinct keys from 1 µs to
    60 s — safely inside Histogram.MAX_KEYS, where exact-microsecond
    values overflow within a few hundred requests. Rounds DOWN to the
    bucket floor, so a budget comparison against the bucketed value
    under-reports by at most one sub-bucket (~19%) — set `above_us`
    with that margin in mind."""
    us = int(us)
    if us <= 4:
        return max(us, 0)
    base = 1 << (us.bit_length() - 1)
    step = base >> 2
    return base + (us - base) // step * step

_RULE_TYPES = ("threshold", "rate", "absence", "burn_rate")
_OPS = {
    ">": lambda a, b: a > b,
    ">=": lambda a, b: a >= b,
    "<": lambda a, b: a < b,
    "<=": lambda a, b: a <= b,
    "==": lambda a, b: a == b,
    "!=": lambda a, b: a != b,
}


def load_rules(path: str) -> list[dict]:
    """Parse a rules file: a JSON list of rule objects, or
    ``{"rules": [...]}``. Raises ValueError on malformed input (the
    CALLER decides whether that is fatal — observability() reports it
    loudly and falls back to the defaults, because telemetry never
    kills runs)."""
    with open(path) as f:
        obj = json.load(f)
    if isinstance(obj, dict) and isinstance(obj.get("rules"), list):
        obj = obj["rules"]
    if not isinstance(obj, list):
        raise ValueError(f"{path}: alert rules must be a JSON list "
                         "(or {'rules': [...]})")
    for r in obj:
        if not isinstance(r, dict) or not r.get("name"):
            raise ValueError(f"{path}: every rule needs a 'name'")
    return obj


def merge_rules(*rule_lists) -> list[dict]:
    """Later lists override earlier ones by rule name; a rule with
    ``disable: true`` removes the name entirely."""
    out: dict[str, dict] = {}
    for rules in rule_lists:
        for r in rules or ():
            name = str(r.get("name"))
            if r.get("disable"):
                out.pop(name, None)
            else:
                out[name] = r
    return list(out.values())


def _read_metric(reg, addr: str):
    """Resolve ``counters.X`` / ``gauges.X`` / ``histograms.X.FIELD``
    against the live registry WITHOUT creating the metric. Returns a
    float, or None when the metric has not appeared. Raises ValueError
    on a malformed address (counted as a rule error, not raised out
    of evaluate)."""
    parts = addr.split(".")
    if len(parts) < 2:
        raise ValueError(f"bad metric address {addr!r}")
    kind, name = parts[0], ".".join(parts[1:])
    if kind == "counters":
        m = reg._counters.get(name)
        return None if m is None else float(m.value)
    if kind == "gauges":
        m = reg._gauges.get(name)
        return None if m is None else float(m.value)
    if kind == "histograms":
        name, _, field = name.rpartition(".")
        if not name or field not in ("count", "sum", "mean"):
            raise ValueError(f"bad histogram address {addr!r} "
                             "(histograms.NAME.count|sum|mean)")
        h = reg._hists.get(name)
        if h is None:
            return None
        if field == "count":
            return float(h.count)
        if field == "sum":
            return float(h.sum)
        return float(h.sum) / h.count if h.count else 0.0
    raise ValueError(f"bad metric address {addr!r} "
                     "(counters.|gauges.|histograms.)")


def _hist_above(reg, name: str, above: float) -> tuple[float, float]:
    """(count_above, count_attributable) of an exact-count histogram
    — the error series for latency-budget burn rules. Observations
    that landed in the cardinality-guard "overflow" key carry no
    value and are excluded from BOTH sides (counting them only in
    the total would silently dilute the ratio toward zero on a
    high-cardinality histogram); feed these rules a quantized
    histogram (latency_bucket_us) so nothing overflows at all."""
    h = reg._hists.get(name)
    if h is None:
        return 0.0, 0.0
    with h._lock:
        counts = dict(h.counts)
    bad = known = 0
    for v, n in counts.items():
        if isinstance(v, int):
            known += n
            if v > above:
                bad += n
    return float(bad), float(known)


class _Rule:
    """One parsed rule plus its evaluation state."""

    def __init__(self, spec: dict):
        self.spec = dict(spec)
        self.name = str(spec["name"])
        self.type = spec.get("type")
        if self.type not in _RULE_TYPES:
            raise ValueError(f"rule {self.name!r}: unknown type "
                             f"{self.type!r} (one of {_RULE_TYPES})")
        self.severity = str(spec.get("severity", "warn"))
        # `dump: true` — a firing transition additionally triggers a
        # flight-recorder crash dump (telemetry/flight.py): the alert
        # that says "this run is dying" also captures why
        self.dump = bool(spec.get("dump"))
        self.firing = False
        self.fired_count = 0
        self.error_reported = False
        # sliding-window sample history: [(t, (v0, v1, ...)), ...]
        self.samples: list[tuple[float, tuple]] = []
        # absence bookkeeping
        self.last_value = None
        self.last_change: float | None = None
        # burn-rate reporting (slo_status)
        self.burns: dict[str, float] = {}
        if self.type == "threshold":
            self.metric = str(spec["metric"])
            self.op = str(spec.get("op", ">"))
            if self.op not in _OPS:
                raise ValueError(f"rule {self.name!r}: bad op "
                                 f"{self.op!r}")
            self.value = float(spec["value"])
        elif self.type == "rate":
            self.metric = str(spec["metric"])
            self.op = str(spec.get("op", ">"))
            if self.op not in _OPS:
                raise ValueError(f"rule {self.name!r}: bad op "
                                 f"{self.op!r}")
            self.value = float(spec["value"])
            self.window_s = float(spec.get("window_s", 300.0))
        elif self.type == "absence":
            self.metric = spec.get("metric")
            self.for_s = float(spec.get("for_s", 300.0))
        else:  # burn_rate
            objective = float(spec.get("objective", 0.999))
            if not 0.0 < objective < 1.0:
                raise ValueError(f"rule {self.name!r}: objective must "
                                 "be in (0, 1)")
            self.budget = 1.0 - objective
            self.windows = [(float(w), float(f))
                            for w, f in spec.get(
                                "windows", [[3600.0, 1.0], [300.0, 6.0]])]
            if not self.windows:
                raise ValueError(f"rule {self.name!r}: needs windows")
            self.hist = spec.get("hist")
            self.above_us = float(spec.get("above_us", 0))
            self.bad = list(spec.get("bad", ()))
            self.total = list(spec.get("total", ()))
            if self.hist is None and (not self.bad or not self.total):
                raise ValueError(f"rule {self.name!r}: burn_rate "
                                 "needs bad+total counters or "
                                 "hist+above_us")

    # -- sampling ---------------------------------------------------------
    def _sample(self, now: float, values: tuple) -> None:
        self.samples.append((now, values))
        if self.type == "burn_rate":
            longest = max(w for w, _f in self.windows)
        else:
            longest = self.window_s
        cut = now - (longest * 1.25 + 1.0)
        while len(self.samples) > 2 and self.samples[1][0] <= cut:
            self.samples.pop(0)

    def _at(self, now: float, window_s: float) -> tuple | None:
        """The newest sample at or before now - window_s, falling back
        to the oldest sample (burn over available history — standard
        for engines younger than their longest window)."""
        if not self.samples:
            return None
        target = now - window_s
        best = None
        for t, v in self.samples:
            if t <= target:
                best = (t, v)
            else:
                break
        return best or self.samples[0]

    # -- evaluation -------------------------------------------------------
    def check(self, reg, now: float, beat_age: float):
        """-> (firing: bool, value: float, detail: str)."""
        if self.type == "threshold":
            v = _read_metric(reg, self.metric)
            if v is None:
                return False, 0.0, "metric absent"
            return (_OPS[self.op](v, self.value), v,
                    f"{self.metric} {self.op} {self.value}")
        if self.type == "rate":
            v = _read_metric(reg, self.metric)
            if v is None:
                return False, 0.0, "metric absent"
            self._sample(now, (v,))
            prev = self._at(now, self.window_s)
            dt = now - prev[0]
            if dt <= 0:
                return False, 0.0, "no history"
            rate = (v - prev[1][0]) / dt
            return (_OPS[self.op](rate, self.value), rate,
                    f"d({self.metric})/dt over {self.window_s}s "
                    f"{self.op} {self.value}/s")
        if self.type == "absence":
            if self.metric is None:
                if beat_age is None:  # never armed: no beat ever seen
                    return False, 0.0, "no heartbeat yet (unarmed)"
                age = beat_age
                detail = f"no heartbeat for {age:.1f}s (> {self.for_s}s)"
            else:
                v = _read_metric(reg, self.metric)
                if v != self.last_value:
                    self.last_value = v
                    self.last_change = now
                age = now - (self.last_change
                             if self.last_change is not None else now)
                detail = (f"{self.metric} unchanged for {age:.1f}s "
                          f"(> {self.for_s}s)")
            return age > self.for_s, age, detail
        # burn_rate
        if self.hist is not None:
            bad, total = _hist_above(reg, self.hist, self.above_us)
        else:
            bad = sum(_read_metric(reg, f"counters.{c}") or 0.0
                      for c in self.bad)
            total = sum(_read_metric(reg, f"counters.{c}") or 0.0
                        for c in self.total)
        self._sample(now, (bad, total))
        firing = bool(self.samples)
        worst = 0.0
        details = []
        for window_s, factor in self.windows:
            prev = self._at(now, window_s)
            d_bad = bad - prev[1][0]
            d_total = total - prev[1][1]
            ratio = d_bad / d_total if d_total > 0 else 0.0
            burn = ratio / self.budget if self.budget > 0 else 0.0
            self.burns[f"{window_s:g}s"] = round(burn, 4)
            worst = max(worst, burn)
            details.append(f"{window_s:g}s burn {burn:.2f} "
                           f"(need >= {factor:g})")
            if burn < factor:
                firing = False
        return firing, worst, "; ".join(details)


class AlertEngine:
    """The evaluator: rules + state over ONE registry.

    `attach(period_s)` wires it into the registry's exporter
    notifications (heartbeat cadence — exporters self-rate-limit) and
    starts the ticker daemon thread that keeps evaluating while the
    run is silent (the absence case). `now` is injectable for
    mocked-clock tests; the ticker is real-time and only started by
    `attach`, so tests drive `evaluate()` directly.
    """

    def __init__(self, registry, rules: list[dict] | None = None,
                 now=time.monotonic):
        self.registry = registry
        self._now = now
        self._lock = threading.RLock()
        self._closed = False
        self._thread = None
        self._stop = threading.Event()
        self._period = 5.0
        self._last_eval = -1e18
        # None until the first beat: heartbeat-absence rules ARM on
        # real activity, so a registry that never heartbeats (the
        # driver manifest) cannot false-fire at for_s
        self._last_beat: float | None = None
        self.rules: list[_Rule] = []
        bad: list[str] = []
        for spec in (rules if rules is not None else DEFAULT_RULES):
            try:
                self.rules.append(_Rule(spec))
            except (KeyError, TypeError, ValueError) as e:
                bad.append(f"{spec.get('name', '?')}: {e}")
        reg = registry
        if getattr(reg, "enabled", False):
            # the surface exists from setup, zeros included, so
            # metrics_check can require the names whenever meta
            # declares alert rules active
            reg.counter("alerts_fired_total")
            errs = reg.counter("alert_rule_errors_total")
            for msg in bad:
                errs.inc()
                reg.event("alert_rule_error", error=msg)
            reg.gauge("alert_rules_active").set(len(self.rules))
            for rule in self.rules:
                reg.gauge(labeled("alerts_firing",
                                  rule=rule.name)).set(0)
            reg.set_meta(alert_rules=[r.name for r in self.rules])

    # -- liveness + cadence -----------------------------------------------
    def beat(self) -> None:
        """A sign of life from the run (every exporter notification —
        i.e. every registry heartbeat — counts)."""
        self._last_beat = self._now()

    def _exporter(self, reg, final: bool = False) -> None:
        """Registered via registry.add_exporter: called on every
        heartbeat (rate-limited here) and once at the final write —
        which is what heals an absence rule on a clean exit (a
        finished run is not a stalled one)."""
        if self._closed:
            return
        self.beat()
        now = self._now()
        if final or now - self._last_eval >= self._period:
            self.evaluate()

    def attach(self, period_s: float | None = None) -> None:
        """Start periodic evaluation: exporter hook (heartbeat
        cadence) plus the ticker thread that fires while the run is
        silent."""
        if period_s and period_s > 0:
            self._period = float(period_s)
        self.registry.add_exporter(self._exporter)
        if self._thread is None:
            self._thread = threading.Thread(
                target=self._tick_loop, name="quorum-alerts",
                daemon=True)
            self._thread.start()

    def _tick_loop(self) -> None:
        while not self._stop.wait(self._period):
            try:
                self.evaluate()
            except Exception:  # noqa: BLE001 - never kill the ticker
                # evaluate() is designed not to raise, so anything
                # landing here is an engine bug — count it (the
                # quorum-lint thread-swallowed-exception class: a
                # silently degrading ticker means a stalled run stops
                # alerting, which is exactly what the ticker exists
                # to catch)
                try:
                    self.registry.counter(
                        "alert_rule_errors_total").inc()
                except Exception:  # noqa: BLE001  # qlint: disable=thread-swallowed-exception
                    pass  # counting failed too: registry torn down

    # -- evaluation -------------------------------------------------------
    def evaluate(self) -> list[str]:
        """One pass over every rule; returns the names currently
        firing. Never raises: a rule whose metric address is
        malformed (or whose evaluation explodes) is counted in
        `alert_rule_errors_total` once and skipped — the heartbeat
        thread must survive any rules file."""
        reg = self.registry
        with self._lock:
            if self._closed or not getattr(reg, "enabled", False):
                return [r.name for r in self.rules if r.firing]
            now = self._now()
            self._last_eval = now
            beat_age = (None if self._last_beat is None
                        else now - self._last_beat)
            firing: list[str] = []
            for rule in self.rules:
                try:
                    cond, value, detail = rule.check(reg, now, beat_age)
                except Exception as e:  # noqa: BLE001 - counted, not raised
                    if not rule.error_reported:
                        rule.error_reported = True
                        reg.counter("alert_rule_errors_total").inc()
                        reg.event("alert_rule_error", rule=rule.name,
                                  error=f"{type(e).__name__}: {e}")
                    continue
                if cond and not rule.firing:
                    rule.firing = True
                    rule.fired_count += 1
                    reg.counter("alerts_fired_total").inc()
                    reg.gauge(labeled("alerts_firing",
                                      rule=rule.name)).set(1)
                    reg.event("alert", rule=rule.name, state="firing",
                              severity=rule.severity,
                              value=round(float(value), 6),
                              detail=detail)
                    if rule.dump:
                        try:
                            from . import flight as flight_mod
                            flight_mod.try_dump(
                                "alert", detail=detail,
                                site=rule.name)
                        except Exception:  # noqa: BLE001 - alerts never kill runs
                            pass
                elif not cond and rule.firing:
                    rule.firing = False
                    reg.gauge(labeled("alerts_firing",
                                      rule=rule.name)).set(0)
                    reg.event("alert", rule=rule.name, state="healed",
                              severity=rule.severity,
                              value=round(float(value), 6),
                              detail=detail)
                if rule.firing:
                    firing.append(rule.name)
            return firing

    # -- introspection ----------------------------------------------------
    def summary(self) -> dict:
        """The /healthz detail block: rule count, firing names, and
        how many rule evaluations have errored."""
        with self._lock:
            return {
                "rules": len(self.rules),
                "firing": sorted(r.name for r in self.rules
                                 if r.firing),
                "fired_total": sum(r.fired_count for r in self.rules),
                "rule_errors": sum(1 for r in self.rules
                                   if r.error_reported),
            }

    def slo_status(self) -> dict:
        """Per burn-rate rule: the last computed burn per window and
        the firing flag — the serve /healthz 'slo' section. Empty
        when no burn rules are configured."""
        with self._lock:
            out = {}
            for r in self.rules:
                if r.type != "burn_rate":
                    continue
                out[r.name] = {
                    "objective": round(1.0 - r.budget, 6),
                    "burn": dict(r.burns),
                    "firing": r.firing,
                }
            return out

    def close(self) -> None:
        """Stop the ticker and run one last evaluation (so the final
        document reflects the end-of-run state), then go inert: a
        closed engine never lands another event — the registry's
        event sink is about to close."""
        if self._closed:
            return
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=self._period + 2)
        # reaching teardown is itself a sign of life: a finished run
        # is not a stalled one, so an absence rule still firing heals
        # in the final evaluation (threshold/burn state is untouched)
        self.beat()
        self.evaluate()
        with self._lock:
            self._closed = True
