"""Push/remote-write metrics transport (ISSUE 10 tentpole, ROADMAP
item 4's "fleets that can't be scraped" gap).

The pull-side exposition (`--metrics-port`, export.py) assumes a
scraper can reach every host — false for batch fleets behind NAT,
short-lived CI runs, and serve replicas on ephemeral addresses. The
pusher inverts the arrow: a daemon thread periodically POSTs the SAME
Prometheus text `render_live()` serves (so every in-process registry
— driver plus both stages — rides one push stream) to
`--metrics-push-url`, and on exit flushes the run's FINAL metrics
JSON document so the receiver can aggregate per-host finals into one
fleet document (`tools/push_receiver.py`, via
`parallel/multihost.merge_host_docs` — the same merge rules
`aggregate_metrics` uses collectively).

Transport discipline:

* pushes are best-effort and NEVER fail the run — a dead receiver
  costs a counter (`metrics_push_failures_total`), not an exception;
* failed pushes retry on the next tick under capped exponential
  backoff (a flapping receiver is not hammered at the push period);
* `close()` performs the terminal flush — final exposition text plus
  the final JSON document — with its own bounded retry loop, so a
  receiver that was briefly down mid-run still gets the run's last
  word (`metrics_pushed` meta records whether it landed).

Protocol (stdlib HTTP, mirrored by tools/push_receiver.py):

* ``POST <url>`` — body: Prometheus text exposition
  (``Content-Type: text/plain; version=0.0.4``);
* ``POST <url>/final`` — body: the final metrics JSON document
  (``Content-Type: application/json``).

Both carry ``X-Quorum-Host`` (the per-host identity the receiver
keys on; default ``<hostname>:<pid>``, override with
``QUORUM_PUSH_HOST`` for stable fleet identities) and
``X-Quorum-Stage`` (the registry's stage/driver label).
"""

from __future__ import annotations

import http.client
import json
import os
import socket
import threading
import urllib.error
import urllib.request

from ..utils import levers
from ..utils.vlog import vlog

DEFAULT_PERIOD_S = 5.0
DEFAULT_TIMEOUT_S = 5.0
MAX_BACKOFF_S = 30.0
FINAL_ATTEMPTS = 4
FINAL_BACKOFF_S = 0.25


def default_host_id() -> str:
    """The per-host push identity: QUORUM_PUSH_HOST when set (stable
    fleet names), else hostname:pid (unique per process, so two local
    runs never clobber each other's shard in the fleet document)."""
    env = levers.raw("QUORUM_PUSH_HOST")
    if env:
        return env
    return f"{socket.gethostname()}:{os.getpid()}"


class MetricsPusher:
    """One per observability() lifecycle when `--metrics-push-url` is
    given. Counters land on the owning registry
    (`metrics_push_total` / `metrics_push_failures_total`, created at
    start so a zero-push run still declares the surface)."""

    def __init__(self, registry, url: str,
                 period_s: float = DEFAULT_PERIOD_S,
                 timeout_s: float = DEFAULT_TIMEOUT_S,
                 max_backoff_s: float = MAX_BACKOFF_S,
                 host_id: str | None = None,
                 _urlopen=None, _sleep=None):
        self.registry = registry
        self.url = url.rstrip("/")
        self.period_s = max(0.05, float(period_s))
        self.timeout_s = float(timeout_s)
        self.max_backoff_s = float(max_backoff_s)
        self.host_id = host_id or default_host_id()
        # injectable for tests (deterministic failure/backoff)
        import time
        self._urlopen = _urlopen or urllib.request.urlopen
        self._sleep = _sleep or time.sleep
        self._stop = threading.Event()
        self._backoff = 0.0
        registry.counter("metrics_push_total")
        registry.counter("metrics_push_failures_total")
        registry.set_meta(metrics_push_url=self.url,
                          metrics_push_host=self.host_id)
        self._thread = threading.Thread(target=self._loop,
                                        name="quorum-metrics-push",
                                        daemon=True)
        self._thread.start()

    # -- transport --------------------------------------------------------
    def _stage_label(self) -> str:
        meta = getattr(self.registry, "meta", {}) or {}
        return str(meta.get("stage") or meta.get("driver") or "run")

    def _post(self, url: str, body: bytes, ctype: str) -> None:
        req = urllib.request.Request(
            url, data=body, method="POST",
            headers={"Content-Type": ctype,
                     "X-Quorum-Host": self.host_id,
                     "X-Quorum-Stage": self._stage_label()})
        with self._urlopen(req, timeout=self.timeout_s) as resp:
            resp.read()
            if resp.status >= 300:
                raise OSError(f"push receiver answered {resp.status}")

    def _render(self) -> bytes:
        from . import export
        return export.render_live().encode()

    def _push_once(self, final_doc: dict | None = None) -> bool:
        """One push attempt: exposition text, plus the final document
        when given. Returns True when everything landed."""
        reg = self.registry
        try:
            self._post(self.url, self._render(),
                       "text/plain; version=0.0.4; charset=utf-8")
            if final_doc is not None:
                self._post(self.url + "/final",
                           (json.dumps(final_doc) + "\n").encode(),
                           "application/json")
        except (OSError, urllib.error.URLError, ValueError,
                http.client.HTTPException) as e:
            # HTTPException covers e.g. BadStatusLine from a non-HTTP
            # peer — it is NOT an OSError, and an uncaught raise here
            # would silently kill the daemon push loop
            reg.counter("metrics_push_failures_total").inc()
            vlog("metrics push to ", self.url, " failed: ", e)
            return False
        reg.counter("metrics_push_total").inc()
        return True

    # -- the loop ---------------------------------------------------------
    def _loop(self) -> None:
        while not self._stop.wait(self.period_s + self._backoff):
            if self._push_once():
                self._backoff = 0.0
            else:
                # capped exponential: the next tick waits period +
                # backoff, so a dead receiver sees a decaying rate
                # instead of a steady hammer
                self._backoff = min(
                    self.max_backoff_s,
                    max(self.period_s, self._backoff * 2))

    @property
    def failures(self) -> int:
        return self.registry.counter("metrics_push_failures_total").value

    def close(self, final_doc: dict | None = None) -> bool:
        """Stop the periodic loop, then terminal-flush: the final
        exposition text plus `final_doc` (when given), retried a few
        times with short backoff so a receiver that hiccuped at run
        end still gets the document. Returns True when the flush
        landed; stamps `metrics_pushed` meta either way. Idempotent —
        a second close just re-attempts the flush."""
        self._stop.set()
        self._thread.join(timeout=self.timeout_s + 1.0)
        ok = False
        delay = FINAL_BACKOFF_S
        for attempt in range(FINAL_ATTEMPTS):
            if self._push_once(final_doc=final_doc):
                ok = True
                break
            if attempt < FINAL_ATTEMPTS - 1:
                self._sleep(delay)
                delay = min(delay * 2, 2.0)
        self.registry.set_meta(metrics_pushed=bool(ok))
        if not ok:
            vlog("terminal metrics push to ", self.url,
                 " failed after ", FINAL_ATTEMPTS, " attempts")
        return ok
