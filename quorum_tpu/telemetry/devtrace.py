"""Device-truth telemetry (ISSUE 10 tentpole): parse the `--profile`
directory jax.profiler already writes and attribute DEVICE kernel time
to the pipeline's batches and stages.

Every timing the pipeline reports elsewhere is host-observed: the
dispatch/wait split brackets `block_until_ready`, so "device time"
silently includes host scheduling jitter. The profiler's own trace is
the ground truth — XLA stamps each kernel execution on the device (or
XLA runtime-thread, on CPU) timeline, and the `StepTraceAnnotation`
every batch loop already emits (`tracer.step(...)`, spans.py) brackets
each batch with its step id. This module joins the two:

* **Kernel events** are the trace's `X` complete events carrying an
  `hlo_op` arg (XLA stamps it on every op execution, on every
  backend), plus — on real accelerators — any event on a process the
  trace names `/device:...` (whose lanes carry op executions even when
  an arg set is trimmed). Runtime bookkeeping (`ThreadpoolListener`,
  the thunk executor's *wait*) is excluded by name.
* **Step windows** are the `X` events carrying a `step_num` arg — one
  per `tracer.step(name, step)` call, named after the loop that
  emitted it (`stage2_device`, `stage1_insert`, `shard_build_step`,
  `serve_device`...).

A kernel joins the step window covering its midpoint, which yields
per-batch `device_kernel_us` (one histogram observation per window),
per-stage totals (one entry per step name), per-window **device idle**
(window wall minus the union of its kernels — the device waiting on
the host), and top-K per-kernel totals.

Two sources, same join:

* `plugins/profile/*/​*.trace.json.gz` — the Chrome trace the profiler
  always writes; the primary source.
* `*.xplane.pb` — the raw XPlane protobuf, decoded by the minimal
  wire-format reader below (no tensorflow/protobuf dependency); the
  fallback when the Chrome trace is missing or unreadable.

`record_profile_metrics(reg, profile_dir)` lands the summary in the
run's live registry (cli/observability.py calls it post-run on every
`--profile` CLI), and `tools/trace_summary.py --device` renders the
host-dispatch / device-execute / device-idle attribution table from
the recorded metrics.
"""

from __future__ import annotations

import bisect
import dataclasses
import glob
import gzip
import json
import os

# runtime bookkeeping that lives on the XLA worker lanes but is not
# kernel compute: thread-pool region markers and the executor's idle
# wait-for-completion park
_NOT_KERNEL_PREFIXES = (
    "ThreadpoolListener",
    "ThunkExecutor::Execute (wait",
)

TOP_K = 10


@dataclasses.dataclass
class StepWindow:
    """One StepTraceAnnotation occurrence on the trace timeline."""

    name: str
    step: int
    ts_us: float
    dur_us: float
    kernel_us: float = 0.0
    idle_us: float = 0.0
    n_kernels: int = 0
    _intervals: list = dataclasses.field(default_factory=list, repr=False)

    @property
    def end_us(self) -> float:
        return self.ts_us + self.dur_us


@dataclasses.dataclass
class DevtraceSummary:
    """What a profile directory says about device time."""

    source: str = "none"  # trace_json | xplane | none
    files: list = dataclasses.field(default_factory=list)
    steps: list = dataclasses.field(default_factory=list)  # StepWindow
    kernels: dict = dataclasses.field(default_factory=dict)  # name -> us
    total_kernel_us: float = 0.0
    total_step_us: float = 0.0
    total_idle_us: float = 0.0
    unattributed_kernel_us: float = 0.0

    def stage_kernel_us(self) -> dict:
        """Per step-NAME kernel totals (stage attribution): one entry
        per distinct annotation name the batch loops emitted."""
        out: dict[str, float] = {}
        for w in self.steps:
            out[w.name] = out.get(w.name, 0.0) + w.kernel_us
        return out

    def stage_idle_us(self) -> dict:
        out: dict[str, float] = {}
        for w in self.steps:
            out[w.name] = out.get(w.name, 0.0) + w.idle_us
        return out

    def top_kernels(self, k: int = TOP_K) -> list:
        """[(name, total_us)] sorted by device time, largest first."""
        return sorted(self.kernels.items(), key=lambda kv: -kv[1])[:k]


# ---------------------------------------------------------------------------
# source discovery
# ---------------------------------------------------------------------------

def find_trace_files(profile_dir: str) -> list[str]:
    """Chrome traces under `profile_dir`, recursively: the profiler
    writes `plugins/profile/<run>/<host>.trace.json.gz`; the quorum
    driver nests per-stage profile dirs (`stage1/`, `stage2/`) under
    one root, so the search must recurse."""
    out: list[str] = []
    for pat in ("**/*.trace.json.gz", "**/*.trace.json"):
        out.extend(glob.glob(os.path.join(profile_dir, pat),
                             recursive=True))
    # spans.trace.json is the HOST span twin observability() exports
    # into the same directory — host spans are not device truth
    return sorted(p for p in set(out)
                  if os.path.basename(p) != "spans.trace.json")


def find_xplane_files(profile_dir: str) -> list[str]:
    return sorted(set(glob.glob(os.path.join(profile_dir,
                                             "**/*.xplane.pb"),
                                recursive=True)))


# ---------------------------------------------------------------------------
# Chrome-trace source
# ---------------------------------------------------------------------------

def _load_chrome_events(path: str) -> tuple[list, list]:
    """(step_events, kernel_events) from one trace.json[.gz]: each
    entry is (name, ts_us, dur_us, extra) — extra is the step id for
    steps, nothing for kernels."""
    opener = gzip.open if path.endswith(".gz") else open
    with opener(path, "rb") as f:
        doc = json.loads(f.read().decode())
    events = doc.get("traceEvents", [])
    device_pids = set()
    for e in events:
        if (e.get("ph") == "M" and e.get("name") == "process_name"
                and str((e.get("args") or {}).get("name", ""))
                .startswith("/device:")):
            device_pids.add(e.get("pid"))
    steps: list = []
    kernels: list = []
    for e in events:
        if e.get("ph") != "X":
            continue
        args = e.get("args") or {}
        name = e.get("name", "")
        if "step_num" in args:
            try:
                step = int(args["step_num"])
            except (TypeError, ValueError):
                continue
            steps.append((name, float(e.get("ts", 0.0)),
                          float(e.get("dur", 0.0)), step))
        elif "hlo_op" in args or (e.get("pid") in device_pids
                                  and not name.startswith(
                                      _NOT_KERNEL_PREFIXES)):
            dur = float(e.get("dur", 0.0) or 0.0)
            if dur > 0:
                kernels.append((name, float(e.get("ts", 0.0)), dur))
    return steps, kernels


# ---------------------------------------------------------------------------
# XPlane fallback: minimal protobuf wire reader (no proto dependency)
# ---------------------------------------------------------------------------
# Field numbers from tsl/profiler/protobuf/xplane.proto:
#   XSpace.planes = 1
#   XPlane: id=1 name=2 lines=3 event_metadata=4(map) stat_metadata=5(map)
#   XLine:  id=1 name=2 timestamp_ns=3 events=4 (display_name=11)
#   XEvent: metadata_id=1 offset_ps=2 duration_ps=3 stats=4
#   XEventMetadata: id=1 name=2
#   XStat: metadata_id=1 (value: one of fields 2-7; ints are varints)
#   XStatMetadata: id=1 name=2
# The reader only walks the fields above and skips everything else —
# enough to recover (line, event name, ts, dur, step_num/hlo_op stats).

def _varint(buf: bytes, i: int) -> tuple[int, int]:
    r = s = 0
    while True:
        b = buf[i]
        i += 1
        r |= (b & 0x7F) << s
        if not b & 0x80:
            return r, i
        s += 7


def _fields(buf: bytes):
    """Yield (field_number, wire_type, value) over one message's
    bytes: varints as ints, length-delimited as bytes, fixed32/64 as
    raw bytes."""
    i, end = 0, len(buf)
    while i < end:
        key, i = _varint(buf, i)
        fn, wt = key >> 3, key & 7
        if wt == 0:
            v, i = _varint(buf, i)
        elif wt == 2:
            ln, i = _varint(buf, i)
            v = buf[i:i + ln]
            i += ln
        elif wt == 5:
            v = buf[i:i + 4]
            i += 4
        elif wt == 1:
            v = buf[i:i + 8]
            i += 8
        else:  # groups (3/4) never appear in xplane
            raise ValueError(f"unsupported wire type {wt}")
        yield fn, wt, v


def _map_entry(buf: bytes) -> tuple[int | None, bytes | None]:
    k = v = None
    for fn, _wt, val in _fields(buf):
        if fn == 1:
            k = val
        elif fn == 2:
            v = val
    return k, v


def _meta_name(buf: bytes) -> str:
    for fn, wt, v in _fields(buf):
        if fn == 2 and wt == 2:
            return v.decode(errors="replace")
    return ""


def _load_xplane_events(path: str) -> tuple[list, list]:
    """(step_events, kernel_events) from one xplane.pb, in the same
    shape `_load_chrome_events` returns. Kernel events are the ones
    carrying an `hlo_op` stat; step events the ones carrying
    `step_num`; device-plane events (plane name `/device:...`) count
    as kernels too, minus the runtime-bookkeeping names."""
    with open(path, "rb") as f:
        data = f.read()
    steps: list = []
    kernels: list = []
    for fn, wt, plane in _fields(data):
        if fn != 1 or wt != 2:
            continue
        pname = ""
        lines: list[bytes] = []
        emeta: dict[int, str] = {}
        smeta: dict[int, str] = {}
        for f2, w2, v2 in _fields(plane):
            if f2 == 2 and w2 == 2:
                pname = v2.decode(errors="replace")
            elif f2 == 3 and w2 == 2:
                lines.append(v2)
            elif f2 == 4 and w2 == 2:
                k, v = _map_entry(v2)
                if k is not None and v is not None:
                    emeta[k] = _meta_name(v)
            elif f2 == 5 and w2 == 2:
                k, v = _map_entry(v2)
                if k is not None and v is not None:
                    smeta[k] = _meta_name(v)
        is_device_plane = pname.startswith("/device:")
        for line in lines:
            ts_ns = 0
            events: list[bytes] = []
            for f3, w3, v3 in _fields(line):
                if f3 == 3 and w3 == 0:
                    ts_ns = v3
                elif f3 == 4 and w3 == 2:
                    events.append(v3)
            for ev in events:
                mid = off_ps = dur_ps = 0
                stats: dict[str, int] = {}
                for f4, w4, v4 in _fields(ev):
                    if f4 == 1 and w4 == 0:
                        mid = v4
                    elif f4 == 2 and w4 == 0:
                        off_ps = v4
                    elif f4 == 3 and w4 == 0:
                        dur_ps = v4
                    elif f4 == 4 and w4 == 2:
                        sm = sv = None
                        for f5, w5, v5 in _fields(v4):
                            if f5 == 1 and w5 == 0:
                                sm = v5
                            elif w5 == 0:
                                sv = v5
                        if sm is not None:
                            stats[smeta.get(sm, str(sm))] = sv
                name = emeta.get(mid, "")
                ts_us = ts_ns / 1e3 + off_ps / 1e6
                dur_us = dur_ps / 1e6
                if "step_num" in stats:
                    steps.append((name, ts_us, dur_us,
                                  int(stats["step_num"] or 0)))
                elif "hlo_op" in stats or (
                        is_device_plane
                        and not name.startswith(_NOT_KERNEL_PREFIXES)):
                    if dur_us > 0:
                        kernels.append((name, ts_us, dur_us))
    return steps, kernels


# ---------------------------------------------------------------------------
# the join
# ---------------------------------------------------------------------------

def _join(steps_raw: list, kernels_raw: list) -> DevtraceSummary:
    """Assign each kernel to the step window covering its midpoint
    and derive per-window kernel/idle time. Windows never overlap on
    one timeline (the batch loops emit one annotation at a time), so
    midpoint containment against the window starting at-or-before the
    midpoint is exact."""
    s = DevtraceSummary()
    windows = [StepWindow(name, step, ts, dur)
               for name, ts, dur, step in steps_raw]
    windows.sort(key=lambda w: w.ts_us)
    starts = [w.ts_us for w in windows]
    for name, ts, dur in kernels_raw:
        s.kernels[name] = s.kernels.get(name, 0.0) + dur
        s.total_kernel_us += dur
        mid = ts + dur / 2.0
        i = bisect.bisect_right(starts, mid) - 1
        if i >= 0 and mid <= windows[i].end_us:
            w = windows[i]
            w.kernel_us += dur
            w.n_kernels += 1
            # clip to the window for the idle union — kernels on
            # parallel lanes overlap in wall time, so idle needs the
            # interval UNION, not the sum
            w._intervals.append((max(ts, w.ts_us),
                                 min(ts + dur, w.end_us)))
        else:
            s.unattributed_kernel_us += dur
    for w in windows:
        busy = _union_us(w._intervals)
        w.idle_us = max(0.0, w.dur_us - busy)
        s.total_step_us += w.dur_us
        s.total_idle_us += w.idle_us
    s.steps = windows
    return s


def _union_us(intervals: list) -> float:
    if not intervals:
        return 0.0
    intervals = sorted(intervals)
    total = 0.0
    cur_a, cur_b = intervals[0]
    for a, b in intervals[1:]:
        if a > cur_b:
            total += cur_b - cur_a
            cur_a, cur_b = a, b
        else:
            cur_b = max(cur_b, b)
    return total + (cur_b - cur_a)


def summarize_profile(profile_dir: str) -> DevtraceSummary:
    """Parse every trace under `profile_dir` (Chrome traces first,
    xplane.pb for directories whose Chrome trace is missing or
    unreadable) and join kernels to step windows. Files are joined
    PER SESSION DIRECTORY: each profiler session stamps timestamps
    against its own epoch, so pooling the driver's nested stage1/ and
    stage2/ dumps onto one timeline would bisect one stage's kernels
    into the other stage's windows — the per-group joins are merged
    afterwards. Returns an empty summary (`source="none"`) when the
    directory holds no readable trace — callers record zeros rather
    than failing the run."""
    groups: dict[str, tuple[list, list]] = {}  # session dir -> events
    files: list[str] = []
    source = "none"
    skip_xplane_dirs = set()
    for path in find_trace_files(profile_dir):
        try:
            st, kn = _load_chrome_events(path)
        except (OSError, ValueError):
            continue
        d = os.path.dirname(path)
        steps, kernels = groups.setdefault(d, ([], []))
        steps.extend(st)
        kernels.extend(kn)
        files.append(path)
        skip_xplane_dirs.add(d)
        source = "trace_json"
    for path in find_xplane_files(profile_dir):
        d = os.path.dirname(path)
        if d in skip_xplane_dirs:
            continue  # the Chrome twin already covered this dump
        try:
            st, kn = _load_xplane_events(path)
        except (OSError, ValueError, IndexError):
            continue
        steps, kernels = groups.setdefault(d, ([], []))
        steps.extend(st)
        kernels.extend(kn)
        files.append(path)
        if source == "none":
            source = "xplane"
    s = DevtraceSummary()
    for d in sorted(groups):
        part = _join(*groups[d])
        s.steps.extend(part.steps)
        for name, us in part.kernels.items():
            s.kernels[name] = s.kernels.get(name, 0.0) + us
        s.total_kernel_us += part.total_kernel_us
        s.total_step_us += part.total_step_us
        s.total_idle_us += part.total_idle_us
        s.unattributed_kernel_us += part.unattributed_kernel_us
    s.source = source
    s.files = files
    return s


# ---------------------------------------------------------------------------
# registry recording (cli/observability.py, post-run)
# ---------------------------------------------------------------------------

def record_profile_metrics(reg, profile_dir: str,
                           top_k: int = TOP_K) -> bool:
    """Land the device-truth summary in the run's registry. The
    counter/gauge/histogram names exist even when the directory holds
    no trace (value-0 counts — tools/metrics_check.py requires the
    names whenever meta declares `profile`). Returns True when the
    registry is enabled (the caller re-writes an already-written
    final document so the devtrace section lands in it)."""
    if not getattr(reg, "enabled", False):
        return False
    try:
        s = summarize_profile(profile_dir)
    except Exception as e:  # noqa: BLE001 - telemetry never kills runs
        s = DevtraceSummary()
        reg.set_meta(devtrace_error=str(e))
    reg.counter("device_kernel_us_total").inc(int(s.total_kernel_us))
    reg.counter("device_step_us_total").inc(int(s.total_step_us))
    reg.counter("device_idle_us_total").inc(int(s.total_idle_us))
    reg.counter("device_kernel_unattributed_us_total").inc(
        int(s.unattributed_kernel_us))
    reg.gauge("devtrace_steps").set(len(s.steps))
    hist = reg.histogram("device_kernel_us")
    for w in s.steps:
        hist.observe(int(w.kernel_us))
    reg.set_meta(
        devtrace_source=s.source,
        devtrace_files=len(s.files),
        devtrace_stage_kernel_us={k: round(v, 1) for k, v in
                                  sorted(s.stage_kernel_us().items())},
        devtrace_stage_idle_us={k: round(v, 1) for k, v in
                                sorted(s.stage_idle_us().items())},
        devtrace_top_kernels=[f"{name}={round(us, 1)}"
                              for name, us in s.top_kernels(top_k)],
    )
    return True
