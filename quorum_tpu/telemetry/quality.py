"""Correction-quality scorecard: data-plane telemetry for the
*product* (ISSUE 17).

Every observability tier before this one watched the machine —
latency, kernels, alerts, crashes — while "did we correct reads
well?" was three scalar counters. This module turns the per-read
outcome tallies the render path already produces
(models/error_correct.render_result -> record_outcome, the single
choke point shared by the offline drain loop and the serve engine)
into distributions and drift signals:

* a substitution-position spectrum per read cycle (fixed-cardinality
  bucketed via :func:`bounded` — the classic Illumina 3'-decay
  signature is a rising tail in the last buckets);
* 3'/5' truncation-cycle histograms (the cut position of each
  ``pos:3_trunc`` / ``pos:5_trunc`` edit-log entry; for a 3' cut the
  cycle IS the surviving read length, so the histogram doubles as a
  truncation-length distribution);
* the skip-reason breakdown (one ``skipped_<slug>`` counter per
  ``REASON_SLUGS`` entry, pre-created so zeros land — the PR-7
  zero-count lesson);
* data-plane rates per batch window — corrections/read, skip rate,
  truncation rate, contaminant-hit rate, anchor (trusted-k-mer hit)
  rate vs the coverage the DB header's ``poisson_stats`` predicts —
  with EWMA drift scores feeding the default drift alert rules
  (``quality_drift`` / ``contam_spike`` / ``coverage_drop``,
  telemetry/alerts.DEFAULT_QUALITY_RULES).

Two read surfaces:

* **live** — the ``quality_*`` gauges a :class:`QualityScorecard`
  refreshes on the heartbeat cadence (windowed rates + drift score),
  which the PR 11 alert engine evaluates and the PR 16 flight ring
  snapshots when a ``dump: true`` rule fires;
* **final** — the ``quality`` section of every final metrics
  document, computed by :func:`section_from_doc` as a PURE function
  of the document's own counters/histograms — no wall-clock inputs —
  so two runs over the same input produce byte-identical sections
  (the determinism `tools/quality_diff.py` gates CI on).
"""

from __future__ import annotations

import math
import threading
import time

from ..utils import levers

# the quality section's own schema stamp (telemetry/schema.py
# validates the shape; tools/quality_diff.py keys its extraction on it)
QUALITY_SCHEMA = "quorum-tpu-quality/1"

# Fixed-cardinality position bucketing (satellite: no unbounded
# label/value cardinality reaches Prometheus exposition). 64 buckets
# of 8 cycles cover reads up to 512 cycles; longer reads fold their
# tail into the last bucket — well inside Histogram.MAX_KEYS (512).
SPECTRUM_BUCKETS = 64
SPECTRUM_CYCLES_PER_BUCKET = 8

# the live gauges a scorecard pre-creates (telemetry/contract.py
# QUALITY_GAUGES mirrors this — keep in sync, quorum-lint insists on
# the catalogs, metrics_check requires them when meta.quality is set)
RATE_GAUGES = ("quality_corrections_per_read", "quality_skip_rate",
               "quality_trunc_rate", "quality_contam_rate")
# pre-created at their QUIET values: anchor/coverage start at 1.0 so
# the `coverage_drop` rule (fires on `< 0.5`) cannot page before the
# first data window
UNIT_GAUGES = ("quality_anchor_rate", "quality_coverage_ratio")
DRIFT_GAUGE = "quality_drift_score"

# the cumulative outcome counters a window samples (all pre-created
# by models/error_correct.precreate_outcome_counters)
_WINDOW_COUNTERS = ("reads_in", "reads_corrected", "reads_skipped",
                    "substitutions", "truncations_3p",
                    "truncations_5p", "skipped_contaminant",
                    "skipped_no_anchor")


def bounded(value, cap) -> int:
    """THE shared bucketing clamp: a non-negative int no greater than
    `cap`. Reused by the substitution-position spectrum, the
    truncation-cycle histograms, and the `substitutions_per_read`
    value bound at the config `maxe` — one helper so no surface can
    drift into unbounded cardinality."""
    v = int(value)
    cap = int(cap)
    if v < 0:
        return 0
    return cap if v > cap else v


def position_bucket(pos) -> int:
    """Read-cycle position -> fixed spectrum bucket (the per-cycle
    substitution spectrum's x axis)."""
    return bounded(int(pos) // SPECTRUM_CYCLES_PER_BUCKET,
                   SPECTRUM_BUCKETS - 1)


def _ratio(num, den) -> float:
    return round(float(num) / float(den), 6) if den else 0.0


def _sorted_counts(hist: dict | None) -> dict:
    """A histogram `counts` map re-keyed deterministically: numeric
    keys ascending, the cardinality-guard "overflow" key last."""
    if not hist:
        return {}
    counts = hist.get("counts", {})

    def key(kv):
        k = kv[0]
        try:
            return (0, int(k), "")
        except (TypeError, ValueError):
            return (1, 0, str(k))

    return {str(k): int(n) for k, n in sorted(counts.items(), key=key)}


def predicted_anchor_rate(coverage_mean: float) -> float:
    """The anchor-rate the DB header's coverage statistics predict: a
    mer drawn from the sequenced genome is trusted unless its site
    went unsampled, so P(a read finds at least one trusted anchor
    k-mer) >= 1 - e^-c for mean high-quality coverage c (Poisson
    sampling; a lower bound because a read holds many mers). The
    `coverage_drop` rule compares the OBSERVED anchor rate to this."""
    c = float(coverage_mean)
    if c <= 0:
        return 0.0
    return round(1.0 - math.exp(-c), 6)


def section_from_doc(doc: dict) -> dict:
    """The `quality` section, derived from a final metrics document's
    own counters/histograms/meta — a PURE function with no wall-clock
    inputs, so two deterministic runs produce byte-identical sections
    (what `tools/quality_diff.py` and the golden tests compare)."""
    c = doc.get("counters", {})
    h = doc.get("histograms", {})
    meta = doc.get("meta", {})
    reads = int(c.get("reads_in", 0))
    corrected = int(c.get("reads_corrected", 0))
    skipped = int(c.get("reads_skipped", 0))
    subs = int(c.get("substitutions", 0))
    t3 = int(c.get("truncations_3p", 0))
    t5 = int(c.get("truncations_5p", 0))
    no_anchor = int(c.get("skipped_no_anchor", 0))
    skip_reasons = {k[len("skipped_"):]: int(v)
                    for k, v in sorted(c.items())
                    if k.startswith("skipped_")}
    section = {
        "schema": QUALITY_SCHEMA,
        "reads": reads,
        "corrected": corrected,
        "skipped": skipped,
        "substitutions": subs,
        "truncations_3p": t3,
        "truncations_5p": t5,
        "rates": {
            "anchor_rate": (round(1.0 - no_anchor / reads, 6)
                            if reads else 1.0),
            "contam_rate": _ratio(c.get("skipped_contaminant", 0),
                                  reads),
            "corrections_per_read": _ratio(subs, corrected),
            "skip_rate": _ratio(skipped, reads),
            "trunc_rate_3p": _ratio(t3, corrected),
            "trunc_rate_5p": _ratio(t5, corrected),
        },
        "skip_reasons": skip_reasons,
        "spectrum_cycles_per_bucket": SPECTRUM_CYCLES_PER_BUCKET,
        "sub_pos_spectrum": _sorted_counts(h.get("sub_pos_bucket")),
        "substitutions_per_read":
            _sorted_counts(h.get("substitutions_per_read")),
        "trunc_cycle_3p": _sorted_counts(h.get("trunc_cycle_3p")),
        "trunc_cycle_5p": _sorted_counts(h.get("trunc_cycle_5p")),
    }
    cm = meta.get("coverage_mean")
    if isinstance(cm, (int, float)) and not isinstance(cm, bool) \
            and cm > 0:
        section["coverage"] = {
            "predicted_mean": round(float(cm), 4),
            "predicted_anchor_rate": predicted_anchor_rate(cm),
        }
    return section


def summarize_results(results) -> dict:
    """A per-request quality summary derived from the (fa_text,
    log_text) render pairs the serve engine returns — the
    ``X-Quorum-Quality`` response header's payload and the request
    ledger's quality fields. Counting ``:sub:`` etc. in the rendered
    text is exact: the edit-log entries live in the `.fa` header
    lines and colons cannot appear in sequence data, so the header
    sums reconcile against the final document's outcome counters
    (the serve/offline parity check)."""
    corrected = skipped = subs = t3 = t5 = 0
    for fa, lg in results:
        if lg:
            # render_result's contract: skipped reads are exactly the
            # ones that contribute a `.log` line (no-discard reads
            # also emit a placeholder `.fa` record, so `fa` alone
            # cannot classify)
            skipped += 1
        else:
            corrected += 1
            subs += fa.count(":sub:")
            t3 += fa.count(":3_trunc")
            t5 += fa.count(":5_trunc")
    return {"reads": len(results), "corrected": corrected,
            "skipped": skipped, "subs": subs, "t3": t3, "t5": t5}


def coverage_from_histo(bins) -> float:
    """Fit the mean trusted-mer coverage from a mer-count histogram
    (`quorum_histo_mer_database --json` sidecar rows:
    ``[count, n_lowqual, n_highqual]``): the high-quality spectrum's
    mode PAST the first valley — the error/signal split every k-mer
    spectrum shows (errors pile up at count 1-2, real coverage peaks
    near c). Returns 0.0 when no valley exists (error-dominated or
    flat histograms), so callers fall back to the header's
    `poisson_stats`."""
    hq: dict[int, int] = {}
    for row in bins or ():
        count, _low, high = int(row[0]), int(row[1]), int(row[2])
        if count > 0 and high > 0:
            hq[count] = hq.get(count, 0) + high
    if not hq:
        return 0.0
    xs = sorted(hq)
    valley = None
    for a, b in zip(xs, xs[1:]):
        if hq[b] > hq[a]:
            valley = a
            break
    if valley is None:
        return 0.0
    past = [x for x in xs if x > valley]
    mode = max(past, key=lambda x: (hq[x], -x))
    return float(mode)


class QualityScorecard:
    """The live half: windowed data-plane rates + EWMA drift scores
    over ONE registry's outcome counters.

    Installed by `cli/observability.observability()` on every enabled
    registry (all four entry points). Hooks:

    * `registry.quality = self` — `MetricsRegistry.as_dict` calls
      :meth:`snapshot_from` so every final document carries the
      `quality` section;
    * `registry.add_exporter` — :meth:`tick` runs on the heartbeat
      cadence (and once at the final write), closing a rate window
      whenever at least `window_reads` new reads arrived and
      refreshing the `quality_*` gauges the drift alert rules read.

    `now` is injectable for mocked-clock tests (the AlertEngine
    precedent); `alpha`/`window_reads` default to the
    ``QUORUM_QUALITY_*`` levers.
    """

    def __init__(self, registry, alpha: float | None = None,
                 window_reads: int | None = None, now=time.monotonic):
        self.registry = registry
        self._now = now
        if alpha is None:
            raw = levers.raw("QUORUM_QUALITY_EWMA_ALPHA")
            alpha = float(raw) if raw else 0.2
        if window_reads is None:
            raw = levers.raw("QUORUM_QUALITY_WINDOW_READS")
            window_reads = int(raw) if raw else 2048
        if not 0.0 < alpha <= 1.0:
            raise ValueError("EWMA alpha must be in (0, 1]")
        self.alpha = float(alpha)
        self.window_reads = max(1, int(window_reads))
        self._lock = threading.Lock()
        self._prev: dict[str, int] = {}
        self._ewma: dict[str, float] = {}
        self.windows = 0
        reg = registry
        if getattr(reg, "enabled", False):
            # the gauge surface exists from setup (zeros / quiet
            # values included) so metrics_check can require the names
            # whenever meta declares the scorecard installed
            for g in RATE_GAUGES:
                reg.gauge(g).set(0)
            for g in UNIT_GAUGES:
                reg.gauge(g).set(1.0)
            reg.gauge(DRIFT_GAUGE).set(0)
            reg.set_meta(quality=True)
            reg.quality = self
            reg.add_exporter(self._exporter)

    # -- final-document hook ----------------------------------------------
    def snapshot_from(self, sections: dict) -> dict:
        """Called by MetricsRegistry.as_dict with the already-built
        document sections (under the registry lock — this must not
        call back into registry accessors)."""
        return section_from_doc(sections)

    # -- live windowing ---------------------------------------------------
    def _exporter(self, reg, final: bool = False) -> None:
        self.tick(final=final)

    def _read(self, name: str) -> int:
        # direct map read, no get-or-create: the alerts._read_metric
        # precedent — a tick must not materialize absent counters
        m = self.registry._counters.get(name)
        return 0 if m is None else int(m.value)

    def tick(self, final: bool = False) -> bool:
        """Close a rate window if enough reads arrived (always, at
        the final write, when any arrived): refresh the windowed
        `quality_*` gauges, fold the window into the EWMA baselines,
        and publish the worst normalized drift score. Returns True
        when a window closed."""
        reg = self.registry
        if not getattr(reg, "enabled", False):
            return False
        with self._lock:
            cur = {k: self._read(k) for k in _WINDOW_COUNTERS}
            d = {k: cur[k] - self._prev.get(k, 0) for k in cur}
            reads = d["reads_in"]
            if reads <= 0 or (reads < self.window_reads and not final):
                return False
            self._prev = cur
            self.windows += 1
            corrected = max(d["reads_corrected"], 1)
            window = {
                "quality_corrections_per_read":
                    d["substitutions"] / corrected,
                "quality_skip_rate": d["reads_skipped"] / reads,
                "quality_trunc_rate":
                    (d["truncations_3p"] + d["truncations_5p"])
                    / corrected,
                "quality_contam_rate":
                    d["skipped_contaminant"] / reads,
                "quality_anchor_rate":
                    1.0 - d["skipped_no_anchor"] / reads,
            }
            drift = 0.0
            for name, v in window.items():
                reg.gauge(name).set(round(v, 6))
                m = self._ewma.get(name)
                if m is None:
                    # first window seeds the baseline — drift is
                    # change AGAINST history, so a short run that
                    # only ever closes one window cannot page
                    self._ewma[name] = v
                    continue
                # normalized deviation from the smoothed baseline;
                # the 0.02 floor keeps a near-zero baseline (clean
                # data) from turning rounding noise into a page
                drift = max(drift, abs(v - m) / max(abs(m), 0.02))
                self._ewma[name] = (self.alpha * v
                                    + (1.0 - self.alpha) * m)
            reg.gauge(DRIFT_GAUGE).set(round(drift, 4))
            cm = reg.meta.get("coverage_mean")
            if isinstance(cm, (int, float)) \
                    and not isinstance(cm, bool) and cm > 0:
                predicted = predicted_anchor_rate(cm)
                if predicted > 0.05:
                    reg.gauge("quality_coverage_ratio").set(
                        round(min(window["quality_anchor_rate"]
                                  / predicted, 2.0), 4))
            return True
