"""Metrics registry: counters, gauges, histograms, and a JSONL sink.

The machine-readable counterpart of vlog/StageTimer (ISSUE 1): every
layer of the pipeline records what it did into ONE registry per run,
which writes a final schema-versioned JSON document (schema.py) plus —
when a heartbeat interval is configured — a JSONL event stream
(run manifest, hash grows, period-limited progress lines with Gb/h
so-far). The reference keeps this information in vlog timestamps and
the per-read err_log; KMC 3 (PAPERS.md) exposes it as a queryable
per-stage statistics artifact, which is the model followed here.

Zero-cost when disabled: `registry_for(None)` returns the NULL
singleton whose methods are all no-ops and whose `enabled` flag lets
per-read hot paths skip metric derivation entirely. No dependencies
beyond the standard library.

Thread model: counters/gauges take a per-object lock (the pipeline
updates them from the prefetch, writer, and render threads); the
registry's name->metric maps and the event sink share one registry
lock. All costs are per-batch or per-event, never per-base.
"""

from __future__ import annotations

import json
import os
import threading
import time
import weakref

from .schema import SCHEMA_VERSION


def atomic_write(path: str, data) -> None:
    """The one atomic-replace idiom every telemetry artifact uses
    (final JSON, Chrome trace, Prometheus textfile, multi-host
    aggregate — and the fault-tolerance layer's checkpoint cursors,
    io/checkpoint.py): write a sibling tmp, fsync, then os.replace,
    then fsync the parent directory — a reader at `path` can never
    observe a torn file, and a committed artifact survives power
    loss, not just process death (renames are only durable once the
    directory entry is down; ISSUE 8). Accepts str or bytes."""
    tmp = path + ".tmp"
    mode = "wb" if isinstance(data, (bytes, bytearray)) else "w"
    with open(tmp, mode) as f:
        f.write(data)
        f.flush()
        try:
            os.fsync(f.fileno())
        except OSError:  # pragma: no cover - exotic filesystems
            pass
    os.replace(tmp, path)
    # directory durability, open-coded (telemetry must not import io)
    d = os.path.dirname(path) or "."
    try:
        fd = os.open(d, os.O_RDONLY)
    except OSError:  # pragma: no cover - unreadable parent
        return
    try:
        os.fsync(fd)
    except OSError:  # pragma: no cover
        pass
    finally:
        os.close(fd)


def _scalar(v):
    """Coerce a value to a JSON-safe scalar (numpy ints/floats pass
    through their __int__/__float__)."""
    if isinstance(v, bool) or v is None or isinstance(v, (int, float, str)):
        return v
    for cast in (int, float):
        try:
            return cast(v)
        except (TypeError, ValueError):
            continue
    return str(v)


class Counter:
    """Monotone integer count."""

    __slots__ = ("value", "_lock")

    def __init__(self):
        self.value = 0
        self._lock = threading.Lock()

    def inc(self, n: int = 1) -> None:
        with self._lock:
            self.value += int(n)


class Gauge:
    """Last-set (or max/accumulated) numeric value."""

    __slots__ = ("value", "_lock")

    def __init__(self):
        self.value = 0
        self._lock = threading.Lock()

    def set(self, v) -> None:
        with self._lock:
            self.value = _scalar(v)

    def set_max(self, v) -> None:
        v = _scalar(v)
        with self._lock:
            if v > self.value:
                self.value = v

    def add(self, v) -> None:
        with self._lock:
            self.value += v


class Histogram:
    """Integer-valued histogram: exact per-value counts plus count/sum
    (substitutions-per-read and friends take a handful of distinct
    small values, so exact counts beat fixed buckets)."""

    __slots__ = ("counts", "count", "sum", "_lock")

    MAX_KEYS = 512

    def __init__(self):
        self.counts: dict[int, int] = {}
        self.count = 0
        self.sum = 0
        self._lock = threading.Lock()

    def observe(self, value, n: int = 1) -> None:
        value, n = int(value), int(n)
        with self._lock:
            self.count += n
            self.sum += value * n
            if value in self.counts or len(self.counts) < self.MAX_KEYS:
                self.counts[value] = self.counts.get(value, 0) + n
            else:  # pragma: no cover - cardinality guard
                self.counts["overflow"] = (
                    self.counts.get("overflow", 0) + n)


class MetricsRegistry:
    """One per instrumented run. `path` receives the final JSON via
    `write()`; `heartbeat_s > 0` additionally opens `events_path`
    (default: <path minus .json>.events.jsonl) and rate-limits
    `heartbeat()` to that period. An EXPLICIT `events_path` is honored
    even when `path` is None (a heartbeat-only run writes no final
    JSON but still streams events); with `heartbeat_s <= 0` an
    explicit events path heartbeats unlimited (every call emits)."""

    enabled = True

    def __init__(self, path: str | None = None,
                 heartbeat_s: float = 0.0,
                 events_path: str | None = None):
        self.path = path
        self.heartbeat_s = float(heartbeat_s)
        if events_path is None and path and self.heartbeat_s > 0:
            base = path[:-5] if path.endswith(".json") else path
            events_path = base + ".events.jsonl"
        self.events_path = events_path
        self.meta: dict = {}
        self.timers: dict = {}
        self._counters: dict[str, Counter] = {}
        self._gauges: dict[str, Gauge] = {}
        self._hists: dict[str, Histogram] = {}
        self._lock = threading.Lock()
        self._events_f = None
        self._events_closed = False
        self._t0 = time.perf_counter()
        self._last_beat = -1e18
        self._exporters: list = []
        # flight-recorder tap (ISSUE 16): observability() points this
        # at the session's FlightRecorder so every event feeds the
        # forensic ring — BEFORE the events-path gate (the ring wants
        # history even when no JSONL sink is configured) and outside
        # self._lock (the ring lock never nests inside the registry's)
        self.flight = None
        # quality-scorecard tap (ISSUE 17): observability() installs a
        # QualityScorecard which sets this; as_dict() then derives the
        # `quality` section from the document's own serialized
        # counters/histograms — a pure function of the built sections,
        # so the hook never re-enters the (non-reentrant) registry lock
        self.quality = None

    # -- metric accessors (get-or-create) --------------------------------
    def counter(self, name: str) -> Counter:
        with self._lock:
            m = self._counters.get(name)
            if m is None:
                m = self._counters[name] = Counter()
            return m

    def gauge(self, name: str) -> Gauge:
        with self._lock:
            m = self._gauges.get(name)
            if m is None:
                m = self._gauges[name] = Gauge()
            return m

    def histogram(self, name: str) -> Histogram:
        with self._lock:
            m = self._hists.get(name)
            if m is None:
                m = self._hists[name] = Histogram()
            return m

    def set_meta(self, **fields) -> None:
        self.meta.update(fields)

    def set_timer(self, name: str, timer_dict: dict) -> None:
        """Attach a StageTimer.as_dict() under `timers`."""
        self.timers[name] = timer_dict

    def elapsed(self) -> float:
        return time.perf_counter() - self._t0

    # -- JSONL event sink -------------------------------------------------
    def event(self, kind: str, **fields) -> None:
        """Append one event line; no-op unless an events path is
        configured (heartbeat_s > 0 or explicit events_path). The
        flight tap fires either way — the ring is the always-on
        bounded sink the JSONL stream is the durable one of."""
        fl = self.flight
        if fl is not None:
            fl.record("event", kind, **fields)
        if not self.events_path:
            return
        obj = {"event": kind, "t": round(self.elapsed(), 3)}
        for k, v in fields.items():
            obj[k] = _scalar(v)
        line = json.dumps(obj) + "\n"
        with self._lock:
            if self._events_closed:
                # a straggler event after write() closed the sink
                # (an alert ticker, a late exporter) must not REOPEN
                # the path — the lazy "wb" open would truncate the
                # stream it is trying to append to
                return
            if self._events_f is None:
                # line-journal discipline: an UNBUFFERED binary stream
                # and exactly one os-level write per complete line,
                # fsync'd — a hard kill (os._exit fault plans, SIGKILL,
                # power loss) can land between lines but never inside
                # one, so a reader never sees a torn last record.
                # Buffered text IO could flush a line across several
                # write(2) calls. Events are per-batch at most (and
                # heartbeats rate-limited), so the fsync is noise.
                # a guarded line-journal stream, not an artifact
                # write: opened exactly once (None check above),
                # sealed by write() (_events_closed) so no re-open
                # can truncate it — the hardened PR-11 site
                self._events_f = open(  # qlint: disable=raw-artifact-write,append-truncation
                    self.events_path, "wb", buffering=0)
            self._events_f.write(line.encode())
            try:
                os.fsync(self._events_f.fileno())
            except OSError:  # pragma: no cover - exotic filesystems
                pass

    def add_exporter(self, fn) -> None:
        """Register a live exporter: `fn(reg, final=False)` is called
        on every `heartbeat()` (exporters self-rate-limit) and once
        with `final=True` from `write()` (the Prometheus textfile
        writer attaches here, telemetry/export.py)."""
        with self._lock:
            self._exporters.append(fn)

    def _notify_exporters(self, final: bool = False) -> None:
        for fn in list(self._exporters):
            try:
                fn(self, final=final)
            except Exception:  # noqa: BLE001 - exposition never kills runs
                pass

    def heartbeat(self, **fields) -> None:
        """Rate-limited progress event. A `bases` field gets derived
        `gb_per_h` (so-far, since registry creation) for free. Every
        record carries a monotonic `elapsed_s`. Live exporters are
        notified on EVERY call (they rate-limit themselves), so the
        textfile/endpoint stay fresh even when JSONL events are
        off."""
        self._notify_exporters()
        if not self.events_path:
            return
        now = time.perf_counter()
        if self.heartbeat_s > 0 and now - self._last_beat < self.heartbeat_s:
            return
        self._last_beat = now
        el = self.elapsed()
        if "bases" in fields and el > 0:
            fields["gb_per_h"] = round(
                _scalar(fields["bases"]) / el * 3600.0 / 1e9, 4)
        self.event("heartbeat", elapsed_s=round(el, 3), **fields)

    # -- output -----------------------------------------------------------
    def as_dict(self) -> dict:
        with self._lock:
            out = {
                "schema": SCHEMA_VERSION,
                "meta": dict(self.meta),
                "counters": {k: c.value
                             for k, c in sorted(self._counters.items())},
                "gauges": {k: g.value
                           for k, g in sorted(self._gauges.items())},
                "histograms": {
                    k: {"count": h.count, "sum": h.sum,
                        "counts": {str(v): n
                                   for v, n in sorted(
                                       h.counts.items(),
                                       key=lambda kv: str(kv[0]))}}
                    for k, h in sorted(self._hists.items())},
                "timers": dict(self.timers),
            }
            if self.quality is not None:
                # derived from the sections built above, NOT from the
                # live metric maps: snapshot_from is pure (quality.
                # section_from_doc), so it cannot deadlock on
                # self._lock and the section is byte-deterministic
                # whenever the counters are
                out["quality"] = self.quality.snapshot_from(out)
            return out

    def write(self, path: str | None = None) -> str | None:
        """Write the final metrics JSON (atomic replace), give live
        exporters their final refresh, and close the event sink.
        Returns the path written (None for an exposition-only
        registry, which still flushes exporters and events)."""
        # compile-sentinel ledger export (ISSUE 15): a run under
        # QUORUM_COMPILE_SENTINEL=1 stamps its per-site compile
        # counts into the final document (compile_events counter,
        # compiles{site=...} counters, meta.compile_sites) so
        # tools/perf_diff.py gates compile-count regressions like
        # wall clock. One installed() check when the sentinel is off.
        from ..analysis import compile_sentinel
        if compile_sentinel.installed():
            compile_sentinel.export(self)
        self._notify_exporters(final=True)
        path = path or self.path
        doc = None
        if path:
            doc = self.as_dict()
            atomic_write(path, json.dumps(doc, indent=1) + "\n")
        with self._lock:
            if self._events_f is not None:
                self._events_f.close()
                self._events_f = None
            # even an event-less run seals the sink: a straggler
            # event after write() must not create (or truncate) the
            # stream post-hoc
            self._events_closed = True
        return path if doc is not None else None


class NullRegistry:
    """The disabled registry: every method is a no-op, `enabled` is
    False so hot paths can skip metric derivation entirely."""

    enabled = False
    path = None
    events_path = None
    flight = None
    quality = None

    def counter(self, name):
        return _NULL_COUNTER

    def gauge(self, name):
        return _NULL_GAUGE

    def histogram(self, name):
        return _NULL_HIST

    def set_meta(self, **fields):
        pass

    def set_timer(self, name, timer_dict):
        pass

    def add_exporter(self, fn):
        pass

    def event(self, kind, **fields):
        pass

    def heartbeat(self, **fields):
        pass

    def elapsed(self):
        return 0.0

    def as_dict(self):
        return {"schema": SCHEMA_VERSION, "meta": {}, "counters": {},
                "gauges": {}, "histograms": {}, "timers": {}}

    def write(self, path=None):
        return None


class _NullMetric:
    __slots__ = ()

    def inc(self, n=1):
        pass

    def set(self, v):
        pass

    def set_max(self, v):
        pass

    def add(self, v):
        pass

    def observe(self, value, n=1):
        pass


_NULL_COUNTER = _NullMetric()
_NULL_GAUGE = _NullMetric()
_NULL_HIST = _NullMetric()

NULL = NullRegistry()


def registry_for(path: str | None,
                 heartbeat_s: float = 0.0,
                 events_path: str | None = None,
                 force: bool = False) -> MetricsRegistry | NullRegistry:
    """The one constructor call sites use: a real registry when a
    `--metrics PATH` (or an explicit `events_path`) was given, the
    no-op NULL singleton when not. `force=True` returns a real
    registry even with no output path — the live-exposition case
    (`--metrics-port`/`--metrics-textfile` without `--metrics`), where
    counters must accumulate for scraping but no final JSON lands.
    Enabled registries self-register with the live exposition layer
    (telemetry/export.py) so `/metrics` sees every stage in-process."""
    if not path and not events_path and not force:
        return NULL
    reg = MetricsRegistry(path, heartbeat_s=heartbeat_s,
                          events_path=events_path)
    from .export import register_live
    register_live(reg)
    return reg


def labeled(name: str, **labels) -> str:
    """A registry metric name carrying an embedded Prometheus label
    set — `labeled("lane_wait_us", lane="bulk")` ->
    `lane_wait_us{lane="bulk"}`. The flat registry stores it as an
    ordinary key; the exposition renderer (export.split_labeled_name)
    splits it back into a base name + labels so scrapers see a real
    labelled series. Label values must not contain `"` or newlines
    (they are embedded verbatim)."""
    lab = ",".join(f'{k}="{v}"' for k, v in sorted(labels.items()))
    return f"{name}{{{lab}}}"


def observe_dispatch_wait(reg, prefix: str, t0: float, t1: float,
                          t2: float, timer=None) -> None:
    """The per-batch device-time attribution every device loop
    records (ISSUE 2), in one place instead of a copy per loop:
    dispatch (t0->t1, handing XLA the program — host-side queueing)
    lands as `<prefix>_dispatch_us`, the block-until-ready wait
    (t1->t2, device compute + transfer) as `<prefix>_wait_us`.
    Microsecond histograms so sub-ms dispatches keep signal. `timer`
    (a StageTimer, or None) additionally gets `<prefix>_dispatch` /
    `<prefix>_wait` stages for the timers table. Call sites: stage-1
    insert (`insert`), stage-2 correct (`device`), sharded build
    (`shard_step`), and the serve engine (`serve`)."""
    if timer is not None:
        timer.add_time(f"{prefix}_dispatch", t1 - t0)
        timer.add_time(f"{prefix}_wait", t2 - t1)
    if getattr(reg, "enabled", False):
        reg.histogram(f"{prefix}_dispatch_us").observe(
            int((t1 - t0) * 1e6))
        reg.histogram(f"{prefix}_wait_us").observe(int((t2 - t1) * 1e6))
        fl = reg.flight
        if fl is not None:
            # per-batch dispatch/wait sample into the flight ring: a
            # pure-Python append, no device sync (rules_hotpath-safe)
            fl.record("dispatch", prefix,
                      dispatch_us=int((t1 - t0) * 1e6),
                      wait_us=int((t2 - t1) * 1e6))


# jax.monitoring offers register but no unregister, so exactly ONE
# listener is ever installed; it fans out to whichever registries are
# still alive (WeakSet: a finished run's registry just drops out, no
# per-run leak in long-lived processes that call main() repeatedly).
_cache_listener_installed = False
_cache_listener_targets: weakref.WeakSet = weakref.WeakSet()


def _cache_listener(event, *a, **kw):
    if event == "/jax/compilation_cache/cache_hits":
        name = "jax_cache_hits"
    elif event == "/jax/compilation_cache/compile_requests_use_cache":
        name = "jax_cache_requests"
    else:
        return
    for reg in list(_cache_listener_targets):
        reg.counter(name).inc()


def track_jax_compile_cache(reg) -> None:
    """Subscribe `reg` to the jax.monitoring compile-cache events,
    feeding `jax_cache_hits` / `jax_cache_requests` counters (misses =
    requests - hits; the driver derives a `jax_cache_misses` gauge at
    write time). Best-effort: silently a no-op on jax versions without
    monitoring or with different event names."""
    global _cache_listener_installed
    if not reg.enabled:
        return
    try:
        from jax import monitoring
    except Exception:  # noqa: BLE001 - jax absent / too old
        return
    if not _cache_listener_installed:
        try:
            monitoring.register_event_listener(_cache_listener)
        except Exception:  # noqa: BLE001 - listener API drift
            return
        _cache_listener_installed = True
    _cache_listener_targets.add(reg)
