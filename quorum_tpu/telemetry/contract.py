"""The telemetry metric-name contract: the required-counter catalogs
that `tools/metrics_check.py` gates CI documents against, single-
sourced here (ISSUE 12).

These lists used to live in the checker tool, which meant the
contract and the code that fulfils it could drift: a counter renamed
in `quorum_tpu/serve/` kept passing local tests while the CI gate
went quietly vacuous (the PR-7 SERVE_FEATURE_COUNTERS lesson was
exactly this shape — feature counters exist only if the serve layers
pre-create them at setup, so a missing name must FAIL the document,
which only works while the checker's list matches the creators).

Now three consumers import ONE catalog:

* ``tools/metrics_check.py`` — requires the names in produced
  documents (dispatching on meta, as before);
* ``quorum_tpu/analysis`` — the ``counter-not-precreated`` rule
  statically verifies every counter named here is created by a
  literal ``.counter("name")`` call somewhere in ``quorum_tpu/``, so
  a rename or deletion breaks the lint, not just the late CI gate;
* the telemetry layers themselves, as the canonical spelling.

Keep entries appendable: removing or renaming one is a contract
change and must update the creators, the goldens, and this file in
the same PR (quorum-lint will insist).
"""

from __future__ import annotations

# The serve request/batch metric surface (quorum_tpu/serve/): a final
# metrics document stamped `meta.stage == "serve"` must carry these.
# Counters appear once the first request is admitted; the histograms
# once the first batch dispatches.
SERVE_REQUIRED_COUNTERS = (
    "requests_accepted",
    "requests_completed",
    "reads_in",
    "reads_corrected",
    "batches",
    "engine_compiles",
)
SERVE_REQUIRED_HISTOGRAMS = (
    "batch_reads",
    "queue_wait_us",
    "request_us",
    "request_reads",
    "serve_dispatch_us",
    "serve_wait_us",
)

# The serve resilience surface (ISSUE 7): a serve document whose meta
# declares one of these features enabled must carry its counter (the
# serve layers create them at setup, so value 0 counts).
#   meta.step_timeout_ms > 0 -> engine_restarts_total (watchdog)
#   meta.max_hedges > 0      -> hedges_total
#   meta.reload truthy       -> reload_total
#   meta.quota_rps > 0       -> quota_rejections_total
SERVE_FEATURE_COUNTERS = (
    ("step_timeout_ms", "engine_restarts_total"),
    ("max_hedges", "hedges_total"),
    ("reload", "reload_total"),
    ("quota_rps", "quota_rejections_total"),
)

# The fault-tolerance metric surface (ISSUE 4): documents that declare
# the corresponding feature in meta must carry its counters.
#   meta.checkpoint_every > 0  -> checkpoint_writes_total
#   meta.resumed truthy        -> resume_skipped_reads
#   meta.on_bad_read in
#     ("skip", "quarantine")   -> bad_reads_total
#   meta.driver == "quorum"    -> stage_retries_total
FAULT_COUNTERS = ("checkpoint_writes_total", "resume_skipped_reads",
                  "bad_reads_total", "stage_retries_total")

# The data-integrity surface (ISSUE 8): a document whose meta declares
# a checksummed database (db_version >= 5) or a verification mode
# (verify_db) must carry the integrity counters.
INTEGRITY_COUNTERS = ("integrity_errors_total",
                      "integrity_bytes_verified_total")

# The device-truth telemetry surface (ISSUE 10): a document whose
# meta declares a `profile` directory must carry the devtrace metrics
# (cli/observability.py records them post-run, zeros included).
DEVTRACE_COUNTERS = ("device_kernel_us_total", "device_step_us_total",
                     "device_idle_us_total",
                     "device_kernel_unattributed_us_total")
DEVTRACE_GAUGES = ("devtrace_steps",)
DEVTRACE_HISTOGRAMS = ("device_kernel_us",)
DEVTRACE_META = ("devtrace_source",)

# The push transport surface (ISSUE 10): a document whose meta
# declares `metrics_push_url` must carry the pusher's counters.
PUSH_COUNTERS = ("metrics_push_total", "metrics_push_failures_total")
PUSH_META = ("metrics_push_host",)

# The alerting surface (ISSUE 11): a document whose meta declares
# alert rules active must carry the engine's counters and gauges.
ALERT_COUNTERS = ("alerts_fired_total", "alert_rule_errors_total")
ALERT_GAUGES = ("alert_rules_active",)

# The memory-frugal counting surface (ISSUE 14): a stage-1 document
# whose meta declares a prefilter mode must carry the prefilter
# counters (pre-created at setup, so 0 counts); one declaring
# partitions > 1 must carry the pass counter plus one
# `partition_distinct{partition="K"}` gauge per partition — a missing
# gauge means a pass's telemetry (or the pass itself) was dropped.
PREFILTER_COUNTERS = ("prefilter_dropped_total",
                      "prefilter_false_pass_total")
PARTITION_COUNTERS = ("partition_passes_total",)
PARTITION_GAUGE_PREFIX = "partition_distinct{partition="

# The compile-sentinel surface (ISSUE 15): a document whose meta
# declares `compile_sentinel` was produced under
# QUORUM_COMPILE_SENTINEL=1 and must carry the ledger export — the
# total compile counter plus the per-site map (the per-site
# `compiles{site="..."}` labeled counters ride along but are not
# individually required: the set of sites a run touches is workload-
# shaped).
COMPILE_COUNTERS = ("compile_events",)
COMPILE_META = ("compile_sites",)

# The flight-recorder surface (ISSUE 16): a document whose meta
# declares `flight` (the recorder was installed and enabled) must
# carry the dump/drop counters — pre-created by FlightRecorder at
# construction, so a clean zero-dump run still proves the black box
# was armed.
FLIGHT_COUNTERS = ("flight_dumps_total", "flight_events_dropped_total")

# The correction-quality surface (ISSUE 17): the data-plane outcome
# names every stage-2 path (offline drain loop and serve engine)
# pre-creates via models/error_correct.precreate_outcome_counters —
# one `skipped_<slug>` counter per REASON_SLUGS slug plus the
# "other" fallback, so zero-count reasons still land in the final
# document (the PR-7 zero-count lesson). A document whose
# meta.stage is "error_correct" or "serve" must carry all of them.
QUALITY_COUNTERS = (
    "substitutions",
    "truncations_3p",
    "truncations_5p",
    "skipped_contaminant",
    "skipped_no_anchor",
    "skipped_homopolymer",
    "skipped_other",
)
QUALITY_HISTOGRAMS = ("substitutions_per_read", "sub_pos_bucket",
                      "trunc_cycle_3p", "trunc_cycle_5p")
# The live scorecard surface: a document whose meta declares
# `quality` (a QualityScorecard was installed) must carry the
# windowed-rate/drift gauges the quality alert rules read — the
# scorecard sets them to quiet values at construction, so they exist
# before the first window closes — plus a top-level `quality`
# section (schema-validated by telemetry/schema.validate_quality).
QUALITY_GAUGES = (
    "quality_corrections_per_read",
    "quality_skip_rate",
    "quality_trunc_rate",
    "quality_contam_rate",
    "quality_anchor_rate",
    "quality_coverage_ratio",
    "quality_drift_score",
)

# The live ingestion surface (ISSUE 18): a serve document whose meta
# declares `live_ingest` (quorum-serve --ingest) must carry the
# ingest/epoch counters (pre-created by IngestDispatcher at
# construction, so a zero-chunk run still proves the tier was armed)
# and the cursor/floor gauges (set at construction and advanced by
# the worker).
LIVE_INGEST_COUNTERS = (
    "ingest_requests_total",
    "ingest_reads_total",
    "epoch_swaps_total",
    "epoch_swap_failures_total",
)
LIVE_INGEST_GAUGES = ("ingest_cursor", "live_floor")

# The resource-guard surface (ISSUE 19): a document whose meta
# declares `resource_guard` (utils/resources.install armed a disk
# monitor over the run's artifact filesystems) must carry the guard
# counters — pre-created by install() so a clean run still proves the
# guard was armed (the PR-7 zero-count lesson) — plus the monitor's
# scalar gauges (published at the synchronous first tick, so they
# exist even if the run finishes inside one interval). The per-path
# `disk_free_bytes{path="..."}` labeled gauges ride along: at least
# one must exist (the watched-path set is run-shaped, so individual
# paths are not required by name).
RESOURCE_COUNTERS = ("writer_degraded_total",
                     "preflight_refusals_total",
                     "stall_aborts_total")
RESOURCE_GAUGES = ("disk_free_bytes_min", "host_rss_bytes")
RESOURCE_GAUGE_PREFIX = "disk_free_bytes{path="

# The multi-host fleet surface (ISSUE 20): a document whose meta
# declares `host_process_count > 1` is the ONE aggregated fleet
# document multihost.aggregate_metrics writes on process 0. It must
# carry the per-host shard documents under top-level `hosts` (exactly
# host_process_count of them, meta.aggregated_hosts agreeing), the
# fleet-reduced resource gauges (free-space gauges min-reduced across
# hosts — see merge_host_docs — so the document reports the TIGHTEST
# disk anywhere in the fleet), and, for every host shard whose meta
# declares compile_sentinel, at least one per-site
# `compiles{site="..."}` counter in that shard (a sentinel host whose
# compile ledger vanished is a host whose compile telemetry was
# dropped, not a host that compiled nothing — stage CLIs always jit).
FLEET_META = ("host_process_count", "aggregated_hosts")
FLEET_GAUGES = RESOURCE_GAUGES
FLEET_COMPILE_PREFIX = "compiles{site="

# The sharded (--devices N) metric surface (ISSUE 5): a stage-1
# document built over more than one shard must carry the per-shard
# telemetry parallel/tile_sharded.record_shard_metrics writes.
SHARD_REQUIRED_COUNTERS = ("shard_batches", "shard_reads",
                           "shard_inserts_total", "distinct_mers")
SHARD_REQUIRED_GAUGES = ("n_shards", "shard_distinct_min",
                         "shard_distinct_max", "shard_inserts_min",
                         "shard_inserts_max")
SHARD_REQUIRED_META_LISTS = ("shard_distinct_mers", "shard_inserts")


def precreate_serve_metrics(registry) -> None:
    """Zero-fill the unconditional serve surface on a registry so a
    serve process that drains before its FIRST /correct request (an
    ingest-only warm-up period, an operator bounce) still writes a
    final document metrics_check accepts — the same pre-creation
    discipline as precreate_outcome_counters. Lazy creation at
    first-request time remains the writer of record; this only
    guarantees the names exist at zero."""
    for name in SERVE_REQUIRED_COUNTERS:
        registry.counter(name)
    for name in SERVE_REQUIRED_HISTOGRAMS:
        registry.histogram(name)


def precreated_counter_names() -> tuple[str, ...]:
    """Every counter name the contract expects quorum_tpu code to
    create with a LITERAL ``.counter("name")`` call — the analyzer's
    pre-creation catalog (quorum-lint `counter-not-precreated`).
    Union of the per-surface lists above, deduplicated, sorted."""
    names: set[str] = set()
    names.update(SERVE_REQUIRED_COUNTERS)
    names.update(name for _, name in SERVE_FEATURE_COUNTERS)
    names.update(FAULT_COUNTERS)
    names.update(INTEGRITY_COUNTERS)
    names.update(DEVTRACE_COUNTERS)
    names.update(PUSH_COUNTERS)
    names.update(ALERT_COUNTERS)
    names.update(COMPILE_COUNTERS)
    names.update(FLIGHT_COUNTERS)
    names.update(SHARD_REQUIRED_COUNTERS)
    names.update(PREFILTER_COUNTERS)
    names.update(PARTITION_COUNTERS)
    names.update(QUALITY_COUNTERS)
    names.update(LIVE_INGEST_COUNTERS)
    names.update(RESOURCE_COUNTERS)
    return tuple(sorted(names))
