"""Batched device corrector: stage 2 (`quorum_error_correct_reads`) as
lockstep masked tensor programs.

The reference corrects one read per thread with data-dependent control
flow (src/error_correct_reads.cc: find_starting_mer :609-643, extend
:384-565, err_log src/err_log.hpp). The TPU-native design runs a whole
batch of reads in lockstep:

* **Anchor phase** (`find_anchors`): rolling k-mers for every position
  of every read are computed by one scan, their DB values fetched by one
  batched lookup, and the reference's sequential anchor scan (k "good"
  mers in a row, contaminant discard, N-resets) becomes a `lax.scan`
  over positions with per-lane counters.

* **Extension phase** (`extend`, one jit per direction): a
  `lax.while_loop` advances every read one base per iteration. Each
  iteration does the shifted-mer contaminant check, one batched
  `get_best_alternatives` (4 lookups/lane), and — for lanes on the
  ambiguous path — the 16-lookup continuation probe, all masked so
  retired/finished lanes cost no probes. Per-lane edit logs (the
  reference's err_log window machinery, including remove_last_window
  rewind) live in fixed-size device buffers.

Semantics are pinned to the pure-Python oracle (models/oracle.py),
which is itself pinned to the reference binary (bug-compatibility
standard: byte parity, including the int-overflow dead code at
error_correct_reads.cc:520 and the inverted backward force_truncate of
err_log.hpp:42-46). The device Poisson test computes in float32; the
oracle mirrors it with poisson_dtype="float32".

Direction convention follows the oracle: d=+1 extends 5'->3', d=-1
extends 3'->5'; positions are raw 0-based read indices throughout.
"""

from __future__ import annotations

import dataclasses
import functools
from typing import NamedTuple

import numpy as np
import jax
import jax.numpy as jnp

from ..ops import ctable, mer, table
from ..ops.poisson import poisson_term
from .ec_config import (
    ECConfig,
    ERROR_CONTAMINANT,
    ERROR_HOMOPOLYMER,
    ERROR_NO_STARTING_MER,
)
from .oracle import ReadResult

# status codes per lane
OK = 0
ST_CONTAMINANT = 1
ST_NO_ANCHOR = 2
ST_HOMOPOLYMER = 3

STATUS_ERRORS = {
    ST_CONTAMINANT: ERROR_CONTAMINANT,
    ST_NO_ANCHOR: ERROR_NO_STARTING_MER,
    ST_HOMOPOLYMER: ERROR_HOMOPOLYMER,
}

# entry meta packing: bit0 type (0=sub, 1=trunc), bits1-3 from, bits4-6
# to; from/to are base codes with 4 = 'N'
_T_SUB = 0
_T_TRUNC = 1
_BASES = "ACGTN"


class LogState(NamedTuple):
    """Per-lane err_log state (err_log.hpp:22-106): entry count, window
    start index, and the entry buffers (raw positions + packed meta)."""

    n: jax.Array  # int32[B]
    lwin: jax.Array  # int32[B]
    pos: jax.Array  # int32[B, E]
    meta: jax.Array  # int32[B, E]


def make_log(b: int, maxe: int) -> LogState:
    z = jnp.zeros((b,), jnp.int32)
    return LogState(z, z, jnp.zeros((b, maxe), jnp.int32),
                    jnp.zeros((b, maxe), jnp.int32))


def _advance_lwin(pos_buf, n, lwin, back, guard, window: int, d: int):
    """The while-advance of err_log::check_nb_error (err_log.hpp:89-92):
    entry positions are monotone in direction order, so the first index
    whose distance from `back` is within the window equals the count of
    over-window entries (a prefix)."""
    maxe = pos_buf.shape[1]
    j = jnp.arange(maxe, dtype=jnp.int32)[None, :]
    dist = d * (back[:, None] - pos_buf)
    over = (j < n[:, None]) & (dist > window)
    cnt = jnp.sum(over.astype(jnp.int32), axis=1)
    return jnp.where(guard, jnp.maximum(lwin, cnt), lwin)


def _log_append(log: LogState, mask, raw_pos, meta_val, window: int,
                error: int, d: int):
    """Append an entry for `mask` lanes and run check_nb_error.
    Returns (log, trip) where trip = error budget exceeded."""
    b = log.n.shape[0]
    maxe = log.pos.shape[1]
    lane = jnp.arange(b, dtype=jnp.int32)
    # masked lanes scatter to index maxe, dropped as out-of-bounds
    # (negative sentinels would *wrap*, silently hitting the last slot)
    idx = jnp.where(mask, log.n, maxe)
    pos_buf = log.pos.at[lane, idx].set(raw_pos, mode="drop")
    meta_buf = log.meta.at[lane, idx].set(meta_val, mode="drop")
    n = log.n + mask.astype(jnp.int32)
    guard = mask & ((raw_pos > window) if d == 1 else (raw_pos < window))
    lwin = _advance_lwin(pos_buf, n, log.lwin, raw_pos, guard, window, d)
    trip = mask & ((n - lwin - 1) >= error)
    return LogState(n, lwin, pos_buf, meta_buf), trip


def _log_remove_last_window(log: LogState, mask, window: int, d: int):
    """err_log::remove_last_window (err_log.hpp:97-106): erase entries
    [lwin:], reset lwin, re-run check_nb_error. Returns (log, diff)
    with diff in direction units (0 for unmasked lanes)."""
    b = log.n.shape[0]
    lane = jnp.arange(b, dtype=jnp.int32)
    back = log.pos[lane, jnp.clip(log.n - 1, 0)]
    at_lwin = log.pos[lane, jnp.clip(log.lwin, 0)]
    diff = jnp.where(mask & (log.n > 0), d * (back - at_lwin), 0)
    n = jnp.where(mask, jnp.where(log.n > 0, log.lwin, 0), log.n)
    lwin = jnp.where(mask, 0, log.lwin)
    nb = log.pos[lane, jnp.clip(n - 1, 0)]
    guard = mask & (n > 0) & ((nb > window) if d == 1 else (nb < window))
    lwin = _advance_lwin(log.pos, n, lwin, nb, guard, window, d)
    return LogState(n, lwin, log.pos, log.meta), diff


def _append_trunc(log: LogState, mask, cpos, window: int, error: int, d: int):
    """log.truncation(cpos): the backward log records pos-1 in direction
    units = raw+1 (error_correct_reads.hpp:170-172)."""
    raw = cpos + (1 if d == -1 else 0)
    meta_val = jnp.full_like(cpos, _T_TRUNC)
    log, _ = _log_append(log, mask, raw, meta_val, window, error, d)
    return log


def _pack_sub(frm, to):
    f = jnp.where(frm >= 0, frm, 4)
    t = jnp.where(to >= 0, to, 4)
    return _T_SUB | (f << 1) | (t << 4)


# ---------------------------------------------------------------------------
# Batched get_best_alternatives
# ---------------------------------------------------------------------------

def _db_lookup(state, tmeta, khi, klo, active=None):
    """Backend dispatch (trace-time; tmeta is static in every caller):
    tile-bucket tables (ops/ctable — one row gather per lookup, the
    fast path) or legacy wide tables (ops/table — probe walk)."""
    if isinstance(tmeta, ctable.TileMeta):
        return ctable.tile_lookup_impl(state, tmeta, khi, klo, active)
    return table._lookup_impl(state, tmeta, khi, klo, active)


def _gba(state, tmeta, fhi, flo, rhi, rlo, d: int, active):
    """database_query::get_best_alternatives (src/mer_database.hpp:
    302-329) for a [B] batch: counts of the 4 base-0 variants kept only
    at the best quality level present; 4 table probes per lane, masked
    by `active`. Returns (counts[B,4] int32, ucode, level, count)."""
    k = tmeta.k
    vhis, vlos = [], []
    for i in range(4):
        nfhi, nflo, nrhi, nrlo = mer.dir_replace0(
            fhi, flo, rhi, rlo, mer.u32(i), d, k)
        chi, clo = mer.canonical(nfhi, nflo, nrhi, nrlo)
        vhis.append(chi)
        vlos.append(clo)
    chi = jnp.stack(vhis).ravel()  # [4B], variant-major
    clo = jnp.stack(vlos).ravel()
    act4 = jnp.tile(active, 4)
    vals = _db_lookup(state, tmeta, chi, clo, act4)
    vals = vals.reshape(4, -1).T  # [B, 4]
    cnt = (vals >> 1).astype(jnp.int32)
    q = (vals & 1).astype(jnp.int32)
    present = cnt > 0
    level = jnp.max(jnp.where(present, q, 0), axis=1)
    counts = jnp.where(present & (q == level[:, None]), cnt, 0)
    has = counts > 0
    count = jnp.sum(has.astype(jnp.int32), axis=1)
    ucode = jnp.zeros_like(count)
    for i in range(4):
        ucode = jnp.where(has[:, i], i, ucode)
    return counts, ucode, level, count


def _contam_hit(contam_state, contam_meta, fhi, flo, rhi, rlo, active):
    chi, clo = mer.canonical(fhi, flo, rhi, rlo)
    v = _db_lookup(contam_state, contam_meta, chi, clo, active)
    return active & (v != 0)


# ---------------------------------------------------------------------------
# Anchor phase
# ---------------------------------------------------------------------------

class AnchorResult(NamedTuple):
    found: jax.Array  # bool[B]
    status: jax.Array  # int32[B] (OK / ST_CONTAMINANT / ST_NO_ANCHOR)
    start_off: jax.Array  # int32[B] first raw index after the anchor mer
    fhi: jax.Array
    flo: jax.Array
    rhi: jax.Array
    rlo: jax.Array
    prev_count: jax.Array  # int32[B] get_val(anchor mer)


@functools.partial(jax.jit, static_argnums=(1, 4, 6, 7))
def find_anchors(state: table.TableState, tmeta: table.TableMeta,
                 codes, lengths, cfg: ECConfig,
                 contam_state, contam_meta, has_contam: bool
                 ) -> AnchorResult:
    """find_starting_mer (error_correct_reads.cc:609-643) over a batch.

    The sequential build/check loop is equivalent to scanning all
    positions p (last base of a window) in order: windows with k
    consecutive ACGT bases starting at >= skip are "checked" while
    p <= len-2; an N resets the good-run counter; contaminated windows
    are skipped (counter unchanged) or kill the read. Anchor at the
    first p where `good` consecutive checked mers had HQ count >=
    anchor_count; start_off = p + 1."""
    k = cfg.k
    b, l = codes.shape
    codes32 = codes.astype(jnp.int32)
    fhi, flo, rhi, rlo, validk = mer.rolling_kmers(codes32, k)
    chi, clo = mer.canonical(fhi, flo, rhi, rlo)
    p_idx = jnp.arange(l, dtype=jnp.int32)[None, :]
    vw = validk & (p_idx >= cfg.skip + k - 1)
    vals = _db_lookup(
        state, tmeta, chi.ravel(), clo.ravel(), vw.ravel()
    ).reshape(b, l)
    val_hq = jnp.where((vals & 1) == 1, vals >> 1, 0).astype(jnp.int32)
    if has_contam:
        con = _db_lookup(
            contam_state, contam_meta, chi.ravel(), clo.ravel(), vw.ravel()
        ).reshape(b, l) != 0
    else:
        con = jnp.zeros((b, l), bool)
    checked = vw & (p_idx <= (lengths[:, None] - 2))

    # lax.scan over positions with per-lane counters
    def scan_step(carry, x):
        found, done, anchor_p, contam_flag = carry
        vwp, chkp, valp, conp, p = x
        is_checked = chkp & ~done
        con_event = is_checked & conp & (not cfg.trim_contaminant)
        contam_flag = contam_flag | con_event
        done = done | con_event
        upd = is_checked & ~conp & ~con_event
        found = jnp.where(
            upd, jnp.where(valp >= cfg.anchor_count, found + 1, 0), found)
        hit = upd & (found >= cfg.good) & ~done
        anchor_p = jnp.where(hit, p, anchor_p)
        done = done | hit
        found = jnp.where(~vwp & ~done, 0, found)
        return (found, done, anchor_p, contam_flag), None

    z = jnp.zeros((b,), jnp.int32)
    fz = jnp.zeros((b,), bool)
    xs = (vw.T, checked.T, val_hq.T, con.T,
          jnp.arange(l, dtype=jnp.int32)[:, None] + jnp.zeros((l, b), jnp.int32))
    (found, done, anchor_p, contam_flag), _ = jax.lax.scan(
        scan_step, (z, fz, z, fz), xs)

    anchor_found = done & ~contam_flag
    status = jnp.where(anchor_found, OK,
                       jnp.where(contam_flag, ST_CONTAMINANT, ST_NO_ANCHOR))
    lane = jnp.arange(b, dtype=jnp.int32)
    ap = jnp.clip(anchor_p, 0)
    return AnchorResult(
        anchor_found, status, anchor_p + 1,
        fhi[lane, ap], flo[lane, ap], rhi[lane, ap], rlo[lane, ap],
        val_hq[lane, ap],
    )


# ---------------------------------------------------------------------------
# Extension phase
# ---------------------------------------------------------------------------

class ExtendResult(NamedTuple):
    out: jax.Array  # int32[B, L]
    opos: jax.Array  # int32[B] one-past-last-written in direction d
    status: jax.Array  # int32[B]
    log: LogState


def _extend_env(state, tmeta, codes, quals, cfg, end, contam_state,
                contam_meta, d: int, has_contam: bool):
    """Shared helpers closed over the static extension environment."""
    window = cfg.effective_window
    error = cfg.effective_error
    b, l = codes.shape
    lane = jnp.arange(b, dtype=jnp.int32)
    codes32 = codes.astype(jnp.int32)
    quals32 = quals.astype(jnp.int32)

    def in_range(pos):
        return (pos < end) if d == 1 else (pos > end)

    def gather_code(arr, idx, mask):
        safe = jnp.clip(idx, 0, l - 1)
        v = jnp.take_along_axis(arr, safe[:, None], axis=1)[:, 0]
        return jnp.where(mask, v, -1)

    def take4(counts, idx):
        safe = jnp.clip(idx, 0, 3)
        return jnp.take_along_axis(counts, safe[:, None], axis=1)[:, 0]

    def contam(fh, fl, rh, rl, mask):
        if not has_contam:
            return jnp.zeros_like(mask)
        return _contam_hit(contam_state, contam_meta, fh, fl, rh, rl, mask)

    return (in_range, gather_code, take4, contam, lane, codes32, quals32,
            window, error, b, l)


@functools.partial(jax.jit, static_argnums=(1, 4, 8, 9, 10))
def _extend_loop(state, tmeta, codes, quals, cfg: ECConfig,
                 carry, end,
                 contam_state, contam_meta, d: int, has_contam: bool):
    """The lockstep extension loop; the ambiguous-path continuation
    probe runs inline via _ambig_core (see extend's docstring for why
    inline beats parking)."""
    k = cfg.k
    (in_range, gather_code, take4, contam, lane, codes32, quals32,
     window, error, b, l) = _extend_env(
        state, tmeta, codes, quals, cfg, end, contam_state, contam_meta,
        d, has_contam)

    def body(carry):
        (fh, fl, rh, rl, pos, opos, prev, alive, status, outb, log) = carry
        active = alive & in_range(pos)
        cpos = pos
        pos = jnp.where(active, pos + d, pos)

        ori = gather_code(codes32, cpos, active)
        qualc = jnp.where(active,
                          gather_code(quals32, cpos, active), 0)

        shift_code = mer.u32(jnp.maximum(ori, 0))
        sfh, sfl, srh, srl = mer.dir_shift(fh, fl, rh, rl, shift_code, d, k)
        fh = jnp.where(active, sfh, fh)
        fl = jnp.where(active, sfl, fl)
        rh = jnp.where(active, srh, rh)
        rl = jnp.where(active, srl, rl)

        # contaminant on the shifted mer (error_correct_reads.cc:401-407)
        con1 = contam(fh, fl, rh, rl, active & (ori >= 0))
        con1_trim = con1 if cfg.trim_contaminant else jnp.zeros_like(con1)
        con1_err = con1 & ~con1_trim
        log = _append_trunc(log, con1_trim, cpos, window, error, d)
        status = jnp.where(con1_err, ST_CONTAMINANT, status)
        alive = alive & ~con1
        live = active & ~con1

        counts, ucode, level, count = _gba(
            state, tmeta, fh, fl, rh, rl, d, live)

        # count == 0: truncate (cc:416-419)
        t0 = live & (count == 0)
        log = _append_trunc(log, t0, cpos, window, error, d)
        alive = alive & ~t0
        live = live & ~t0

        # count == 1 (cc:421-430)
        c1 = live & (count == 1)
        prev = jnp.where(c1, take4(counts, ucode), prev)
        sub1 = c1 & (ori != ucode)
        nfh, nfl, nrh, nrl = mer.dir_replace0(
            fh, fl, rh, rl, mer.u32(jnp.clip(ucode, 0)), d, k)
        fh = jnp.where(c1, nfh, fh)
        fl = jnp.where(c1, nfl, fl)
        rh = jnp.where(c1, nrh, rh)
        rl = jnp.where(c1, nrl, rl)
        # log_substitution (cc:360-379): contaminant check on the
        # substituted mer, then window-budget bookkeeping
        con2 = contam(fh, fl, rh, rl, sub1)
        con2_trim = con2 if cfg.trim_contaminant else jnp.zeros_like(con2)
        con2_err = con2 & ~con2_trim
        log = _append_trunc(log, con2_trim, cpos, window, error, d)
        status = jnp.where(con2_err, ST_CONTAMINANT, status)
        alive = alive & ~con2
        sub1 = sub1 & ~con2
        log, trip1 = _log_append(
            log, sub1, cpos, _pack_sub(ori, ucode), window, error, d)
        log, diff1 = _log_remove_last_window(log, trip1, window, d)
        log = _append_trunc(log, trip1, cpos - d * diff1, window, error, d)
        opos = jnp.where(trip1, opos - d * diff1, opos)
        alive = alive & ~trip1
        write1 = c1 & ~con2 & ~trip1

        # count > 1 (cc:432-561)
        cm = live & (count > 1)
        c_ori = jnp.where(cm & (ori >= 0), take4(counts, ori), 0)
        ori_hi = cm & (ori >= 0) & (c_ori > cfg.min_count)
        keep_cut = ori_hi & ((c_ori >= cfg.cutoff)
                             | (qualc >= cfg.qual_cutoff))
        p_lam = (jnp.sum(counts, axis=1).astype(jnp.float32)
                 * jnp.float32(cfg.collision_prob))
        prob = poisson_term(p_lam, c_ori)
        keep_poi = ori_hi & ~keep_cut & (prob < cfg.poisson_threshold)
        keep_simple = keep_cut | keep_poi
        t_a = cm & (ori >= 0) & ~ori_hi & (level == 0) & (c_ori == 0)
        t_b = cm & (ori < 0) & (level == 0)
        log = _append_trunc(log, t_a | t_b, cpos, window, error, d)
        alive = alive & ~(t_a | t_b)
        ambig = cm & ~keep_simple & ~t_a & ~t_b
        env = (in_range, gather_code, take4, contam, lane, codes32,
               quals32, window, error, b, l)
        (fh, fl, rh, rl, pos, opos, prev, alive, status, outb,
         log) = _ambig_core(env, state, tmeta, cfg, d,
                            fh, fl, rh, rl, pos, opos, prev, alive,
                            status, outb, log, ambig, cpos, ori,
                            counts, level)

        write = write1 | (keep_simple & alive & active)
        base0 = mer.dir_base0(fh, fl, d, k).astype(jnp.int32)
        # out-of-range positive sentinel: dropped (negative would wrap)
        widx = jnp.where(write, opos, l)
        outb = outb.at[lane, widx].set(base0, mode="drop")
        opos = jnp.where(write, opos + d, opos)

        return (fh, fl, rh, rl, pos, opos, prev, alive, status, outb, log)

    def cond(carry):
        (_, _, _, _, pos, _, _, alive, _, _, _) = carry
        return jnp.any(alive & in_range(pos))

    return jax.lax.while_loop(cond, body, carry)


def _ambig_core(env, state, tmeta, cfg, d: int,
                fh, fl, rh, rl, pos, opos, prev, alive, status, outb, log,
                ambig, cpos, ori, counts, level):
    """The ambiguous-path continuation probe + tie-break
    (error_correct_reads.cc:473-545), shared by the host-orchestrated
    resolve step and the traceable inline path (shard_map)."""
    k = cfg.k
    (in_range, gather_code, take4, contam, lane, codes32, quals32,
     window, error, b, l) = env
    read_nbase = gather_code(codes32, pos, in_range(pos) & ambig)
    chis, clos = [], []
    for i in range(4):
        ifh, ifl, irh, irl = mer.dir_replace0(
            fh, fl, rh, rl, mer.u32(i), d, k)
        ifh, ifl, irh, irl = mer.dir_shift(
            ifh, ifl, irh, irl, mer.u32(0), d, k)
        for j in range(4):
            jfh, jfl, jrh, jrl = mer.dir_replace0(
                ifh, ifl, irh, irl, mer.u32(j), d, k)
            chi, clo = mer.canonical(jfh, jfl, jrh, jrl)
            chis.append(chi)
            clos.append(clo)
    elig = jnp.stack([ambig & (counts[:, i] > cfg.min_count)
                      for i in range(4)], axis=1)  # [B, 4]
    act16 = jnp.repeat(elig.T, 4, axis=0).reshape(-1)  # [16B] i-major
    nvals = _db_lookup(
        state, tmeta, jnp.stack(chis).ravel(), jnp.stack(clos).ravel(),
        act16,
    ).reshape(4, 4, b)  # [i, j, B]
    ncnt = (nvals >> 1).astype(jnp.int32)
    nq = (nvals & 1).astype(jnp.int32)
    npresent = ncnt > 0
    nlevel = jnp.max(jnp.where(npresent, nq, 0), axis=1)  # [i, B]
    ncounts = jnp.where(npresent & (nq == nlevel[:, None, :]), ncnt, 0)
    ncount = jnp.sum((ncounts > 0).astype(jnp.int32), axis=1)  # [i, B]

    succ = jnp.stack([
        elig[:, i] & (ncount[i] > 0) & (nlevel[i] >= level)
        for i in range(4)], axis=1)  # [B, 4]
    cont_counts = jnp.where(succ, counts, 0)
    safe_nb = jnp.clip(read_nbase, 0, 3)
    cwn = jnp.stack([
        succ[:, i] & (read_nbase >= 0)
        & (ncounts[i][safe_nb, lane] > 0)
        for i in range(4)], axis=1)  # [B, 4]

    check_code = jnp.where(ambig, ori, 0)
    for i in range(4):
        check_code = jnp.where(elig[:, i], i, check_code)
    success = ambig & jnp.any(succ, axis=1)

    # tie-break chain (cc:509-545). prev_count <= min_count takes
    # the int-overflow dead-code path: no candidate ever matches.
    prev_ok = prev > cfg.min_count
    diffs = jnp.abs(cont_counts - prev[:, None])
    min_diff = jnp.min(
        jnp.where(cont_counts > 0, diffs, jnp.int32(2**31 - 1)), axis=1)
    cand = success[:, None] & prev_ok[:, None] & (diffs == min_diff[:, None])
    ncand = jnp.sum(cand.astype(jnp.int32), axis=1)
    cc2 = jnp.full((b,), -1, jnp.int32)
    for i in range(4):
        cc2 = jnp.where(cand[:, i], i, cc2)
    tie = (ncand > 1) & (read_nbase >= 0)
    ncand = jnp.where(tie, jnp.sum((cand & cwn).astype(jnp.int32), axis=1),
                      ncand)
    for i in range(4):
        cc2 = jnp.where(tie & cand[:, i] & cwn[:, i], i, cc2)
    cc2 = jnp.where(ncand != 1, -1, cc2)
    check_code = jnp.where(success, cc2, check_code)

    sub2 = success & (check_code >= 0) & (check_code != ori)
    nfh, nfl, nrh, nrl = mer.dir_replace0(
        fh, fl, rh, rl, mer.u32(jnp.clip(check_code, 0)), d, k)
    do_rep = success & (check_code >= 0)
    fh = jnp.where(do_rep, nfh, fh)
    fl = jnp.where(do_rep, nfl, fl)
    rh = jnp.where(do_rep, nrh, rh)
    rl = jnp.where(do_rep, nrl, rl)
    con3 = contam(fh, fl, rh, rl, sub2)
    con3_trim = con3 if cfg.trim_contaminant else jnp.zeros_like(con3)
    con3_err = con3 & ~con3_trim
    log = _append_trunc(log, con3_trim, cpos, window, error, d)
    status = jnp.where(con3_err, ST_CONTAMINANT, status)
    alive = alive & ~con3
    sub2 = sub2 & ~con3
    log, trip2 = _log_append(
        log, sub2, cpos, _pack_sub(ori, check_code), window, error, d)
    log, diff2 = _log_remove_last_window(log, trip2, window, d)
    log = _append_trunc(log, trip2, cpos - d * diff2, window, error, d)
    opos = jnp.where(trip2, opos - d * diff2, opos)
    alive = alive & ~trip2

    # N base with no good substitution: truncate (cc:553-556)
    t_c = ambig & ~con3 & ~trip2 & (ori < 0) & (check_code < 0)
    log = _append_trunc(log, t_c, cpos, window, error, d)
    alive = alive & ~t_c

    write = ambig & alive
    base0 = mer.dir_base0(fh, fl, d, k).astype(jnp.int32)
    widx = jnp.where(write, opos, l)
    outb = outb.at[lane, widx].set(base0, mode="drop")
    opos = jnp.where(write, opos + d, opos)

    return (fh, fl, rh, rl, pos, opos, prev, alive, status, outb, log)


def extend(state, tmeta, codes, quals, cfg: ECConfig,
           out, fhi, flo, rhi, rlo, prev0, alive0,
           pos0, end, status0,
           contam_state, contam_meta, d: int, has_contam: bool):
    """extend (error_correct_reads.cc:384-565) in lockstep over a batch:
    one fused while_loop advancing every live lane one base per
    iteration, with the ambiguous-path continuation probe inline
    (_ambig_core). Measured on real-coverage data the ambiguous branch
    fires on a large minority of lanes (error k-mers recorded in the DB
    make count > 1 common), so parking/compacting those lanes loses to
    simply keeping the probe in the loop."""
    b = codes.shape[0]
    maxe = out.shape[1] + 2
    log0 = make_log(b, maxe)
    carry = (fhi, flo, rhi, rlo, pos0, pos0, prev0, alive0, status0, out,
             log0)
    carry = _extend_loop(state, tmeta, codes, quals, cfg, carry, end,
                         contam_state, contam_meta, d, has_contam)
    (_, _, _, _, _, opos, _, _, status, outb, log) = carry
    return ExtendResult(outb, opos, status, log)


# ---------------------------------------------------------------------------
# Batch glue + host finishing
# ---------------------------------------------------------------------------

class BatchResult(NamedTuple):
    """Device-side result of correcting one batch."""

    out: jax.Array  # int32[B, L] corrected base codes
    start: jax.Array  # int32[B] first kept index (5_trunc)
    end: jax.Array  # int32[B] one past last kept index (3_trunc)
    status: jax.Array  # int32[B]
    fwd_log: LogState
    bwd_log: LogState


def _dummy_contam(k: int):
    meta = table.TableMeta(k=k, bits=1, size_log2=4)
    return table.make_table(meta), meta


def correct_batch(state: table.TableState, tmeta: table.TableMeta,
                  codes, quals, lengths, cfg: ECConfig,
                  contam=None) -> BatchResult:
    """Correct a batch of reads on device. `contam` is an optional
    (TableState, TableMeta) k-mer membership set (value word != 0).
    Mirrors error_correct_instance::start (error_correct_reads.cc:
    246-341): anchor, forward extend, backward extend."""
    codes = jnp.asarray(codes, jnp.int32)
    quals = jnp.asarray(quals, jnp.int32)
    lengths = jnp.asarray(lengths, jnp.int32)
    has_contam = contam is not None
    cstate, cmeta = contam if has_contam else _dummy_contam(cfg.k)
    if has_contam and cmeta.k != cfg.k:
        raise ValueError(
            f"Contaminant mer length ({cmeta.k}) different than correction "
            f"mer length ({cfg.k})")

    anc = find_anchors(state, tmeta, codes, lengths, cfg,
                       cstate, cmeta, has_contam)
    b = codes.shape[0]
    out0 = codes
    fwd = extend(state, tmeta, codes, quals, cfg, out0,
                 anc.fhi, anc.flo, anc.rhi, anc.rlo,
                 anc.prev_count, anc.found,
                 anc.start_off, lengths, anc.status,
                 cstate, cmeta, 1, has_contam)
    bwd_alive = anc.found & (fwd.status == OK)
    bpos0 = anc.start_off - cfg.k - 1
    bend = jnp.full((b,), -1, jnp.int32)
    bwd = extend(state, tmeta, codes, quals, cfg, fwd.out,
                 anc.fhi, anc.flo, anc.rhi, anc.rlo,
                 anc.prev_count, bwd_alive,
                 bpos0, bend, fwd.status,
                 cstate, cmeta, -1, has_contam)
    return BatchResult(bwd.out, bwd.opos + 1, fwd.opos, bwd.status,
                       fwd.log, bwd.log)


def _render_entries(pos, meta, n, trunc_string: str) -> str:
    parts = []
    for j in range(n):
        m = int(meta[j])
        if m & 1:
            parts.append(f"{int(pos[j])}:{trunc_string}")
        else:
            frm = (m >> 1) & 7
            to = (m >> 4) & 7
            parts.append(f"{int(pos[j])}:sub:{_BASES[frm]}-{_BASES[to]}")
    return " ".join(parts)


def _homo_trim_np(out, start, end, ok, homo_trim_val: int):
    """Vectorized homo_trim (error_correct_reads.cc:567-597): walking
    from the 3' end, score +1 per repeated base, -1 per change; trim at
    the highest-scoring position (largest position wins ties) if the
    max score reaches the threshold. Returns (trim_mask, max_pos)."""
    b, l = out.shape
    q = np.arange(l - 1)[None, :]
    t = np.where((q >= start[:, None]) & (q <= end[:, None] - 2),
                 2 * (out[:, :-1] == out[:, 1:]).astype(np.int64) - 1, 0)
    scores = np.flip(np.cumsum(np.flip(t, 1), 1), 1)  # S[p] = sum t[p:]
    valid = (q >= start[:, None]) & (q <= end[:, None] - 2) & ok[:, None]
    neg = np.int64(-(2**62))
    masked = np.where(valid, scores, neg)
    max_score = masked.max(axis=1)
    has = valid.any(axis=1)
    is_max = valid & (masked == max_score[:, None])
    max_pos = np.where(has,
                       np.where(is_max, q, -1).max(axis=1), -1)
    trim = has & (max_score >= homo_trim_val)
    return trim, max_pos


def finish_batch(res: BatchResult, n: int, cfg: ECConfig
                 ) -> list[ReadResult]:
    """Host post-processing: optional homo-trim, log rendering, and
    ReadResult assembly (same shape as the oracle's results)."""
    out = np.asarray(res.out)
    start = np.asarray(res.start).copy()
    end = np.asarray(res.end).copy()
    status = np.asarray(res.status).copy()
    f_n = np.asarray(res.fwd_log.n).copy()
    f_pos = np.asarray(res.fwd_log.pos).copy()
    f_meta = np.asarray(res.fwd_log.meta).copy()
    b_n = np.asarray(res.bwd_log.n).copy()
    b_pos = np.asarray(res.bwd_log.pos).copy()
    b_meta = np.asarray(res.bwd_log.meta).copy()

    extra_fwd: dict[int, list[tuple[int, int]]] = {}
    if cfg.do_homo_trim:
        ok = status[:n] == OK
        trim, max_pos = _homo_trim_np(out[:n], start[:n], end[:n], ok,
                                      cfg.homo_trim)
        for i in np.nonzero(trim)[0]:
            mp = int(max_pos[i])
            if mp < start[i]:  # pragma: no cover - dead in the binary too
                status[i] = ST_HOMOPOLYMER
                continue
            # force_truncate, binary parity (see oracle module
            # docstring): forward drops raw >= pos, backward raw <= pos
            keep = f_pos[i, : f_n[i]] < mp
            f_pos[i, : keep.sum()] = f_pos[i, : f_n[i]][keep]
            f_meta[i, : keep.sum()] = f_meta[i, : f_n[i]][keep]
            f_n[i] = keep.sum()
            bkeep = b_pos[i, : b_n[i]] > mp
            b_pos[i, : bkeep.sum()] = b_pos[i, : b_n[i]][bkeep]
            b_meta[i, : bkeep.sum()] = b_meta[i, : b_n[i]][bkeep]
            b_n[i] = bkeep.sum()
            extra_fwd[int(i)] = [(mp, _T_TRUNC)]
            end[i] = mp

    results: list[ReadResult] = []
    for i in range(n):
        st = int(status[i])
        if st != OK:
            results.append(ReadResult(False, STATUS_ERRORS[st]))
            continue
        s, e = int(start[i]), int(end[i])
        seq_codes = out[i, s:e]
        seq = mer.codes_to_seq(seq_codes) if e > s else ""
        fwd_s = _render_entries(f_pos[i], f_meta[i], int(f_n[i]), "3_trunc")
        if int(i) in extra_fwd:
            extra = " ".join(f"{p}:3_trunc" for p, _ in extra_fwd[int(i)])
            fwd_s = f"{fwd_s} {extra}" if fwd_s else extra
        bwd_s = _render_entries(b_pos[i], b_meta[i], int(b_n[i]), "5_trunc")
        results.append(ReadResult(True, "", seq, fwd_s, bwd_s, s, e))
    return results
