"""Batched device corrector: stage 2 (`quorum_error_correct_reads`) as
lockstep masked tensor programs.

The reference corrects one read per thread with data-dependent control
flow (src/error_correct_reads.cc: find_starting_mer :609-643, extend
:384-565, err_log src/err_log.hpp). The TPU-native design runs a whole
batch of reads in lockstep:

* **Anchor phase** (`find_anchors`): rolling k-mers for every position
  of every read are computed by vectorized taps, their DB values
  fetched by one batched lookup, and the reference's sequential anchor
  scan (k "good" mers in a row, contaminant discard, N-resets) is
  evaluated in closed form (cumsum/cummax run lengths).

* **Extension phase** (`extend`, ONE jit for both directions): a
  `lax.while_loop` advances every lane one base per iteration, 2B
  lanes wide — the backward half runs in the reverse-complement frame
  (rc codes, swapped mer strands, mirrored positions; `correct_batch`
  docstring), so forward and backward extension share one d=+1
  executable and overlap in time. Each iteration does the shifted-mer
  contaminant check, one batched `get_best_alternatives` (4
  lookups/lane), and — for the sparse ambiguous lanes, compacted into
  a fixed capacity — the 16-lookup continuation probe. Per-lane edit
  logs (the reference's err_log window machinery, including
  remove_last_window rewind) live in fixed-size device buffers.

Semantics are pinned to the pure-Python oracle (models/oracle.py),
which is itself pinned to the reference binary (bug-compatibility
standard: byte parity, including the int-overflow dead code at
error_correct_reads.cc:520 and the inverted backward force_truncate of
err_log.hpp:42-46). The device Poisson test computes in float32; the
oracle mirrors it with poisson_dtype="float32".

Direction convention follows the oracle: d=+1 extends 5'->3', d=-1
extends 3'->5'; positions are raw 0-based read indices throughout.
"""

from __future__ import annotations

import functools
from typing import NamedTuple

import numpy as np
import jax
import jax.numpy as jnp

from ..io import packing
from ..utils import levers
from ..ops import ctable, mer
from ..ops.poisson import poisson_term
from .ec_config import (
    ECConfig,
    ERROR_CONTAMINANT,
    ERROR_HOMOPOLYMER,
    ERROR_NO_STARTING_MER,
)
from .oracle import ReadResult

# status codes per lane
OK = 0
ST_CONTAMINANT = 1
ST_NO_ANCHOR = 2
ST_HOMOPOLYMER = 3

STATUS_ERRORS = {
    ST_CONTAMINANT: ERROR_CONTAMINANT,
    ST_NO_ANCHOR: ERROR_NO_STARTING_MER,
    ST_HOMOPOLYMER: ERROR_HOMOPOLYMER,
}

# entry meta packing: bit0 type (0=sub, 1=trunc), bits1-3 from, bits4-6
# to; from/to are base codes with 4 = 'N'
_T_SUB = 0
_T_TRUNC = 1
_BASES = "ACGTN"


class LogState(NamedTuple):
    """Per-lane err_log state (err_log.hpp:22-106): entry count, window
    start index, and the entry buffers (raw positions + packed meta)."""

    n: jax.Array  # int32[B]
    lwin: jax.Array  # int32[B]
    pos: jax.Array  # int32[B, E]
    meta: jax.Array  # int32[B, E]


def make_log(b: int, maxe: int) -> LogState:
    z = jnp.zeros((b,), jnp.int32)
    return LogState(z, z, jnp.zeros((b, maxe), jnp.int32),
                    jnp.zeros((b, maxe), jnp.int32))


def _advance_lwin(pos_buf, n, lwin, back, guard, window: int, d: int):
    """The while-advance of err_log::check_nb_error (err_log.hpp:89-92):
    entry positions are monotone in direction order, so the first index
    whose distance from `back` is within the window equals the count of
    over-window entries (a prefix)."""
    maxe = pos_buf.shape[1]
    j = jnp.arange(maxe, dtype=jnp.int32)[None, :]
    dist = d * (back[:, None] - pos_buf)
    over = (j < n[:, None]) & (dist > window)
    cnt = jnp.sum(over.astype(jnp.int32), axis=1)
    return jnp.where(guard, jnp.maximum(lwin, cnt), lwin)


def _log_append(log: LogState, mask, raw_pos, meta_val, window: int,
                error: int, d: int, thresh=None):
    """Append an entry for `mask` lanes and run check_nb_error.
    Returns (log, trip) where trip = error budget exceeded.

    `thresh` is the guard threshold: the advance runs only once the
    append position is more than a window past the direction origin —
    `d * (raw - thresh) > 0` expresses both the forward (raw > window)
    and backward (raw < window) forms of err_log.hpp:89. It defaults to
    the scalar window; the merged fwd+bwd loop passes a per-lane array
    (len-1-window for reverse-complement-frame lanes)."""
    b = log.n.shape[0]
    maxe = log.pos.shape[1]
    lane = jnp.arange(b, dtype=jnp.int32)
    if thresh is None:
        thresh = window
    # masked lanes scatter to index maxe, dropped as out-of-bounds
    # (negative sentinels would *wrap*, silently hitting the last slot)
    idx = jnp.where(mask, log.n, maxe)
    pos_buf = log.pos.at[lane, idx].set(raw_pos, mode="drop")
    meta_buf = log.meta.at[lane, idx].set(meta_val, mode="drop")
    n = log.n + mask.astype(jnp.int32)
    guard = mask & (d * (raw_pos - thresh) > 0)
    lwin = _advance_lwin(pos_buf, n, log.lwin, raw_pos, guard, window, d)
    trip = mask & ((n - lwin - 1) >= error)
    return LogState(n, lwin, pos_buf, meta_buf), trip


def _log_remove_last_window(log: LogState, mask, window: int, d: int,
                            thresh=None):
    """err_log::remove_last_window (err_log.hpp:97-106): erase entries
    [lwin:], reset lwin, re-run check_nb_error. Returns (log, diff)
    with diff in direction units (0 for unmasked lanes)."""
    b = log.n.shape[0]
    lane = jnp.arange(b, dtype=jnp.int32)
    if thresh is None:
        thresh = window
    back = log.pos[lane, jnp.clip(log.n - 1, 0)]
    at_lwin = log.pos[lane, jnp.clip(log.lwin, 0)]
    diff = jnp.where(mask & (log.n > 0), d * (back - at_lwin), 0)
    n = jnp.where(mask, jnp.where(log.n > 0, log.lwin, 0), log.n)
    lwin = jnp.where(mask, 0, log.lwin)
    nb = log.pos[lane, jnp.clip(n - 1, 0)]
    guard = mask & (n > 0) & (d * (nb - thresh) > 0)
    lwin = _advance_lwin(log.pos, n, lwin, nb, guard, window, d)
    return LogState(n, lwin, log.pos, log.meta), diff


def _append_trunc(log: LogState, mask, cpos, window: int, error: int, d: int,
                  thresh=None):
    """log.truncation(cpos): the backward log records pos-1 in direction
    units = raw+1 (error_correct_reads.hpp:170-172). The merged loop
    runs backward lanes in the reverse-complement frame with d=+1; the
    +1 quirk is applied there by the entry remap in _bwd_epilogue.

    INVARIANT: truncation is terminal — every call site retires the
    lane (alive &= ~mask) in the same iteration, so the lwin/trip
    produced here (computed with the merged loop's sub-entry guard
    threshold, an off-by-one vs the reference's raw backward trunc
    guard on raw+1) are never read afterwards. A future non-terminal
    truncation append must NOT reuse this helper as-is."""
    raw = cpos + (1 if d == -1 else 0)
    meta_val = jnp.full_like(cpos, _T_TRUNC)
    log, _ = _log_append(log, mask, raw, meta_val, window, error, d, thresh)
    return log


def _pack_sub(frm, to):
    f = jnp.where(frm >= 0, frm, 4)
    t = jnp.where(to >= 0, to, 4)
    return _T_SUB | (f << 1) | (t << 4)


# ---------------------------------------------------------------------------
# Batched get_best_alternatives
# ---------------------------------------------------------------------------

def _db_lookup(state, tmeta, khi, klo, active=None):
    """Backend dispatch (trace-time; tmeta is static in every caller):
    tile-bucket tables (ops/ctable — one row gather per lookup, the
    fast path), mesh-ROUTED sharded tile tables (parallel/tile_sharded
    RoutedTileMeta — the capacity path for tables beyond one chip's
    HBM; only valid under shard_map), or legacy wide tables (ops/table
    — probe walk)."""
    if getattr(tmeta, "routed_axis", None) is not None:
        from ..parallel import tile_sharded

        return tile_sharded.routed_lookup_local(state.rows, tmeta, khi,
                                                klo, active)
    return ctable.tile_lookup_impl(state, tmeta, khi, klo, active)


# Max rows per single lookup op in the TOP-LEVEL sweeps: a tile-row
# gather can materialize [N, 128] u32 (512 B/row), so an unchunked
# multi-million-row sweep transiently costs gigabytes of HBM
# (RESOURCE_EXHAUSTED at 32k-read batches). Chunking top-level passes
# costs only a few extra dispatch-free ops; IN-LOOP lookups must stay
# single ops (each in-loop op costs ~0.16 ms) and are kept small by
# their compaction caps instead.
_LOOKUP_CHUNK = 2 * 1024 * 1024


def _db_lookup_big(state, tmeta, khi, klo, active=None):
    n = khi.shape[0]
    if n <= _LOOKUP_CHUNK:
        return _db_lookup(state, tmeta, khi, klo, active)
    parts = []
    for s in range(0, n, _LOOKUP_CHUNK):
        e = min(n, s + _LOOKUP_CHUNK)
        parts.append(_db_lookup(
            state, tmeta, khi[s:e], klo[s:e],
            None if active is None else active[s:e]))
    return jnp.concatenate(parts)


def _compact_select(mask, cap: int, idx):
    """THE cumsum/scatter compaction idiom, shared by every compacted
    probe: the first `cap` set lanes of `mask` scatter their `idx`
    value into a [cap] selector. Masked / overflow lanes use POSITIVE
    out-of-bounds sentinels with mode="drop" (negative indices would
    silently wrap — PERF_NOTES layout landmines). Returns
    (slot[n], fitted[n], sel[cap], slot_live[cap])."""
    slot = jnp.cumsum(mask.astype(jnp.int32)) - 1
    fitted = mask & (slot < cap)
    sel = jnp.zeros((cap,), idx.dtype).at[
        jnp.where(fitted, slot, cap)].set(idx, mode="drop")
    n_fit = jnp.sum(fitted.astype(jnp.int32))
    slot_live = jnp.arange(cap, dtype=jnp.int32) < n_fit
    return slot, fitted, sel, slot_live


def _gba_reduce(vals):
    """The best-quality-level reduction of get_best_alternatives
    (src/mer_database.hpp:302-329), shared by every caller that has the
    4 variant value words: keep counts only at the best quality level
    present; ucode = largest variant code with a kept count.

    `vals` is a LIST of 4 same-shaped uint32 value words (variant code
    order) — lists rather than a stacked [..., 4] array because a
    resident minor-dim-4 array invites the T(8,128) padded layout
    (32x memory blowup, PERF_NOTES.md). Returns (counts list[4] int32,
    ucode, level, count)."""
    cnts = [(v >> 1).astype(jnp.int32) for v in vals]
    qs = [(v & 1).astype(jnp.int32) for v in vals]
    level = jnp.zeros_like(cnts[0])
    for c, q in zip(cnts, qs):
        level = jnp.maximum(level, jnp.where(c > 0, q, 0))
    counts = [jnp.where((c > 0) & (q == level), c, 0)
              for c, q in zip(cnts, qs)]
    count = counts[0] * 0
    for c in counts:
        count = count + (c > 0).astype(jnp.int32)
    ucode = jnp.zeros_like(count)
    for i, c in enumerate(counts):
        ucode = jnp.where(c > 0, i, ucode)
    return counts, ucode, level, count


def _gba(state, tmeta, fhi, flo, rhi, rlo, d: int, active):
    """database_query::get_best_alternatives (src/mer_database.hpp:
    302-329) for a [B] batch: counts of the 4 base-0 variants kept only
    at the best quality level present; 4 table probes per lane, masked
    by `active`. Returns (counts[B,4] int32, ucode, level, count)."""
    k = tmeta.k
    vhis, vlos = [], []
    for i in range(4):
        nfhi, nflo, nrhi, nrlo = mer.dir_replace0(
            fhi, flo, rhi, rlo, mer.u32(i), d, k)
        chi, clo = mer.canonical(nfhi, nflo, nrhi, nrlo)
        vhis.append(chi)
        vlos.append(clo)
    chi = jnp.stack(vhis).ravel()  # [4B], variant-major
    clo = jnp.stack(vlos).ravel()
    act4 = jnp.tile(active, 4)
    vals = _db_lookup(state, tmeta, chi, clo, act4).reshape(4, -1)
    counts_l, ucode, level, count = _gba_reduce(list(vals))
    return jnp.stack(counts_l, axis=1), ucode, level, count


def _contam_hit(contam_state, contam_meta, fhi, flo, rhi, rlo, active):
    chi, clo = mer.canonical(fhi, flo, rhi, rlo)
    v = _db_lookup(contam_state, contam_meta, chi, clo, active)
    return active & (v != 0)


# ---------------------------------------------------------------------------
# Position sweep + anchor phase
# ---------------------------------------------------------------------------

class SweepResult(NamedTuple):
    """Per-position facts about the ORIGINAL read windows, shared by the
    anchor scan and the event-driven extension planes: one batched
    lookup covers both (the canonical mer of a window is
    strand-invariant, so the forward and reverse-complement frames
    share it too)."""

    fhi: jax.Array  # uint32[B, L] forward mer of window ending at p
    flo: jax.Array
    rhi: jax.Array  # uint32[B, L] revcomp mer
    rlo: jax.Array
    validk: jax.Array  # bool[B, L] window is k consecutive ACGT
    vals: jax.Array  # value word of the canonical window mer (0 absent)
    con: jax.Array  # bool[B, L] contaminant hit (all-False w/o contam DB)


def _position_sweep(state, tmeta, codes32, cfg: ECConfig,
                    contam_state, contam_meta, has_contam: bool
                    ) -> SweepResult:
    """ONE batched lookup per read position (plus one contaminant
    lookup when a contaminant DB is present). Lookups are UNMASKED:
    windows containing N carry the N-as-A encoding — exactly the mer
    the live extension shifts (rolling_kmers and dir_shift both encode
    N as code 0), so plane consumers see the same value the live
    lookup would."""
    k = cfg.k
    b, l = codes32.shape
    fhi, flo, rhi, rlo, validk = mer.rolling_kmers(codes32, k)
    chi, clo = mer.canonical(fhi, flo, rhi, rlo)
    vals = _db_lookup_big(state, tmeta, chi.ravel(),
                          clo.ravel()).reshape(b, l)
    if has_contam:
        con = _db_lookup_big(
            contam_state, contam_meta, chi.ravel(), clo.ravel(),
            validk.ravel()
        ).reshape(b, l) != 0
    else:
        con = jnp.zeros((b, l), bool)
    return SweepResult(fhi, flo, rhi, rlo, validk, vals, con)


class AnchorResult(NamedTuple):
    found: jax.Array  # bool[B]
    status: jax.Array  # int32[B] (OK / ST_CONTAMINANT / ST_NO_ANCHOR)
    start_off: jax.Array  # int32[B] first raw index after the anchor mer
    fhi: jax.Array
    flo: jax.Array
    rhi: jax.Array
    rlo: jax.Array
    prev_count: jax.Array  # int32[B] get_val(anchor mer)


def find_anchors(state: ctable.TileState, tmeta: ctable.TileMeta,
                 codes, lengths, cfg: ECConfig,
                 contam_state, contam_meta, has_contam: bool,
                 sweep: SweepResult | None = None) -> AnchorResult:
    """find_starting_mer (error_correct_reads.cc:609-643) over a batch.

    The sequential build/check loop is equivalent to scanning all
    positions p (last base of a window) in order: windows with k
    consecutive ACGT bases starting at >= skip are "checked" while
    p <= len-2; an N resets the good-run counter; contaminated windows
    are skipped (counter unchanged) or kill the read. Anchor at the
    first p where `good` consecutive checked mers had HQ count >=
    anchor_count; start_off = p + 1."""
    k = cfg.k
    b, l = codes.shape
    codes32 = codes.astype(jnp.int32)
    if sweep is None:
        sweep = _position_sweep(state, tmeta, codes32, cfg,
                                contam_state, contam_meta, has_contam)
    fhi, flo, rhi, rlo = sweep.fhi, sweep.flo, sweep.rhi, sweep.rlo
    validk, vals, con = sweep.validk, sweep.vals, sweep.con
    p_idx = jnp.arange(l, dtype=jnp.int32)[None, :]
    vw = validk & (p_idx >= cfg.skip + k - 1)
    val_hq = jnp.where(vw & ((vals & 1) == 1), vals >> 1,
                       0).astype(jnp.int32)
    con = con & vw
    checked = vw & (p_idx <= (lengths[:, None] - 2))

    # The reference's sequential scan, in closed form. Classify every
    # position: A (checked, clean, HQ count >= anchor_count) extends
    # the good run; Z (invalid window, or checked-clean with a low
    # count) resets it; everything else (past the checked range, or a
    # contaminant window under --trim-contaminant) leaves it alone.
    # run(p) = #A since the last Z, via cumsum minus its value at the
    # last Z (a cummax of Z positions).
    a = checked & ~con & (val_hq >= cfg.anchor_count)
    z = (~vw) | (checked & ~con & (val_hq < cfg.anchor_count))
    cum_a = jnp.cumsum(a.astype(jnp.int32), axis=1)
    last_z = jax.lax.cummax(jnp.where(z, p_idx, jnp.int32(-1)), axis=1)
    cum_at_z = jnp.take_along_axis(cum_a, jnp.clip(last_z, 0), axis=1)
    run = cum_a - jnp.where(last_z >= 0, cum_at_z, 0)
    hit = a & (run >= cfg.good)
    has_hit = jnp.any(hit, axis=1)
    anchor_p = jnp.argmax(hit, axis=1).astype(jnp.int32)  # first True

    # a contaminant window kills the read only if the scan reaches it
    # before the anchor (is_checked & ~done in the sequential form)
    if has_contam and not cfg.trim_contaminant:
        kill = checked & con
        has_kill = jnp.any(kill, axis=1)
        kill_p = jnp.argmax(kill, axis=1).astype(jnp.int32)
        contam_flag = has_kill & (~has_hit | (kill_p < anchor_p))
        anchor_found = has_hit & ~contam_flag
    else:
        contam_flag = jnp.zeros((b,), bool)
        anchor_found = has_hit

    status = jnp.where(anchor_found, OK,
                       jnp.where(contam_flag, ST_CONTAMINANT, ST_NO_ANCHOR))
    lane = jnp.arange(b, dtype=jnp.int32)
    ap = jnp.where(anchor_found, anchor_p, 0)
    return AnchorResult(
        anchor_found, status, anchor_p + 1,
        fhi[lane, ap], flo[lane, ap], rhi[lane, ap], rlo[lane, ap],
        val_hq[lane, ap],
    )


# ---------------------------------------------------------------------------
# Extension phase
# ---------------------------------------------------------------------------

class ExtendResult(NamedTuple):
    out: jax.Array  # int32[B, L]
    opos: jax.Array  # int32[B] one-past-last-written in direction d
    status: jax.Array  # int32[B]
    log: LogState


def _extend_env(state, tmeta, codes, quals, cfg, end, contam_state,
                contam_meta, d: int, has_contam: bool, guard_thresh=None):
    """Shared helpers closed over the static extension environment."""
    window = cfg.effective_window
    error = cfg.effective_error
    b, l = codes.shape
    lane = jnp.arange(b, dtype=jnp.int32)
    codes32 = codes.astype(jnp.int32)
    quals32 = quals.astype(jnp.int32)
    thresh = window if guard_thresh is None else guard_thresh

    def in_range(pos):
        return (pos < end) if d == 1 else (pos > end)

    def gather_code(arr, idx, mask):
        safe = jnp.clip(idx, 0, l - 1)
        v = jnp.take_along_axis(arr, safe[:, None], axis=1)[:, 0]
        return jnp.where(mask, v, -1)

    def take4(counts, idx):
        safe = jnp.clip(idx, 0, 3)
        return jnp.take_along_axis(counts, safe[:, None], axis=1)[:, 0]

    def contam(fh, fl, rh, rl, mask):
        if not has_contam:
            return jnp.zeros_like(mask)
        return _contam_hit(contam_state, contam_meta, fh, fl, rh, rl, mask)

    return (in_range, gather_code, take4, contam, lane, codes32, quals32,
            window, error, b, l, thresh)


def compact_sweep_default() -> bool:
    """Round-7 accelerator default (see ctable.accel_backend): the
    sibling sweep runs compacted (exact own-value pre-pass + candidate
    probe + c1k walk). QUORUM_COMPACT_SWEEP=1/0 forces it either way
    (A/B escape hatch); between the env var and the backend-keyed
    guess sits the autotune profile (ops/tuning.py, ISSUE 11) — the
    setting `quorum-autotune` measured to win on THIS backend."""
    raw = levers.raw("QUORUM_COMPACT_SWEEP")
    if raw is not None and raw != "":
        return raw != "0"
    from ..ops import tuning
    prof = tuning.lever("QUORUM_COMPACT_SWEEP")
    if prof is not None:
        return prof != "0"
    return ctable.accel_backend()


def drain_levels_default() -> int:
    """Round-7 accelerator default (see ctable.accel_backend): the
    event-driven extension loop re-compacts live lanes to half then
    quarter width as lanes retire. QUORUM_DRAIN_LEVELS forces a level
    count (0 = single-level loop); an autotune profile
    (ops/tuning.py) supplies the measured count when no env forces
    one."""
    raw = levers.raw("QUORUM_DRAIN_LEVELS")
    if raw is not None and raw != "":
        try:
            return max(0, min(2, int(raw)))
        except ValueError:
            pass
    from ..ops import tuning
    prof = tuning.lever("QUORUM_DRAIN_LEVELS")
    if prof is not None:
        try:
            return max(0, min(2, int(prof)))
        except ValueError:
            pass
    return 2 if ctable.accel_backend() else 0


# Steps per while_loop iteration. Each step is fully masked
# (active = alive & in_range), so running several per iteration is a
# pure strength reduction: same total work, fewer loop iterations —
# ~20% faster at 2 on the v5e. 4 is marginally faster still but its
# XLA compile time is prohibitive (the whole loop body is cloned per
# step; see PERF_NOTES.md). The event-driven loop (planes != None)
# uses 1: iterations are few and the body is much bigger.
UNROLL = 2


# aux plane bit layout (EventPlanes.aux)
_AX_LEVEL = 0   # bit 0: gba level
_AX_COUNT = 1   # bits 1-3: gba count (0-4)
_AX_UCODE = 4   # bits 4-5: gba ucode
_AX_PRE = 6     # bit 6: ambig continuation pre-pass data valid
_AX_C1K = 7     # bit 7: teleportable count==1 keep (prev-defining)
_AX_SUCC = 8    # bits 8-11: ambig continuation success per variant
_AX_CWN = 12    # bits 12-15: continues-with-next-base per variant


class EventPlanes(NamedTuple):
    """Per-frame-position planes driving event-driven stepping, all
    [2B, L] in frame coordinates (p = window END index; fwd half then
    reverse-complement half). Built from the position sweep plus a
    3-row/position sibling sweep: the full get_best_alternatives facts
    of every ORIGINAL window, so a synced lane (mer == original window)
    consumes plane data instead of in-loop lookups. The fwd and rc
    frames consume DISJOINT position ranges (above/below the anchor),
    so the sibling sweep computes each position's facts for the one
    frame that will read them (3 rows/base total, not 6).

    clean[p]: the live step at p keeps the original base and appends
    nothing (c1-keep, cutoff/qual keep, or Poisson keep; contaminant-
    free). cnt[p]: the 4 level-filtered variant counts packed 7 bits
    each. aux[p]: level/count/ucode plus the ambig continuation
    pre-pass bits (_AX_*). lastc1/prevval: running last prev-defining
    position and its value, so a teleport updates prev in O(1)."""

    clean: jax.Array  # bool[2B, L]
    nd: jax.Array  # int32[2B, L] first event index >= p (L if none)
    cnt: jax.Array  # uint32[2B, L] packed gba counts (4 x 7 bits)
    aux: jax.Array  # uint32[2B, L] _AX_* bit fields
    lastc1: jax.Array  # int32[2B, L] last c1-keep position <= p (-1 none)
    prevval: jax.Array  # int32[2B, L] count at lastc1[p]
    mfh: jax.Array  # uint32[2B, L] frame-forward mer of window ending at p
    mfl: jax.Array
    mrh: jax.Array  # uint32[2B, L] frame-revcomp mer
    mrl: jax.Array


def _extend_loop(state, tmeta, codes, quals, cfg: ECConfig,
                 carry, end, guard_thresh,
                 contam_state, contam_meta, d: int, has_contam: bool,
                 unroll: int = UNROLL, ambig_cap: int = 1 << 30,
                 planes: EventPlanes | None = None,
                 drain_levels: int = 0):
    """The lockstep extension loop.

    Plain mode (planes=None): every live lane advances one base per
    iteration with a full-width get_best_alternatives; the ambiguous
    continuation probe runs compacted (_ambig_probe) with
    stall-and-retry past `ambig_cap`.

    Event mode (planes): lanes whose mer equals the original window
    (synced, pos >= resync) TELEPORT over runs of proven-clean
    positions — skipped keeps write nothing (the out buffer already
    holds the original codes), append nothing to the log, and update
    prev_count in O(1) from the lastc1/prevval planes. Synced events
    consume the planes' exact per-position gba (and pre-passed ambig
    continuation bits) instead of in-loop lookups; only DESYNCED lanes
    (within k-1 of a substitution) pay live lookups, compacted to a
    small capacity. A compacted TAIL PROBE (full 4-variant gba of the
    would-be mers under a no-further-edit assumption) teleports over
    the desync region's exact-keep prefix in one step. Iterations
    collapse from ~L to ~(events on the worst lane): measured 1.5 mean
    / 8 max events per 150 bp read at 40x coverage (PERF_NOTES.md).

    `drain_levels` (event mode only): the per-iteration cost of the
    loop is CONSTANT in the lane count, not live-lane-proportional
    (masked gathers pay per index — PERF_NOTES round 4), so once most
    lanes retire, every remaining iteration still bills full width.
    With drain_levels=N, the loop exits once the live count drops to
    half the current width, re-compacts the live lanes (and their
    whole step environment) into a half-width buffer, and keeps
    stepping there — repeated N times (full -> B/2 -> B/4). Stalls and
    caps shrink with the width, so per-lane semantics are unchanged
    (stall = pure delay); output is bit-identical to the single-level
    loop (round-7 parity tests)."""
    k = cfg.k
    if planes is not None:
        assert d == 1, "event-driven stepping runs in the merged d=+1 frame"
    else:
        drain_levels = 0  # plain mode keeps the single-level loop
    if drain_levels and guard_thresh is None:
        guard_thresh = jnp.full((codes.shape[0],), cfg.effective_window,
                                jnp.int32)
    tail_t = k - 1

    def _make_level(codes_lv, quals_lv, end_lv, thresh_lv, planes_lv):
        """Build the loop body closed over ONE width's environment:
        the drained levels re-instantiate it at half/quarter width so
        the compaction caps and the per-iteration op volume shrink
        with the buffer."""
        (in_range, gather_code, take4, contam, lane, codes32, quals32,
         window, error, b, l, thresh) = _extend_env(
            state, tmeta, codes_lv, quals_lv, cfg, end_lv, contam_state,
            contam_meta, d, has_contam, thresh_lv)
        planes = planes_lv
        end = end_lv
        # 92 rows/slot: bound the in-loop gather transient
        cap_tail = max(1, min(b // 4, 12288))
        cap_gba = max(1, b // 8)

        def gat(plane, idx):
            safe = jnp.clip(idx, 0, l - 1)
            return jnp.take_along_axis(plane, safe[:, None], axis=1)[:, 0]

        def _compact(mask, cap):
            """The shared compaction idiom over this level's lanes:
            (slot, fitted, lane_of, slot_live)."""
            return _compact_select(mask, cap, lane)

        def _ambig_probe(need, fh, fl, rh, rl, counts, level, read_nbase):
            """The 16-lookup continuation probe (error_correct_reads.cc:
            473-507) over compacted lanes; returns full-width
            (succ[B,4] incl. the elig gate, cwn[B,4], stalled)."""
            cap = min(max(1, ambig_cap), b)
            slot, fitted, lane_of, slot_live = _compact(need, cap)
            stalled = need & ~fitted
            cfh, cfl = fh[lane_of], fl[lane_of]
            crh, crl = rh[lane_of], rl[lane_of]
            elig_c = [(counts[:, i] > cfg.min_count)[lane_of] & slot_live
                      for i in range(4)]
            level_c = level[lane_of]
            nb_c = read_nbase[lane_of]
            safe_nb = jnp.clip(nb_c, 0, 3)
            chis, clos, acts = [], [], []
            for i in range(4):
                ifh, ifl, irh, irl = mer.dir_replace0(
                    cfh, cfl, crh, crl, mer.u32(i), d, k)
                ifh, ifl, irh, irl = mer.dir_shift(
                    ifh, ifl, irh, irl, mer.u32(0), d, k)
                for j in range(4):
                    jfh, jfl, jrh, jrl = mer.dir_replace0(
                        ifh, ifl, irh, irl, mer.u32(j), d, k)
                    chi, clo = mer.canonical(jfh, jfl, jrh, jrl)
                    chis.append(chi)
                    clos.append(clo)
                    acts.append(elig_c[i])
            nv = _db_lookup(
                state, tmeta, jnp.stack(chis).ravel(), jnp.stack(clos).ravel(),
                jnp.stack(acts).ravel(),
            ).reshape(4, 4, cap)
            succ_c, cwn_c = [], []
            for i in range(4):
                ncounts, _nu, nlevel, ncount = _gba_reduce(list(nv[i]))
                s_i = elig_c[i] & (ncount > 0) & (nlevel >= level_c)
                succ_c.append(s_i)
                cwn_c.append(s_i & (nb_c >= 0) & (_sel4(ncounts, safe_nb) > 0))
            safe_slot = jnp.clip(slot, 0, cap - 1)
            succ = jnp.stack(
                [jnp.where(fitted, s[safe_slot], False) for s in succ_c],
                axis=1)
            cwn = jnp.stack(
                [jnp.where(fitted, c[safe_slot], False) for c in cwn_c],
                axis=1)
            return succ, cwn, stalled

        def _tail_probe(want, fh, fl, rh, rl, pos, opos, prev, resync):
            """Teleport through the desync region after a substitution:
            compute the next tail_t mers under a no-further-edit assumption
            (the shifted-in bases are the original read), run the full
            4-variant gba on each, and advance over the maximal EXACT-KEEP
            prefix (c1-keep with ucode==ori, keep_cut, or Poisson keep;
            anything else — another sub, ambiguity, truncation,
            contaminant, N — stops the teleport and is re-processed live,
            which is always correct). prev updates from count==1 positions
            in the prefix are exact (full sibling info)."""
            slot, fitted, lane_of, slot_live = _compact(want, cap_tail)
            li = lane_of[:, None]
            tpos = pos[lane_of]
            tend = jnp.minimum(resync[lane_of], end[lane_of])
            tq = tpos[:, None] + jnp.arange(tail_t, dtype=jnp.int32)[None, :]
            stq = jnp.clip(tq, 0, l - 1)
            tori = codes32[li, stq]  # [cap, T]
            tqual = quals32[li, stq]
            t_in = slot_live[:, None] & (tq < tend[:, None])
            cfh, cfl = fh[lane_of], fl[lane_of]
            crh, crl = rh[lane_of], rl[lane_of]
            m_fh, m_fl, m_rh, m_rl = [cfh], [cfl], [crh], [crl]
            chis, clos, acts = [], [], []
            cchis, cclos = [], []
            for t in range(tail_t):
                code_t = mer.u32(jnp.maximum(tori[:, t], 0))
                nfh, nfl, nrh, nrl = mer.dir_shift(
                    m_fh[-1], m_fl[-1], m_rh[-1], m_rl[-1], code_t, d, k)
                m_fh.append(nfh)
                m_fl.append(nfl)
                m_rh.append(nrh)
                m_rl.append(nrl)
                if has_contam:
                    cchi, cclo = mer.canonical(nfh, nfl, nrh, nrl)
                    cchis.append(cchi)
                    cclos.append(cclo)
                for i in range(4):
                    vfh, vfl, vrh, vrl = mer.dir_replace0(
                        nfh, nfl, nrh, nrl, mer.u32(i), d, k)
                    chi, clo = mer.canonical(vfh, vfl, vrh, vrl)
                    chis.append(chi)
                    clos.append(clo)
                    acts.append(t_in[:, t] & (tori[:, t] >= 0))
            act = jnp.stack(acts).ravel()
            tv = _db_lookup(
                state, tmeta, jnp.stack(chis).ravel(), jnp.stack(clos).ravel(),
                act,
            ).reshape(tail_t, 4, cap_tail)
            keep_rows, c1keep_rows, cori_rows = [], [], []
            for t in range(tail_t):
                tcounts, tuc, tlev, tcnt = _gba_reduce(list(tv[t]))
                ori_t = tori[:, t]
                safe_o = jnp.clip(ori_t, 0, 3)
                c_ori = jnp.where(ori_t >= 0, _sel4(tcounts, safe_o), 0)
                c1k = (tcnt == 1) & (tuc == ori_t)
                hi = c_ori > cfg.min_count
                kcut = (tcnt > 1) & hi & ((c_ori >= cfg.cutoff)
                                         | (tqual[:, t] >= cfg.qual_cutoff))
                lam = ((tcounts[0] + tcounts[1] + tcounts[2] + tcounts[3])
                       .astype(jnp.float32) * jnp.float32(cfg.collision_prob))
                kpoi = ((tcnt > 1) & hi & ~kcut
                        & (poisson_term(lam, c_ori) < cfg.poisson_threshold))
                keep_rows.append((c1k | kcut | kpoi) & t_in[:, t]
                                 & (ori_t >= 0))
                c1keep_rows.append(c1k)
                cori_rows.append(c_ori)
            keep_t = jnp.stack(keep_rows)  # [T, cap]
            if has_contam:
                tcon = _db_lookup(
                    contam_state, contam_meta,
                    jnp.stack(cchis).ravel(), jnp.stack(cclos).ravel(),
                    (t_in & (tori >= 0)).T.ravel(),
                ).reshape(tail_t, cap_tail) != 0
                keep_t = keep_t & ~tcon
            pk = jnp.cumprod(keep_t.astype(jnp.int32), axis=0) > 0
            plen = jnp.sum(pk.astype(jnp.int32), axis=0)  # [cap]
            c1p = jnp.stack(c1keep_rows) & pk
            has_c1p = jnp.any(c1p, axis=0)
            t_last = (tail_t - 1) - jnp.argmax(c1p[::-1, :], axis=0)
            arange_cap = jnp.arange(cap_tail, dtype=jnp.int32)
            prev_t = jnp.stack(cori_rows)[t_last, arange_cap]
            sel_fh = jnp.stack(m_fh)[plen, arange_cap]
            sel_fl = jnp.stack(m_fl)[plen, arange_cap]
            sel_rh = jnp.stack(m_rh)[plen, arange_cap]
            sel_rl = jnp.stack(m_rl)[plen, arange_cap]
            safe_slot = jnp.clip(slot, 0, cap_tail - 1)
            adv = jnp.where(fitted, plen[safe_slot], 0)
            fh = jnp.where(fitted, sel_fh[safe_slot], fh)
            fl = jnp.where(fitted, sel_fl[safe_slot], fl)
            rh = jnp.where(fitted, sel_rh[safe_slot], rh)
            rl = jnp.where(fitted, sel_rl[safe_slot], rl)
            pos = pos + adv
            opos = opos + adv
            prev = jnp.where(fitted & has_c1p[safe_slot], prev_t[safe_slot],
                             prev)
            return fh, fl, rh, rl, pos, opos, prev

        def body(carry):
            (fh, fl, rh, rl, pos, opos, prev, alive, status, outb, log,
             resync) = carry

            if planes is not None:
                # ---- teleport phase: synced lanes jump to the next event,
                # prev updated in O(1) from the lastc1/prevval planes
                synced = pos >= resync
                at_clean = alive & in_range(pos) & synced & gat(planes.clean,
                                                                pos)
                tgt = jnp.minimum(gat(planes.nd, pos), end)
                nfh = gat(planes.mfh, tgt - 1)
                nfl = gat(planes.mfl, tgt - 1)
                nrh = gat(planes.mrh, tgt - 1)
                nrl = gat(planes.mrl, tgt - 1)
                lc = gat(planes.lastc1, tgt - 1)
                pv = gat(planes.prevval, tgt - 1)
                fh = jnp.where(at_clean, nfh, fh)
                fl = jnp.where(at_clean, nfl, fl)
                rh = jnp.where(at_clean, nrh, rh)
                rl = jnp.where(at_clean, nrl, rl)
                prev = jnp.where(at_clean & (lc >= pos), pv, prev)
                opos = opos + jnp.where(at_clean, tgt - pos, 0)
                pos = jnp.where(at_clean, tgt, pos)

            active = alive & in_range(pos)
            cpos = pos
            pos = jnp.where(active, pos + d, pos)

            ori = gather_code(codes32, cpos, active)
            qualc = jnp.where(active,
                              gather_code(quals32, cpos, active), 0)

            # pre-step mers, restored for stalled lanes
            pfh, pfl, prh, prl = fh, fl, rh, rl
            shift_code = mer.u32(jnp.maximum(ori, 0))
            sfh, sfl, srh, srl = mer.dir_shift(fh, fl, rh, rl, shift_code, d, k)
            fh = jnp.where(active, sfh, fh)
            fl = jnp.where(active, sfl, fl)
            rh = jnp.where(active, srh, rh)
            rl = jnp.where(active, srl, rl)

            # contaminant on the shifted mer (error_correct_reads.cc:401-407)
            con1 = contam(fh, fl, rh, rl, active & (ori >= 0))
            con1_trim = con1 if cfg.trim_contaminant else jnp.zeros_like(con1)
            con1_err = con1 & ~con1_trim
            status = jnp.where(con1_err, ST_CONTAMINANT, status)
            alive = alive & ~con1
            live = active & ~con1

            if planes is not None:
                # ---- mixed gba: synced lanes unpack the planes; only
                # desynced lanes pay live lookups, compacted
                synced_step = cpos >= resync
                pcnt = gat(planes.cnt, cpos)
                paux = gat(planes.aux, cpos)
                need_live = live & ~synced_step
                slot_g, fit_g, lane_g, live_g = _compact(need_live, cap_gba)
                stall_g = need_live & ~fit_g
                lcounts, lucode, llevel, lcount = _gba(
                    state, tmeta, fh[lane_g], fl[lane_g], rh[lane_g],
                    rl[lane_g], d, live_g)
                safe_g = jnp.clip(slot_g, 0, cap_gba - 1)
                counts = jnp.stack([
                    jnp.where(synced_step,
                              ((pcnt >> (7 * i)) & 127).astype(jnp.int32),
                              jnp.where(fit_g, lcounts[safe_g, i], 0))
                    for i in range(4)], axis=1)
                level = jnp.where(synced_step,
                                  (paux & 1).astype(jnp.int32),
                                  llevel[safe_g])
                count = jnp.where(synced_step,
                                  ((paux >> _AX_COUNT) & 7).astype(jnp.int32),
                                  lcount[safe_g])
                ucode = jnp.where(synced_step,
                                  ((paux >> _AX_UCODE) & 3).astype(jnp.int32),
                                  lucode[safe_g])
                live = live & ~stall_g
            else:
                synced_step = jnp.zeros_like(live)
                paux = None
                stall_g = jnp.zeros_like(live)
                counts, ucode, level, count = _gba(
                    state, tmeta, fh, fl, rh, rl, d, live)

            # count == 0: truncate (cc:416-419)
            t0 = live & (count == 0)
            alive = alive & ~t0
            live = live & ~t0

            # count == 1 (cc:421-430)
            c1 = live & (count == 1)
            prev = jnp.where(c1, take4(counts, ucode), prev)
            sub1 = c1 & (ori != ucode)
            nfh, nfl, nrh, nrl = mer.dir_replace0(
                fh, fl, rh, rl, mer.u32(jnp.clip(ucode, 0)), d, k)
            fh = jnp.where(c1, nfh, fh)
            fl = jnp.where(c1, nfl, fl)
            rh = jnp.where(c1, nrh, rh)
            rl = jnp.where(c1, nrl, rl)
            # log_substitution (cc:360-379): contaminant check on the
            # substituted mer, then window-budget bookkeeping
            con2 = contam(fh, fl, rh, rl, sub1)
            con2_trim = con2 if cfg.trim_contaminant else jnp.zeros_like(con2)
            con2_err = con2 & ~con2_trim
            status = jnp.where(con2_err, ST_CONTAMINANT, status)
            alive = alive & ~con2
            sub1 = sub1 & ~con2
            log, trip1 = _log_append(
                log, sub1, cpos, _pack_sub(ori, ucode), window, error, d, thresh)
            log, diff1 = _log_remove_last_window(log, trip1, window, d, thresh)
            log = _append_trunc(log, trip1, cpos - d * diff1, window, error, d,
                                thresh)
            opos = jnp.where(trip1, opos - d * diff1, opos)
            alive = alive & ~trip1
            write1 = c1 & ~con2 & ~trip1

            # count > 1 (cc:432-561)
            cm = live & (count > 1)
            c_ori = jnp.where(cm & (ori >= 0), take4(counts, ori), 0)
            ori_hi = cm & (ori >= 0) & (c_ori > cfg.min_count)
            keep_cut = ori_hi & ((c_ori >= cfg.cutoff)
                                 | (qualc >= cfg.qual_cutoff))
            p_lam = (jnp.sum(counts, axis=1).astype(jnp.float32)
                     * jnp.float32(cfg.collision_prob))
            prob = poisson_term(p_lam, c_ori)
            keep_poi = ori_hi & ~keep_cut & (prob < cfg.poisson_threshold)
            keep_simple = keep_cut | keep_poi
            t_a = cm & (ori >= 0) & ~ori_hi & (level == 0) & (c_ori == 0)
            t_b = cm & (ori < 0) & (level == 0)
            alive = alive & ~(t_a | t_b)
            # one merged truncation append: the five masks are disjoint per
            # lane (each lane takes one branch), all at cpos, and no
            # intermediate computation reads the log — 5 sets of [B, E]
            # log ops become 1
            log = _append_trunc(log, con1_trim | t0 | con2_trim | t_a | t_b,
                                cpos, window, error, d, thresh)
            ambig = cm & ~keep_simple & ~t_a & ~t_b

            # ---- ambiguous path (cc:473-545): synced lanes with pre-pass
            # data take the elementwise tie-break directly; the rest run
            # the compacted continuation probe (stall-and-retry past cap)
            read_nbase = gather_code(codes32, pos, in_range(pos) & ambig)
            if planes is not None:
                pre_ok = ambig & synced_step & (((paux >> _AX_PRE) & 1) == 1)
            else:
                pre_ok = jnp.zeros_like(ambig)
            probe_need = ambig & ~pre_ok
            succ_p, cwn_p, stall_a = _ambig_probe(
                probe_need, fh, fl, rh, rl, counts, level, read_nbase)
            if planes is not None:
                psucc = jnp.stack([(((paux >> (_AX_SUCC + i)) & 1) == 1)
                                   for i in range(4)], axis=1)
                pcwn = jnp.stack([(((paux >> (_AX_CWN + i)) & 1) == 1)
                                  for i in range(4)], axis=1)
                succ4 = jnp.where(pre_ok[:, None], psucc, succ_p)
                cwn4 = jnp.where(pre_ok[:, None], pcwn, cwn_p)
            else:
                succ4, cwn4 = succ_p, cwn_p
            amb_go = ambig & ~stall_a
            succ4 = succ4 & amb_go[:, None]
            cwn4 = cwn4 & amb_go[:, None]

            cont_counts = jnp.where(succ4, counts, 0)
            check_code = jnp.where(amb_go, ori, 0)
            for i in range(4):
                check_code = jnp.where(
                    amb_go & (counts[:, i] > cfg.min_count), i, check_code)
            success = jnp.any(succ4, axis=1)

            # tie-break chain (cc:509-545). prev_count <= min_count takes
            # the int-overflow dead-code path: no candidate ever matches.
            prev_ok = prev > cfg.min_count
            diffs = jnp.abs(cont_counts - prev[:, None])
            min_diff = jnp.min(
                jnp.where(cont_counts > 0, diffs, jnp.int32(2**31 - 1)), axis=1)
            cand = (success[:, None] & prev_ok[:, None]
                    & (diffs == min_diff[:, None]))
            ncand = jnp.sum(cand.astype(jnp.int32), axis=1)
            cc2 = jnp.full((b,), -1, jnp.int32)
            for i in range(4):
                cc2 = jnp.where(cand[:, i], i, cc2)
            tie = (ncand > 1) & (read_nbase >= 0)
            ncand = jnp.where(
                tie, jnp.sum((cand & cwn4).astype(jnp.int32), axis=1), ncand)
            for i in range(4):
                cc2 = jnp.where(tie & cand[:, i] & cwn4[:, i], i, cc2)
            cc2 = jnp.where(ncand != 1, -1, cc2)
            check_code = jnp.where(success, cc2, check_code)

            sub2 = success & (check_code >= 0) & (check_code != ori)
            nfh, nfl, nrh, nrl = mer.dir_replace0(
                fh, fl, rh, rl, mer.u32(jnp.clip(check_code, 0)), d, k)
            do_rep = success & (check_code >= 0)
            fh = jnp.where(do_rep, nfh, fh)
            fl = jnp.where(do_rep, nfl, fl)
            rh = jnp.where(do_rep, nrh, rh)
            rl = jnp.where(do_rep, nrl, rl)
            con3 = contam(fh, fl, rh, rl, sub2)
            con3_trim = con3 if cfg.trim_contaminant else jnp.zeros_like(con3)
            con3_err = con3 & ~con3_trim
            status = jnp.where(con3_err, ST_CONTAMINANT, status)
            alive = alive & ~con3
            sub2 = sub2 & ~con3
            log, trip2 = _log_append(
                log, sub2, cpos, _pack_sub(ori, check_code), window, error, d,
                thresh)
            log, diff2 = _log_remove_last_window(log, trip2, window, d, thresh)
            log = _append_trunc(log, trip2, cpos - d * diff2, window, error, d,
                                thresh)
            opos = jnp.where(trip2, opos - d * diff2, opos)
            alive = alive & ~trip2

            # N base with no good substitution: truncate (cc:553-556)
            t_c = amb_go & ~con3 & ~trip2 & (ori < 0) & (check_code < 0)
            log = _append_trunc(log, con3_trim | t_c, cpos, window, error, d,
                                thresh)
            alive = alive & ~t_c

            # ---- stall rewind: stalled lanes redo the whole step next
            # iteration (they took no branch, wrote nothing, appended
            # nothing this iteration)
            stalled = stall_g | stall_a
            pos = jnp.where(stalled, cpos, pos)
            fh = jnp.where(stalled, pfh, fh)
            fl = jnp.where(stalled, pfl, fl)
            rh = jnp.where(stalled, prh, rh)
            rl = jnp.where(stalled, prl, rl)

            write = (write1 | (keep_simple & alive & active)
                     | (amb_go & alive))
            base0 = mer.dir_base0(fh, fl, d, k).astype(jnp.int32)
            # out-of-range positive sentinel: dropped (negative would wrap)
            widx = jnp.where(write, opos, l)
            outb = outb.at[lane, widx].set(base0, mode="drop")
            opos = jnp.where(write, opos + d, opos)

            if planes is not None:
                mer_changed = (sub1 | (do_rep & (check_code != ori))) & ~stalled
                resync = jnp.where(mer_changed, cpos + k, resync)
                want_tail = (alive & in_range(pos) & (pos < resync)
                             & ~stalled)
                (fh, fl, rh, rl, pos, opos, prev) = _tail_probe(
                    want_tail, fh, fl, rh, rl, pos, opos, prev, resync)

            return (fh, fl, rh, rl, pos, opos, prev, alive, status, outb, log,
                    resync)

        def body_unrolled(carry):
            for _ in range(unroll):
                carry = body(carry)
            return carry

        return in_range, body_unrolled

    def _run(env, carry_lv, floor):
        codes_lv, quals_lv, end_lv, thresh_lv, planes_lv = env
        in_range, body_unrolled = _make_level(codes_lv, quals_lv,
                                              end_lv, thresh_lv,
                                              planes_lv)

        def cond(carry_c):
            pos, alive = carry_c[4], carry_c[7]
            live = alive & in_range(pos)
            c = jnp.any(live)
            if floor is not None:
                # lane-draining exit: hand the survivors to the next
                # (narrower) level once they'd fit it
                c = c & (jnp.sum(live.astype(jnp.int32)) > floor)
            ax = getattr(tmeta, "routed_axis", None)
            if ax is not None:
                # routed lookups put collectives inside the body:
                # every shard must run the same number of lockstep
                # iterations (and drain at the same moment)
                c = jax.lax.pmax(c.astype(jnp.int32), ax) > 0
            return c

        return jax.lax.while_loop(cond, body_unrolled, carry_lv)

    env = (codes, quals, end, guard_thresh, planes)
    b0 = codes.shape[0]
    widths = [max(1, b0 >> (i + 1)) for i in range(drain_levels)]
    carry = _run(env, carry, widths[0] if widths else None)
    for i, w in enumerate(widths):
        floor = widths[i + 1] if i + 1 < len(widths) else None
        carry = _drain_run(_run, env, carry, w, floor, d)
    return carry


def _drain_run(run, env, carry, width: int, floor, d: int):
    """One drain step of the lane-draining extension loop: compact the
    live lanes (and every per-lane row of their step environment) into
    a `width`-lane buffer, keep stepping there via `run`, and scatter
    the survivors' state back into the full-width carry. The previous
    level's floor equals `width`, so every live lane fits by
    construction; retired lanes' state (out rows, logs, status) never
    moves."""
    codes_l, quals_l, end_l, thresh_l, planes_l = env
    (fh, fl, rh, rl, pos, opos, prev, alive, status, outb, log,
     resync) = carry
    b = pos.shape[0]
    live = alive & ((pos < end_l) if d == 1 else (pos > end_l))
    _slot, _fitted, lane_of, slot_live = _compact_select(
        live, width, jnp.arange(b, dtype=jnp.int32))

    def g(x):
        return x[lane_of]

    sub_env = (g(codes_l), g(quals_l), g(end_l), g(thresh_l),
               None if planes_l is None
               else EventPlanes(*(g(p) for p in planes_l)))
    sub = (g(fh), g(fl), g(rh), g(rl), g(pos), g(opos), g(prev),
           g(alive) & slot_live, g(status), g(outb),
           LogState(g(log.n), g(log.lwin), g(log.pos), g(log.meta)),
           g(resync))
    sub = run(sub_env, sub, floor)
    sidx = jnp.where(slot_live, lane_of, b)

    def s(x, xs):
        return x.at[sidx].set(xs, mode="drop")

    (sfh, sfl, srh, srl, spos, sopos, sprev, salive, sstatus, soutb,
     slog, sresync) = sub
    return (s(fh, sfh), s(fl, sfl), s(rh, srh), s(rl, srl),
            s(pos, spos), s(opos, sopos), s(prev, sprev),
            s(alive, salive), s(status, sstatus), s(outb, soutb),
            LogState(s(log.n, slog.n), s(log.lwin, slog.lwin),
                     s(log.pos, slog.pos), s(log.meta, slog.meta)),
            s(resync, sresync))


def extend(state, tmeta, codes, quals, cfg: ECConfig,
           out, fhi, flo, rhi, rlo, prev0, alive0,
           pos0, end, status0,
           contam_state, contam_meta, d: int, has_contam: bool,
           ambig_cap: int | None = None, guard_thresh=None,
           planes: EventPlanes | None = None, drain_levels: int = 0):
    """extend (error_correct_reads.cc:384-565) in lockstep over a batch:
    one fused while_loop advancing every live lane one base per
    iteration, with the ambiguous-path continuation probe inline over
    capacity-compacted lanes (_ambig_core; stall-and-retry keeps it
    bit-exact). The default cap (b/8, min 256) covers the measured
    ambiguous rate at real coverage (~1-3% of lanes/iteration) with an
    order of magnitude of headroom; pathological batches stall some
    lanes into extra iterations rather than breaking."""
    b = codes.shape[0]
    # Entry-capacity bound: the window budget retires a lane once any
    # window-span holds more than `error` entries (check_nb_error), so
    # a live log retains <= error+1 entries per window-sized block of
    # the read, plus a couple of truncation entries. Every [B, E] log
    # op scales with E, so the tight bound matters at 150 bp (64 vs
    # 152 lanes of per-iteration work).
    l = out.shape[1]
    w = max(1, cfg.effective_window)
    maxe = min(l + 2, -(-l // w) * (cfg.effective_error + 1) + 8)
    log0 = make_log(b, maxe)
    if ambig_cap is None:
        ambig_cap = max(256, b // 8)
    if guard_thresh is None:
        guard_thresh = jnp.full((b,), cfg.effective_window, jnp.int32)
    resync0 = jnp.full((b,), -(1 << 30), jnp.int32)
    carry = (fhi, flo, rhi, rlo, pos0, pos0, prev0, alive0, status0, out,
             log0, resync0)
    unroll = 1 if planes is not None else UNROLL
    carry = _extend_loop(state, tmeta, codes, quals, cfg, carry, end,
                         guard_thresh, contam_state, contam_meta, d,
                         has_contam, unroll, ambig_cap, planes,
                         drain_levels)
    opos, status, outb, log = carry[5], carry[8], carry[9], carry[10]
    return ExtendResult(outb, opos, status, log)


# ---------------------------------------------------------------------------
# Batch glue + host finishing
# ---------------------------------------------------------------------------

class BatchResult(NamedTuple):
    """Device-side result of correcting one batch."""

    out: jax.Array  # int32[B, L] corrected base codes
    start: jax.Array  # int32[B] first kept index (5_trunc)
    end: jax.Array  # int32[B] one past last kept index (3_trunc)
    status: jax.Array  # int32[B]
    fwd_log: LogState
    bwd_log: LogState


def _dummy_contam(k: int):
    """An empty 16-row tile table: every lookup misses (the
    has_contam=False executables never read it, but jit needs a
    concrete operand of the right structure)."""
    meta = ctable.TileMeta(k=k, bits=1, rb_log2=4)
    return ctable.TileState(jnp.zeros((meta.rows, ctable.TILE),
                                      jnp.uint32)), meta


def _rev_rows(x, lengths, uniform_len: int | None, fill):
    """x[b, len-1-p] per lane, `fill` past the length; returns
    (reversed, in_read mask). With a uniform (static) length this is
    flip+static-roll — pure layout ops; the per-lane take_along_axis
    fallback costs ~100 ms/batch at 16k x 150 (the slow gather class,
    PERF_NOTES.md)."""
    l = x.shape[1]
    p = jnp.arange(l, dtype=jnp.int32)[None, :]
    if uniform_len is not None:
        f = jnp.flip(x, axis=1)
        if uniform_len != l:
            f = jnp.roll(f, uniform_len - l, axis=1)
        valid = jnp.broadcast_to(p < uniform_len, x.shape)
        return jnp.where(valid, f, fill), valid
    idx = lengths[:, None] - 1 - p
    g = jnp.take_along_axis(x, jnp.clip(idx, 0, l - 1), axis=1)
    valid = idx >= 0
    return jnp.where(valid, g, fill), valid


@functools.partial(jax.jit, static_argnums=(3,))
def _rc_prologue(codes, quals, lengths, uniform_len: int | None):
    """Per-lane reverse-complement frame: rc[p'] = comp(read[len-1-p'])
    with -2 padding past the length; quals reversed without
    complement."""
    rev, _ = _rev_rows(codes, lengths, uniform_len, jnp.int32(-2))
    rc_codes = jnp.where(rev >= 0, 3 - rev, rev)
    rc_quals, _ = _rev_rows(quals, lengths, uniform_len, jnp.int32(0))
    return rc_codes, rc_quals


@functools.partial(jax.jit, static_argnums=(8,))
def _bwd_epilogue(out_f, status_f, out_rc, opos_rc, status_rc,
                  lengths, bpos0, blog: LogState,
                  uniform_len: int | None = None):
    """Map the rc-frame backward lane results to the original frame.

    out: positions <= bpos0 come from the complemented, re-reversed rc
    plane (unwritten rc positions carry the original codes, so the
    blend is exact for truncated lanes too). start = len - opos_rc
    (one-past-last in rc = first kept original index). Log entries:
    sub at rc p' happened at original len-1-p'; truncation entries get
    the backward log's +1 quirk (error_correct_reads.hpp:170-172), so
    len-1-p'+1 = len-p'. status: forward wins ties so a read that
    failed forward reports the forward reason, exactly like the
    sequential form where backward never ran."""
    l = out_f.shape[1]
    p = jnp.arange(l, dtype=jnp.int32)[None, :]
    rev, in_read = _rev_rows(out_rc, lengths, uniform_len, jnp.int32(-2))
    from_rc = jnp.where(rev >= 0, 3 - rev, rev)
    out = jnp.where((p <= bpos0[:, None]) & in_read, from_rc, out_f)
    start = lengths - opos_rc
    status = jnp.where(status_f != OK, status_f, status_rc)
    is_tr = (blog.meta & 1) == 1
    mapped = jnp.where(is_tr, lengths[:, None] - blog.pos,
                       lengths[:, None] - 1 - blog.pos)
    # sub entries recorded rc-frame base codes: complement them back
    # (N, code 4, is its own complement here)
    frm = (blog.meta >> 1) & 7
    to = (blog.meta >> 4) & 7
    cfrm = jnp.where(frm < 4, 3 - frm, frm)
    cto = jnp.where(to < 4, 3 - to, to)
    meta = jnp.where(is_tr, blog.meta, _T_SUB | (cfrm << 1) | (cto << 4))
    return out, start, status, LogState(blog.n, blog.lwin, mapped, meta)


def _shr(x, n: int, fill):
    """x shifted right along axis 1 by static n: out[:, j] = x[:, j-n]."""
    l = x.shape[1]
    return jnp.pad(x[:, :l - n], ((0, 0), (n, 0)), constant_values=fill)


def _shl(x, n: int, fill):
    """out[:, j] = x[:, j+n]."""
    return jnp.pad(x[:, n:], ((0, 0), (0, n)), constant_values=fill)


def _sel4(arrs, idx):
    """arrs[idx] elementwise for a data-dependent idx in 0..len(arrs)-1."""
    out = arrs[0]
    for i in range(1, len(arrs)):
        out = jnp.where(idx == i, arrs[i], out)
    return out


def _frame_facts(sweep: SweepResult, codes32, quals32, lengths, start_off,
                 k: int):
    """Per original window-end position e, the step facts of the frame
    that will consume it: forward for e >= start_off, rc for
    e <= start_off-2 (the extension ranges are disjoint around the
    anchor). Returns (ori, qual, nbase, wfh, wfl, wrh, wrl) where the
    w* are the consuming frame's mer words (rc frame = original words
    swapped) and nbase is the next ORIGINAL base in frame direction
    (-1 past the read), matching the live loop's read_nbase."""
    l = codes32.shape[1]
    e_idx = jnp.arange(l, dtype=jnp.int32)[None, :]
    is_fwd = e_idx >= start_off[:, None]

    def comp(c):
        return jnp.where(c >= 0, 3 - c, c)

    ori = jnp.where(is_fwd, codes32, comp(_shr(codes32, k - 1, -2)))
    qual = jnp.where(is_fwd, quals32, _shr(quals32, k - 1, 0))
    nb_f = _shl(codes32, 1, -2)
    nb_f = jnp.where(e_idx + 1 < lengths[:, None], nb_f, -1)
    nb_r = comp(_shr(codes32, k, -2))
    nb_r = jnp.where(e_idx - (k - 1) - 1 >= 0, nb_r, -1)
    nbase = jnp.where(is_fwd, nb_f, nb_r)
    nbase = jnp.where(nbase >= 0, nbase, -1)
    wfh = jnp.where(is_fwd, sweep.fhi, sweep.rhi)
    wfl = jnp.where(is_fwd, sweep.flo, sweep.rlo)
    wrh = jnp.where(is_fwd, sweep.rhi, sweep.fhi)
    wrl = jnp.where(is_fwd, sweep.rlo, sweep.flo)
    return ori, qual, nbase, wfh, wfl, wrh, wrl


def _sibling_mers(wfh, wfl, wrh, wrl, orie, k: int):
    """The 3 sibling canonical keys of a frame window (the base-0
    variants other than the original), variant-compressed order:
    slot j holds variant j + (orie <= j). Returns (chis, clos) lists."""
    chis, clos = [], []
    for j in range(3):
        i_j = (j + (orie <= j).astype(jnp.int32)).astype(jnp.uint32)
        vfh, vfl, vrh, vrl = mer.dir_replace0(wfh, wfl, wrh, wrl, i_j, 1, k)
        chi, clo = mer.canonical(vfh, vfl, vrh, vrl)
        chis.append(chi)
        clos.append(clo)
    return chis, clos


def _classify(vals4, ori, qual, con, cfg: ECConfig):
    """Elementwise classification of a position from its exact
    4-variant value words — every branch of the live step (cited masks
    mirror _extend_loop's body / error_correct_reads.cc:384-565).
    Shape-agnostic (full [B, L] planes or compacted [cap] lanes).
    Returns (counts list[4], level, count, ucode, clean, c1keep,
    ambig_class)."""
    counts, ucode, level, count = _gba_reduce(vals4)
    orie = jnp.clip(ori, 0, 3)
    c_ori = jnp.where(ori >= 0, _sel4(counts, orie), 0)
    c1keep = (count == 1) & (ucode == ori)
    ori_hi = (ori >= 0) & (c_ori > cfg.min_count)
    total = counts[0] + counts[1] + counts[2] + counts[3]
    keep_cut = ((count > 1) & ori_hi
                & ((c_ori >= cfg.cutoff) | (qual >= cfg.qual_cutoff)))
    lam = total.astype(jnp.float32) * jnp.float32(cfg.collision_prob)
    keep_poi = ((count > 1) & ori_hi & ~keep_cut
                & (poisson_term(lam, c_ori) < cfg.poisson_threshold))
    clean = (c1keep | keep_cut | keep_poi) & ~con
    t_a = (count > 1) & (ori >= 0) & ~ori_hi & (level == 0) & (c_ori == 0)
    t_b = (count > 1) & (ori < 0) & (level == 0)
    ambig_class = (count > 1) & ~(keep_cut | keep_poi) & ~t_a & ~t_b
    return counts, level, count, ucode, clean, c1keep, ambig_class


def _pack_counts(counts):
    """4 level-filtered variant counts -> one u32 (7 bits each; counts
    are bounded by the value word's bits <= 7)."""
    return (counts[0].astype(jnp.uint32)
            | (counts[1].astype(jnp.uint32) << 7)
            | (counts[2].astype(jnp.uint32) << 14)
            | (counts[3].astype(jnp.uint32) << 21))


def _class_planes(state, tmeta, sweep: SweepResult, facts, cfg: ECConfig):
    """The FULL-WIDTH sibling sweep: 3 lookups per position (the
    variants of the consuming frame's base-0 other than the original)
    complete the exact per-position get_best_alternatives. Returns
    (counts list, level, count, ucode, clean, c1keep, ambig_class) —
    all [B, L]. The production default is the compacted form
    (_class_planes_compact); this full form is the A/B + parity
    reference."""
    k = cfg.k
    ori, qual, nbase, wfh, wfl, wrh, wrl = facts
    orie = jnp.clip(ori, 0, 3)  # N windows are A-encoded: variant 0
    chis, clos = _sibling_mers(wfh, wfl, wrh, wrl, orie, k)
    sv = _db_lookup_big(
        state, tmeta, jnp.stack(chis).ravel(), jnp.stack(clos).ravel(),
    ).reshape(3, *ori.shape)
    svl = list(sv)
    vals4 = [
        jnp.where(orie == i, sweep.vals,
                  _sel4(svl, jnp.where(i > orie, i - 1, i)))
        for i in range(4)
    ]
    counts, level, count, ucode, clean, c1keep, ambig_class = _classify(
        vals4, ori, qual, sweep.con, cfg)
    return counts, level, count, ucode, clean, c1keep, ambig_class


def _certainly_clean(sweep: SweepResult, ori, qual, cfg: ECConfig):
    """The exact own-value pre-pass of the compacted sibling sweep:
    positions whose canonical lookup alone proves them clean. Own HQ
    with count past min_count and (count >= cutoff or qual >= cutoff)
    is clean WHATEVER the siblings hold: own HQ pins level=1, so the
    filtered own count equals the raw one; count==1 then means
    ucode==ori (c1-keep), count>1 means keep_cut — both clean. Every
    other position (incl. N windows and anything contaminated) stays a
    candidate for the sibling probe. What this pre-pass CANNOT decide
    is count==1 vs count>1 — the c1keep/prev circularity — which the
    consumption-point walk (_c1k_walk) resolves with O(runs) probes
    instead of O(positions)."""
    co = (sweep.vals >> 1).astype(jnp.int32)
    qo = (sweep.vals & 1).astype(jnp.int32)
    return ((ori >= 0) & (qo == 1) & (co > cfg.min_count)
            & ((co >= cfg.cutoff) | (qual >= cfg.qual_cutoff))
            & ~sweep.con)


def _class_planes_compact(state, tmeta, sweep: SweepResult, facts,
                          cfg: ECConfig):
    """The COMPACTED sibling sweep (round 7): the own-value pre-pass
    classifies ~certainly-clean positions for free; only the surviving
    candidates pay the 3-sibling probe, chunk-looped to a static cap so
    any candidate count is exact (a masked full-width gather pays per
    index whether or not the lane is live — compaction is the only way
    to make the sweep cost follow the candidate rate). Returns
    (cnt_packed, auxcore, clean, c1k_known, ambig_class, certain), all
    [B, L]; cnt/aux fields are exact for candidates and zero for
    certainly-clean positions (never consumed there: synced live steps
    only ever land on non-clean positions, which are candidates)."""
    k = cfg.k
    ori, qual, nbase, wfh, wfl, wrh, wrl = facts
    b, l = ori.shape
    n = b * l
    certain = _certainly_clean(sweep, ori, qual, cfg)
    flat = (~certain).ravel()
    slot = jnp.cumsum(flat.astype(jnp.int32)) - 1
    # padded so the chunk loop's dynamic_slice never clamps; the 3x
    # sibling lookup per chunk must stay under the in-loop row-gather
    # transient bound (_LOOKUP_CHUNK — an unchunked multi-M-row tile
    # gather materializes [N, 128] and OOMs at 32k-read batches)
    ch = min(n, max(4096, min(n // 8, _LOOKUP_CHUNK // 3)))
    pos_of = jnp.full((n + ch,), n, jnp.int32).at[
        jnp.where(flat, slot, n + ch)].set(
        jnp.arange(n, dtype=jnp.int32), mode="drop")
    n_cand = jnp.sum(flat.astype(jnp.int32))
    mf = [x.ravel() for x in (wfh, wfl, wrh, wrl)]
    ori_f = ori.ravel()
    qual_f = qual.ravel()
    con_f = sweep.con.ravel()
    own_f = sweep.vals.ravel()

    def body(c):
        i, cnt_f, auxc_f, clean_f, c1k_f, amb_f = c
        start = i * ch
        live = (start + jnp.arange(ch, dtype=jnp.int32)) < n_cand
        idx = jnp.where(live,
                        jax.lax.dynamic_slice(pos_of, (start,), (ch,)), 0)
        o = ori_f[idx]
        q = qual_f[idx]
        cn = con_f[idx]
        ov = own_f[idx]
        orie = jnp.clip(o, 0, 3)
        cfh, cfl, crh, crl = (f[idx] for f in mf)
        chis, clos = _sibling_mers(cfh, cfl, crh, crl, orie, k)
        sv = _db_lookup(
            state, tmeta, jnp.stack(chis).ravel(),
            jnp.stack(clos).ravel(), jnp.tile(live, 3)).reshape(3, ch)
        svl = list(sv)
        vals4 = [jnp.where(orie == v, ov,
                           _sel4(svl, jnp.where(v > orie, v - 1, v)))
                 for v in range(4)]
        counts, level, count, ucode, clean_c, c1k_c, amb_c = _classify(
            vals4, o, q, cn, cfg)
        auxc = (level.astype(jnp.uint32)
                | (count.astype(jnp.uint32) << _AX_COUNT)
                | (ucode.astype(jnp.uint32) << _AX_UCODE))
        sidx = jnp.where(live, idx, n)
        return (i + 1,
                cnt_f.at[sidx].set(_pack_counts(counts), mode="drop"),
                auxc_f.at[sidx].set(auxc, mode="drop"),
                clean_f.at[sidx].set(clean_c, mode="drop"),
                c1k_f.at[sidx].set(c1k_c, mode="drop"),
                amb_f.at[sidx].set(amb_c, mode="drop"))

    def cond(c):
        go = c[0] * ch < n_cand
        ax = getattr(tmeta, "routed_axis", None)
        if ax is not None:
            # routed lookups are collectives: every shard runs the
            # same number of chunk iterations
            go = jax.lax.pmax(go.astype(jnp.int32), ax) > 0
        return go

    zf = jnp.zeros((n,), jnp.uint32)
    zb = jnp.zeros((n,), bool)
    _i, cnt_f, auxc_f, clean_f, c1k_f, amb_f = jax.lax.while_loop(
        cond, body, (jnp.int32(0), zf, zf, zb, zb, zb))
    clean = certain | clean_f.reshape(b, l)
    return (cnt_f.reshape(b, l), auxc_f.reshape(b, l), clean,
            c1k_f.reshape(b, l), amb_f.reshape(b, l), certain)


def _c1k_walk(state, tmeta, clean2, kc1k0, unk0, mfh2, mfl2, mrh2, mrl2,
              ori2, lengths2, cfg: ECConfig):
    """Resolve the c1-keep bits the prev chain actually CONSUMES —
    the compacted sweep's answer to the count==1 vs count>1
    circularity (PERF_NOTES round 5): a certainly-clean position is
    prev-defining iff it has no HQ sibling, which only a probe can
    tell, but the chain is only ever read at CONSUMPTION POINTS
    (teleports read lastc1/prevval at tgt-1, which is always the
    position before a non-clean event or the last in-read position).
    So instead of probing every clean position (that would be the full
    sweep again), walk backward from each consumption point and probe
    only until the run's LAST prev-definer is known: positions below
    it are dominated and never influence a consumed value. At 40x,
    ~77% of clean positions are count==1, so the expected probes per
    run are ~1.3 (geometric) — O(runs), not O(positions).

    Frame-space [2B, L] inputs: `clean2` exact everywhere, `kc1k0` the
    known prev-definers (probed candidates), `unk0` the
    certainly-clean positions whose c1k bit is unknown. Returns the
    resolved kc1k plane (exact at and above every run's last
    prev-definer; dominated positions may stay 0 — consumption-
    equivalent, proven by the round-7 parity tests)."""
    k = cfg.k
    b2, l = clean2.shape
    n = b2 * l
    p_idx = jnp.arange(l, dtype=jnp.int32)[None, :]
    # consumption points: last position of a clean run, plus the last
    # in-read position of each lane (tgt = min(nd, end)). Positions at
    # or past the read end can never be consumed (tgt <= end), so
    # masking them skips whole walks over garbage windows — and every
    # position of a padding row.
    next_nonclean = ~_shl(clean2, 1, False)
    cp = (clean2 & (p_idx < lengths2[:, None])
          & (next_nonclean | (p_idx == lengths2[:, None] - 1)))
    cap = min(max(1, n), max(1024, min(n // 16, _LOOKUP_CHUNK // 3)))
    # walk stride: probe up to this many unknowns per consumption
    # point per round (the last W of the run) instead of one — rounds
    # collapse from the walk depth to depth/W at a bounded number of
    # wasted probes (only positions below a c1k found in the same
    # window)
    stride = 8
    neg = jnp.int32(-1)
    mf = [x.ravel() for x in (mfh2, mfl2, mrh2, mrl2)]
    ori_f = ori2.ravel()
    # the run boundary never moves: hoist its cummax out of the loop
    lastE = jax.lax.cummax(jnp.where(~clean2, p_idx, neg), axis=1)
    big = jnp.int32(l + 1)
    # next consumption point at-or-after p (per lane; big if none)
    nextcp = jax.lax.cummin(jnp.where(cp, p_idx, big), axis=1,
                            reverse=True)

    def needed_plane(kc1k, unk):
        """Positions to probe: unknowns within `stride` of an
        UNRESOLVED consumption point, above that point's last known
        stopper (event or prev-definer)."""
        lastK = jax.lax.cummax(jnp.where(kc1k, p_idx, neg), axis=1)
        lastU = jax.lax.cummax(jnp.where(unk, p_idx, neg), axis=1)
        stopper = jnp.maximum(lastE, lastK)
        unres = cp & (lastU > stopper)
        safe_ncp = jnp.clip(nextcp, 0, l - 1)
        unres_at = jnp.take_along_axis(unres, safe_ncp, axis=1)
        stop_at = jnp.take_along_axis(stopper, safe_ncp, axis=1)
        # window anchored at the unknown FRONTIER (the deepest unknown
        # below the point), not the point itself: known-non-definer
        # stretches between them could otherwise starve the window and
        # stall the loop. p == frontier always qualifies -> progress.
        front_at = jnp.take_along_axis(lastU, safe_ncp, axis=1)
        need = (unk & (nextcp < big) & unres_at
                & (p_idx > stop_at) & (p_idx > front_at - stride))
        return need, jnp.any(unres)

    def cond(c):
        go = c[2]
        ax = getattr(tmeta, "routed_axis", None)
        if ax is not None:
            go = jax.lax.pmax(go.astype(jnp.int32), ax) > 0
        return go

    def body(c):
        kc1k, unk, _go, needed = c
        # leftovers past the cap simply re-surface next round
        _slot, _fit, pos_of, live = _compact_select(
            needed.ravel(), cap, jnp.arange(n, dtype=jnp.int32))
        o = ori_f[pos_of]
        orie = jnp.clip(o, 0, 3)
        cfh, cfl, crh, crl = (f[pos_of] for f in mf)
        chis, clos = _sibling_mers(cfh, cfl, crh, crl, orie, k)
        sv = _db_lookup(
            state, tmeta, jnp.stack(chis).ravel(),
            jnp.stack(clos).ravel(), jnp.tile(live, 3)).reshape(3, cap)
        # walked positions are certainly-clean, i.e. own-HQ: level is
        # pinned at 1 and count==1 iff no sibling carries the HQ bit
        isc1k = live & (((sv[0] | sv[1] | sv[2]) & 1) == 0)
        sidx = jnp.where(live, pos_of, n)
        probed = jnp.zeros((n,), bool).at[sidx].set(True, mode="drop")
        newc1k = jnp.zeros((n,), bool).at[sidx].set(isc1k, mode="drop")
        kc1k = kc1k | newc1k.reshape(b2, l)
        unk = unk & ~probed.reshape(b2, l)
        needed, go = needed_plane(kc1k, unk)
        return kc1k, unk, go, needed

    needed0, go0 = needed_plane(kc1k0, unk0)
    kc1k, _unk, _go, _need = jax.lax.while_loop(
        cond, body, (kc1k0, unk0, go0, needed0))
    return kc1k


def _ambig_prepass(state, tmeta, ambig_class, counts, level, nbase, facts,
                   cfg: ECConfig, cap: int):
    """Precompute the ambiguous-path continuation probe
    (error_correct_reads.cc:473-507) for ambig-class positions, top
    level and compacted: 16 lookups per selected position yield the
    success and continues-with-next-base bits per variant, so a synced
    ambiguous event at runtime is a pure elementwise tie-break — no
    in-loop probe, no compaction-cap stall cascade. Positions past the
    static cap simply keep pre=0 and fall back to the in-loop probe.
    Returns (pre, succ_bits, cwn_bits) as [B, L] (uint32 bits)."""
    k = cfg.k
    _ori, _qual, _nb, wfh, wfl, wrh, wrl = facts
    b, l = ambig_class.shape
    n = b * l
    flat = ambig_class.ravel()
    slot = jnp.cumsum(flat.astype(jnp.int32)) - 1
    fitted = flat & (slot < cap)
    pos_of = jnp.zeros((cap,), jnp.int32).at[
        jnp.where(fitted, slot, cap)].set(jnp.arange(n, dtype=jnp.int32),
                                          mode="drop")
    n_fit = jnp.sum(fitted.astype(jnp.int32))
    slot_live = jnp.arange(cap, dtype=jnp.int32) < n_fit
    cfh, cfl = wfh.ravel()[pos_of], wfl.ravel()[pos_of]
    crh, crl = wrh.ravel()[pos_of], wrl.ravel()[pos_of]
    elig = [(c.ravel()[pos_of] > cfg.min_count) & slot_live for c in counts]
    level_c = level.ravel()[pos_of]
    nb_c = nbase.ravel()[pos_of]
    safe_nb = jnp.clip(nb_c, 0, 3)
    chis, clos, acts = [], [], []
    for i in range(4):
        ifh, ifl, irh, irl = mer.dir_replace0(
            cfh, cfl, crh, crl, mer.u32(i), 1, k)
        ifh, ifl, irh, irl = mer.dir_shift(
            ifh, ifl, irh, irl, mer.u32(0), 1, k)
        for j in range(4):
            jfh, jfl, jrh, jrl = mer.dir_replace0(
                ifh, ifl, irh, irl, mer.u32(j), 1, k)
            chi, clo = mer.canonical(jfh, jfl, jrh, jrl)
            chis.append(chi)
            clos.append(clo)
            acts.append(elig[i])
    nv = _db_lookup_big(
        state, tmeta, jnp.stack(chis).ravel(), jnp.stack(clos).ravel(),
        jnp.stack(acts).ravel(),
    ).reshape(4, 4, cap)
    succ_bits = jnp.zeros((cap,), jnp.uint32)
    cwn_bits = jnp.zeros((cap,), jnp.uint32)
    for i in range(4):
        ncounts, _nu, nlevel, ncount = _gba_reduce(list(nv[i]))
        succ_i = elig[i] & (ncount > 0) & (nlevel >= level_c)
        cwn_i = succ_i & (nb_c >= 0) & (_sel4(ncounts, safe_nb) > 0)
        succ_bits = succ_bits | (succ_i.astype(jnp.uint32) << i)
        cwn_bits = cwn_bits | (cwn_i.astype(jnp.uint32) << i)
    zf = jnp.zeros((n,), jnp.uint32)
    succ = zf.at[pos_of].set(jnp.where(slot_live, succ_bits, 0),
                             mode="drop").reshape(b, l)
    cwn = zf.at[pos_of].set(jnp.where(slot_live, cwn_bits, 0),
                            mode="drop").reshape(b, l)
    pre = (jnp.zeros((n,), bool).at[pos_of]
           .set(slot_live, mode="drop").reshape(b, l) & ambig_class)
    return pre, succ, cwn


def _event_planes(state, tmeta, sweep: SweepResult, codes32, quals32,
                  lengths, start_off, cfg: ECConfig,
                  uniform_len: int | None, prepass_cap: int,
                  compact_sweep: bool = True) -> EventPlanes:
    """Build the [2B, L] event planes (see EventPlanes): sibling sweep
    -> exact per-position class, ambig continuation pre-pass, then the
    frame remap. The rc half is a pure index remap of the original-
    orientation facts: the window ending at rc position p' is the
    original window ending at len+k-2-p', and the rc-frame forward/
    revcomp mer words are the original window's revcomp/forward
    words.

    `compact_sweep` (the round-7 default) replaces the full 3-row/base
    sibling sweep with the own-value pre-pass + compacted candidate
    probe (_class_planes_compact), and resolves the c1keep/prev chain
    with the consumption-point walk (_c1k_walk) — consumed plane
    values are bit-exact against the full sweep (round-7 parity
    tests)."""
    k = cfg.k
    l = codes32.shape[1]
    facts = _frame_facts(sweep, codes32, quals32, lengths, start_off, k)
    if compact_sweep:
        (cnt_packed, auxcore, clean, c1k_known, ambig_class,
         certain) = _class_planes_compact(state, tmeta, sweep, facts,
                                          cfg)
        counts = [((cnt_packed >> (7 * i)) & 127).astype(jnp.int32)
                  for i in range(4)]
        level = (auxcore & 1).astype(jnp.int32)
        c1k_bit = clean & c1k_known
    else:
        (counts, level, count, ucode, clean, c1keep,
         ambig_class) = _class_planes(state, tmeta, sweep, facts, cfg)
        certain = None
        cnt_packed = _pack_counts(counts)
        auxcore = (level.astype(jnp.uint32)
                   | (count.astype(jnp.uint32) << _AX_COUNT)
                   | (ucode.astype(jnp.uint32) << _AX_UCODE))
        c1k_bit = clean & c1keep
    pre, succ, cwn = _ambig_prepass(state, tmeta, ambig_class, counts,
                                    level, facts[2], facts, cfg,
                                    prepass_cap)
    aux = (auxcore
           | (pre.astype(jnp.uint32) << _AX_PRE)
           | (c1k_bit.astype(jnp.uint32) << _AX_C1K)
           | (succ << _AX_SUCC) | (cwn << _AX_CWN))

    def rc_map(x, fill):
        rev, _valid = _rev_rows(x, lengths, uniform_len, fill)
        if k > 1:
            rev = jnp.pad(rev[:, :l - (k - 1)], ((0, 0), (k - 1, 0)),
                          constant_values=fill)
        return rev

    cat = jnp.concatenate
    clean2 = cat([clean, rc_map(clean, False)])
    cnt2 = cat([cnt_packed, rc_map(cnt_packed, 0)])
    aux2 = cat([aux, rc_map(aux, 0)])
    mfh2 = cat([sweep.fhi, rc_map(sweep.rhi, 0)])
    mfl2 = cat([sweep.flo, rc_map(sweep.rlo, 0)])
    mrh2 = cat([sweep.rhi, rc_map(sweep.fhi, 0)])
    mrl2 = cat([sweep.rlo, rc_map(sweep.flo, 0)])
    p_idx = jnp.arange(l, dtype=jnp.int32)[None, :]
    nd2 = jax.lax.cummin(jnp.where(clean2, jnp.int32(l), p_idx), axis=1,
                         reverse=True)
    c1k2 = ((aux2 >> _AX_C1K) & 1) == 1
    lengths2 = cat([lengths, lengths])
    # prevval at a prev-defining position is always the OWN count as
    # stored: count==1 pins ucode==ori, and the level filter keeps the
    # raw own count whether the own mer is HQ (level 1) or the lone
    # LQ survivor (level 0) — so the chain value comes straight from
    # the canonical sweep, no sibling data needed
    co = (sweep.vals >> 1).astype(jnp.int32)
    co2 = cat([co, rc_map(co, 0)])
    if compact_sweep:
        certain2 = cat([certain, rc_map(certain, False)])
        ori2 = cat([facts[0], rc_map(facts[0], -2)])
        c1k2 = _c1k_walk(state, tmeta, clean2, c1k2, certain2,
                         mfh2, mfl2, mrh2, mrl2, ori2, lengths2, cfg)
    lastc1 = jax.lax.cummax(jnp.where(c1k2, p_idx, jnp.int32(-1)), axis=1)
    prevval = jnp.take_along_axis(co2, jnp.clip(lastc1, 0), axis=1)
    return EventPlanes(clean2, nd2, cnt2, aux2, lastc1, prevval,
                       mfh2, mfl2, mrh2, mrl2)


def correct_batch(state: ctable.TileState, tmeta: ctable.TileMeta,
                  codes, quals, lengths, cfg: ECConfig,
                  contam=None, ambig_cap: int | None = None,
                  event_driven: bool = True, pack_cap: int | None = None,
                  compact_sweep: bool | None = None,
                  drain_levels: int | None = None):
    """Correct a batch of reads on device. `contam` is an optional
    (TableState, TableMeta) k-mer membership set (value word != 0).
    Mirrors error_correct_instance::start (error_correct_reads.cc:
    246-341): anchor, then forward and backward extension run
    CONCURRENTLY as one 2B-lane d=+1 loop — the backward half operates
    on the reverse-complement frame (rc codes, swapped mer strands,
    mirrored positions), which is the same computation the reference
    expresses with its backward_* pointer adapters, and halves the
    sequential iteration count vs running two loops back to back.
    Backward lanes run even when forward later fails; the epilogue's
    forward-priority status combine makes that unobservable (a failed
    read's backward output is discarded), matching the sequential
    semantics bit-for-bit. `ambig_cap` overrides the ambiguous-lane
    compaction capacity (tests use tiny caps to exercise the stall
    path)."""
    codes = jnp.asarray(codes)
    quals = jnp.asarray(quals)
    uniform, cstate, cmeta, has_contam, ambig_cap = _batch_prologue(
        lengths, codes.shape[0], cfg, contam, ambig_cap)
    if compact_sweep is None:
        compact_sweep = compact_sweep_default()
    if drain_levels is None:
        drain_levels = drain_levels_default()
    # H2D in the NARROW dtype (int8 codes / uint8 quals are 4x smaller
    # than int32 over the ~170 ms/MB tunnel); _correct_device widens on
    # device. (correct_batch_packed goes further: 0.5 B/base planes.)
    lengths = jnp.asarray(lengths, jnp.int32)
    return _correct_device(state, tmeta, codes, quals, lengths, cfg,
                           cstate, cmeta, has_contam, uniform, ambig_cap,
                           event_driven, pack_cap, compact_sweep,
                           drain_levels)


def _batch_prologue(lengths, b: int, cfg: ECConfig, contam,
                    ambig_cap: int | None):
    """Host-side prologue shared by the packed and unpacked entry
    points (they must stay bit-identical; tests/test_packing.py)."""
    # uniform-length batches (the Illumina norm) get a static flip
    # reversal instead of per-lane gathers; decided host-side, ideally
    # from the numpy lengths the reader hands over (no D2H). Under a
    # trace (sharded_correct's shard_map) lengths are abstract — use
    # the general per-lane gather path.
    # Only full pad-free batches take it: a trailing partial batch is
    # "accidentally uniform" (often a single read), and letting it pick
    # arbitrary static lengths would compile fresh executables per
    # distinct tail length. One gather-path compile for the tail beats
    # unbounded churn.
    uniform = None
    if not isinstance(lengths, jax.core.Tracer):
        ln = np.asarray(lengths)
        if len(ln) and (ln > 0).all() and (ln == ln[0]).all():
            uniform = int(ln[0])
    has_contam = contam is not None
    cstate, cmeta = contam if has_contam else _dummy_contam(cfg.k)
    if has_contam and cmeta.k != cfg.k:
        raise ValueError(
            f"Contaminant mer length ({cmeta.k}) different than correction "
            f"mer length ({cfg.k})")
    if ambig_cap is None:
        from ..ops import tuning
        # stall-and-retry keeps any cap bit-exact, so the ambiguous-
        # continuation lane budget is a pure perf knob: env / autotune
        # profile / b-derived default (ops/tuning.py, ISSUE 11). This
        # prologue is the one resolution point every production entry
        # (packed, unpacked, sharded) funnels through.
        ambig_cap = max(1, int(tuning.cap("QUORUM_AMBIG_CAP",
                                          max(256, (2 * b) // 8))))
    return uniform, cstate, cmeta, has_contam, ambig_cap


def correct_batch_packed(state: ctable.TileState, tmeta: ctable.TileMeta,
                         packed, cfg: ECConfig,
                         contam=None, ambig_cap: int | None = None,
                         event_driven: bool = True,
                         pack_cap: int | None = None,
                         compact_sweep: bool | None = None,
                         drain_levels: int | None = None):
    """correct_batch over the bit-packed wire format (io/packing
    .PackedReads): 0.5 B/base crosses the H2D link instead of 2, the
    device widens. Requires the batch to have been packed with
    cfg.qual_cutoff among its thresholds. Bit-identical to
    correct_batch (tests/test_packing.py)."""
    packed.require_plane(cfg.qual_cutoff)
    uniform, cstate, cmeta, has_contam, ambig_cap = _batch_prologue(
        packed.lengths, packed.n_reads, cfg, contam, ambig_cap)
    if compact_sweep is None:
        compact_sweep = compact_sweep_default()
    if drain_levels is None:
        drain_levels = drain_levels_default()
    return _correct_device_packed(
        state, tmeta, jnp.asarray(packed.to_wire()), cfg, cstate, cmeta,
        has_contam, uniform, ambig_cap, event_driven, pack_cap,
        packed.n_reads, packed.length, packed.thresholds, compact_sweep,
        drain_levels)


@functools.partial(jax.jit,
                   static_argnums=(1, 5, 7, 8, 9, 10, 11, 12, 13, 14))
def _correct_device(state, tmeta, codes, quals, lengths, cfg: ECConfig,
                    cstate, cmeta, has_contam: bool, uniform: int | None,
                    ambig_cap: int, event_driven: bool,
                    pack_cap: int | None = None,
                    compact_sweep: bool = True, drain_levels: int = 2):
    """The whole device-side correction of one batch as ONE executable:
    position sweep, anchor scan, rc prologue, event planes, the merged
    extension loop, and the backward epilogue (separate dispatches cost
    ~25 ms each through the tunnel; see PERF_NOTES.md).

    The levers arrive RESOLVED (`compact_sweep`, `drain_levels`) as
    static arguments — the wrappers call the `*_default()` resolvers
    at dispatch time, so the executable count is one per (geometry,
    batch shape, lever tuple) and flipping a lever re-keys instead of
    silently serving a stale trace. That discipline is now enforced:
    quorum-lint's `trace-lever-read` rejects a resolver call from
    inside any jitted body, and this site's executable count is
    budgeted in analysis/compile_budget.COMPILE_BUDGET with the
    runtime sentinel (`QUORUM_COMPILE_SENTINEL=1`) counting the
    compiles that actually happen (ISSUE 15)."""
    codes = codes.astype(jnp.int32)
    quals = quals.astype(jnp.int32)
    return _correct_core(state, tmeta, codes, quals, lengths, cfg,
                         cstate, cmeta, has_contam, uniform, ambig_cap,
                         event_driven, pack_cap, compact_sweep,
                         drain_levels)


@functools.partial(jax.jit,
                   static_argnums=(1, 3, 5, 6, 7, 8, 9, 10, 11, 12, 13,
                                   14, 15))
def _correct_device_packed(state, tmeta, wire, cfg: ECConfig,
                           cstate, cmeta,
                           has_contam: bool, uniform: int | None,
                           ambig_cap: int, event_driven: bool,
                           pack_cap: int | None, b: int, length: int,
                           thresholds: tuple,
                           compact_sweep: bool = True,
                           drain_levels: int = 2):
    """Same executable as _correct_device but fed the bit-packed wire
    format (io/packing.py: 2-bit codes + N mask + the 1-bit
    qual>=cutoff predicate plane — 0.5 B/base over the tunnel instead
    of 2), fused into ONE u8 H2D buffer (the tunnel charges a large
    fixed cost PER TRANSFER). The widening at the head is elementwise
    [B, L] work; the synthetic qual plane is bit-equivalent under the
    corrector's only quality use, the >= qual_cutoff predicate."""
    pcodes, nmask, hq, lengths = mer.wire_parts_device(
        wire, b, length, thresholds)
    codes = packing.unpack_codes_device(pcodes, nmask, lengths, length)
    quals = packing.synth_quals_device(hq[int(cfg.qual_cutoff)], length,
                                       cfg.qual_cutoff)
    return _correct_core(state, tmeta, codes, quals, lengths, cfg,
                         cstate, cmeta, has_contam, uniform, ambig_cap,
                         event_driven, pack_cap, compact_sweep,
                         drain_levels)


def _correct_core(state, tmeta, codes, quals, lengths, cfg: ECConfig,
                  cstate, cmeta, has_contam: bool, uniform: int | None,
                  ambig_cap: int, event_driven: bool,
                  pack_cap: int | None = None,
                  compact_sweep: bool = True, drain_levels: int = 2):
    b, l = codes.shape
    sweep = _position_sweep(state, tmeta, codes, cfg, cstate, cmeta,
                            has_contam)
    anc = find_anchors(state, tmeta, codes, lengths, cfg,
                       cstate, cmeta, has_contam, sweep)
    rc_codes, rc_quals = _rc_prologue(codes, quals, lengths, uniform)
    if event_driven:
        # ambig-class positions are ~2-4% at 40x coverage; the cap
        # gives ~2x headroom, and overflow just falls back to the
        # in-loop probe (pre bit stays 0)
        prepass_cap = max(256, (b * l) // 16)
        planes = _event_planes(state, tmeta, sweep, codes, quals,
                               lengths, anc.start_off, cfg, uniform,
                               prepass_cap, compact_sweep)
    else:
        planes = None
    w = cfg.effective_window
    cat = jnp.concatenate
    codes2 = cat([codes, rc_codes])
    quals2 = cat([quals, rc_quals])
    pos0 = cat([anc.start_off, lengths - anc.start_off + cfg.k])
    end2 = cat([lengths, lengths])
    thresh = cat([jnp.full((b,), w, jnp.int32), lengths - 1 - w])
    res = extend(state, tmeta, codes2, quals2, cfg, codes2,
                 cat([anc.fhi, anc.rhi]), cat([anc.flo, anc.rlo]),
                 cat([anc.rhi, anc.fhi]), cat([anc.rlo, anc.flo]),
                 cat([anc.prev_count, anc.prev_count]),
                 cat([anc.found, anc.found]),
                 pos0, end2, cat([anc.status, anc.status]),
                 cstate, cmeta, 1, has_contam, ambig_cap, thresh, planes,
                 drain_levels)
    flog = LogState(res.log.n[:b], res.log.lwin[:b], res.log.pos[:b],
                    res.log.meta[:b])
    blog_rc = LogState(res.log.n[b:], res.log.lwin[b:], res.log.pos[b:],
                       res.log.meta[b:])
    out, start, status, blog = _bwd_epilogue(
        res.out[:b], res.status[:b], res.out[b:], res.opos[b:],
        res.status[b:], lengths, anc.start_off - cfg.k - 1, blog_rc,
        uniform)
    result = BatchResult(out, start, res.opos[:b], status, flog, blog)
    if pack_cap is not None:
        # the lean finish buffer fused into the SAME executable: one
        # dispatch instead of two per batch (each costs ~25-90 ms
        # through the tunnel)
        return result, _pack_finish_lean(result, pack_cap)
    return result



def _render_dir_flat(nv: np.ndarray, offs: np.ndarray, pos: np.ndarray,
                     meta: np.ndarray, trunc_string: str) -> list[str]:
    """Batched log rendering over FLAT entry arrays: read i's entries
    live at [offs[i], offs[i]+nv[i]). One flat pass over every entry in
    the batch (total entries ~ a few per read), then per-read joins."""
    counts = nv.astype(np.int64)
    tot = int(counts.sum())
    if tot == 0:
        return [""] * len(nv)
    cc = np.cumsum(counts)
    base = np.repeat(cc - counts, counts)
    idx = np.repeat(offs.astype(np.int64), counts) + (np.arange(tot) - base)
    p = pos[idx].tolist()
    m = meta[idx]
    is_tr = (m & 1).astype(bool).tolist()
    frm = ((m >> 1) & 7).tolist()
    to = ((m >> 4) & 7).tolist()
    ents = [
        f"{pp}:{trunc_string}" if t
        else f"{pp}:sub:{_BASES[f]}-{_BASES[tt]}"
        for pp, t, f, tt in zip(p, is_tr, frm, to)
    ]
    bounds = np.concatenate([[0], cc])
    return [" ".join(ents[bounds[i]:bounds[i + 1]]) for i in range(len(nv))]


# host LUT: packed byte -> 4 ASCII base chars (little codes first)
_UNPACK_LUT = np.empty((256, 4), np.uint8)
for _b in range(256):
    for _j in range(4):
        _UNPACK_LUT[_b, _j] = b"ACGT"[(_b >> (2 * _j)) & 3]

_BASE_U8 = np.frombuffer(b"ACGTN", np.uint8)

# log positions are packed biased into u16 lanes (+_POS_BIAS) so the
# occasional small negative raw position survives the round trip
_POS_BIAS = 4

# per-(batch, maxe) entry-capacity guess for the lean finish buffer
# (self-tuning; an overflow re-packs once with the exact size)
_LEAN_CAP_CACHE: dict = {}


def _i16_bytes(x):
    """[B, W] int16 -> [B, 2W] u8 (little-endian byte planes)."""
    lo = (x.astype(jnp.uint16) & 0xFF).astype(jnp.uint8)
    hi = (x.astype(jnp.uint16) >> 8).astype(jnp.uint8)
    return jnp.stack([lo, hi], axis=2).reshape(x.shape[0], -1)


@functools.partial(jax.jit, static_argnums=(1,))
def _pack_finish(res: BatchResult, width: int):
    """Device-side compression before D2H: ONE u8 buffer per batch.

    The tunnel's D2H path costs ~90 ms fixed per transfer plus
    ~170 ms/MB (PERF_NOTES.md) — transferring the raw BatchResult
    (50 MB, 8 transfers) cost 2.5x the device compute. Packing 2-bit
    codes + int16-clipped logs into a single [B, row_bytes] u8 plane
    makes it one ~1.5 MB transfer.

    Row layout (all int16 little-endian unless noted):
    [seq 2-bit packed: ceil(L/4) u8][start][end][status]
    [f_n][b_n][f_pos width][f_meta width][b_pos width][b_meta width]
    """
    codes4 = jnp.clip(res.out, 0, 3).astype(jnp.uint32)
    b, l = codes4.shape
    l4 = -(-l // 4) * 4
    codes4 = jnp.pad(codes4, ((0, 0), (0, l4 - l)))
    g = codes4.reshape(b, l4 // 4, 4)
    packed = (g[:, :, 0] | (g[:, :, 1] << 2) | (g[:, :, 2] << 4)
              | (g[:, :, 3] << 6)).astype(jnp.uint8)

    def clip(lg: LogState):
        return (_i16_bytes(lg.pos[:, :width].astype(jnp.int16)),
                _i16_bytes(lg.meta[:, :width].astype(jnp.int16)))

    fp, fm = clip(res.fwd_log)
    bp, bm = clip(res.bwd_log)
    cols = [packed]
    for v in (res.start, res.end, res.status, res.fwd_log.n,
              res.bwd_log.n):
        cols.append(_i16_bytes(v.astype(jnp.int16)[:, None]))
    cols.extend([fp, fm, bp, bm])
    return jnp.concatenate(cols, axis=1)


def _unpack_finish(buf: np.ndarray, l: int, width: int):
    """Host-side inverse of `_pack_finish`'s row layout."""
    nb = -(-l // 4)
    seq_ascii = _UNPACK_LUT[buf[:, :nb]].reshape(buf.shape[0], -1)[:, :l]

    def i16(col):
        u = (buf[:, col].astype(np.uint16)
             | (buf[:, col + 1].astype(np.uint16) << 8))
        return u.view(np.int16)

    def i16w(col, w):
        raw = buf[:, col:col + 2 * w].reshape(buf.shape[0], w, 2)
        u = (raw[:, :, 0].astype(np.uint16)
             | (raw[:, :, 1].astype(np.uint16) << 8))
        return np.ascontiguousarray(u).view(np.int16)

    o = nb
    start, end, status, f_n, b_n = (i16(o), i16(o + 2), i16(o + 4),
                                    i16(o + 6), i16(o + 8))
    o += 10
    f_pos = i16w(o, width)
    f_meta = i16w(o + 2 * width, width)
    b_pos = i16w(o + 4 * width, width)
    b_meta = i16w(o + 6 * width, width)
    return (seq_ascii, start.copy(), end.copy(), status.copy(),
            f_n.copy(), f_pos, f_meta, b_n.copy(), b_pos, b_meta)


@functools.partial(jax.jit, static_argnums=(1,))
def _pack_finish_lean(res: BatchResult, cap_e: int):
    """The D2H diet: ONE u32 buffer with NO sequence plane and
    length-compacted log entries.

    The corrected sequence is reconstructible host-side from the INPUT
    read plus the substitution entries (every kept position is either
    the never-rewritten anchor window or was written with either the
    original base or a logged substitution), so the 2-bit seq plane —
    the bulk of _pack_finish's bytes — need not cross the tunnel.
    Entries are scattered to a flat [cap_e] plane at cumsum offsets
    (read i: fwd entries then bwd entries), one packed u32 each
    (biased pos << 16 | meta), instead of padding every read to the
    batch-max width.

    Layout: [maxn u32][total u32] [B x (start<<16|end)]
    [B x (status<<16|f_n)] [B x b_n] [cap_e x entry]. The leading
    geometry scalars let the host detect entry overflow (total >
    cap_e -> re-pack bigger) from the SAME transfer, instead of paying
    a separate ~90 ms scalar D2H round trip per batch."""
    u16 = lambda x: (x.astype(jnp.int32) & 0xFFFF).astype(jnp.uint32)
    f_n, b_n = res.fwd_log.n, res.bwd_log.n
    tot = f_n + b_n
    offs = jnp.cumsum(tot) - tot  # exclusive prefix
    b, maxe = res.fwd_log.pos.shape
    j = jnp.arange(maxe, dtype=jnp.int32)[None, :]

    def pack_entries(lg, base):
        enc = (u16(lg.pos + _POS_BIAS) << 16) | u16(lg.meta)
        slot = jnp.where(j < lg.n[:, None], base[:, None] + j, cap_e)
        return enc, slot

    fe, fs = pack_entries(res.fwd_log, offs)
    be, bs = pack_entries(res.bwd_log, offs + f_n)
    flat = jnp.zeros((cap_e,), jnp.uint32)
    flat = flat.at[fs.ravel()].set(fe.ravel(), mode="drop")
    flat = flat.at[bs.ravel()].set(be.ravel(), mode="drop")
    h1 = (u16(res.start) << 16) | u16(res.end)
    h2 = (u16(res.status) << 16) | u16(f_n)
    h3 = u16(b_n)
    geom = jnp.stack([
        jnp.maximum(jnp.max(f_n), jnp.max(b_n)).astype(jnp.uint32),
        jnp.sum(tot).astype(jnp.uint32)])
    return jnp.concatenate([geom, h1, h2, h3, flat])


def _homo_trim_np(out, start, end, ok, homo_trim_val: int):
    """Vectorized homo_trim (error_correct_reads.cc:567-597): walking
    from the 3' end, score +1 per repeated base, -1 per change; trim at
    the highest-scoring position (largest position wins ties) if the
    max score reaches the threshold. Returns (trim_mask, max_pos)."""
    b, l = out.shape
    q = np.arange(l - 1)[None, :]
    t = np.where((q >= start[:, None]) & (q <= end[:, None] - 2),
                 2 * (out[:, :-1] == out[:, 1:]).astype(np.int64) - 1, 0)
    scores = np.flip(np.cumsum(np.flip(t, 1), 1), 1)  # S[p] = sum t[p:]
    valid = (q >= start[:, None]) & (q <= end[:, None] - 2) & ok[:, None]
    neg = np.int64(-(2**62))
    masked = np.where(valid, scores, neg)
    max_score = masked.max(axis=1)
    has = valid.any(axis=1)
    is_max = valid & (masked == max_score[:, None])
    max_pos = np.where(has,
                       np.where(is_max, q, -1).max(axis=1), -1)
    trim = has & (max_score >= homo_trim_val)
    return trim, max_pos


def _finish_host(n: int, l: int, cfg: ECConfig, seq_ascii, start, end,
                 status, f_n, b_n, offs_f, offs_b, pos_flat, meta_flat
                 ) -> list[ReadResult]:
    """Shared host tail of finish_batch over the FLAT entry layout:
    read i's fwd entries at [offs_f[i], offs_f[i]+f_n[i]), bwd at
    [offs_b[i], offs_b[i]+b_n[i]) (offsets fixed; homo-trim may shrink
    the live counts in place)."""
    extra_fwd: dict[int, list[tuple[int, int]]] = {}
    if cfg.do_homo_trim:
        ok = status[:n] == OK
        trim, max_pos = _homo_trim_np(seq_ascii[:n], start[:n], end[:n],
                                      ok, cfg.homo_trim)
        for i in np.nonzero(trim)[0]:
            mp = int(max_pos[i])
            if mp < start[i]:  # pragma: no cover - dead in the binary too
                status[i] = ST_HOMOPOLYMER
                continue
            # force_truncate, binary parity (see oracle module
            # docstring): forward drops raw >= pos, backward raw <= pos
            s0, k0 = int(offs_f[i]), int(f_n[i])
            seg_p, seg_m = pos_flat[s0:s0 + k0], meta_flat[s0:s0 + k0]
            keep = seg_p < mp
            nk = int(keep.sum())
            pos_flat[s0:s0 + nk] = seg_p[keep]
            meta_flat[s0:s0 + nk] = seg_m[keep]
            f_n[i] = nk
            s0, k0 = int(offs_b[i]), int(b_n[i])
            seg_p, seg_m = pos_flat[s0:s0 + k0], meta_flat[s0:s0 + k0]
            keep = seg_p > mp
            nk = int(keep.sum())
            pos_flat[s0:s0 + nk] = seg_p[keep]
            meta_flat[s0:s0 + nk] = seg_m[keep]
            b_n[i] = nk
            extra_fwd[int(i)] = [(mp, _T_TRUNC)]
            end[i] = mp

    fwd_strs = _render_dir_flat(f_n[:n], offs_f[:n], pos_flat, meta_flat,
                                "3_trunc")
    bwd_strs = _render_dir_flat(b_n[:n], offs_b[:n], pos_flat, meta_flat,
                                "5_trunc")
    seq_buf = seq_ascii[:n].tobytes()

    results: list[ReadResult] = []
    for i in range(n):
        st = int(status[i])
        if st != OK:
            results.append(ReadResult(False, STATUS_ERRORS[st]))
            continue
        s, e = int(start[i]), int(end[i])
        seq = seq_buf[i * l + s:i * l + e].decode() if e > s else ""
        fwd_s = fwd_strs[i]
        if i in extra_fwd:
            extra = " ".join(f"{p}:3_trunc" for p, _ in extra_fwd[i])
            fwd_s = f"{fwd_s} {extra}" if fwd_s else extra
        results.append(ReadResult(True, "", seq, fwd_s, bwd_strs[i], s, e))
    return results


def finish_batch(res: BatchResult, n: int, cfg: ECConfig,
                 codes=None, packed=None) -> list[ReadResult]:
    """Host post-processing: optional homo-trim, log rendering, and
    ReadResult assembly (same shape as the oracle's results).

    With `codes` (the host-side INPUT code array the reads were built
    from, int8/int32 [B, L]) the LEAN path runs: no sequence plane
    crosses the tunnel — the corrected sequence is reconstructed from
    the input plus the logged substitutions — and log entries transfer
    length-compacted (_pack_finish_lean), cutting the D2H from ~2 MB to
    a few hundred KB per 16k-read batch. Without `codes`, the original
    packed-plane path runs. Both feed the shared flat-layout host tail
    (_finish_host)."""
    maxe = res.fwd_log.pos.shape[1]
    # the packed D2H narrows positions to int16/u16 lanes; real errors,
    # not asserts — under python -O an overflow would silently drop log
    # entries (mode="drop" scatter) and misalign the render offsets
    if res.out.shape[1] >= (1 << 15) - _POS_BIAS:
        raise ValueError(
            f"read length {res.out.shape[1]} overflows the int16 packed "
            "layout")
    l = res.out.shape[1]

    if codes is not None:
        buf = fetch_finish(res, packed)
        return finish_batch_host(buf, n, cfg, codes,
                                 res.out.shape[0], l, maxe)

    # wide path continues below
    return _finish_wide(res, n, cfg, maxe, l)


def fetch_finish(res: BatchResult, packed=None) -> np.ndarray:
    """MAIN-THREAD half of the lean finish: the single packed D2H (and
    the rare exact-size re-pack dispatch on overflow — a device call,
    which must stay on the tunnel's one thread; PERF_NOTES.md r4).
    Returns the host buffer, ready for finish_batch_host on any
    thread."""
    b = res.out.shape[0]
    maxe = res.fwd_log.pos.shape[1]
    key = (b, maxe)
    if packed is not None:
        buf = np.asarray(packed)
        cap_e = len(buf) - 2 - 3 * b
    else:
        cap_e = _LEAN_CAP_CACHE.get(key, 16384)
        buf = np.asarray(_pack_finish_lean(res, cap_e))
    total = int(buf[1])
    if total > cap_e:
        # the entry-capacity guess overflowed: re-pack once, exact
        cap_e = 4096
        while cap_e < total:
            cap_e *= 2
        buf = np.asarray(_pack_finish_lean(res, cap_e))
    if packed is None:
        # monotone per shape: a shrinking guess would re-pack every
        # other batch when totals straddle a pow2 boundary. (Not
        # updated on the prepacked path — its cap is the caller's
        # fixed choice, not a tuned guess.)
        _LEAN_CAP_CACHE[key] = max(
            cap_e, 4096, 1 << (max(1, total) - 1).bit_length())
    return buf


def finish_batch_host(buf: np.ndarray, n: int, cfg: ECConfig, codes,
                      b: int, l: int, maxe: int) -> list[ReadResult]:
    """WORKER-SAFE half of the lean finish: pure numpy/str work on the
    fetched buffer — no device interaction, so the stage-2 pipeline
    renders batch i while the device corrects batch i+1."""
    maxn, total = int(buf[0]), int(buf[1])
    if maxn > maxe:
        raise RuntimeError(
            f"log overflow: {maxn} entries > buffer {maxe}")
    buf = buf[2:]
    h1, h2, h3 = buf[:b], buf[b:2 * b], buf[2 * b:3 * b]
    flat = buf[3 * b:]

    def s16(x):
        return x.astype(np.uint16).view(np.int16).astype(np.int32)

    start, end = s16(h1 >> 16), s16(h1 & 0xFFFF)
    status, f_n = s16(h2 >> 16), s16(h2 & 0xFFFF)
    b_n = s16(h3 & 0xFFFF)
    tot_n = f_n + b_n
    offs_f = (np.cumsum(tot_n) - tot_n).astype(np.int64)
    offs_b = offs_f + f_n
    pos_flat = (s16(flat >> 16) - _POS_BIAS).astype(np.int32)
    meta_flat = s16(flat & 0xFFFF).astype(np.int32)
    # reconstruct the corrected sequence: input bases + logged subs
    codes_np = np.asarray(codes)
    seq_ascii = _BASE_U8[np.clip(codes_np[:, :l], 0, 3)].copy()
    if total:
        counts = tot_n.astype(np.int64)
        ri = np.repeat(np.arange(b), counts)
        m = meta_flat[:total]
        p = pos_flat[:total]
        is_sub = (m & 1) == 0
        to = (m >> 4) & 7
        sel = is_sub & (to < 4) & (p >= 0) & (p < l)
        seq_ascii[ri[sel], p[sel]] = _BASE_U8[to[sel]]
    return _finish_host(n, l, cfg, seq_ascii, start, end, status,
                        f_n, b_n, offs_f, offs_b, pos_flat, meta_flat)


def _finish_wide(res: BatchResult, n: int, cfg: ECConfig, maxe: int,
                 l: int) -> list[ReadResult]:
    # wide path: one tiny D2H decides the clip width, one packed D2H
    # moves the rest
    maxn = int(np.asarray(jnp.maximum(jnp.max(res.fwd_log.n),
                                      jnp.max(res.bwd_log.n))))
    if maxn > maxe:
        raise RuntimeError(
            f"log overflow: {maxn} entries > buffer {maxe}")
    width = 1
    while width < maxn:
        width *= 2
    width = min(width, maxe)
    buf = np.asarray(_pack_finish(res, width))
    (seq_ascii, start, end, status, f_n, f_pos, f_meta, b_n, b_pos,
     b_meta) = _unpack_finish(buf, l, width)
    # widen to the flat layout: fwd entries then bwd entries per read
    b = res.out.shape[0]
    f_n32, b_n32 = f_n.astype(np.int32), b_n.astype(np.int32)
    tot_n = f_n32 + b_n32
    offs_f = (np.cumsum(tot_n) - tot_n).astype(np.int64)
    offs_b = offs_f + f_n32
    tot = int(tot_n.sum())
    pos_flat = np.zeros((tot,), np.int32)
    meta_flat = np.zeros((tot,), np.int32)
    j = np.arange(width)[None, :]
    fm = j < f_n32[:, None]
    bm = j < b_n32[:, None]
    fidx = (offs_f[:, None] + j)[fm]
    bidx = (offs_b[:, None] + j)[bm]
    pos_flat[fidx] = f_pos[fm]
    meta_flat[fidx] = f_meta[fm]
    pos_flat[bidx] = b_pos[bm]
    meta_flat[bidx] = b_meta[bm]
    return _finish_host(n, l, cfg, seq_ascii, start.astype(np.int32),
                        end.astype(np.int32), status.astype(np.int32),
                        f_n32, b_n32, offs_f, offs_b, pos_flat, meta_flat)
