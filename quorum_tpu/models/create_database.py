"""Stage 1: build the quality-aware k-mer database from FASTQ reads.

TPU-native rebuild of `quorum_create_database`
(reference: src/create_database.cc). The reference streams reads into N
pthreads that CAS into a shared hash; here each fixed-shape read batch
becomes one device program: rolling canonical k-mers + quality-run
tracking (the low_len/high_len logic of create_database.cc:64-91) are
computed for every position of every read in parallel and counted
straight into the tile-bucket table (ops/ctable: write-then-verify
claim rounds over 64-slot hardware-tile buckets). The table auto-grows
on overflow exactly once per key (placed-mask retry), mirroring the
reference's cooperative resize (src/mer_database.hpp:137-187) with a
host-orchestrated re-scatter. The finished table IS the query layout —
one row gather per lookup in stage 2.
"""

from __future__ import annotations

import dataclasses
import os
import time
from typing import Sequence

import numpy as np
import jax
import jax.numpy as jnp

from ..io import checkpoint as ckpt_mod
from ..io import fastq, db_format, packing
from ..ops import ctable, mer
from ..ops import sketch as sketch_mod
from ..telemetry import NULL as NULL_METRICS
from ..telemetry import NULL_TRACER, observe_dispatch_wait
from ..utils import faults, resources
from ..utils.pipeline import prefetch
from ..utils.profiling import StageTimer, trace
from ..utils.vlog import vlog


@dataclasses.dataclass(frozen=True)
class BuildConfig:
    k: int = 24
    bits: int = 7
    qual_thresh: int = 38  # ASCII code: base qual char >= this is "high"
    initial_size: int = 200_000_000
    max_reprobe: int = 126  # wide-table compatibility (unused by tile)
    batch_size: int = 8192
    threads: int = 1  # -t: parallel host decode workers (multi-file)
    max_grows: int = 16
    profile: str | None = None  # --profile DIR: jax.profiler trace
    # fault tolerance (ISSUE 4): --checkpoint-dir enables atomic
    # snapshots of the counting table every --checkpoint-every
    # batches; --resume continues from the last valid one
    checkpoint_dir: str | None = None
    checkpoint_every: int = 64  # batches between snapshots
    resume: bool = False
    # --on-bad-read: malformed-record policy (io/fastq.BadReadPolicy)
    on_bad_read: str = "abort"
    quarantine_path: str | None = None
    # --devices (ISSUE 5): 1 = the single-chip path; >1 shards the
    # table by leading row bits over a local device mesh
    # (parallel/tile_sharded) and routes observations owner-bucketed
    devices: int = 1
    # --db-version (ISSUE 8): 5 (default) writes the checksummed
    # export (per-section CRC32C + whole-file trailer digest); 4 the
    # bare round-5 layout. The payload bytes are identical.
    db_version: int = 5
    # --db-layout (ISSUE 9): "single" gathers a sharded table to one
    # chip and writes the one-file format (compatibility default);
    # "sharded" streams each shard D2H independently into
    # PREFIX.shard-K-of-S.qdb v5 files under a sealed manifest — no
    # cross-device gather, no single-chip geometry cap
    db_layout: str = "single"
    # --prefilter (ISSUE 14): the RESOLVED singleton-prefilter mode —
    # "off", "two-pass" (sketch pass then exact gated inserts), or
    # "inline" (khmer-style online gating). Non-off modes imply the
    # stage-2 presence floor (ops/sketch docstring).
    prefilter: str = "off"
    # --partitions (ISSUE 14): P > 1 builds the table in P sequential
    # passes over the input, each counting one disjoint leading-bit
    # row range at 1/P the table memory, exported streaming into the
    # PR 9 sharded manifest — byte-identical payload to a single-pass
    # build
    partitions: int = 1


def s1_overlap_default() -> bool:
    """The sharded build's pack/exchange overlap (ISSUE 9): ON unless
    QUORUM_S1_OVERLAP=0 — the double-buffered dispatch is bit-exact
    (resolution order is dispatch order, retries stay synchronous), so
    the switch exists for A/B measurement, not correctness."""
    from ..utils import levers
    return levers.raw("QUORUM_S1_OVERLAP", "1") != "0"


# canonical home is ops/ctable (so the fused stage-1 dispatch can use
# it); re-exported here for the sharded builds and tests
extract_observations_impl = ctable.extract_observations_impl


extract_observations = jax.jit(extract_observations_impl,
                               static_argnums=(2, 3))


@dataclasses.dataclass
class BuildStats:
    reads: int = 0
    bases: int = 0
    batches: int = 0
    grows: int = 0
    distinct: int = 0
    # prefilter accounting (ISSUE 14; zero when the prefilter is off).
    # poisson_* are the FULL-table Poisson-cutoff statistics (table
    # stats + the dropped hq singletons' exact contribution), exported
    # in the database header so stage 2 computes the same cutoff it
    # would from the unfiltered table.
    prefilter_mode: str = "off"
    prefilter_dropped: int = 0
    prefilter_dropped_hq: int = 0
    prefilter_false_pass: int = 0
    sketch_cells_log2: int = 0
    poisson_distinct_hq: int = 0
    poisson_total_hq: int = 0

    def db_extra_header(self) -> dict | None:
        """The prefilter declaration + corrected Poisson stats for the
        database export header; None for unfiltered builds (no header
        change, byte-compatible)."""
        if self.prefilter_mode == "off":
            return None
        return {
            "prefilter": {
                "mode": self.prefilter_mode,
                "min_obs": 2,
                "dropped": int(self.prefilter_dropped),
                "dropped_hq": int(self.prefilter_dropped_hq),
                "false_pass": int(self.prefilter_false_pass),
                "sketch_cells_log2": int(self.sketch_cells_log2),
            },
            "poisson_stats": {
                "distinct_hq": int(self.poisson_distinct_hq),
                "total_hq": int(self.poisson_total_hq),
            },
        }


def build_database(
    paths: Sequence[str],
    cfg: BuildConfig,
    batches=None,
    metrics=None,
    tracer=None,
    batches_factory=None,
):
    """Run the full stage-1 pipeline. Returns
    (TileState, TileMeta, stats) — the query-ready tile table.

    `batches` (optional) overrides the disk readers: an iterable of
    (ReadBatch, PackedReads) pairs whose hq planes include
    cfg.qual_thresh (the quorum driver uses this to share one
    parse+pack between both stages). `batches_factory` (optional)
    is the multi-pass variant: a zero-arg callable returning a FRESH
    such iterable per call — required by the two-pass prefilter
    (ISSUE 14), which streams the input once into the sketch and once
    into the table.

    `metrics` (optional telemetry registry, --metrics on the CLI)
    records reads/bases/batches/distinct-mer counters, hash geometry
    and fill gauges, grow events, per-batch dispatch/wait histograms,
    and the stage timer table. `tracer` (optional span tracer,
    --trace-spans) records per-batch hierarchical spans with the
    device steps StepTraceAnnotation-tagged.

    Raises RuntimeError("Hash is full") only if growth itself fails
    (allocation), preserving the reference's failure contract
    (create_database.cc:87, README.md:46-47).
    """
    reg = metrics if metrics is not None else NULL_METRICS
    tracer = tracer if tracer is not None else NULL_TRACER
    if cfg.partitions > 1:
        raise ValueError(
            "partitioned builds stream their export per pass — run "
            "them through create_database_main, not build_database")
    if cfg.prefilter not in sketch_mod.PREFILTER_MODES:
        raise ValueError(f"unknown prefilter mode {cfg.prefilter!r} "
                         f"(one of {sketch_mod.PREFILTER_MODES})")
    if cfg.prefilter != "off" and cfg.devices > 1:
        raise ValueError(
            "--prefilter composes with --devices 1 today; use "
            "--partitions for multi-pass capacity over a mesh")
    if cfg.prefilter == "two-pass":
        return _build_two_pass(paths, cfg, batches, batches_factory,
                               reg, tracer)
    if batches is None and batches_factory is not None:
        # single-pass build handed the multi-pass plumbing: consume
        # one fresh iterable, exactly like a plain `batches`
        batches = batches_factory()
    if cfg.devices > 1:
        # --devices N: the tile-sharded multi-device build
        # (parallel/tile_sharded), fed by the SAME packed-wire
        # producer; bit-identical table content by construction
        return _build_database_sharded(paths, cfg, batches, reg, tracer)
    rb = ctable.tile_rb_for(cfg.initial_size, cfg.k, cfg.bits)
    meta = ctable.TileMeta(k=cfg.k, bits=cfg.bits, rb_log2=rb)
    bstate = ctable.make_tile_build(meta)
    stats = BuildStats()
    reg.set_meta(stage="create_database", k=cfg.k, bits=cfg.bits,
                 qual_thresh=cfg.qual_thresh, batch_size=cfg.batch_size,
                 s1_aggregate=ctable.s1_aggregate_default())
    # inline prefilter (ISSUE 14): gate inserts behind the online
    # sketch, khmer-style. Rides the normal loop; incompatible with
    # batch-level checkpoints (the sketch is not snapshotted — a
    # resumed table without its sketch would re-open every gate).
    sk = smeta = None
    if cfg.prefilter == "inline":
        if cfg.checkpoint_dir:
            raise RuntimeError(
                "--prefilter=inline does not checkpoint (the online "
                "sketch is not snapshotted); use --prefilter=two-pass "
                "with --checkpoint-dir")
        smeta = sketch_mod.SketchMeta(
            sketch_mod.cells_log2_for(cfg.initial_size))
        sk = sketch_mod.make_sketch(smeta)
        stats.prefilter_mode = "inline"
        stats.sketch_cells_log2 = smeta.cells_log2
        reg.set_meta(prefilter="inline",
                     sketch_cells_log2=smeta.cells_log2)
        reg.counter("prefilter_dropped_total")
        reg.counter("prefilter_false_pass_total")

    # crash safety (ISSUE 4): resume from the last atomic snapshot —
    # the table planes come back exactly as checkpointed, and the
    # first `cursor` batches of the deterministically re-batched
    # input are skipped instead of re-counted
    ck = (ckpt_mod.Stage1Checkpoint(cfg.checkpoint_dir)
          if cfg.checkpoint_dir else None)
    skip_batches = 0
    if ck is not None and cfg.resume:
        snap = ck.load()
        if snap is not None:
            snap.check_config(cfg.k, cfg.bits, cfg.qual_thresh,
                              cfg.batch_size, paths)
            meta = ctable.TileMeta(k=cfg.k, bits=cfg.bits,
                                   rb_log2=snap.rb_log2)
            bstate = ctable.TBuildState(jnp.asarray(snap.tag),
                                        jnp.asarray(snap.hq),
                                        jnp.asarray(snap.lq))
            h = snap.header
            stats.reads, stats.bases = h["reads"], h["bases"]
            stats.batches, stats.grows = h["batches"], h["grows"]
            skip_batches = snap.cursor
            reg.counter("resume_skipped_reads")  # lands even at 0
            reg.set_meta(resumed=True, resumed_from_batch=skip_batches)
            reg.event("resume", stage="create_database",
                      cursor=skip_batches)
            vlog("Resuming stage 1 from checkpoint: ", skip_batches,
                 " batches (", stats.reads, " reads) already counted")
    if ck is not None:
        reg.counter("checkpoint_writes_total")
        reg.set_meta(checkpoint_every=cfg.checkpoint_every)

    if batches is None:
        batches = _default_batches(paths, cfg, reg, tracer)
    timer = StageTimer()
    with trace(cfg.profile):
        for batch, pk in batches:
            if skip_batches > 0:
                # resume fast-path: already counted before the crash
                # (stats were restored from the snapshot)
                skip_batches -= 1
                reg.counter("resume_skipped_reads").inc(batch.n)
                continue
            step_i = stats.batches
            faults.inject("stage1.insert", batch=step_i)
            resources.watchdog_beat("stage1.insert", step_i)
            stats.batches += 1
            stats.reads += batch.n
            nb = int(batch.lengths.sum())
            stats.bases += nb
            timer.add_units("insert_wait", nb)
            reg.heartbeat(stage="create_database", reads=stats.reads,
                          bases=stats.bases, batches=stats.batches)
            with tracer.span("stage1_batch", step=step_i,
                             reads=batch.n):
                # per-batch device-time attribution: dispatch (handing
                # XLA the fused extract+insert program) split from the
                # wait for the device result (`bool(full)` is the sync
                # point — full comes out of the same executable as the
                # table planes), under a StepTraceAnnotation so the
                # split lines up with the XLA timeline under --profile
                t0 = time.perf_counter()
                with tracer.step("stage1_insert", step_i,
                                 reads=batch.n):
                    # ONE dispatch: extract + insert fused (the
                    # inline-prefiltered variant gates behind the
                    # sketch in the same executable)
                    if sk is not None:
                        (bstate, sk, full,
                         (chi, clo, q, valid, placed),
                         d_hq, d_lq) = \
                            sketch_mod.tile_insert_reads_packed_gated(
                                bstate, meta, sk, smeta, pk,
                                cfg.qual_thresh, "inline")
                    else:
                        bstate, full, (chi, clo, q, valid, placed) = \
                            ctable.tile_insert_reads_packed(
                                bstate, meta, pk, cfg.qual_thresh)
                        d_hq = d_lq = 0
                    t1 = time.perf_counter()
                    full = bool(full)
                    t2 = time.perf_counter()
                observe_dispatch_wait(reg, "insert", t0, t1, t2,
                                      timer=timer)
                if d_hq or d_lq:
                    stats.prefilter_dropped += d_hq + d_lq
                    stats.prefilter_dropped_hq += d_hq
                    reg.counter("prefilter_dropped_total").inc(
                        d_hq + d_lq)
                if full:
                    pending = jnp.logical_and(valid,
                                              jnp.logical_not(placed))
                for _ in range(cfg.max_grows + 1):
                    if not full:
                        break
                    vlog("Hash table full at ", meta.rows,
                         " buckets; doubling")
                    rows_before = meta.rows
                    with timer.stage("grow"), tracer.span(
                            "hash_grow", rows_before=rows_before):
                        bstate, meta = ctable.tile_grow_build(bstate,
                                                              meta)
                        stats.grows += 1
                        reg.counter("hash_grows").inc()
                        reg.event("hash_grow", rows_before=rows_before,
                                  rows_after=meta.rows)
                        bstate, full, placed = \
                            ctable.tile_insert_observations(
                                bstate, meta, chi, clo, q, pending)
                        full = bool(full)
                        pending = jnp.logical_and(
                            pending, jnp.logical_not(placed))
                else:
                    if full:
                        raise RuntimeError("Hash is full")
            if (ck is not None and cfg.checkpoint_every > 0
                    and stats.batches % cfg.checkpoint_every == 0):
                # atomic snapshot: table planes + batch cursor. The
                # D2H here is the sync point --checkpoint-every
                # amortizes; a kill at ANY instant leaves either the
                # old snapshot or the new one, never a torn file.
                with timer.stage("checkpoint"), tracer.span(
                        "checkpoint", batch=stats.batches):
                    ck.save(bstate, meta, cfg, stats.batches, stats,
                            paths)
                reg.counter("checkpoint_writes_total").inc()
                reg.event("checkpoint", stage="create_database",
                          cursor=stats.batches)
    with timer.stage("seal"), tracer.span("seal"):
        # ONE dispatch: dup check + finalize + stats fused (separate
        # calls each walk the full build planes; measured seconds per
        # pass at production table sizes)
        if sk is not None:
            # single-observation entries pre-seal = the sketch's
            # false passes (ops/sketch.singleton_entries)
            stats.prefilter_false_pass = int(
                sketch_mod.singleton_entries(bstate))
            reg.counter("prefilter_false_pass_total").inc(
                stats.prefilter_false_pass)
        state, dup, occ, d_hq, t_hq = ctable.tile_seal(bstate, meta)
        occ = int(occ)
        if sk is not None:
            # full-table Poisson stats: each dropped hq singleton
            # would have been one distinct hq mer of count 1
            stats.poisson_distinct_hq = (int(d_hq)
                                         + stats.prefilter_dropped_hq)
            stats.poisson_total_hq = (int(t_hq)
                                      + stats.prefilter_dropped_hq)
        if bool(dup):  # pragma: no cover
            raise RuntimeError(
                "internal error: duplicate tag pair in a bucket (torn "
                "tag write) — please report")
    timer.report(stats.bases)
    stats.distinct = occ
    if reg.enabled:
        reg.counter("reads").inc(stats.reads)
        reg.counter("bases").inc(stats.bases)
        reg.counter("batches").inc(stats.batches)
        reg.counter("distinct_mers").inc(stats.distinct)
        slots = meta.rows * ctable.TSLOTS
        reg.gauge("hash_buckets").set(meta.rows)
        reg.gauge("hash_slots").set(slots)
        reg.gauge("hash_fill").set(round(stats.distinct / slots, 6))
        reg.set_timer("stage1", timer.as_dict(stats.bases))
    vlog("Counted ", stats.reads, " reads, ", stats.bases, " bases, ",
         stats.distinct, " distinct mers")
    return state, meta, stats


def _default_batches(paths, cfg: BuildConfig, reg, tracer,
                     quiet: bool = False):
    """The disk -> decode -> bit-pack producer BOTH build paths (and
    the quorum driver's shared replay cache) consume: host
    decode/encode/bit-packing overlaps device rounds (double
    buffering, the PP row of SURVEY §2.4). H2D stays on the MAIN
    thread in the packed wire format (io/packing.py, 0.5 B/base):
    device_put from the prefetch thread measured slower (tunnel
    client degrades under concurrent access; PERF_NOTES.md r4).

    `quiet` marks a REPEAT pass of a multi-pass build (ISSUE 14): the
    bad-read policy degrades to a silent skip (identical batching —
    quarantine also skips the record — without double-counting
    bad_reads_total or rewriting the quarantine file) and no meta is
    re-stamped."""
    def _pack(it):
        for b in it:
            pk = packing.pack_reads(b.codes, b.quals, b.lengths,
                                    thresholds=(cfg.qual_thresh,))
            pk.to_wire()  # warm the fused H2D buffer off-thread
            yield b, pk
    import jax as _jax
    if _jax.process_count() > 1:
        from ..parallel import fleet as _fleet
        if _fleet.active() is None:
            # per-host runs of this CLI would write racing PARTIAL
            # tables / race on one output path. Multi-host stage 1 =
            # the fleet tier (parallel/fleet bring-up + the
            # partition-binned build: every host streams the full
            # input and runs only its owned passes) or the sharded
            # pipeline fed by parallel.multihost.
            raise RuntimeError(
                "multi-host build requires the fleet tier "
                "(--coordinator/--num-processes/--process-id, "
                "parallel.fleet) or the sharded pipeline over a "
                "global mesh fed by parallel.multihost, not bare "
                "per-host runs of this single-controller CLI")
    policy = None
    if cfg.on_bad_read != "abort":
        if quiet:
            policy = fastq.BadReadPolicy("skip", None, None)
        else:
            # read_batches owns the policy's lifecycle: its generator
            # finally closes the quarantine stream however this build
            # ends
            policy = fastq.BadReadPolicy(
                cfg.on_bad_read, cfg.quarantine_path,
                reg if reg.enabled else None)
            reg.counter("bad_reads_total")  # lands even at 0
            reg.set_meta(on_bad_read=cfg.on_bad_read)
    src = fastq.read_batches(paths, cfg.batch_size,
                             threads=cfg.threads, policy=policy)
    return prefetch(_pack(src),
                    metrics=reg if reg.enabled and not quiet else None,
                    tracer=tracer if not quiet else NULL_TRACER)


def _build_database_sharded(paths, cfg: BuildConfig, batches, reg,
                            tracer):
    """Stage 1 over a local device mesh (`--devices N`): the
    tile-sharded build of parallel/tile_sharded promoted to the
    production path — packed-wire input (the same producer as the
    single-chip loop), routed owner-bucketed inserts, sharded
    grow/finalize, per-shard checkpoints under one manifest
    (io/checkpoint.Stage1ShardedCheckpoint), and the per-shard
    occupancy/insert telemetry. Returns (TileState row-sharded,
    TileShardedMeta, stats) — same contract as build_database, with
    the sharded meta standing in for TileMeta (duck-typed)."""
    from jax.sharding import NamedSharding, PartitionSpec

    from ..parallel import tile_sharded as ts

    S = cfg.devices
    mesh = ts.make_mesh(S)
    owner_bits = int(S).bit_length() - 1
    rb = ctable.tile_rb_for(cfg.initial_size, cfg.k, cfg.bits)
    # global geometry: at least a few rows per shard, at most the
    # per-chip cap on every shard (growth lifts it from there)
    rb = min(max(rb, owner_bits + 4), 24 + owner_bits)
    meta = ts.TileShardedMeta(k=cfg.k, bits=cfg.bits, rb_log2=rb,
                              n_shards=S)
    bstate = ts.make_build_state(meta, mesh)
    stats = BuildStats()
    reg.set_meta(stage="create_database", k=cfg.k, bits=cfg.bits,
                 qual_thresh=cfg.qual_thresh, batch_size=cfg.batch_size,
                 devices=S, s1_aggregate=ctable.s1_aggregate_default())

    ck = (ckpt_mod.Stage1ShardedCheckpoint(cfg.checkpoint_dir)
          if cfg.checkpoint_dir else None)
    skip_batches = 0
    if ck is not None and cfg.resume:
        snap = ck.load()
        if snap is not None:
            snap.check_config(cfg.k, cfg.bits, cfg.qual_thresh,
                              cfg.batch_size, paths, S)
            meta = ts.TileShardedMeta(k=cfg.k, bits=cfg.bits,
                                      rb_log2=snap.rb_log2, n_shards=S)
            sh = NamedSharding(mesh, PartitionSpec(ts.AXIS))
            bstate = ctable.TBuildState(
                jax.device_put(snap.tag, sh),
                jax.device_put(snap.hq, sh),
                jax.device_put(snap.lq, sh))
            h = snap.header
            stats.reads, stats.bases = h["reads"], h["bases"]
            stats.batches, stats.grows = h["batches"], h["grows"]
            skip_batches = snap.cursor
            reg.counter("resume_skipped_reads")  # lands even at 0
            reg.set_meta(resumed=True, resumed_from_batch=skip_batches)
            reg.event("resume", stage="create_database",
                      cursor=skip_batches, devices=S)
            vlog("Resuming sharded stage 1 from checkpoint: ",
                 skip_batches, " batches (", stats.reads,
                 " reads) already counted on ", S, " shards")
    if ck is not None:
        reg.counter("checkpoint_writes_total")
        reg.set_meta(checkpoint_every=cfg.checkpoint_every)

    if batches is None:
        batches = _default_batches(paths, cfg, reg, tracer)
    timer = StageTimer()
    steps: dict = {}
    shard_inserts = np.zeros((S,), np.int64)
    # pack/exchange overlap (ISSUE 9, the ROADMAP carried-over gap):
    # the first insert pass of batch N dispatches WITHOUT syncing, so
    # the host packs + H2Ds batch N+1's wire while N's all_to_all
    # exchange runs on the devices; N resolves (flag sync + any
    # grow/overflow retries, which are rare and stay synchronous)
    # right before N+1 dispatches — so the exact-once retry contract
    # and the checkpoint cursor semantics are untouched.
    overlap = s1_overlap_default()

    def _get_step(b_rows, length, thresholds):
        key = (meta.rb_log2, b_rows, length, thresholds)
        step = steps.get(key)
        if step is None:
            step = ts.build_step_wire(mesh, meta, cfg.qual_thresh,
                                      b_rows, length, thresholds)
            steps[key] = step
        return step

    def _dispatch(batch, pk, wire, step_i):
        """Async first insert pass: returns the in-flight job. The
        new bstate HANDLE is current immediately (XLA chains the next
        dispatch on it); only the flag sync waits."""
        nonlocal bstate
        pending = jnp.ones((pk.n_reads * pk.length,), bool)
        t0 = time.perf_counter()
        with tracer.step("stage1_insert", step_i, reads=batch.n):
            bstate, full, over, placed, n_ins = _get_step(
                pk.n_reads, pk.length, pk.thresholds)(
                    bstate, wire, pending)
        t1 = time.perf_counter()
        return (step_i, batch, pk, wire, pending, t0, t1, full, over,
                placed, n_ins)

    def _resolve(job):
        """Sync the in-flight pass's flags, run any grow/overflow
        retries to completion, then account the batch (stats,
        heartbeat, checkpoint). Called in dispatch order."""
        nonlocal bstate, meta, shard_inserts
        (step_i, batch, pk, wire, pending, t0, t1, full, over,
         placed, n_ins) = job
        with tracer.span("stage1_batch", step=step_i, reads=batch.n):
            tw = time.perf_counter()
            full_b, over_b = bool(full), bool(over)
            # the host-observed wait is the blocked time HERE — with
            # the overlap on, the exchange that used to serialize
            # behind the pack now hides under it
            observe_dispatch_wait(reg, "insert", t0, t1,
                                  t1 + (time.perf_counter() - tw),
                                  timer=timer)
            shard_inserts += np.asarray(n_ins, np.int64)
            grows = 0
            # overflow-only retries always make progress; the budget
            # per grow LEVEL only guards a wedged loop (see
            # tile_sharded.build_database_tile_sharded)
            level_budget = 2 * S + 8
            passes = 0
            while full_b or over_b:
                pending = jnp.logical_and(pending,
                                          jnp.logical_not(placed))
                if full_b:
                    if grows >= cfg.max_grows:
                        raise RuntimeError("Hash is full")
                    grows += 1
                    passes = 0
                    rows_before = meta.rows
                    vlog("Sharded hash full at ", rows_before,
                         " buckets; doubling")
                    with timer.stage("grow"), tracer.span(
                            "hash_grow", rows_before=rows_before):
                        bstate, meta = ts.grow(bstate, meta, mesh)
                        stats.grows += 1
                        reg.counter("hash_grows").inc()
                        reg.counter("shard_grows").inc()
                        reg.event("hash_grow",
                                  rows_before=rows_before,
                                  rows_after=meta.rows)
                    steps.clear()  # old geometry's executables
                else:
                    passes += 1
                    reg.counter("shard_overflow_passes").inc()
                    if passes > level_budget:
                        raise RuntimeError("Hash is full")
                t0r = time.perf_counter()
                with tracer.step("stage1_insert", step_i,
                                 reads=batch.n):
                    bstate, full, over, placed, n_ins = _get_step(
                        pk.n_reads, pk.length, pk.thresholds)(
                            bstate, wire, pending)
                    t1r = time.perf_counter()
                    full_b, over_b = bool(full), bool(over)
                    t2r = time.perf_counter()
                observe_dispatch_wait(reg, "insert", t0r, t1r, t2r,
                                      timer=timer)
                shard_inserts += np.asarray(n_ins, np.int64)
        # the batch is fully inserted: account it and maybe checkpoint
        # (cursor = RESOLVED batches, so a kill mid-pipeline resumes
        # exactly at the last fully-inserted batch)
        stats.batches += 1
        stats.reads += batch.n
        nb = int(batch.lengths.sum())
        stats.bases += nb
        timer.add_units("insert_wait", nb)
        reg.heartbeat(stage="create_database", reads=stats.reads,
                      bases=stats.bases, batches=stats.batches,
                      devices=S)
        reg.counter("shard_batches").inc()
        reg.counter("shard_reads").inc(batch.n)
        if (ck is not None and cfg.checkpoint_every > 0
                and stats.batches % cfg.checkpoint_every == 0):
            # per-shard snapshots under one manifest; the manifest
            # swap is the commit point (kill-safe at any instant)
            with timer.stage("checkpoint"), tracer.span(
                    "checkpoint", batch=stats.batches):
                ck.save(bstate, meta, cfg, stats.batches, stats,
                        paths)
            reg.counter("checkpoint_writes_total").inc()
            reg.event("checkpoint", stage="create_database",
                      cursor=stats.batches)

    inflight = None
    # global batch index: resumes from the checkpoint cursor so fault
    # `batch=` matching and trace step ids stay aligned with the
    # pre-kill run (and with the single-device loop's step_i)
    step_i = skip_batches
    with trace(cfg.profile):
        for batch, pk in batches:
            if skip_batches > 0:
                skip_batches -= 1
                reg.counter("resume_skipped_reads").inc(batch.n)
                continue
            t_h0 = time.perf_counter()
            wire = jnp.asarray(pk.to_wire())  # H2D under N's exchange
            if inflight is not None:
                if reg.enabled:
                    reg.histogram("s1_pack_overlap_us").observe(
                        round((time.perf_counter() - t_h0) * 1e6))
                _resolve(inflight)
                inflight = None
            faults.inject("stage1.insert", batch=step_i)
            resources.watchdog_beat("stage1.insert", step_i)
            inflight = _dispatch(batch, pk, wire, step_i)
            step_i += 1
            if not overlap:
                _resolve(inflight)
                inflight = None
        if inflight is not None:
            _resolve(inflight)
    with timer.stage("seal"), tracer.span("seal"):
        state = ts.finalize(bstate, meta, mesh)
        per = ts.shard_occupancy(state, meta)
    timer.report(stats.bases)
    stats.distinct = sum(per)
    if reg.enabled:
        reg.counter("reads").inc(stats.reads)
        reg.counter("bases").inc(stats.bases)
        reg.counter("batches").inc(stats.batches)
        slots = meta.rows * ctable.TSLOTS
        reg.gauge("hash_buckets").set(meta.rows)
        reg.gauge("hash_slots").set(slots)
        reg.gauge("hash_fill").set(round(stats.distinct / slots, 6))
        ts.record_shard_metrics(reg, state, meta, shard_inserts,
                                per=per)
        reg.set_timer("stage1", timer.as_dict(stats.bases))
    vlog("Counted ", stats.reads, " reads, ", stats.bases, " bases, ",
         stats.distinct, " distinct mers over ", S, " shards")
    return state, meta, stats


# ---------------------------------------------------------------------------
# Memory-frugal counting (ISSUE 14): two-pass prefilter + partitioned
# multi-pass builds
# ---------------------------------------------------------------------------


class _PartitionGrew(Exception):
    """A partition pass overflowed its table. Growing in place would
    change the partition predicate mid-stream (the partition bits are
    the remainder bits AT the planned local geometry), so the whole
    partitioned attempt restarts at the next geometry instead — rare
    with an honest -s, and always correct."""

    def __init__(self, rb_local: int):
        super().__init__(f"partition pass needs rb_local={rb_local}")
        self.rb_local = rb_local


def _resolve_batches_factory(paths, cfg: BuildConfig, batches,
                             batches_factory, reg, tracer):
    """Multi-pass input plumbing: a zero-arg callable returning a
    fresh (ReadBatch, PackedReads) iterable per pass. The FIRST call
    gets the full-fat producer (telemetry, bad-read policy side
    effects); repeat passes re-parse quietly (deterministic batching,
    no double counting). A one-shot `batches` iterable cannot be
    replayed — callers that own one (the quorum driver) pass a
    factory instead."""
    if batches_factory is not None:
        return batches_factory
    if batches is not None:
        raise ValueError(
            "multi-pass builds (--prefilter=two-pass / --partitions) "
            "re-stream the input once per pass: pass batches_factory "
            "(a fresh iterable per call), not a one-shot batches "
            "iterable")
    calls = {"n": 0}

    def factory():
        first = calls["n"] == 0
        calls["n"] += 1
        return _default_batches(paths, cfg,
                                reg if first else NULL_METRICS,
                                tracer if first else NULL_TRACER,
                                quiet=not first)
    return factory


def _run_sketch_pass(batches, cfg: BuildConfig, smeta, reg, tracer,
                     timer, stats: BuildStats, count_stats: bool):
    """Pass 1 of the two-pass prefilter: stream every batch into the
    counting sketch (ops/sketch), one fused dispatch per batch.
    Returns the finished SketchState. Counts reads/bases into `stats`
    only when this is the run's first look at the input."""
    sk = sketch_mod.make_sketch(smeta)
    t_pass = time.perf_counter()
    n_batches = 0
    for batch, pk in batches:
        step_i = n_batches
        n_batches += 1
        reg.heartbeat(stage="create_database", partition="sketch",
                      reads=stats.reads, batches=step_i)
        with tracer.span("sketch_batch", step=step_i, reads=batch.n):
            t0 = time.perf_counter()
            with tracer.step("stage1_sketch", step_i, reads=batch.n):
                sk, n_obs = sketch_mod.sketch_update_packed(
                    sk, smeta, cfg.k, pk, cfg.qual_thresh)
                t1 = time.perf_counter()
                n_obs = int(n_obs)
                t2 = time.perf_counter()
            observe_dispatch_wait(reg, "sketch", t0, t1, t2,
                                  timer=timer)
        if count_stats:
            stats.reads += batch.n
            stats.bases += int(batch.lengths.sum())
            stats.batches += 1
    reg.counter("partition_passes_total").inc()
    reg.event("partition_pass", partition="sketch",
              n_partitions=cfg.partitions, batches=n_batches,
              seconds=round(time.perf_counter() - t_pass, 3))
    return sk


def _run_insert_pass(batches, cfg: BuildConfig, lmeta, sk, smeta,
                     part, n_parts: int, reg, tracer, timer,
                     stats: BuildStats, count_stats: bool,
                     allow_grow: bool, step0: int = 0):
    """One gated/partition-filtered insert pass over the input:
    builds a fresh tile table at `lmeta` and returns
    (bstate, lmeta, n_batches). With `allow_grow` (the non-partitioned
    two-pass build) a full table grows in place like the plain loop;
    without it (partition passes) a full table raises _PartitionGrew —
    the partition predicate is pinned to the planned geometry.
    Dropped-observation counters accumulate into `stats` when the
    prefilter is active."""
    bstate = ctable.make_tile_build(lmeta)
    n_batches = 0
    # NOTE: the gated insert DONATES the sketch buffer (inline mode
    # rewrites it in place); the returned handle must replace it even
    # in read-only two-pass mode, and flows back to the caller for
    # the next pass.
    for batch, pk in batches:
        step_i = step0 + n_batches
        faults.inject("stage1.insert", batch=step_i)
        resources.watchdog_beat("stage1.insert", step_i)
        n_batches += 1
        if count_stats:
            stats.batches += 1
            stats.reads += batch.n
            nb = int(batch.lengths.sum())
            stats.bases += nb
            timer.add_units("insert_wait", nb)
        reg.heartbeat(stage="create_database", reads=stats.reads,
                      bases=stats.bases, batches=stats.batches,
                      partition=part if part is not None else 0)
        with tracer.span("stage1_batch", step=step_i, reads=batch.n,
                         partition=part if part is not None else 0):
            t0 = time.perf_counter()
            with tracer.step("stage1_insert", step_i, reads=batch.n):
                if sk is not None:
                    (bstate, sk, full, (chi, clo, q, valid, placed),
                     d_hq, d_lq) = \
                        sketch_mod.tile_insert_reads_packed_gated(
                            bstate, lmeta, sk, smeta, pk,
                            cfg.qual_thresh, "two-pass", part=part,
                            n_parts=n_parts)
                else:
                    bstate, full, (chi, clo, q, valid, placed) = \
                        ctable.tile_insert_reads_packed(
                            bstate, lmeta, pk, cfg.qual_thresh,
                            part=part, n_parts=n_parts)
                    d_hq = d_lq = 0
                t1 = time.perf_counter()
                full = bool(full)
                t2 = time.perf_counter()
            observe_dispatch_wait(reg, "insert", t0, t1, t2,
                                  timer=timer)
            if d_hq or d_lq:
                stats.prefilter_dropped += d_hq + d_lq
                stats.prefilter_dropped_hq += d_hq
                reg.counter("prefilter_dropped_total").inc(d_hq + d_lq)
            if full:
                pending = jnp.logical_and(valid,
                                          jnp.logical_not(placed))
            for _ in range(cfg.max_grows + 1):
                if not full:
                    break
                if not allow_grow:
                    raise _PartitionGrew(lmeta.rb_log2 + 1)
                rows_before = lmeta.rows
                vlog("Hash table full at ", rows_before,
                     " buckets; doubling")
                with timer.stage("grow"), tracer.span(
                        "hash_grow", rows_before=rows_before):
                    bstate, lmeta = ctable.tile_grow_build(bstate,
                                                           lmeta)
                    stats.grows += 1
                    reg.counter("hash_grows").inc()
                    reg.event("hash_grow", rows_before=rows_before,
                              rows_after=lmeta.rows)
                    bstate, full, placed = \
                        ctable.tile_insert_observations(
                            bstate, lmeta, chi, clo, q, pending)
                    full = bool(full)
                    pending = jnp.logical_and(
                        pending, jnp.logical_not(placed))
            else:
                if full:
                    raise RuntimeError("Hash is full")
    return bstate, lmeta, n_batches, sk


def _build_two_pass(paths, cfg: BuildConfig, batches, batches_factory,
                    reg, tracer):
    """The two-pass prefiltered build at full geometry (partitions ==
    1, devices == 1): pass 1 streams the input into the sketch, pass
    2 inserts only mers the sketch saw >= 2 times. Same return
    contract as build_database; the caller's export attaches the
    prefilter declaration + corrected Poisson stats
    (BuildStats.db_extra_header)."""
    factory = _resolve_batches_factory(paths, cfg, batches,
                                       batches_factory, reg, tracer)
    smeta = sketch_mod.SketchMeta(
        sketch_mod.cells_log2_for(cfg.initial_size))
    rb = ctable.tile_rb_for(cfg.initial_size, cfg.k, cfg.bits)
    meta = ctable.TileMeta(k=cfg.k, bits=cfg.bits, rb_log2=rb)
    stats = BuildStats(prefilter_mode="two-pass",
                       sketch_cells_log2=smeta.cells_log2)
    reg.set_meta(stage="create_database", k=cfg.k, bits=cfg.bits,
                 qual_thresh=cfg.qual_thresh,
                 batch_size=cfg.batch_size, prefilter="two-pass",
                 sketch_cells_log2=smeta.cells_log2,
                 s1_aggregate=ctable.s1_aggregate_default())
    reg.counter("partition_passes_total")
    reg.counter("prefilter_dropped_total")
    reg.counter("prefilter_false_pass_total")
    timer = StageTimer()
    sk_ck = (ckpt_mod.SketchCheckpoint(cfg.checkpoint_dir)
             if cfg.checkpoint_dir else None)
    sk_identity = {"k": cfg.k, "qual_thresh": cfg.qual_thresh,
                   "batch_size": cfg.batch_size, "paths": list(paths),
                   "cells_log2": smeta.cells_log2}
    with trace(cfg.profile):
        sk = None
        if sk_ck is not None and cfg.resume:
            cells = sk_ck.load(sk_identity)
            if cells is not None:
                sk = sketch_mod.SketchState(jnp.asarray(cells))
                reg.event("resume", stage="create_database",
                          sketch="loaded")
                vlog("Resuming two-pass prefilter: sketch restored "
                     "from checkpoint (skipping pass 1)")
        if sk is None:
            with timer.stage("sketch_pass"):
                sk = _run_sketch_pass(factory(), cfg, smeta, reg,
                                      tracer, timer, stats,
                                      count_stats=True)
            if sk_ck is not None:
                sk_ck.save(np.asarray(sk.cells), sk_identity)
        count_stats = stats.batches == 0  # resumed past the sketch?
        t_pass = time.perf_counter()
        bstate, meta, n_b, sk = _run_insert_pass(
            factory(), cfg, meta, sk, smeta, None, 1, reg, tracer,
            timer, stats, count_stats=count_stats, allow_grow=True)
        reg.counter("partition_passes_total").inc()
        reg.event("partition_pass", partition=0, n_partitions=1,
                  batches=n_b,
                  seconds=round(time.perf_counter() - t_pass, 3))
    with timer.stage("seal"), tracer.span("seal"):
        stats.prefilter_false_pass = int(
            sketch_mod.singleton_entries(bstate))
        reg.counter("prefilter_false_pass_total").inc(
            stats.prefilter_false_pass)
        state, dup, occ, d_hq, t_hq = ctable.tile_seal(bstate, meta)
        occ = int(occ)
        stats.poisson_distinct_hq = (int(d_hq)
                                     + stats.prefilter_dropped_hq)
        stats.poisson_total_hq = int(t_hq) + stats.prefilter_dropped_hq
        if bool(dup):  # pragma: no cover
            raise RuntimeError(
                "internal error: duplicate tag pair in a bucket (torn "
                "tag write) — please report")
    timer.report(stats.bases)
    stats.distinct = occ
    if reg.enabled:
        reg.counter("reads").inc(stats.reads)
        reg.counter("bases").inc(stats.bases)
        reg.counter("batches").inc(stats.batches)
        reg.counter("distinct_mers").inc(stats.distinct)
        slots = meta.rows * ctable.TSLOTS
        reg.gauge("hash_buckets").set(meta.rows)
        reg.gauge("hash_slots").set(slots)
        reg.gauge("hash_fill").set(round(stats.distinct / slots, 6))
        reg.set_timer("stage1", timer.as_dict(stats.bases))
    if sk_ck is not None:
        sk_ck.clear()
    vlog("Counted ", stats.reads, " reads, ", stats.bases, " bases, ",
         stats.distinct, " distinct mers (two-pass prefilter dropped ",
         stats.prefilter_dropped, " singleton observations)")
    return state, meta, stats


def _partition_identity(cfg: BuildConfig, paths, rb_local: int,
                        cells_log2: int) -> dict:
    """What a partition cursor must match to be resumable: the exact
    run shape INCLUDING the local geometry (a geometry restart makes
    prior shard files stale) and the sketch size."""
    return {"k": cfg.k, "bits": cfg.bits,
            "qual_thresh": cfg.qual_thresh,
            "batch_size": cfg.batch_size, "paths": list(paths),
            "partitions": cfg.partitions, "devices": cfg.devices,
            "db_version": cfg.db_version, "prefilter": cfg.prefilter,
            "rb_local": rb_local, "cells_log2": cells_log2}


def _global_export_meta(cfg: BuildConfig, rb_global: int):
    """The GLOBAL-geometry meta the per-partition shard files are
    written under: a plain TileMeta inside the single-chip cap, the
    duck-typed sharded meta past it (exactly how rb_log2 > 24
    manifests load — io/db_format._read_db_manifest)."""
    if rb_global <= 24:
        return ctable.TileMeta(k=cfg.k, bits=cfg.bits,
                               rb_log2=rb_global)
    from ..parallel.tile_sharded import TileShardedMeta
    return TileShardedMeta(k=cfg.k, bits=cfg.bits, rb_log2=rb_global,
                           n_shards=cfg.partitions)


def _run_partition_pass_sharded(batches, cfg: BuildConfig, rb_local,
                                part, n_parts, reg, tracer, timer,
                                stats, count_stats, step0):
    """One partition pass over the --devices N mesh: the tile-sharded
    build at the pass-local geometry with the partition filter fused
    into the step (tile_sharded.build_step_wire part=), then a gather
    of the (1/P-sized) finished plane for the departition transform.
    A full table raises _PartitionGrew like the single-chip pass."""
    from jax.sharding import NamedSharding, PartitionSpec  # noqa: F401

    from ..parallel import tile_sharded as ts

    S = cfg.devices
    mesh = ts.make_mesh(S)
    lmeta = ts.TileShardedMeta(k=cfg.k, bits=cfg.bits,
                               rb_log2=rb_local, n_shards=S)
    bstate = ts.make_build_state(lmeta, mesh)
    steps: dict = {}

    def _get_step(b_rows, length, thresholds):
        key = (b_rows, length, thresholds)
        step = steps.get(key)
        if step is None:
            step = ts.build_step_wire(mesh, lmeta, cfg.qual_thresh,
                                      b_rows, length, thresholds,
                                      part=part, n_parts=n_parts)
            steps[key] = step
        return step

    n_batches = 0
    level_budget = 2 * S + 8
    for batch, pk in batches:
        step_i = step0 + n_batches
        faults.inject("stage1.insert", batch=step_i)
        resources.watchdog_beat("stage1.insert", step_i)
        n_batches += 1
        if count_stats:
            stats.batches += 1
            stats.reads += batch.n
            nb = int(batch.lengths.sum())
            stats.bases += nb
            timer.add_units("insert_wait", nb)
        reg.heartbeat(stage="create_database", reads=stats.reads,
                      bases=stats.bases, batches=stats.batches,
                      partition=part, devices=S)
        wire = jnp.asarray(pk.to_wire())
        pending = jnp.ones((pk.n_reads * pk.length,), bool)
        passes = 0
        with tracer.span("stage1_batch", step=step_i, reads=batch.n,
                         partition=part):
            while True:
                t0 = time.perf_counter()
                with tracer.step("stage1_insert", step_i,
                                 reads=batch.n):
                    bstate, full, over, placed, _n_ins = _get_step(
                        pk.n_reads, pk.length, pk.thresholds)(
                            bstate, wire, pending)
                    t1 = time.perf_counter()
                    full_b, over_b = bool(full), bool(over)
                    t2 = time.perf_counter()
                observe_dispatch_wait(reg, "insert", t0, t1, t2,
                                      timer=timer)
                if full_b:
                    raise _PartitionGrew(rb_local + 1)
                if not over_b:
                    break
                passes += 1
                reg.counter("shard_overflow_passes").inc()
                if passes > level_budget:
                    raise RuntimeError("Hash is full")
                pending = jnp.logical_and(pending,
                                          jnp.logical_not(placed))
    with timer.stage("seal"), tracer.span("seal", partition=part):
        state = ts.finalize(bstate, lmeta, mesh)
        gstate, glmeta = ts.gather_table(state, lmeta)
    return gstate, glmeta, n_batches


def _build_database_partitioned(paths, cfg: BuildConfig, output: str,
                                cmdline, handoff, reg, tracer,
                                batches=None, batches_factory=None
                                ) -> BuildStats:
    """The minimizer-partitioned multi-pass build (`--partitions P`,
    ISSUE 14; KMC 2's disk-partitioned counting, arxiv 1407.1507,
    adapted to a hash-addressed table): P sequential passes over the
    input, pass p counting ONLY the mers whose hash remainder's low
    log2(P) bits equal p — at the pass-local geometry those mers fill
    an entire table of rows/P rows that IS, after the departition
    rebase (ctable.tile_departition_rows), the global table's
    contiguous leading-bit row range. Each finished pass streams its
    range straight into a PR 9 shard file (io/db_format.
    write_db_shard_file) and commits a pass-granular cursor
    (Stage1PartitionCursor), so peak table memory drops by ~P, the
    reassembled payload is byte-identical to a single-pass build, and
    a killed run re-runs only its torn partition.

    Why the bin key is the bucket ADDRESS and not the raw minimizer
    KMC bins by: a shard file is a contiguous row range, and only an
    address-derived bin makes a partition a row range (byte-exact
    reassembly) — and the Feistel-mixed address is uniform where raw
    minimizer bins are famously skewed. ops/mer.minimizer_kmers is
    the measurement-grade extractor (bench.py --ab reports the
    balance gap); a future disk-binned super-mer spill would be its
    consumer (ROADMAP item 2 notes)."""
    P = cfg.partitions
    g = P.bit_length() - 1
    # the composition rules live HERE, not just in the CLIs: a
    # library caller must not get an unfiltered table whose header
    # claims a prefilter ran (ISSUE 14 review)
    if cfg.prefilter == "inline":
        raise ValueError(
            "--prefilter=inline does not compose with --partitions "
            "(the online sketch is not pass-stable); use two-pass")
    if cfg.prefilter != "off" and cfg.devices > 1:
        raise ValueError(
            "--prefilter composes with --devices 1 today")
    from ..parallel import fleet as fleet_mod
    flt = fleet_mod.active()
    if flt is not None and P < flt.num_processes:
        raise ValueError(
            f"fleet build needs --partitions >= the process count "
            f"({flt.num_processes}); the CLIs plan this via "
            "fleet.plan_partitions")
    factory = _resolve_batches_factory(paths, cfg, batches,
                                       batches_factory, reg, tracer)
    S = cfg.devices
    owner_bits = S.bit_length() - 1
    timer = StageTimer()
    stats = BuildStats(prefilter_mode=cfg.prefilter)
    reg.set_meta(stage="create_database", k=cfg.k, bits=cfg.bits,
                 qual_thresh=cfg.qual_thresh,
                 batch_size=cfg.batch_size, devices=S, partitions=P,
                 prefilter=cfg.prefilter,
                 s1_aggregate=ctable.s1_aggregate_default())
    reg.counter("partition_passes_total")
    if cfg.prefilter != "off":
        reg.counter("prefilter_dropped_total")
        reg.counter("prefilter_false_pass_total")

    rb_req = ctable.tile_rb_for(cfg.initial_size, cfg.k, cfg.bits)
    rb_local = max(rb_req - g, ctable.min_tile_rb_log2(cfg.k, cfg.bits),
                   4 + owner_bits)
    rb_local = min(rb_local, 24 + owner_bits)
    # on a fleet, hosts share one filesystem in CI (and may on NFS
    # pods): every checkpoint artifact gets a per-host subdirectory
    ckpt_dir = (flt.host_scoped_dir(cfg.checkpoint_dir)
                if flt is not None and cfg.checkpoint_dir
                else cfg.checkpoint_dir)
    cursor = (ckpt_mod.Stage1PartitionCursor(ckpt_dir)
              if ckpt_dir else None)
    sk_ck = (ckpt_mod.SketchCheckpoint(ckpt_dir)
             if ckpt_dir and cfg.prefilter == "two-pass"
             else None)
    smeta = (sketch_mod.SketchMeta(
        sketch_mod.cells_log2_for(cfg.initial_size))
        if cfg.prefilter == "two-pass" else None)
    if smeta is not None:
        stats.prefilter_mode = "two-pass"
        stats.sketch_cells_log2 = smeta.cells_log2
        reg.set_meta(sketch_cells_log2=smeta.cells_log2)
    out_dir = os.path.dirname(os.path.abspath(output)) or "."
    # the sketch is GEOMETRY-INDEPENDENT (a pure function of the
    # observation stream), so it survives partition-geometry restarts
    # and its checkpoint identity carries no rb_local
    sk_holder: dict = {"sk": None}
    sk_identity = {"k": cfg.k, "qual_thresh": cfg.qual_thresh,
                   "batch_size": cfg.batch_size, "paths": list(paths),
                   "cells_log2": (smeta.cells_log2
                                  if smeta is not None else 0)}

    def _attempt(rb_local: int):
        identity = _partition_identity(
            cfg, paths, rb_local,
            smeta.cells_log2 if smeta is not None else 0)
        completed: dict[int, dict] = {}
        if cursor is not None and cfg.resume:
            prior = cursor.load(identity, out_dir)
            if prior:
                completed = {int(r["shard"]): r for r in prior}
                reg.event("resume", stage="create_database",
                          partitions_done=sorted(completed))
                vlog("Resuming partitioned build: partitions ",
                     sorted(completed), " already exported")
                # restore the skipped passes' accounting (the cursor
                # records ride the manifest fields plus the per-pass
                # stats the final header needs)
                for p_done, r in completed.items():
                    stats.distinct += int(r["n_entries"])
                    stats.poisson_distinct_hq += int(
                        r.get("distinct_hq", 0))
                    stats.poisson_total_hq += int(r.get("total_hq", 0))
                    fp = int(r.get("false_pass", 0))
                    dr = int(r.get("dropped", 0))
                    dr_hq = int(r.get("dropped_hq", 0))
                    stats.prefilter_false_pass += fp
                    stats.prefilter_dropped += dr
                    stats.prefilter_dropped_hq += dr_hq
                    if cfg.prefilter != "off":
                        reg.counter("prefilter_dropped_total").inc(dr)
                        reg.counter(
                            "prefilter_false_pass_total").inc(fp)
                    reg.gauge(
                        f'partition_distinct{{partition="{p_done}"}}'
                    ).set(int(r["n_entries"]))
        sk = sk_holder["sk"]
        if smeta is not None and sk is None:
            if sk_ck is not None and cfg.resume:
                cells = sk_ck.load(sk_identity)
                if cells is not None:
                    sk = sketch_mod.SketchState(jnp.asarray(cells))
                    vlog("Resuming two-pass prefilter: sketch "
                         "restored (skipping pass 1)")
            if sk is None:
                with timer.stage("sketch_pass"):
                    sk = _run_sketch_pass(
                        factory(), cfg, smeta, reg, tracer, timer,
                        stats, count_stats=stats.batches == 0)
                if sk_ck is not None:
                    sk_ck.save(np.asarray(sk.cells), sk_identity)
            sk_holder["sk"] = sk
        gmeta = _global_export_meta(cfg, rb_local + g)
        step0 = 0
        for p in range(P):
            if flt is not None and not flt.owns_pass(p):
                # partition-binned fleet decomposition: host h runs
                # only passes p % num_processes == h. A pass's shard
                # file depends only on (input stream, geometry, p), so
                # which host runs it cannot change its bytes — and the
                # owned bins are disjoint, so there is zero cross-host
                # insert traffic (the KMC-2 property).
                continue
            if p in completed:
                continue
            t_pass = time.perf_counter()
            count_stats = stats.batches == 0
            dropped0 = stats.prefilter_dropped
            dropped_hq0 = stats.prefilter_dropped_hq
            if S > 1:
                gstate, lmeta, n_b = _run_partition_pass_sharded(
                    factory(), cfg, rb_local, p, P, reg, tracer,
                    timer, stats, count_stats, step0)
                false_pass = 0
                occ, d_hq, t_hq = (int(x) for x in
                                   ctable.tile_stats(gstate, lmeta))
                local_state = gstate
            else:
                lmeta = ctable.TileMeta(k=cfg.k, bits=cfg.bits,
                                        rb_log2=rb_local)
                bstate, lmeta_after, n_b, sk = _run_insert_pass(
                    factory(), cfg, lmeta, sk, smeta, p, P, reg,
                    tracer, timer, stats, count_stats,
                    allow_grow=False, step0=step0)
                # the gated insert donates the sketch buffer: keep
                # the holder on the LIVE handle so a geometry restart
                # never resurrects a donated-away one
                sk_holder["sk"] = sk
                with timer.stage("seal"), tracer.span("seal",
                                                      partition=p):
                    false_pass = (int(sketch_mod.singleton_entries(
                        bstate)) if sk is not None else 0)
                    local_state, dup, occ, d_hq, t_hq = \
                        ctable.tile_seal(bstate, lmeta_after)
                    occ, d_hq, t_hq = int(occ), int(d_hq), int(t_hq)
                    if bool(dup):  # pragma: no cover
                        raise RuntimeError(
                            "internal error: duplicate tag pair in a "
                            "bucket (torn tag write) — please report")
            step0 += n_b
            with timer.stage("export"), tracer.span("partition_export",
                                                    partition=p):
                dstate, bad = ctable.tile_departition_rows(
                    local_state, lmeta, g, p)
                if bool(bad):  # pragma: no cover - routing invariant
                    raise RuntimeError(
                        "internal error: partition pass counted a mer "
                        "outside its bin — please report")
                rec = db_format.write_db_shard_file(
                    output, dstate.rows, gmeta, p, P, cmdline,
                    db_version=cfg.db_version)
            # the cursor record = the manifest record plus the
            # per-pass stats a RESUMED run must restore (stripped
            # before the final manifest commits)
            completed[p] = {
                **rec, "distinct_hq": d_hq, "total_hq": t_hq,
                "false_pass": false_pass,
                "dropped": stats.prefilter_dropped - dropped0,
                "dropped_hq": stats.prefilter_dropped_hq - dropped_hq0,
            }
            if sk is not None:
                stats.prefilter_false_pass += false_pass
                reg.counter("prefilter_false_pass_total").inc(
                    false_pass)
            stats.distinct += occ
            stats.poisson_distinct_hq += d_hq
            stats.poisson_total_hq += t_hq
            reg.counter("partition_passes_total").inc()
            reg.gauge(f'partition_distinct{{partition="{p}"}}').set(occ)
            reg.event("partition_pass", partition=p, n_partitions=P,
                      batches=n_b, distinct=occ,
                      seconds=round(time.perf_counter() - t_pass, 3))
            if cursor is not None:
                cursor.save(identity,
                            [completed[i] for i in sorted(completed)],
                            out_dir)
            else:
                faults.inject("partition.commit", path=rec["path"])
        return completed, gmeta

    with trace(cfg.profile):
        for _ in range(cfg.max_grows + 1):
            grew = None
            completed = {}
            try:
                completed, gmeta = _attempt(rb_local)
            except _PartitionGrew as e:
                grew = e.rb_local
            if flt is not None:
                # the fleet grow vote: every host posts the local
                # geometry it needs (its current one when it finished
                # clean) and adopts the max, so pass files from
                # different geometries can never meet in one manifest
                agreed = flt.grow_vote(
                    rb_local if grew is None else grew)
                if agreed > rb_local:
                    grew = agreed
            if grew is None:
                break
            vlog("Partition pass overflowed at local rb_log2=",
                 rb_local, "; restarting all passes at ", grew)
            reg.counter("hash_grows").inc()
            reg.event("partition_geometry_grow",
                      rb_local_before=rb_local,
                      rb_local_after=grew)
            stats.grows += 1
            stats.distinct = 0
            stats.poisson_distinct_hq = 0
            stats.poisson_total_hq = 0
            stats.prefilter_dropped = 0
            stats.prefilter_dropped_hq = 0
            stats.prefilter_false_pass = 0
            # the input accounting restarts with the passes: a
            # partial first attempt must not freeze reads/bases
            # at a prefix (count_stats keys off batches == 0)
            stats.reads = 0
            stats.bases = 0
            stats.batches = 0
            rb_local = grew
            if cursor is not None:
                cursor.clear()
        else:
            raise RuntimeError("Hash is full")
    if flt is not None:
        # exchange the per-pass records: every host learns every
        # shard file (the ONE fleet manifest names them all), the
        # ownership plan is verified exact-cover, and the global
        # header stats are recomputed from the records. Posting
        # records also proves each host's shard files are durable
        # before process 0 commits the manifest.
        docs = flt.exchange_json(
            "partition_records",
            {str(p): completed[p] for p in sorted(completed)})
        merged_recs: dict[int, dict] = {}
        for doc in docs:
            for key, r in doc.items():
                p_g = int(key)
                if p_g in merged_recs:
                    raise RuntimeError(
                        f"fleet partition exchange: pass {p_g} "
                        "exported by two hosts — the ownership plan "
                        "diverged; refusing to seal a manifest over "
                        "racing shard files")
                merged_recs[p_g] = r
        missing = [p_g for p_g in range(P) if p_g not in merged_recs]
        if missing:
            raise RuntimeError(
                f"fleet partition exchange: passes {missing} exported "
                "by no host — the ownership plan diverged")
        completed = merged_recs
        stats.distinct = sum(
            int(r["n_entries"]) for r in completed.values())
        stats.poisson_distinct_hq = sum(
            int(r.get("distinct_hq", 0)) for r in completed.values())
        stats.poisson_total_hq = sum(
            int(r.get("total_hq", 0)) for r in completed.values())
        stats.prefilter_false_pass = sum(
            int(r.get("false_pass", 0)) for r in completed.values())
        stats.prefilter_dropped = sum(
            int(r.get("dropped", 0)) for r in completed.values())
        stats.prefilter_dropped_hq = sum(
            int(r.get("dropped_hq", 0)) for r in completed.values())
    # manifest records proper: the cursor's per-pass stat fields
    # stay checkpoint-local
    keep = ("path", "shard", "n_entries", "value_bytes",
            "file_crc32c")
    recs = [{k: completed[p][k] for k in keep} for p in range(P)]
    if smeta is not None:
        # full-table Poisson stats: each dropped hq singleton would
        # have been one distinct hq mer of count 1 (exact — a dropped
        # mer has exactly one observation)
        stats.poisson_distinct_hq += stats.prefilter_dropped_hq
        stats.poisson_total_hq += stats.prefilter_dropped_hq
    # every shard is durable: the manifest is the commit point, and
    # the pass-granular checkpoint artifacts die with it. On a fleet
    # there is ONE manifest — process 0 commits it (the record
    # exchange above already proved every host's shards durable), and
    # the barrier keeps other hosts from racing into stage 2 before
    # the commit lands.
    if flt is None or flt.process_id == 0:
        db_format.write_db_manifest(output, recs, gmeta, P, cmdline,
                                    db_version=cfg.db_version,
                                    extra_header=stats.db_extra_header())
    if flt is not None:
        flt.barrier("stage1_manifest")
    if cursor is not None:
        cursor.clear()
    if sk_ck is not None:
        sk_ck.clear()
    timer.report(stats.bases)
    if reg.enabled:
        reg.counter("reads").inc(stats.reads)
        reg.counter("bases").inc(stats.bases)
        reg.counter("batches").inc(stats.batches)
        reg.counter("distinct_mers").inc(stats.distinct)
        rows_g = (1 << (rb_local + g))
        slots = rows_g * ctable.TSLOTS
        reg.gauge("hash_buckets").set(rows_g)
        reg.gauge("hash_slots").set(slots)
        reg.gauge("hash_fill").set(round(stats.distinct / slots, 6))
        reg.gauge("partition_rows_local").set(1 << rb_local)
        reg.set_timer("stage1", timer.as_dict(stats.bases))
    vlog("Counted ", stats.reads, " reads, ", stats.bases, " bases, ",
         stats.distinct, " distinct mers over ", P,
         " partition passes (peak table rows 1/", P, " of global)")
    return stats


def create_database_main(
    paths: Sequence[str],
    output: str,
    cfg: BuildConfig,
    cmdline: list[str] | None = None,
    ref_format: bool = False,
    handoff: dict | None = None,
    batches=None,
    metrics=None,
    tracer=None,
    batches_factory=None,
) -> BuildStats:
    """With `handoff` (a dict), the built device-resident table is
    stashed as handoff["db"] = (state, meta) so an in-process stage-2
    can skip re-reading and re-uploading it (the tunnel H2D of a
    full-size table costs ~0.1 s/MB — ~50 s for a 0.5 GB table — while
    the reference's equivalent, re-mmapping a page-cached file, is
    free; quorum.in:154-231 runs both stages over the same file).
    Partitioned builds (`cfg.partitions > 1`) stream their export per
    pass and never hold the whole table — no handoff, stage 2 loads
    the manifest (its peak-memory contract is the point)."""
    if ref_format and (cfg.partitions > 1 or cfg.prefilter != "off"):
        raise ValueError(
            "--ref-format supports neither --partitions nor "
            "--prefilter (the reference format carries no manifest "
            "or prefilter declaration)")
    from ..parallel import fleet as fleet_mod
    if fleet_mod.active() is not None and cfg.partitions < 2:
        raise ValueError(
            "a fleet build is partition-binned: it needs "
            "--partitions >= the fleet process count (the CLIs plan "
            "this via fleet.plan_partitions)")
    if cfg.partitions > 1:
        # the minimizer-partitioned multi-pass build (ISSUE 14):
        # exports ARE per-pass (sharded manifest), peak table memory
        # is 1/P, and there is no whole-table handoff by design
        return _build_database_partitioned(
            paths, cfg, output, cmdline, handoff,
            metrics if metrics is not None else NULL_METRICS,
            tracer if tracer is not None else NULL_TRACER,
            batches=batches, batches_factory=batches_factory)
    state, meta, stats = build_database(paths, cfg, batches=batches,
                                        metrics=metrics, tracer=tracer,
                                        batches_factory=batches_factory)
    if handoff is not None:
        # the sharded build hands over the ROW-SHARDED table +
        # TileShardedMeta; stage 2 reshards once per its chosen layout
        handoff["db"] = (state, meta)
    if not ref_format and cfg.db_layout == "sharded":
        # the no-gather export (ISSUE 9): each shard's rows compact on
        # their own device and stream D2H into PREFIX.shard-K-of-S.qdb
        # under a sealed manifest — gather_table is never called, so
        # the single-chip geometry cap and the ~13 min cross-device
        # gather (PR 5 notes) both disappear
        db_format.write_db_sharded(output, state, meta, cmdline,
                                   db_version=cfg.db_version,
                                   extra_header=stats.db_extra_header())
        if cfg.checkpoint_dir:
            cls = (ckpt_mod.Stage1ShardedCheckpoint if cfg.devices > 1
                   else ckpt_mod.Stage1Checkpoint)
            cls(cfg.checkpoint_dir).clear()
        return stats
    write_state, write_meta = state, meta
    if getattr(meta, "n_shards", 1) > 1:
        # the concatenated shard rows ARE the single-chip table
        # (leading-bit sharding), so the on-disk format is unchanged
        # and --devices N and --devices 1 write identical databases
        from ..parallel import tile_sharded as ts
        try:
            write_state, write_meta = ts.gather_table(state, meta)
        except ValueError as e:
            # rb_log2 grew past the single-chip cap: the table content
            # is fine, and the sharded layout holds it without any
            # gather — point the operator at it
            raise RuntimeError(
                f"the sharded table grew past the single-file "
                f"database geometry ({e}); export it with "
                "--db-layout=sharded (per-shard files under a "
                "manifest, no single-chip cap), or reduce the "
                "distinct-mer load (smaller input set, larger -m, or "
                "a higher -q threshold) to fit rb_log2<=24") from None
    if ref_format:
        # the reference's own binary/quorum_db on-disk format
        # (io/quorum_db; mer_database.hpp:115-126)
        from ..io import quorum_db
        from ..ops import ctable

        khi, klo, vals = ctable.tile_iterate(write_state, write_meta)
        quorum_db.write_ref_db(output, khi, klo, vals, write_meta.k,
                               write_meta.bits, cmdline=cmdline)
    else:
        db_format.write_db(output, write_state, write_meta, cmdline,
                           n_entries=stats.distinct,
                           db_version=cfg.db_version,
                           extra_header=stats.db_extra_header())
    if cfg.checkpoint_dir:
        # the finished database IS the durable artifact now; a stale
        # snapshot must not feed a later unrelated --resume
        cls = (ckpt_mod.Stage1ShardedCheckpoint if cfg.devices > 1
               else ckpt_mod.Stage1Checkpoint)
        cls(cfg.checkpoint_dir).clear()
    return stats
