"""Stage 1: build the quality-aware k-mer database from FASTQ reads.

TPU-native rebuild of `quorum_create_database`
(reference: src/create_database.cc). The reference streams reads into N
pthreads that CAS into a shared hash; here each fixed-shape read batch
becomes one device program: rolling canonical k-mers + quality-run
tracking (the low_len/high_len logic of create_database.cc:64-91) are
computed for every position of every read in parallel and counted
straight into the tile-bucket table (ops/ctable: write-then-verify
claim rounds over 64-slot hardware-tile buckets). The table auto-grows
on overflow exactly once per key (placed-mask retry), mirroring the
reference's cooperative resize (src/mer_database.hpp:137-187) with a
host-orchestrated re-scatter. The finished table IS the query layout —
one row gather per lookup in stage 2.
"""

from __future__ import annotations

import dataclasses
import time
from typing import Sequence

import numpy as np
import jax
import jax.numpy as jnp

from ..io import checkpoint as ckpt_mod
from ..io import fastq, db_format, packing
from ..ops import ctable, mer
from ..telemetry import NULL as NULL_METRICS
from ..telemetry import NULL_TRACER, observe_dispatch_wait
from ..utils import faults
from ..utils.pipeline import prefetch
from ..utils.profiling import StageTimer, trace
from ..utils.vlog import vlog


@dataclasses.dataclass(frozen=True)
class BuildConfig:
    k: int = 24
    bits: int = 7
    qual_thresh: int = 38  # ASCII code: base qual char >= this is "high"
    initial_size: int = 200_000_000
    max_reprobe: int = 126  # wide-table compatibility (unused by tile)
    batch_size: int = 8192
    threads: int = 1  # -t: parallel host decode workers (multi-file)
    max_grows: int = 16
    profile: str | None = None  # --profile DIR: jax.profiler trace
    # fault tolerance (ISSUE 4): --checkpoint-dir enables atomic
    # snapshots of the counting table every --checkpoint-every
    # batches; --resume continues from the last valid one
    checkpoint_dir: str | None = None
    checkpoint_every: int = 64  # batches between snapshots
    resume: bool = False
    # --on-bad-read: malformed-record policy (io/fastq.BadReadPolicy)
    on_bad_read: str = "abort"
    quarantine_path: str | None = None
    # --devices (ISSUE 5): 1 = the single-chip path; >1 shards the
    # table by leading row bits over a local device mesh
    # (parallel/tile_sharded) and routes observations owner-bucketed
    devices: int = 1
    # --db-version (ISSUE 8): 5 (default) writes the checksummed
    # export (per-section CRC32C + whole-file trailer digest); 4 the
    # bare round-5 layout. The payload bytes are identical.
    db_version: int = 5
    # --db-layout (ISSUE 9): "single" gathers a sharded table to one
    # chip and writes the one-file format (compatibility default);
    # "sharded" streams each shard D2H independently into
    # PREFIX.shard-K-of-S.qdb v5 files under a sealed manifest — no
    # cross-device gather, no single-chip geometry cap
    db_layout: str = "single"


def s1_overlap_default() -> bool:
    """The sharded build's pack/exchange overlap (ISSUE 9): ON unless
    QUORUM_S1_OVERLAP=0 — the double-buffered dispatch is bit-exact
    (resolution order is dispatch order, retries stay synchronous), so
    the switch exists for A/B measurement, not correctness."""
    from ..utils import levers
    return levers.raw("QUORUM_S1_OVERLAP", "1") != "0"


# canonical home is ops/ctable (so the fused stage-1 dispatch can use
# it); re-exported here for the sharded builds and tests
extract_observations_impl = ctable.extract_observations_impl


extract_observations = jax.jit(extract_observations_impl,
                               static_argnums=(2, 3))


@dataclasses.dataclass
class BuildStats:
    reads: int = 0
    bases: int = 0
    batches: int = 0
    grows: int = 0
    distinct: int = 0


def build_database(
    paths: Sequence[str],
    cfg: BuildConfig,
    batches=None,
    metrics=None,
    tracer=None,
):
    """Run the full stage-1 pipeline. Returns
    (TileState, TileMeta, stats) — the query-ready tile table.

    `batches` (optional) overrides the disk readers: an iterable of
    (ReadBatch, PackedReads) pairs whose hq planes include
    cfg.qual_thresh (the quorum driver uses this to share one
    parse+pack between both stages).

    `metrics` (optional telemetry registry, --metrics on the CLI)
    records reads/bases/batches/distinct-mer counters, hash geometry
    and fill gauges, grow events, per-batch dispatch/wait histograms,
    and the stage timer table. `tracer` (optional span tracer,
    --trace-spans) records per-batch hierarchical spans with the
    device steps StepTraceAnnotation-tagged.

    Raises RuntimeError("Hash is full") only if growth itself fails
    (allocation), preserving the reference's failure contract
    (create_database.cc:87, README.md:46-47).
    """
    reg = metrics if metrics is not None else NULL_METRICS
    tracer = tracer if tracer is not None else NULL_TRACER
    if cfg.devices > 1:
        # --devices N: the tile-sharded multi-device build
        # (parallel/tile_sharded), fed by the SAME packed-wire
        # producer; bit-identical table content by construction
        return _build_database_sharded(paths, cfg, batches, reg, tracer)
    rb = ctable.tile_rb_for(cfg.initial_size, cfg.k, cfg.bits)
    meta = ctable.TileMeta(k=cfg.k, bits=cfg.bits, rb_log2=rb)
    bstate = ctable.make_tile_build(meta)
    stats = BuildStats()
    reg.set_meta(stage="create_database", k=cfg.k, bits=cfg.bits,
                 qual_thresh=cfg.qual_thresh, batch_size=cfg.batch_size,
                 s1_aggregate=ctable.s1_aggregate_default())

    # crash safety (ISSUE 4): resume from the last atomic snapshot —
    # the table planes come back exactly as checkpointed, and the
    # first `cursor` batches of the deterministically re-batched
    # input are skipped instead of re-counted
    ck = (ckpt_mod.Stage1Checkpoint(cfg.checkpoint_dir)
          if cfg.checkpoint_dir else None)
    skip_batches = 0
    if ck is not None and cfg.resume:
        snap = ck.load()
        if snap is not None:
            snap.check_config(cfg.k, cfg.bits, cfg.qual_thresh,
                              cfg.batch_size, paths)
            meta = ctable.TileMeta(k=cfg.k, bits=cfg.bits,
                                   rb_log2=snap.rb_log2)
            bstate = ctable.TBuildState(jnp.asarray(snap.tag),
                                        jnp.asarray(snap.hq),
                                        jnp.asarray(snap.lq))
            h = snap.header
            stats.reads, stats.bases = h["reads"], h["bases"]
            stats.batches, stats.grows = h["batches"], h["grows"]
            skip_batches = snap.cursor
            reg.counter("resume_skipped_reads")  # lands even at 0
            reg.set_meta(resumed=True, resumed_from_batch=skip_batches)
            reg.event("resume", stage="create_database",
                      cursor=skip_batches)
            vlog("Resuming stage 1 from checkpoint: ", skip_batches,
                 " batches (", stats.reads, " reads) already counted")
    if ck is not None:
        reg.counter("checkpoint_writes_total")
        reg.set_meta(checkpoint_every=cfg.checkpoint_every)

    if batches is None:
        batches = _default_batches(paths, cfg, reg, tracer)
    timer = StageTimer()
    with trace(cfg.profile):
        for batch, pk in batches:
            if skip_batches > 0:
                # resume fast-path: already counted before the crash
                # (stats were restored from the snapshot)
                skip_batches -= 1
                reg.counter("resume_skipped_reads").inc(batch.n)
                continue
            step_i = stats.batches
            faults.inject("stage1.insert", batch=step_i)
            stats.batches += 1
            stats.reads += batch.n
            nb = int(batch.lengths.sum())
            stats.bases += nb
            timer.add_units("insert_wait", nb)
            reg.heartbeat(stage="create_database", reads=stats.reads,
                          bases=stats.bases, batches=stats.batches)
            with tracer.span("stage1_batch", step=step_i,
                             reads=batch.n):
                # per-batch device-time attribution: dispatch (handing
                # XLA the fused extract+insert program) split from the
                # wait for the device result (`bool(full)` is the sync
                # point — full comes out of the same executable as the
                # table planes), under a StepTraceAnnotation so the
                # split lines up with the XLA timeline under --profile
                t0 = time.perf_counter()
                with tracer.step("stage1_insert", step_i,
                                 reads=batch.n):
                    # ONE dispatch: extract + insert fused
                    bstate, full, (chi, clo, q, valid, placed) = \
                        ctable.tile_insert_reads_packed(
                            bstate, meta, pk, cfg.qual_thresh)
                    t1 = time.perf_counter()
                    full = bool(full)
                    t2 = time.perf_counter()
                observe_dispatch_wait(reg, "insert", t0, t1, t2,
                                      timer=timer)
                if full:
                    pending = jnp.logical_and(valid,
                                              jnp.logical_not(placed))
                for _ in range(cfg.max_grows + 1):
                    if not full:
                        break
                    vlog("Hash table full at ", meta.rows,
                         " buckets; doubling")
                    rows_before = meta.rows
                    with timer.stage("grow"), tracer.span(
                            "hash_grow", rows_before=rows_before):
                        bstate, meta = ctable.tile_grow_build(bstate,
                                                              meta)
                        stats.grows += 1
                        reg.counter("hash_grows").inc()
                        reg.event("hash_grow", rows_before=rows_before,
                                  rows_after=meta.rows)
                        bstate, full, placed = \
                            ctable.tile_insert_observations(
                                bstate, meta, chi, clo, q, pending)
                        full = bool(full)
                        pending = jnp.logical_and(
                            pending, jnp.logical_not(placed))
                else:
                    if full:
                        raise RuntimeError("Hash is full")
            if (ck is not None and cfg.checkpoint_every > 0
                    and stats.batches % cfg.checkpoint_every == 0):
                # atomic snapshot: table planes + batch cursor. The
                # D2H here is the sync point --checkpoint-every
                # amortizes; a kill at ANY instant leaves either the
                # old snapshot or the new one, never a torn file.
                with timer.stage("checkpoint"), tracer.span(
                        "checkpoint", batch=stats.batches):
                    ck.save(bstate, meta, cfg, stats.batches, stats,
                            paths)
                reg.counter("checkpoint_writes_total").inc()
                reg.event("checkpoint", stage="create_database",
                          cursor=stats.batches)
    with timer.stage("seal"), tracer.span("seal"):
        # ONE dispatch: dup check + finalize + stats fused (separate
        # calls each walk the full build planes; measured seconds per
        # pass at production table sizes)
        state, dup, occ, _d, _t = ctable.tile_seal(bstate, meta)
        occ = int(occ)
        if bool(dup):  # pragma: no cover
            raise RuntimeError(
                "internal error: duplicate tag pair in a bucket (torn "
                "tag write) — please report")
    timer.report(stats.bases)
    stats.distinct = occ
    if reg.enabled:
        reg.counter("reads").inc(stats.reads)
        reg.counter("bases").inc(stats.bases)
        reg.counter("batches").inc(stats.batches)
        reg.counter("distinct_mers").inc(stats.distinct)
        slots = meta.rows * ctable.TSLOTS
        reg.gauge("hash_buckets").set(meta.rows)
        reg.gauge("hash_slots").set(slots)
        reg.gauge("hash_fill").set(round(stats.distinct / slots, 6))
        reg.set_timer("stage1", timer.as_dict(stats.bases))
    vlog("Counted ", stats.reads, " reads, ", stats.bases, " bases, ",
         stats.distinct, " distinct mers")
    return state, meta, stats


def _default_batches(paths, cfg: BuildConfig, reg, tracer):
    """The disk -> decode -> bit-pack producer BOTH build paths (and
    the quorum driver's shared replay cache) consume: host
    decode/encode/bit-packing overlaps device rounds (double
    buffering, the PP row of SURVEY §2.4). H2D stays on the MAIN
    thread in the packed wire format (io/packing.py, 0.5 B/base):
    device_put from the prefetch thread measured slower (tunnel
    client degrades under concurrent access; PERF_NOTES.md r4)."""
    def _pack(it):
        for b in it:
            pk = packing.pack_reads(b.codes, b.quals, b.lengths,
                                    thresholds=(cfg.qual_thresh,))
            pk.to_wire()  # warm the fused H2D buffer off-thread
            yield b, pk
    import jax as _jax
    if _jax.process_count() > 1:
        # per-host runs of this CLI would write racing PARTIAL
        # tables / race on one output path. Multi-host stage 1 =
        # global mesh + the sharded build fed by
        # parallel/multihost.read_batches_multihost.
        raise RuntimeError(
            "multi-host build requires the sharded pipeline over a "
            "global mesh fed by parallel.multihost, not this "
            "single-controller CLI")
    policy = None
    if cfg.on_bad_read != "abort":
        # read_batches owns the policy's lifecycle: its generator
        # finally closes the quarantine stream however this build
        # ends
        policy = fastq.BadReadPolicy(
            cfg.on_bad_read, cfg.quarantine_path,
            reg if reg.enabled else None)
        reg.counter("bad_reads_total")  # lands even at 0
        reg.set_meta(on_bad_read=cfg.on_bad_read)
    src = fastq.read_batches(paths, cfg.batch_size,
                             threads=cfg.threads, policy=policy)
    return prefetch(_pack(src),
                    metrics=reg if reg.enabled else None,
                    tracer=tracer)


def _build_database_sharded(paths, cfg: BuildConfig, batches, reg,
                            tracer):
    """Stage 1 over a local device mesh (`--devices N`): the
    tile-sharded build of parallel/tile_sharded promoted to the
    production path — packed-wire input (the same producer as the
    single-chip loop), routed owner-bucketed inserts, sharded
    grow/finalize, per-shard checkpoints under one manifest
    (io/checkpoint.Stage1ShardedCheckpoint), and the per-shard
    occupancy/insert telemetry. Returns (TileState row-sharded,
    TileShardedMeta, stats) — same contract as build_database, with
    the sharded meta standing in for TileMeta (duck-typed)."""
    from jax.sharding import NamedSharding, PartitionSpec

    from ..parallel import tile_sharded as ts

    S = cfg.devices
    mesh = ts.make_mesh(S)
    owner_bits = int(S).bit_length() - 1
    rb = ctable.tile_rb_for(cfg.initial_size, cfg.k, cfg.bits)
    # global geometry: at least a few rows per shard, at most the
    # per-chip cap on every shard (growth lifts it from there)
    rb = min(max(rb, owner_bits + 4), 24 + owner_bits)
    meta = ts.TileShardedMeta(k=cfg.k, bits=cfg.bits, rb_log2=rb,
                              n_shards=S)
    bstate = ts.make_build_state(meta, mesh)
    stats = BuildStats()
    reg.set_meta(stage="create_database", k=cfg.k, bits=cfg.bits,
                 qual_thresh=cfg.qual_thresh, batch_size=cfg.batch_size,
                 devices=S, s1_aggregate=ctable.s1_aggregate_default())

    ck = (ckpt_mod.Stage1ShardedCheckpoint(cfg.checkpoint_dir)
          if cfg.checkpoint_dir else None)
    skip_batches = 0
    if ck is not None and cfg.resume:
        snap = ck.load()
        if snap is not None:
            snap.check_config(cfg.k, cfg.bits, cfg.qual_thresh,
                              cfg.batch_size, paths, S)
            meta = ts.TileShardedMeta(k=cfg.k, bits=cfg.bits,
                                      rb_log2=snap.rb_log2, n_shards=S)
            sh = NamedSharding(mesh, PartitionSpec(ts.AXIS))
            bstate = ctable.TBuildState(
                jax.device_put(snap.tag, sh),
                jax.device_put(snap.hq, sh),
                jax.device_put(snap.lq, sh))
            h = snap.header
            stats.reads, stats.bases = h["reads"], h["bases"]
            stats.batches, stats.grows = h["batches"], h["grows"]
            skip_batches = snap.cursor
            reg.counter("resume_skipped_reads")  # lands even at 0
            reg.set_meta(resumed=True, resumed_from_batch=skip_batches)
            reg.event("resume", stage="create_database",
                      cursor=skip_batches, devices=S)
            vlog("Resuming sharded stage 1 from checkpoint: ",
                 skip_batches, " batches (", stats.reads,
                 " reads) already counted on ", S, " shards")
    if ck is not None:
        reg.counter("checkpoint_writes_total")
        reg.set_meta(checkpoint_every=cfg.checkpoint_every)

    if batches is None:
        batches = _default_batches(paths, cfg, reg, tracer)
    timer = StageTimer()
    steps: dict = {}
    shard_inserts = np.zeros((S,), np.int64)
    # pack/exchange overlap (ISSUE 9, the ROADMAP carried-over gap):
    # the first insert pass of batch N dispatches WITHOUT syncing, so
    # the host packs + H2Ds batch N+1's wire while N's all_to_all
    # exchange runs on the devices; N resolves (flag sync + any
    # grow/overflow retries, which are rare and stay synchronous)
    # right before N+1 dispatches — so the exact-once retry contract
    # and the checkpoint cursor semantics are untouched.
    overlap = s1_overlap_default()

    def _get_step(b_rows, length, thresholds):
        key = (meta.rb_log2, b_rows, length, thresholds)
        step = steps.get(key)
        if step is None:
            step = ts.build_step_wire(mesh, meta, cfg.qual_thresh,
                                      b_rows, length, thresholds)
            steps[key] = step
        return step

    def _dispatch(batch, pk, wire, step_i):
        """Async first insert pass: returns the in-flight job. The
        new bstate HANDLE is current immediately (XLA chains the next
        dispatch on it); only the flag sync waits."""
        nonlocal bstate
        pending = jnp.ones((pk.n_reads * pk.length,), bool)
        t0 = time.perf_counter()
        with tracer.step("stage1_insert", step_i, reads=batch.n):
            bstate, full, over, placed, n_ins = _get_step(
                pk.n_reads, pk.length, pk.thresholds)(
                    bstate, wire, pending)
        t1 = time.perf_counter()
        return (step_i, batch, pk, wire, pending, t0, t1, full, over,
                placed, n_ins)

    def _resolve(job):
        """Sync the in-flight pass's flags, run any grow/overflow
        retries to completion, then account the batch (stats,
        heartbeat, checkpoint). Called in dispatch order."""
        nonlocal bstate, meta, shard_inserts
        (step_i, batch, pk, wire, pending, t0, t1, full, over,
         placed, n_ins) = job
        with tracer.span("stage1_batch", step=step_i, reads=batch.n):
            tw = time.perf_counter()
            full_b, over_b = bool(full), bool(over)
            # the host-observed wait is the blocked time HERE — with
            # the overlap on, the exchange that used to serialize
            # behind the pack now hides under it
            observe_dispatch_wait(reg, "insert", t0, t1,
                                  t1 + (time.perf_counter() - tw),
                                  timer=timer)
            shard_inserts += np.asarray(n_ins, np.int64)
            grows = 0
            # overflow-only retries always make progress; the budget
            # per grow LEVEL only guards a wedged loop (see
            # tile_sharded.build_database_tile_sharded)
            level_budget = 2 * S + 8
            passes = 0
            while full_b or over_b:
                pending = jnp.logical_and(pending,
                                          jnp.logical_not(placed))
                if full_b:
                    if grows >= cfg.max_grows:
                        raise RuntimeError("Hash is full")
                    grows += 1
                    passes = 0
                    rows_before = meta.rows
                    vlog("Sharded hash full at ", rows_before,
                         " buckets; doubling")
                    with timer.stage("grow"), tracer.span(
                            "hash_grow", rows_before=rows_before):
                        bstate, meta = ts.grow(bstate, meta, mesh)
                        stats.grows += 1
                        reg.counter("hash_grows").inc()
                        reg.counter("shard_grows").inc()
                        reg.event("hash_grow",
                                  rows_before=rows_before,
                                  rows_after=meta.rows)
                    steps.clear()  # old geometry's executables
                else:
                    passes += 1
                    reg.counter("shard_overflow_passes").inc()
                    if passes > level_budget:
                        raise RuntimeError("Hash is full")
                t0r = time.perf_counter()
                with tracer.step("stage1_insert", step_i,
                                 reads=batch.n):
                    bstate, full, over, placed, n_ins = _get_step(
                        pk.n_reads, pk.length, pk.thresholds)(
                            bstate, wire, pending)
                    t1r = time.perf_counter()
                    full_b, over_b = bool(full), bool(over)
                    t2r = time.perf_counter()
                observe_dispatch_wait(reg, "insert", t0r, t1r, t2r,
                                      timer=timer)
                shard_inserts += np.asarray(n_ins, np.int64)
        # the batch is fully inserted: account it and maybe checkpoint
        # (cursor = RESOLVED batches, so a kill mid-pipeline resumes
        # exactly at the last fully-inserted batch)
        stats.batches += 1
        stats.reads += batch.n
        nb = int(batch.lengths.sum())
        stats.bases += nb
        timer.add_units("insert_wait", nb)
        reg.heartbeat(stage="create_database", reads=stats.reads,
                      bases=stats.bases, batches=stats.batches,
                      devices=S)
        reg.counter("shard_batches").inc()
        reg.counter("shard_reads").inc(batch.n)
        if (ck is not None and cfg.checkpoint_every > 0
                and stats.batches % cfg.checkpoint_every == 0):
            # per-shard snapshots under one manifest; the manifest
            # swap is the commit point (kill-safe at any instant)
            with timer.stage("checkpoint"), tracer.span(
                    "checkpoint", batch=stats.batches):
                ck.save(bstate, meta, cfg, stats.batches, stats,
                        paths)
            reg.counter("checkpoint_writes_total").inc()
            reg.event("checkpoint", stage="create_database",
                      cursor=stats.batches)

    inflight = None
    # global batch index: resumes from the checkpoint cursor so fault
    # `batch=` matching and trace step ids stay aligned with the
    # pre-kill run (and with the single-device loop's step_i)
    step_i = skip_batches
    with trace(cfg.profile):
        for batch, pk in batches:
            if skip_batches > 0:
                skip_batches -= 1
                reg.counter("resume_skipped_reads").inc(batch.n)
                continue
            t_h0 = time.perf_counter()
            wire = jnp.asarray(pk.to_wire())  # H2D under N's exchange
            if inflight is not None:
                if reg.enabled:
                    reg.histogram("s1_pack_overlap_us").observe(
                        round((time.perf_counter() - t_h0) * 1e6))
                _resolve(inflight)
                inflight = None
            faults.inject("stage1.insert", batch=step_i)
            inflight = _dispatch(batch, pk, wire, step_i)
            step_i += 1
            if not overlap:
                _resolve(inflight)
                inflight = None
        if inflight is not None:
            _resolve(inflight)
    with timer.stage("seal"), tracer.span("seal"):
        state = ts.finalize(bstate, meta, mesh)
        per = ts.shard_occupancy(state, meta)
    timer.report(stats.bases)
    stats.distinct = sum(per)
    if reg.enabled:
        reg.counter("reads").inc(stats.reads)
        reg.counter("bases").inc(stats.bases)
        reg.counter("batches").inc(stats.batches)
        slots = meta.rows * ctable.TSLOTS
        reg.gauge("hash_buckets").set(meta.rows)
        reg.gauge("hash_slots").set(slots)
        reg.gauge("hash_fill").set(round(stats.distinct / slots, 6))
        ts.record_shard_metrics(reg, state, meta, shard_inserts,
                                per=per)
        reg.set_timer("stage1", timer.as_dict(stats.bases))
    vlog("Counted ", stats.reads, " reads, ", stats.bases, " bases, ",
         stats.distinct, " distinct mers over ", S, " shards")
    return state, meta, stats


def create_database_main(
    paths: Sequence[str],
    output: str,
    cfg: BuildConfig,
    cmdline: list[str] | None = None,
    ref_format: bool = False,
    handoff: dict | None = None,
    batches=None,
    metrics=None,
    tracer=None,
) -> BuildStats:
    """With `handoff` (a dict), the built device-resident table is
    stashed as handoff["db"] = (state, meta) so an in-process stage-2
    can skip re-reading and re-uploading it (the tunnel H2D of a
    full-size table costs ~0.1 s/MB — ~50 s for a 0.5 GB table — while
    the reference's equivalent, re-mmapping a page-cached file, is
    free; quorum.in:154-231 runs both stages over the same file)."""
    state, meta, stats = build_database(paths, cfg, batches=batches,
                                        metrics=metrics, tracer=tracer)
    if handoff is not None:
        # the sharded build hands over the ROW-SHARDED table +
        # TileShardedMeta; stage 2 reshards once per its chosen layout
        handoff["db"] = (state, meta)
    if not ref_format and cfg.db_layout == "sharded":
        # the no-gather export (ISSUE 9): each shard's rows compact on
        # their own device and stream D2H into PREFIX.shard-K-of-S.qdb
        # under a sealed manifest — gather_table is never called, so
        # the single-chip geometry cap and the ~13 min cross-device
        # gather (PR 5 notes) both disappear
        db_format.write_db_sharded(output, state, meta, cmdline,
                                   db_version=cfg.db_version)
        if cfg.checkpoint_dir:
            cls = (ckpt_mod.Stage1ShardedCheckpoint if cfg.devices > 1
                   else ckpt_mod.Stage1Checkpoint)
            cls(cfg.checkpoint_dir).clear()
        return stats
    write_state, write_meta = state, meta
    if getattr(meta, "n_shards", 1) > 1:
        # the concatenated shard rows ARE the single-chip table
        # (leading-bit sharding), so the on-disk format is unchanged
        # and --devices N and --devices 1 write identical databases
        from ..parallel import tile_sharded as ts
        try:
            write_state, write_meta = ts.gather_table(state, meta)
        except ValueError as e:
            # rb_log2 grew past the single-chip cap: the table content
            # is fine, and the sharded layout holds it without any
            # gather — point the operator at it
            raise RuntimeError(
                f"the sharded table grew past the single-file "
                f"database geometry ({e}); export it with "
                "--db-layout=sharded (per-shard files under a "
                "manifest, no single-chip cap), or reduce the "
                "distinct-mer load (smaller input set, larger -m, or "
                "a higher -q threshold) to fit rb_log2<=24") from None
    if ref_format:
        # the reference's own binary/quorum_db on-disk format
        # (io/quorum_db; mer_database.hpp:115-126)
        from ..io import quorum_db
        from ..ops import ctable

        khi, klo, vals = ctable.tile_iterate(write_state, write_meta)
        quorum_db.write_ref_db(output, khi, klo, vals, write_meta.k,
                               write_meta.bits, cmdline=cmdline)
    else:
        db_format.write_db(output, write_state, write_meta, cmdline,
                           n_entries=stats.distinct,
                           db_version=cfg.db_version)
    if cfg.checkpoint_dir:
        # the finished database IS the durable artifact now; a stale
        # snapshot must not feed a later unrelated --resume
        cls = (ckpt_mod.Stage1ShardedCheckpoint if cfg.devices > 1
               else ckpt_mod.Stage1Checkpoint)
        cls(cfg.checkpoint_dir).clear()
    return stats
