"""Stage 1: build the quality-aware k-mer database from FASTQ reads.

TPU-native rebuild of `quorum_create_database`
(reference: src/create_database.cc). The reference streams reads into N
pthreads that CAS into a shared hash; here each fixed-shape read batch
becomes one device program: rolling canonical k-mers + quality-run
tracking (the low_len/high_len logic of create_database.cc:64-91) are
computed for every position of every read in parallel and counted
straight into the tile-bucket table (ops/ctable: write-then-verify
claim rounds over 64-slot hardware-tile buckets). The table auto-grows
on overflow exactly once per key (placed-mask retry), mirroring the
reference's cooperative resize (src/mer_database.hpp:137-187) with a
host-orchestrated re-scatter. The finished table IS the query layout —
one row gather per lookup in stage 2.
"""

from __future__ import annotations

import dataclasses
import functools
import time
from typing import Sequence

import numpy as np
import jax
import jax.numpy as jnp

from ..io import checkpoint as ckpt_mod
from ..io import fastq, db_format, packing
from ..ops import ctable, mer
from ..telemetry import NULL as NULL_METRICS
from ..telemetry import NULL_TRACER, observe_dispatch_wait
from ..utils import faults
from ..utils.pipeline import prefetch
from ..utils.profiling import StageTimer, trace
from ..utils.vlog import vlog


@dataclasses.dataclass(frozen=True)
class BuildConfig:
    k: int = 24
    bits: int = 7
    qual_thresh: int = 38  # ASCII code: base qual char >= this is "high"
    initial_size: int = 200_000_000
    max_reprobe: int = 126  # wide-table compatibility (unused by tile)
    batch_size: int = 8192
    threads: int = 1  # -t: parallel host decode workers (multi-file)
    max_grows: int = 16
    profile: str | None = None  # --profile DIR: jax.profiler trace
    # fault tolerance (ISSUE 4): --checkpoint-dir enables atomic
    # snapshots of the counting table every --checkpoint-every
    # batches; --resume continues from the last valid one
    checkpoint_dir: str | None = None
    checkpoint_every: int = 64  # batches between snapshots
    resume: bool = False
    # --on-bad-read: malformed-record policy (io/fastq.BadReadPolicy)
    on_bad_read: str = "abort"
    quarantine_path: str | None = None


# canonical home is ops/ctable (so the fused stage-1 dispatch can use
# it); re-exported here for the sharded builds and tests
extract_observations_impl = ctable.extract_observations_impl


extract_observations = jax.jit(extract_observations_impl,
                               static_argnums=(2, 3))


@dataclasses.dataclass
class BuildStats:
    reads: int = 0
    bases: int = 0
    batches: int = 0
    grows: int = 0
    distinct: int = 0


def build_database(
    paths: Sequence[str],
    cfg: BuildConfig,
    batches=None,
    metrics=None,
    tracer=None,
):
    """Run the full stage-1 pipeline. Returns
    (TileState, TileMeta, stats) — the query-ready tile table.

    `batches` (optional) overrides the disk readers: an iterable of
    (ReadBatch, PackedReads) pairs whose hq planes include
    cfg.qual_thresh (the quorum driver uses this to share one
    parse+pack between both stages).

    `metrics` (optional telemetry registry, --metrics on the CLI)
    records reads/bases/batches/distinct-mer counters, hash geometry
    and fill gauges, grow events, per-batch dispatch/wait histograms,
    and the stage timer table. `tracer` (optional span tracer,
    --trace-spans) records per-batch hierarchical spans with the
    device steps StepTraceAnnotation-tagged.

    Raises RuntimeError("Hash is full") only if growth itself fails
    (allocation), preserving the reference's failure contract
    (create_database.cc:87, README.md:46-47).
    """
    reg = metrics if metrics is not None else NULL_METRICS
    tracer = tracer if tracer is not None else NULL_TRACER
    rb = ctable.tile_rb_for(cfg.initial_size, cfg.k, cfg.bits)
    meta = ctable.TileMeta(k=cfg.k, bits=cfg.bits, rb_log2=rb)
    bstate = ctable.make_tile_build(meta)
    stats = BuildStats()
    reg.set_meta(stage="create_database", k=cfg.k, bits=cfg.bits,
                 qual_thresh=cfg.qual_thresh, batch_size=cfg.batch_size)

    # crash safety (ISSUE 4): resume from the last atomic snapshot —
    # the table planes come back exactly as checkpointed, and the
    # first `cursor` batches of the deterministically re-batched
    # input are skipped instead of re-counted
    ck = (ckpt_mod.Stage1Checkpoint(cfg.checkpoint_dir)
          if cfg.checkpoint_dir else None)
    skip_batches = 0
    if ck is not None and cfg.resume:
        snap = ck.load()
        if snap is not None:
            snap.check_config(cfg.k, cfg.bits, cfg.qual_thresh,
                              cfg.batch_size, paths)
            meta = ctable.TileMeta(k=cfg.k, bits=cfg.bits,
                                   rb_log2=snap.rb_log2)
            bstate = ctable.TBuildState(jnp.asarray(snap.tag),
                                        jnp.asarray(snap.hq),
                                        jnp.asarray(snap.lq))
            h = snap.header
            stats.reads, stats.bases = h["reads"], h["bases"]
            stats.batches, stats.grows = h["batches"], h["grows"]
            skip_batches = snap.cursor
            reg.counter("resume_skipped_reads")  # lands even at 0
            reg.set_meta(resumed=True, resumed_from_batch=skip_batches)
            reg.event("resume", stage="create_database",
                      cursor=skip_batches)
            vlog("Resuming stage 1 from checkpoint: ", skip_batches,
                 " batches (", stats.reads, " reads) already counted")
    if ck is not None:
        reg.counter("checkpoint_writes_total")
        reg.set_meta(checkpoint_every=cfg.checkpoint_every)

    if batches is None:
        # host decode/encode/bit-packing overlaps device rounds (double
        # buffering, the PP row of SURVEY §2.4). H2D stays on the MAIN
        # thread in the packed wire format (io/packing.py, 0.5 B/base):
        # device_put from the prefetch thread measured slower (tunnel
        # client degrades under concurrent access; PERF_NOTES.md r4).
        def _pack(it):
            for b in it:
                pk = packing.pack_reads(b.codes, b.quals, b.lengths,
                                        thresholds=(cfg.qual_thresh,))
                pk.to_wire()  # warm the fused H2D buffer off-thread
                yield b, pk
        import jax as _jax
        if _jax.process_count() > 1:
            # the single-chip build is host-local state; running it
            # per-host would write racing PARTIAL tables. Multi-host
            # stage 1 = global mesh + parallel/tile_sharded.
            # build_database_tile_sharded fed by
            # parallel/multihost.read_batches_multihost.
            raise RuntimeError(
                "multi-host build requires the sharded pipeline "
                "(parallel.tile_sharded.build_database_tile_sharded + "
                "parallel.multihost), not the single-chip CLI")
        policy = None
        if cfg.on_bad_read != "abort":
            # read_batches owns the policy's lifecycle: its generator
            # finally closes the quarantine stream however this build
            # ends
            policy = fastq.BadReadPolicy(
                cfg.on_bad_read, cfg.quarantine_path,
                reg if reg.enabled else None)
            reg.counter("bad_reads_total")  # lands even at 0
            reg.set_meta(on_bad_read=cfg.on_bad_read)
        src = fastq.read_batches(paths, cfg.batch_size,
                                 threads=cfg.threads, policy=policy)
        batches = prefetch(_pack(src),
                           metrics=reg if reg.enabled else None,
                           tracer=tracer)
    timer = StageTimer()
    with trace(cfg.profile):
        for batch, pk in batches:
            if skip_batches > 0:
                # resume fast-path: already counted before the crash
                # (stats were restored from the snapshot)
                skip_batches -= 1
                reg.counter("resume_skipped_reads").inc(batch.n)
                continue
            step_i = stats.batches
            faults.inject("stage1.insert", batch=step_i)
            stats.batches += 1
            stats.reads += batch.n
            nb = int(batch.lengths.sum())
            stats.bases += nb
            timer.add_units("insert_wait", nb)
            reg.heartbeat(stage="create_database", reads=stats.reads,
                          bases=stats.bases, batches=stats.batches)
            with tracer.span("stage1_batch", step=step_i,
                             reads=batch.n):
                # per-batch device-time attribution: dispatch (handing
                # XLA the fused extract+insert program) split from the
                # wait for the device result (`bool(full)` is the sync
                # point — full comes out of the same executable as the
                # table planes), under a StepTraceAnnotation so the
                # split lines up with the XLA timeline under --profile
                t0 = time.perf_counter()
                with tracer.step("stage1_insert", step_i,
                                 reads=batch.n):
                    # ONE dispatch: extract + insert fused
                    bstate, full, (chi, clo, q, valid, placed) = \
                        ctable.tile_insert_reads_packed(
                            bstate, meta, pk, cfg.qual_thresh)
                    t1 = time.perf_counter()
                    full = bool(full)
                    t2 = time.perf_counter()
                observe_dispatch_wait(reg, "insert", t0, t1, t2,
                                      timer=timer)
                if full:
                    pending = jnp.logical_and(valid,
                                              jnp.logical_not(placed))
                for _ in range(cfg.max_grows + 1):
                    if not full:
                        break
                    vlog("Hash table full at ", meta.rows,
                         " buckets; doubling")
                    rows_before = meta.rows
                    with timer.stage("grow"), tracer.span(
                            "hash_grow", rows_before=rows_before):
                        bstate, meta = ctable.tile_grow_build(bstate,
                                                              meta)
                        stats.grows += 1
                        reg.counter("hash_grows").inc()
                        reg.event("hash_grow", rows_before=rows_before,
                                  rows_after=meta.rows)
                        bstate, full, placed = \
                            ctable.tile_insert_observations(
                                bstate, meta, chi, clo, q, pending)
                        full = bool(full)
                        pending = jnp.logical_and(
                            pending, jnp.logical_not(placed))
                else:
                    if full:
                        raise RuntimeError("Hash is full")
            if (ck is not None and cfg.checkpoint_every > 0
                    and stats.batches % cfg.checkpoint_every == 0):
                # atomic snapshot: table planes + batch cursor. The
                # D2H here is the sync point --checkpoint-every
                # amortizes; a kill at ANY instant leaves either the
                # old snapshot or the new one, never a torn file.
                with timer.stage("checkpoint"), tracer.span(
                        "checkpoint", batch=stats.batches):
                    ck.save(bstate, meta, cfg, stats.batches, stats,
                            paths)
                reg.counter("checkpoint_writes_total").inc()
                reg.event("checkpoint", stage="create_database",
                          cursor=stats.batches)
    with timer.stage("seal"), tracer.span("seal"):
        # ONE dispatch: dup check + finalize + stats fused (separate
        # calls each walk the full build planes; measured seconds per
        # pass at production table sizes)
        state, dup, occ, _d, _t = ctable.tile_seal(bstate, meta)
        occ = int(occ)
        if bool(dup):  # pragma: no cover
            raise RuntimeError(
                "internal error: duplicate tag pair in a bucket (torn "
                "tag write) — please report")
    timer.report(stats.bases)
    stats.distinct = occ
    if reg.enabled:
        reg.counter("reads").inc(stats.reads)
        reg.counter("bases").inc(stats.bases)
        reg.counter("batches").inc(stats.batches)
        reg.counter("distinct_mers").inc(stats.distinct)
        slots = meta.rows * ctable.TSLOTS
        reg.gauge("hash_buckets").set(meta.rows)
        reg.gauge("hash_slots").set(slots)
        reg.gauge("hash_fill").set(round(stats.distinct / slots, 6))
        reg.set_timer("stage1", timer.as_dict(stats.bases))
    vlog("Counted ", stats.reads, " reads, ", stats.bases, " bases, ",
         stats.distinct, " distinct mers")
    return state, meta, stats


def create_database_main(
    paths: Sequence[str],
    output: str,
    cfg: BuildConfig,
    cmdline: list[str] | None = None,
    ref_format: bool = False,
    handoff: dict | None = None,
    batches=None,
    metrics=None,
    tracer=None,
) -> BuildStats:
    """With `handoff` (a dict), the built device-resident table is
    stashed as handoff["db"] = (state, meta) so an in-process stage-2
    can skip re-reading and re-uploading it (the tunnel H2D of a
    full-size table costs ~0.1 s/MB — ~50 s for a 0.5 GB table — while
    the reference's equivalent, re-mmapping a page-cached file, is
    free; quorum.in:154-231 runs both stages over the same file)."""
    state, meta, stats = build_database(paths, cfg, batches=batches,
                                        metrics=metrics, tracer=tracer)
    if handoff is not None:
        handoff["db"] = (state, meta)
    if ref_format:
        # the reference's own binary/quorum_db on-disk format
        # (io/quorum_db; mer_database.hpp:115-126)
        from ..io import quorum_db
        from ..ops import ctable

        khi, klo, vals = ctable.tile_iterate(state, meta)
        quorum_db.write_ref_db(output, khi, klo, vals, meta.k, meta.bits,
                               cmdline=cmdline)
    else:
        db_format.write_db(output, state, meta, cmdline,
                           n_entries=stats.distinct)
    if cfg.checkpoint_dir:
        # the finished database IS the durable artifact now; a stale
        # snapshot must not feed a later unrelated --resume
        ckpt_mod.Stage1Checkpoint(cfg.checkpoint_dir).clear()
    return stats
