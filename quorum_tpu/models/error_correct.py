"""Stage 2 as a program: FASTQ in, corrected FASTA + skip log out.

The orchestration that turns the batched device corrector
(models/corrector.py) into `quorum_error_correct_reads`: database
loading, auto Poisson cutoff, contaminant loading, the streaming
read -> device -> writer pipeline, and the reference's exact output
surfaces (error_correct_reads.cc: do_it :158-171, per-read output
:246-341; formats documented in the reference README.md "Output
format" section).

Output contract (byte-compatible with the reference):
  * `.fa` record: ``>header fwd_log bwd_log\\nseq\\n`` — the two edit
    logs are space-separated ``pos:sub:X-Y`` / ``pos:3_trunc`` /
    ``pos:5_trunc`` entries (err_log.hpp operator<< :111-135); both
    spaces print even when a log is empty.
  * `.log` record per skipped read: ``Skipped <header>: <reason>\\n``.
  * `--no-discard`: skipped reads additionally emit ``>header\\nN\\n``
    so mate pairing survives (error_correct_reads.cc:274-327).
  * `-o PREFIX` writes ``PREFIX.fa``/``PREFIX.log`` (plus ``.gz`` when
    gzipped); without it output goes to stdout and the log to stderr
    (error_correct_reads.cc:133-155 open_file defaults).
"""

from __future__ import annotations

import dataclasses
import gzip as gzip_mod
import sys
import time
from typing import Sequence

import jax

from ..io import checkpoint as ckpt_mod
from ..io import contaminant as contaminant_mod
from ..io import db_format, fastq, packing
from ..ops import ctable
from ..ops.poisson import compute_poisson_cutoff
from ..parallel import fleet
from ..telemetry import observe_dispatch_wait, quality
from ..utils import faults, resources
from ..utils.pipeline import AsyncWriter, ReorderingPool, prefetch
from ..utils.profiling import StageTimer, trace
from ..utils.vlog import vlog
from .corrector import (correct_batch_packed, fetch_finish,
                        finish_batch_host)
from .ec_config import (ECConfig, ERROR_CONTAMINANT, ERROR_HOMOPOLYMER,
                        ERROR_NO_STARTING_MER)

# skip-reason -> counter slug (err_log.hpp semantics: the same reason
# strings the .log channel prints, so metrics counters are exactly
# recoverable from the .log output)
REASON_SLUGS = {
    ERROR_CONTAMINANT: "contaminant",
    ERROR_NO_STARTING_MER: "no_anchor",
    ERROR_HOMOPOLYMER: "homopolymer",
}


def _tally_log(log: str, outcome: dict) -> int:
    """Decode one edit-log string (space-separated ``pos:sub:X-Y`` /
    ``pos:3_trunc`` / ``pos:5_trunc`` entries, err_log.hpp semantics)
    into the outcome tally, bucketing each event's read-cycle
    position for the quality spectra (telemetry/quality.py). Returns
    the substitution count — the same number the old
    ``log.count(":sub:")`` derivation produced, so the counter parity
    the golden tests assert is preserved by construction."""
    ns = 0
    for ent in log.split():
        pos_s, _, kind = ent.partition(":")
        try:
            bucket = quality.position_bucket(int(pos_s))
        except ValueError:  # pragma: no cover - malformed entry
            continue
        if kind.startswith("sub:"):
            ns += 1
            d = outcome["sub_pos"]
        elif kind == "3_trunc":
            outcome["t3"] += 1
            d = outcome["t3_pos"]
        elif kind == "5_trunc":
            outcome["t5"] += 1
            d = outcome["t5_pos"]
        else:  # pragma: no cover - unknown entry kind
            continue
        d[bucket] = d.get(bucket, 0) + 1
    outcome["subs"] += ns
    return ns


def render_result(hdr: str, r, cfg: ECConfig,
                  outcome: dict | None = None,
                  maxe: int | None = None) -> tuple[str, str]:
    """One read's exact output surfaces: the `.fa` text and `.log`
    text the reference writes for result `r` (error_correct_reads.cc
    :246-341; empty strings where the read contributes nothing to a
    channel). THE single rendering — the offline CLI loop and the
    serve engine both go through here, which is what makes
    `POST /correct` byte-identical to `quorum_error_correct_reads` by
    construction. `outcome`, when given, accumulates the per-read
    outcome tallies (err_log.hpp semantics) that feed the telemetry
    counters: keys subs/t3/t5/hist/skips plus the bucketed position
    spectra sub_pos/t3_pos/t5_pos, as built by `new_outcome()`.
    `maxe`, when given, bounds the per-read substitution count
    recorded in `hist` at the config's max-error budget (shared
    quality.bounded clamp — Prometheus exposition must not see
    unbounded histogram values)."""
    if r.ok:
        if outcome is not None:
            ns = _tally_log(r.fwd_log, outcome)
            ns += _tally_log(r.bwd_log, outcome)
            if maxe is not None:
                ns = quality.bounded(ns, maxe)
            outcome["hist"][ns] = outcome["hist"].get(ns, 0) + 1
        return f">{hdr} {r.fwd_log} {r.bwd_log}\n{r.seq}\n", ""
    if outcome is not None:
        slug = REASON_SLUGS.get(r.error, "other")
        outcome["skips"][slug] = outcome["skips"].get(slug, 0) + 1
    fa = f">{hdr}\nN\n" if cfg.no_discard else ""
    return fa, f"Skipped {hdr}: {r.error}\n"


def new_outcome() -> dict:
    """A fresh per-read outcome tally for `render_result`: scalar
    event counts (subs/t3/t5), the per-read substitution histogram
    (hist), the skip-reason breakdown (skips), and the bucketed
    read-cycle position spectra (sub_pos/t3_pos/t5_pos) the quality
    scorecard renders (ISSUE 17)."""
    return {"subs": 0, "t3": 0, "t5": 0, "hist": {}, "skips": {},
            "sub_pos": {}, "t3_pos": {}, "t5_pos": {}}


def precreate_outcome_counters(reg) -> None:
    """Pre-create the full data-plane outcome surface at setup so
    zero-valued names still land in the final document (the PR-7
    zero-count lesson): every `skipped_<slug>` REASON_SLUGS counter
    plus the "other" fallback, the event counters, and the quality
    histograms. Both stage-2 paths call this — the offline pipeline
    (_run_ec) and the serve engine — which is what lets
    telemetry/contract.QUALITY_COUNTERS require the names whenever
    meta declares a stage-2 document."""
    if not getattr(reg, "enabled", False):
        return
    reg.counter("substitutions")
    reg.counter("truncations_3p")
    reg.counter("truncations_5p")
    reg.counter("skipped_contaminant")
    reg.counter("skipped_no_anchor")
    reg.counter("skipped_homopolymer")
    reg.counter("skipped_other")
    reg.histogram("substitutions_per_read")
    reg.histogram("sub_pos_bucket")
    reg.histogram("trunc_cycle_3p")
    reg.histogram("trunc_cycle_5p")


def record_outcome(reg, outcome: dict) -> None:
    """Feed one outcome tally into the registry's counters — shared
    by the offline drain loop and the serve engine so both report the
    same metric names."""
    reg.counter("substitutions").inc(outcome["subs"])
    reg.counter("truncations_3p").inc(outcome["t3"])
    reg.counter("truncations_5p").inc(outcome["t5"])
    hist = reg.histogram("substitutions_per_read")
    for v, n in outcome["hist"].items():
        hist.observe(v, n)
    for name, key in (("sub_pos_bucket", "sub_pos"),
                      ("trunc_cycle_3p", "t3_pos"),
                      ("trunc_cycle_5p", "t5_pos")):
        spectrum = reg.histogram(name)
        for v, n in outcome[key].items():
            spectrum.observe(v, n)
    for slug, n in outcome["skips"].items():
        reg.counter(f"skipped_{slug}").inc(n)


def resolve_render_workers(n: int) -> int:
    """`--render-workers` semantics: 0 (the default) = min(4, cores)
    — enough to hide the ~0.3-0.4 s/batch host finish/render tail
    behind the device at multi-device throughputs without oversubscribing
    the decode/pack threads; an explicit N is taken as-is (1 = the
    pre-ISSUE-9 serial pipeline)."""
    import os
    if n and n > 0:
        return int(n)
    return min(4, os.cpu_count() or 1)


def render_batch_host(batch, buf, b: int, l: int, maxe: int,
                      cfg: ECConfig, count_outcomes: bool):
    """The per-batch HOST tail as one pure function: finish the fetched
    device buffer and render every read's `.fa`/`.log` text. Runs on a
    render worker (ISSUE 9: N of these execute concurrently; the
    sequence-numbered reorder stage in utils/pipeline.ReorderingPool
    re-serializes the results, so output bytes are identical to the
    serial pipeline for any worker count). Returns
    (fa_text, log_text, n_corrected, n_skipped, bases_out, outcome,
    render_seconds)."""
    t0 = time.perf_counter()
    results = finish_batch_host(buf, batch.n, cfg, batch.codes,
                                b, l, maxe)
    fa_parts: list[str] = []
    log_parts: list[str] = []
    n_corr = n_skip = bases_out = 0
    # per-read outcome tallies (err_log.hpp semantics, decoded from
    # the rendered entry strings so counters are exactly what the
    # .fa/.log outputs record); skipped when metrics are off —
    # render_result never sees an outcome dict
    outcome = new_outcome() if count_outcomes else None
    for hdr, r in zip(batch.headers, results):
        fa, lg = render_result(hdr, r, cfg, outcome, maxe=maxe)
        if r.ok:
            n_corr += 1
            bases_out += r.end - r.start
        else:
            n_skip += 1
        if fa:
            fa_parts.append(fa)
        if lg:
            log_parts.append(lg)
    return ("".join(fa_parts), "".join(log_parts), n_corr, n_skip,
            bases_out, outcome, time.perf_counter() - t0)


def _replay_plane_missing(prepacked, qual_cutoff: int) -> bool:
    """True when a materialized replay cache (the quorum driver hands
    a list) was packed WITHOUT this run's qual>=cutoff plane. A
    streaming iterable can't be peeked without consuming it — those
    fall through to require_plane's per-batch error."""
    if isinstance(prepacked, (list, tuple)) and prepacked:
        return int(qual_cutoff) not in prepacked[0][1].hq
    return False


def pack_for_stage2(batch: fastq.ReadBatch, cfg: ECConfig):
    """Bit-pack one ReadBatch for the corrector's wire format (runs in
    the decode/prefetch thread; the main thread only does H2D)."""
    pk = packing.pack_reads(batch.codes, batch.quals, batch.lengths,
                            thresholds=(cfg.qual_cutoff,))
    pk.to_wire()  # warm the fused H2D buffer off the main thread
    return pk


@dataclasses.dataclass
class ECStats:
    reads: int = 0
    corrected: int = 0
    skipped: int = 0
    bases_in: int = 0
    bases_out: int = 0
    cutoff: int = 0


@dataclasses.dataclass(frozen=True)
class ECOptions:
    """CLI-level options beyond ECConfig (yaggo surface,
    src/error_correct_reads_cmdline.yaggo)."""

    output: str | None = None  # -o prefix; None = stdout/stderr
    gzip: bool = False
    contaminant: str | None = None
    cutoff: int | None = None  # -p; None = compute from DB
    apriori_error_rate: float = 0.01
    poisson_threshold: float = 1e-6
    batch_size: int = 8192
    threads: int = 1  # -t: parallel host decode workers (multi-file)
    no_mmap: bool = False  # -M: slurp the DB instead of memmapping
    profile: str | None = None  # --profile DIR: jax.profiler trace
    metrics: str | None = None  # --metrics PATH: final metrics JSON
    metrics_interval: float = 0.0  # heartbeat period (s); 0 = no JSONL
    metrics_port: int | None = None  # --metrics-port: live /metrics
    metrics_textfile: str | None = None  # --metrics-textfile PATH
    metrics_force: bool = False  # --metrics-live: real registry for a
    # parent-owned exposition endpoint (quorum driver --metrics-port)
    trace_spans: str | None = None  # --trace-spans PATH: span JSONL
    # --metrics-push-url (ISSUE 10): periodic push of the live
    # exposition + terminal flush of the final document to a
    # push-gateway (telemetry/push.py) for fleets without a scraper
    metrics_push_url: str | None = None
    metrics_push_interval: float = 0.0
    # --alert-rules (ISSUE 11): rule file evaluated against the live
    # registry on the heartbeat cadence (telemetry/alerts.py)
    alert_rules: str | None = None
    # fault tolerance (ISSUE 4): with checkpoint_every > 0 the output
    # streams to <prefix>.fa/.log.partial with a resume journal
    # committed every N batches; resume=True skips already-corrected
    # reads and atomically finalizes (io/checkpoint.Stage2Journal)
    checkpoint_every: int = 0
    resume: bool = False
    on_bad_read: str = "abort"  # malformed-record policy (io/fastq)
    # --verify-db (ISSUE 8): checksum verification of v5 databases at
    # load — "full" (default), "sample" (seeded chunk scrub), "off"
    verify_db: str = "full"
    # --devices (ISSUE 5): 1 = single-chip; >1 runs data-parallel
    # correction over a local device mesh — table replicated below
    # the size threshold, row-sharded with routed lookups above it
    # (parallel/tile_sharded.ShardedCorrector)
    devices: int = 1
    # --render-workers (ISSUE 9): N host finish/render workers behind
    # a sequence-numbered reorder stage — output bytes identical to
    # the serial pipeline for any N. 0 = auto (min(4, cores))
    render_workers: int = 0
    # --presence-floor (ISSUE 14): entries with count < floor vanish
    # from the table at load (ctable.tile_floor). 0 = auto: a database
    # declaring a prefilter applies its matching floor (min_obs), any
    # other database keeps the full-presence default of 1 — so plain
    # pipelines are bit-unchanged and prefiltered ones are exactly
    # the floored-full-table run (the parity theorem, ops/sketch)
    presence_floor: int = 0
    # resource guards (ISSUE 19): --preflight compares estimated
    # output bytes against free space before the DB load (strict
    # refuses with rc DISK_FULL_RC, warn prints, off skips);
    # --stall-timeout-s arms the offline stall watchdog over the
    # batch cursor (utils/resources.py)
    preflight: str = "warn"
    stall_timeout_s: float = 0.0


def _open_out(prefix: str | None, suffix: str, default_stream, gzip: bool):
    """open_file (error_correct_reads.cc:133-155): default stream when
    no prefix; gzip appends .gz to named files only."""
    if prefix is None:
        if gzip:
            return gzip_mod.open(default_stream.buffer, "wt", compresslevel=1)
        return default_stream
    path = prefix + suffix + (".gz" if gzip else "")
    if gzip:
        return gzip_mod.open(path, "wt", compresslevel=1)
    # the .fa/.log outputs stream gigabytes through AsyncWriter; the
    # checkpointed path writes .partial siblings finalized by rename
    # (io/checkpoint), the non-checkpointed path is a plain stream
    return open(path, "w")  # qlint: disable=raw-artifact-write


def resolve_cutoff(state, meta, opts: ECOptions,
                   header: dict | None = None) -> int:
    """args.cutoff_given ? arg : compute_poisson_cutoff(...) with the
    reference's exact parameterization (error_correct_reads.cc:710-717):
    collision_prob = apriori/3, threshold = poisson_threshold/apriori.
    Returns 0 when the computation fails and no -p was given (caller
    dies with the reference message).

    A PREFILTERED database (ISSUE 14) carries the full-table stats in
    its header (`poisson_stats`: the filtered table's distinct/total
    hq plus the dropped hq singletons' exact contribution) — using
    them keeps the computed cutoff identical to an unfiltered run's,
    which the byte-parity guarantee depends on."""
    if opts.cutoff is not None:
        return opts.cutoff
    vlog("Computing Poisson cutoff")
    ps = (header or {}).get("poisson_stats")
    if ps:
        distinct, total = ps["distinct_hq"], ps["total_hq"]
    else:
        _occ, distinct, total = db_format.db_stats(state, meta)
    return compute_poisson_cutoff(
        int(distinct), int(total),
        opts.apriori_error_rate / 3.0,
        opts.poisson_threshold / opts.apriori_error_rate,
    )


def run_error_correct(db_path: str, sequences: Sequence[str],
                      cfg_in: ECConfig | None, opts: ECOptions,
                      qual_cutoff: int = 127, skip: int = 1, good: int = 2,
                      anchor_count: int = 3, min_count: int = 1,
                      window: int = 10, error: int = 3,
                      homo_trim: int | None = None,
                      trim_contaminant: bool = False,
                      no_discard: bool = False,
                      records=None, db=None, prepacked=None) -> ECStats:
    """Run the full stage-2 pipeline. If `cfg_in` is given it overrides
    the individual knobs (library use); otherwise an ECConfig is built
    from the flags plus the DB geometry, with the cutoff resolved per
    `resolve_cutoff`. If `records` is given (an iterator of
    (header, seq, qual) tuples, e.g. merge_mate_pairs.merge_records) it
    is used instead of reading `sequences` from disk — this is how the
    quorum driver's paired mode streams merged pairs through the
    corrector the way the reference pipes processes together
    (src/quorum.in:172-231). If `prepacked` is given (an iterable of
    (ReadBatch, PackedReads) pairs whose hq planes include this run's
    qual_cutoff) the reads are neither re-read nor re-packed — the
    quorum driver replays stage 1's cache through stage 2, sparing the
    second full parse the reference gets for free from the page
    cache."""
    # telemetry (--metrics): per-read outcome counters decoded from the
    # rendered results, pipeline queue gauges, stage timers. NULL (all
    # no-ops, reg.enabled False) when opts.metrics is unset, so the
    # per-read hot path pays nothing. Live exposition
    # (--metrics-port/--metrics-textfile) forces a real registry even
    # without a final-JSON path; --trace-spans adds the hierarchical
    # span tracer (JSONL + Chrome trace, TraceAnnotation mirror).
    # observability() owns the whole lifecycle: exposition starts
    # inside its umbrella (a busy port still lands the error
    # document), a failed run stamps status=error + writes, and the
    # span file / endpoint close on every exit. The success path
    # writes status=ok itself at the end of _run_ec, which the
    # teardown detects and leaves alone.
    from ..cli.observability import observability
    # the resource-guard frame (ISSUE 19): watch the output and
    # metrics filesystems; stage-2 files (not generators) preflight
    # against their input sizes before the DB upload
    watch = [p for p in (opts.output and opts.output + ".fa",
                         opts.metrics) if p]
    with observability(opts.metrics, opts.metrics_interval,
                       port=opts.metrics_port,
                       textfile=opts.metrics_textfile,
                       live=opts.metrics_force,
                       trace_spans=opts.trace_spans,
                       profile=opts.profile,
                       push_url=opts.metrics_push_url,
                       push_interval=opts.metrics_push_interval,
                       alert_rules=opts.alert_rules,
                       watch_paths=watch,
                       stall_timeout_s=opts.stall_timeout_s,
                       stage="error_correct", batch_size=opts.batch_size,
                       no_discard=bool(no_discard)) as obs:
        if opts.output and records is None and prepacked is None:
            resources.preflight(opts.preflight,
                                resources.estimate_stage2_needs(
                                    opts.output + ".fa", sequences))
        try:
            return _run_ec(db_path, sequences, cfg_in, opts,
                           obs.registry, obs.tracer,
                           qual_cutoff=qual_cutoff, skip=skip,
                           good=good, anchor_count=anchor_count,
                           min_count=min_count, window=window,
                           error=error, homo_trim=homo_trim,
                           trim_contaminant=trim_contaminant,
                           no_discard=no_discard, records=records,
                           db=db, prepacked=prepacked)
        except resources.ResourceExhausted:
            raise  # already laddered (journal guard / preflight)
        except OSError as e:
            if resources.is_enospc(e):
                # a bare ENOSPC escaping stage 2 is the .fa/.log
                # output stream (reads cannot ENOSPC): the run's
                # reason to exist — required, fail fast, no retry
                raise resources.fail_required("output.stream",
                                              e) from e
            raise


def _run_ec(db_path: str, sequences: Sequence[str],
            cfg_in: ECConfig | None, opts: ECOptions, reg, tracer,
            *, qual_cutoff: int, skip: int, good: int,
            anchor_count: int, min_count: int,
            window: int, error: int,
            homo_trim: int | None,
            trim_contaminant: bool,
            no_discard: bool,
            records, db, prepacked) -> ECStats:
    if opts.checkpoint_every > 0 and (not opts.output or opts.gzip):
        # before the DB load: a misconfigured flag must fail fast,
        # not after minutes of device upload
        raise RuntimeError(
            "--checkpoint-every requires -o PREFIX and is "
            "incompatible with --gzip (a gzip stream cannot be "
            "truncated back to a commit point)")
    # before the DB load: the doc declares stage=error_correct from
    # the umbrella, so the full outcome surface must land (as zeros)
    # even when the load refuses the database — metrics_check holds
    # every stage-2 document to the quality contract
    precreate_outcome_counters(reg)
    vlog("Loading mer database")
    if db is not None:
        # in-process handoff from stage 1: the table is already device
        # resident (re-uploading a full-size table through the tunnel
        # costs ~0.1 s/MB; the reference's page-cached re-mmap is free).
        # The header is still read from the (always-written) file for
        # the prefilter declaration + Poisson stats (ISSUE 14) —
        # best-effort: a missing/foreign file just means no
        # declaration, the pre-prefilter behavior.
        state, meta = db
        try:
            header = db_format.read_header(db_path)
        except (OSError, ValueError):
            header = {}
    else:
        to_dev = True
        if opts.devices > 1:
            try:
                hdr = db_format.read_header(db_path)
            except (OSError, ValueError):
                hdr = {}  # ref/v1 formats: read_db handles them
            if (hdr.get("format") == db_format.MANIFEST_FORMAT
                    and int(hdr.get("rb_log2", 0)) > 24):
                # past the single-chip geometry cap: reassemble on the
                # host — ShardedCorrector device_puts the row planes
                # itself (routed layout at this size), so a device-
                # resident single-chip copy would be both impossible
                # and wasted
                to_dev = False
        state, meta, header = db_format.read_db(db_path,
                                                to_device=to_dev,
                                                no_mmap=opts.no_mmap,
                                                verify=opts.verify_db)

    cutoff = resolve_cutoff(state, meta, opts, header=header)
    vlog("Using cutoff of ", cutoff)
    if cutoff == 0 and opts.cutoff is None:
        raise RuntimeError(
            "Cutoff computation failed. Pass it explicitly with -p switch.")

    # presence floor (ISSUE 14): explicit flag > the database's own
    # prefilter declaration > full presence. Applied AFTER cutoff
    # resolution (the cutoff is a full-table statistic in both the
    # filtered and unfiltered runs) and BEFORE the corrector ever
    # probes the table, so a prefiltered database and the floored
    # full database are bit-identical corrector inputs.
    floor = int(opts.presence_floor or 0)
    if floor <= 0:
        floor = int((header.get("prefilter") or {}).get("min_obs", 1))
    if floor > 1:
        state = ctable.tile_floor(state, meta, floor)
        vlog("Applying presence floor of ", floor,
             " (count-below-floor mers treated as absent)")
    if reg.enabled:
        reg.set_meta(presence_floor=floor)
        # the DB header's coverage statistic (ISSUE 13 poisson_stats)
        # feeds the scorecard's coverage model: mean hq multiplicity
        # predicts the trusted-anchor rate (1 - e^-c), which the
        # coverage_drop drift rule compares against observation
        ps = (header or {}).get("poisson_stats")
        if ps and ps.get("distinct_hq"):
            reg.set_meta(coverage_mean=round(
                float(ps["total_hq"]) / float(ps["distinct_hq"]), 4))

    if cfg_in is not None:
        cfg = cfg_in
    else:
        cfg = ECConfig(
            k=meta.k, skip=skip, good=good, anchor_count=anchor_count,
            min_count=min_count, cutoff=cutoff, qual_cutoff=qual_cutoff,
            window=window, error=error, homo_trim=homo_trim,
            trim_contaminant=trim_contaminant, no_discard=no_discard,
            collision_prob=opts.apriori_error_rate / 3.0,
            poisson_threshold=opts.poisson_threshold,
        )

    contam = None
    if opts.contaminant is not None:
        vlog("Loading contaminant sequences")
        contam = contaminant_mod.load_contaminant(opts.contaminant, cfg.k)

    # --devices N: data-parallel correction over a local mesh. The
    # corrector consumes the SAME packed wire and returns the SAME
    # lean finish buffer as correct_batch_packed, so everything
    # downstream (fetch/render/write) is untouched and the output is
    # byte-identical to --devices 1 by construction.
    sharded = None
    if opts.devices > 1:
        from ..parallel import tile_sharded as ts
        if opts.batch_size % opts.devices:
            raise RuntimeError(
                f"--batch-size {opts.batch_size} is not divisible by "
                f"--devices {opts.devices}; round it up")
        mesh = ts.make_mesh(opts.devices)
        sharded = ts.ShardedCorrector(mesh, state, meta, cfg,
                                      contam=contam)
        vlog("Correcting over ", opts.devices, " devices, table ",
             sharded.layout)
        reg.gauge("n_shards").set(opts.devices)
        reg.set_meta(devices=opts.devices, table_layout=sharded.layout)

    # crash safety (ISSUE 4): with journaling the output streams to
    # .partial files, a journal commits completed batches + exact byte
    # offsets, and a kill -> --resume run truncates the torn tail,
    # skips the journaled batches, and finalizes atomically — byte-
    # identical to an uninterrupted run
    journal = None
    jctx = None
    if opts.checkpoint_every > 0:  # flags validated at entry
        journal = ckpt_mod.Stage2Journal(opts.output)
        # the resume identity: same database, same inputs, same
        # correction config — anything else would splice two
        # different corrections into one output file
        jctx = {"db": db_path, "inputs": list(sequences),
                "config": repr(cfg)}
        reg.counter("checkpoint_writes_total")  # lands even at 0
        reg.set_meta(checkpoint_every=opts.checkpoint_every)
    jstate = None
    skip_batches = 0
    stats = ECStats(cutoff=cutoff)
    if journal is not None and opts.resume:
        jstate = journal.load()
        if jstate is not None:
            journal.check_config(jstate, opts.batch_size, jctx)
            skip_batches = int(jstate["batches"])
            stats.reads = int(jstate["reads"])
            stats.corrected = int(jstate["corrected"])
            stats.skipped = int(jstate["skipped"])
            stats.bases_in = int(jstate["bases_in"])
            stats.bases_out = int(jstate["bases_out"])
            reg.counter("resume_skipped_reads")  # lands even at 0
            reg.set_meta(resumed=True, resumed_from_batch=skip_batches)
            reg.event("resume", stage="error_correct",
                      cursor=skip_batches)
            vlog("Resuming stage 2 from journal: ", skip_batches,
                 " batches (", stats.reads, " reads) already written")

    policy = None
    if opts.on_bad_read != "abort":
        qpath = ((opts.output + ".quarantine.fastq")
                 if opts.output else None)
        if opts.on_bad_read == "quarantine" and qpath is None:
            raise RuntimeError(
                "--on-bad-read=quarantine requires -o PREFIX (the "
                "quarantine file lands beside the output)")
        policy = fastq.BadReadPolicy(opts.on_bad_read, qpath,
                                     reg if reg.enabled else None)
        reg.counter("bad_reads_total")  # lands even at 0
        reg.set_meta(on_bad_read=opts.on_bad_read)

    if journal is not None:
        out, log = journal.open_outputs(jstate)
    else:
        out = _open_out(opts.output, ".fa", sys.stdout, opts.gzip)
        log = _open_out(opts.output, ".log", sys.stderr, opts.gzip)
    pipe_metrics = reg if reg.enabled else None
    writer = AsyncWriter([out, log], metrics=pipe_metrics)
    timer = StageTimer()
    vlog("Correcting reads")
    if prepacked is not None and _replay_plane_missing(prepacked,
                                                       cfg.qual_cutoff):
        # the driver's replay cache was packed for a DIFFERENT quality
        # cutoff than this run resolved (config drift between the
        # driver's constant and the stage's flags). Falling back to
        # the disk re-read costs a second parse; dying mid-stream on
        # an uncaught KeyError costs the run (ADVICE r5).
        if not sequences:
            raise RuntimeError(
                f"replay cache lacks the qual>={cfg.qual_cutoff} plane "
                "and no input paths were given to re-read from disk")
        vlog("Replay cache lacks the qual>=", cfg.qual_cutoff,
             " plane; re-reading inputs from disk")
        reg.event("replay_cache_fallback", qual_cutoff=cfg.qual_cutoff)
        prepacked = None
    try:
        if records is not None:
            src = fastq.batch_records(records, opts.batch_size)
        elif prepacked is not None:
            # quorum-driver replay: stage 1 already parsed AND packed
            # these reads (run_quorum); skip the second disk parse
            src = None
        elif jax.process_count() > 1 and not fleet.in_host_run():
            # per-host runs of the single-chip CLI would race on one
            # output path. The fleet tier (parallel/fleet) runs this
            # path per host with DISJOINT per-file output segments
            # under fleet.host_run() and merges them in order; bare
            # multi-host stage 2 otherwise needs the sharded pipeline
            raise RuntimeError(
                "multi-host correction requires the fleet tier "
                "(--coordinator/--num-processes/--process-id, whose "
                "orchestration owns per-host output segments) or the "
                "sharded pipeline (parallel.tile_sharded.correct_step "
                "+ parallel.multihost), not bare per-host runs of the "
                "single-chip CLI")
        else:
            src = fastq.read_batches(sequences, opts.batch_size,
                                     threads=opts.threads,
                                     policy=policy)

        # NOTE: H2D stays on the MAIN thread — device_put from the
        # prefetch thread measured SLOWER end-to-end (3.2 vs 1.4
        # s/batch): the tunnel client degrades under concurrent
        # access, so the prefetch thread does host decode AND
        # bit-packing only; transfers ride the packed wire format
        # (io/packing.py, 0.5 B/base) from the main thread.
        if prepacked is not None:
            batches = prepacked
        else:
            def _pack(it):
                for b in it:
                    yield b, pack_for_stage2(b, cfg)
            batches = prefetch(_pack(src), metrics=pipe_metrics,
                               tracer=tracer)
        # host finish+render pipeline (ISSUE 9): the D2H (fetch_finish)
        # must stay on the MAIN thread (the tunnel degrades under
        # concurrent device access, PERF_NOTES.md r4), but the
        # numpy/str tail is pure host work — N render workers finish
        # batches i..i+N-1 while the device corrects batch i+N
        # (~0.3-0.4 s/batch each, the host roofline PERF_NOTES round 6
        # measured binding the multi-device scaling). The sequence-
        # numbered reorder stage (utils/pipeline.ReorderingPool) drains
        # results in submission order in front of the AsyncWriter, so
        # `.fa`/`.log` bytes are identical to --render-workers 1 for
        # any N, and the journal's batch commit order is unchanged
        # (kill -> resume parity holds).
        count_outcomes = reg.enabled
        n_render = resolve_render_workers(opts.render_workers)
        if reg.enabled:
            reg.set_meta(render_workers=n_render)
            reg.histogram("render_ms")  # land even for an empty input
            reg.histogram("reorder_wait_ms")

        def _render(batch, buf, b, l, maxe):
            with tracer.span("render", reads=batch.n):
                return render_batch_host(batch, buf, b, l, maxe, cfg,
                                         count_outcomes)

        def _drain_sink(res):
            fa, lg, n_corr, n_skip, bases_out, outcome, render_s = res
            wait_s = pool.take_reorder_wait()
            timer.add_time("drain", wait_s)
            stats.corrected += n_corr
            stats.skipped += n_skip
            stats.bases_out += bases_out
            if outcome is not None:
                record_outcome(reg, outcome)
            if reg.enabled:
                reg.histogram("render_ms").observe(
                    round(render_s * 1e3, 3))
                reg.histogram("reorder_wait_ms").observe(
                    round(wait_s * 1e3, 3))
            writer.write(0, fa)
            writer.write(1, lg)

        pool = ReorderingPool(n_render, _drain_sink)
        step_i = 0
        try:
            with trace(opts.profile):
                for batch, pk in batches:
                    if skip_batches > 0:
                        # resume fast-path: this batch's output is
                        # already committed in the journal (stats were
                        # restored from it); parsing is unavoidable —
                        # the cursor is a batch count over the
                        # deterministic re-batching — but no device
                        # step or render runs
                        skip_batches -= 1
                        reg.counter("resume_skipped_reads").inc(batch.n)
                        step_i += 1
                        continue
                    faults.inject("stage2.correct", batch=step_i)
                    # per-batch liveness beat for the offline stall
                    # watchdog (--stall-timeout-s, ISSUE 19): a
                    # cursor that stops advancing soft-aborts this
                    # loop with a StallError -> retryable STALL_RC
                    resources.watchdog_beat("stage2.correct", step_i)
                    with tracer.span("stage2_batch", step=step_i,
                                     reads=batch.n):
                        # per-batch device-time attribution: dispatch
                        # (handing XLA the program; host-side queueing)
                        # measured separately from block_until_ready wait
                        # (device compute + transfer), under a
                        # StepTraceAnnotation so the split is also visible
                        # against the XLA timeline under --profile
                        t0 = time.perf_counter()
                        with tracer.step("stage2_device", step_i,
                                         reads=batch.n):
                            # the lean finish buffer packs inside the same
                            # executable (one dispatch per batch instead
                            # of two). The cap is a DETERMINISTIC function
                            # of the batch shape — a data-dependent cap
                            # would recompile the whole corrector
                            # executable per distinct value (measured:
                            # minutes, mid-run). 4 entries/read covers ~1%
                            # error rates with 2x+ headroom; rarer batches
                            # overflow and re-pack once in fetch_finish.
                            cap = 4 * batch.codes.shape[0]
                            if sharded is not None:
                                res, packed = sharded(pk, cap)
                            else:
                                res, packed = correct_batch_packed(
                                    state, meta, pk, cfg, contam=contam,
                                    pack_cap=cap)
                            t1 = time.perf_counter()
                            jax.block_until_ready(packed)
                            t2 = time.perf_counter()
                        observe_dispatch_wait(reg, "device", t0, t1, t2,
                                              timer=timer)
                        with timer.stage("fetch"), tracer.span("fetch"):
                            buf = fetch_finish(res, packed)
                        b, l = res.out.shape
                        maxe = res.fwd_log.pos.shape[1]
                        pool.submit(_render, batch, buf, b, l, maxe)
                        stats.reads += batch.n
                        nb = int(batch.lengths[:batch.n].sum())
                        stats.bases_in += nb
                        timer.add_units("device_wait", nb)
                        reg.heartbeat(stage="error_correct",
                                      reads=stats.reads,
                                      bases=stats.bases_in)
                    step_i += 1
                    if (journal is not None
                            and step_i % opts.checkpoint_every == 0):
                        # commit point: drain the render pipeline and
                        # the writer so every byte of batches
                        # [0, step_i) is REALLY in the partials, then
                        # journal the cursor + byte offsets atomically
                        with timer.stage("checkpoint"):
                            pool.flush()
                            writer.flush()
                            journal.commit(step_i, stats, out.tell(),
                                           log.tell(), opts.batch_size,
                                           jctx)
                        reg.counter("checkpoint_writes_total").inc()
                        reg.event("checkpoint", stage="error_correct",
                                  cursor=step_i)
                pool.flush()
        finally:
            pool.shutdown()
    finally:
        try:
            writer.close()
        finally:
            # first, so interrupted runs (and disk-full stream closes
            # below) still print the per-stage table under -v; guarded
            # so a broken stderr can't replace the propagating error
            try:
                timer.report(stats.bases_in)
            except Exception:
                pass
            # always runs, even if the writer re-raises: gzip streams
            # need their trailer or the output is unreadable. Close each
            # stream independently so a failing out.close() (e.g. disk
            # full at gzip flush) can't leave log without its trailer.
            def _finish(f):
                if f is not sys.stdout and f is not sys.stderr:
                    f.close()
                else:
                    f.flush()
            try:
                _finish(out)
            finally:
                _finish(log)
                if policy is not None:
                    policy.close()
    if journal is not None:
        # success only (an exception above skips this): promote the
        # partials over the real outputs atomically and drop the
        # journal — a failed run keeps both, ready for --resume
        journal.finalize()
    vlog("Done. ", stats.corrected, " corrected, ", stats.skipped,
         " skipped of ", stats.reads, " reads")
    if reg.enabled:
        reg.counter("reads_in").inc(stats.reads)
        reg.counter("reads_corrected").inc(stats.corrected)
        reg.counter("reads_skipped").inc(stats.skipped)
        reg.counter("bases_in").inc(stats.bases_in)
        reg.counter("bases_out").inc(stats.bases_out)
        reg.gauge("cutoff").set(stats.cutoff)
        reg.set_timer("stage2", timer.as_dict(stats.bases_in))
        reg.set_meta(status="ok")
        reg.write()
    return stats
