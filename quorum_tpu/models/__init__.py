from . import create_database  # noqa: F401
