"""Pure-Python per-read oracle of the Quorum correction semantics.

A direct, slow, readable transcription of the reference algorithm
(src/error_correct_reads.cc: find_starting_mer :609-643, extend
:384-565, err_log src/err_log.hpp, homo_trim :567-597), written from
the spec to serve as the behavioral test oracle for the batched device
corrector and as a host fallback path. All positions are raw 0-based
read indices; direction-generic arithmetic replaces the reference's
forward_/backward_ pointer-and-counter template machinery (d = +1 for
5'->3', -1 for 3'->5').

Bug-compatibility standard: byte-parity with the compiled reference
binary, including behaviors its own comments call unintended. Two such
behaviors are replicated deliberately:

* err_log::force_truncate's position filter (err_log.hpp:42-46) uses
  the counter's overloaded operator>=, which is inverted for
  backward_counter (error_correct_reads.hpp:135-137). So for the
  backward log, force_truncate(pos) drops entries with raw position
  <= pos (entries *inside* the kept region) and keeps those beyond it
  — the opposite of the comment's stated intent. We match the binary.

* The int-overflow dead code in the ambiguous-substitution tie-break
  (error_correct_reads.cc:520): when prev_count <= min_count the
  "pick the largest count" intent never fires; see _extend below.
"""

from __future__ import annotations

import collections
import dataclasses

import numpy as np

from ..ops.poisson import poisson_term_f32, poisson_term_np
from .ec_config import (
    ECConfig,
    ERROR_CONTAMINANT,
    ERROR_HOMOPOLYMER,
    ERROR_NO_STARTING_MER,
)

_INT_MIN = -(2**31)
_UINT32_MAX = 2**32 - 1


def _wrap_int32(x: int) -> int:
    """C-style (int) cast: wrap modulo 2^32 into [-2^31, 2^31)."""
    return ((x + 2**31) % 2**32) - 2**31


class DictDB:
    """Host-side (count, qual) store keyed by canonical k-mer int."""

    def __init__(self, d: dict[int, tuple[int, int]], k: int):
        self.d = d
        self.k = k

    @classmethod
    def from_table(cls, state, meta) -> "DictDB":
        from ..io.db_format import db_iterate

        keys_hi, keys_lo, v = db_iterate(state, meta)
        keys = (keys_hi.astype(np.uint64) << np.uint64(32)) | \
            keys_lo.astype(np.uint64)
        return cls(
            {int(kk): (int(vv) >> 1, int(vv) & 1) for kk, vv in zip(keys, v)},
            meta.k,
        )

    def get(self, key: int) -> tuple[int, int]:
        return self.d.get(key, (0, 0))


class Kmer:
    """fwd + revcomp 2k-bit ints, mirroring kmer_t (src/kmer.hpp:11-61)."""

    __slots__ = ("f", "r", "k")

    def __init__(self, k: int, f: int = 0, r: int = 0):
        self.k = k
        self.f = f
        self.r = r

    def copy(self) -> "Kmer":
        return Kmer(self.k, self.f, self.r)

    def shift_left(self, code: int) -> None:
        mask = (1 << (2 * self.k)) - 1
        self.f = ((self.f << 2) | code) & mask
        self.r = (self.r >> 2) | ((3 - code) << (2 * self.k - 2))

    def shift_right(self, code: int) -> None:
        mask = (1 << (2 * self.k)) - 1
        self.f = (self.f >> 2) | (code << (2 * self.k - 2))
        self.r = ((self.r << 2) | (3 - code)) & mask

    def canonical(self) -> int:
        return self.f if self.f <= self.r else self.r

    # direction-generic ops; d=+1 forward, d=-1 backward. "Base 0" is
    # the most recently shifted-in base in the direction of travel
    # (src/kmer.hpp:75-103: backward adapters mirror the index).
    def shift(self, d: int, code: int) -> None:
        if d == 1:
            self.shift_left(code)
        else:
            self.shift_right(code)

    def base0(self, d: int) -> int:
        i = 0 if d == 1 else self.k - 1
        return (self.f >> (2 * i)) & 3

    def replace0(self, d: int, code: int) -> None:
        i = 0 if d == 1 else self.k - 1
        ri = self.k - 1 - i
        self.f = (self.f & ~(3 << (2 * i))) | (code << (2 * i))
        self.r = (self.r & ~(3 << (2 * ri))) | ((3 - code) << (2 * ri))


class DirLog:
    """err_log<T> with direction-generic raw positions
    (src/err_log.hpp:22-135; see module docstring for the
    force_truncate binary-parity semantics)."""

    def __init__(self, d: int, window: int, error: int, trunc_string: str):
        self.d = d
        self.window = window
        self.error = error
        self.trunc = trunc_string
        self.entries: list[tuple[str, int, str, str]] = []
        self.lwin = 0

    def _dist(self, a_raw: int, b_raw: int) -> int:
        return self.d * (a_raw - b_raw)

    def check_nb_error(self) -> bool:
        if self.entries:
            back = self.entries[-1][1]
            guard = back > self.window if self.d == 1 else back < self.window
            if guard:
                while self._dist(back, self.entries[self.lwin][1]) > self.window:
                    self.lwin += 1
        return len(self.entries) - self.lwin - 1 >= self.error

    def substitution(self, raw: int, frm: str, to: str) -> bool:
        self.entries.append(("sub", raw, frm, to))
        return self.check_nb_error()

    def truncation(self, raw: int) -> bool:
        # backward_log::truncation records pos - 1 (direction units),
        # i.e. raw + 1: the first *kept* base index
        # (src/error_correct_reads.hpp:170-172)
        if self.d == -1:
            raw += 1
        self.entries.append(("trunc", raw, "", ""))
        return self.check_nb_error()

    def force_truncate(self, raw: int) -> bool:
        # Binary parity: the remove_if predicate calls the counter's
        # operator>=, inverted for backward (err_log.hpp:42-46 +
        # error_correct_reads.hpp:135-137): forward drops raw >= pos,
        # backward drops raw <= pos. See module docstring.
        if self.d == 1:
            self.entries = [e for e in self.entries if not e[1] >= raw]
        else:
            self.entries = [e for e in self.entries if not e[1] <= raw]
        self.lwin = 0
        return self.check_nb_error()

    def remove_last_window(self) -> int:
        if not self.entries:
            return 0
        diff = self._dist(self.entries[-1][1], self.entries[self.lwin][1])
        del self.entries[self.lwin :]
        self.lwin = 0
        self.check_nb_error()
        return diff

    def render(self) -> str:
        parts = []
        for typ, raw, frm, to in self.entries:
            if typ == "sub":
                parts.append(f"{raw}:sub:{frm}-{to}")
            else:
                parts.append(f"{raw}:{self.trunc}")
        return " ".join(parts)


@dataclasses.dataclass
class ReadResult:
    ok: bool
    error: str = ""
    seq: str = ""
    fwd_log: str = ""
    bwd_log: str = ""
    start: int = 0
    end: int = 0


_REV = "ACGT"


class OracleCorrector:
    def __init__(self, db: DictDB, cfg: ECConfig,
                 contaminant: set[int] | None = None):
        self.db = db
        self.cfg = cfg
        self.k = db.k
        self.contaminant = contaminant if contaminant is not None else set()
        # branch-coverage counters: tests assert the adversarial inputs
        # actually reach the paths they target (VERDICT r1 weak #3)
        self.counters: dict[str, int] = collections.Counter()

    # -- db primitives ----------------------------------------------------
    def get_val(self, canon: int) -> int:
        cnt, q = self.db.get(canon)
        return cnt if q else 0

    def get_best_alternatives(self, m: Kmer, d: int):
        """database_query::get_best_alternatives
        (src/mer_database.hpp:302-329): counts for the 4 variants of
        base 0, kept only at the best quality level seen (in loop
        order)."""
        counts = [0, 0, 0, 0]
        level = 0
        count = 0
        ucode = 0
        ori = m.base0(d)
        for i in range(4):
            m.replace0(d, i)
            cnt, q = self.db.get(m.canonical())
            if cnt > 0 and q >= level:
                if q > level and count > 0:
                    for j in range(i):
                        counts[j] = 0
                    count = 0
                counts[i] = cnt
                ucode = i
                level = q
                count += 1
        m.replace0(d, ori)
        return counts, ucode, level, count

    def is_contaminant(self, canon: int) -> bool:
        return canon in self.contaminant

    def _poisson(self, lam: float, i: int) -> float:
        if self.cfg.poisson_dtype == "float32":
            return poisson_term_f32(lam, i)
        return poisson_term_np(lam, i)

    # -- the algorithm ----------------------------------------------------
    def correct(self, seq: str, qual: str) -> ReadResult:
        cfg = self.cfg
        k = self.k
        codes = [
            {"A": 0, "C": 1, "G": 2, "T": 3}.get(c.upper(), -1) for c in seq
        ]
        quals = [ord(c) for c in qual] if qual else [0] * len(seq)
        n = len(seq)
        out = list(codes)  # out buffer; positions written as we extend

        # ---- find_starting_mer (error_correct_reads.cc:609-643) ----
        m = Kmer(k)
        inp = cfg.skip
        anchor_found = False
        while inp < n and not anchor_found:
            i = 0
            while inp < n and i < k:
                c = codes[inp]
                inp += 1
                if c >= 0:
                    m.shift_left(c)
                    i += 1
                else:
                    i = 0
            if i < k:
                break
            found = 0
            while inp < n:
                canon = m.canonical()
                contaminated = self.is_contaminant(canon)
                if contaminated and not cfg.trim_contaminant:
                    return ReadResult(False, ERROR_CONTAMINANT)
                if not contaminated:
                    val = self.get_val(canon)
                    found = found + 1 if val >= cfg.anchor_count else 0
                    if found >= cfg.good:
                        anchor_found = True
                        break
                c = codes[inp]
                inp += 1
                if c >= 0:
                    m.shift_left(c)
                else:
                    break
        if not anchor_found:
            return ReadResult(False, ERROR_NO_STARTING_MER)

        start_off = inp
        fwd_log = DirLog(+1, cfg.effective_window, cfg.effective_error,
                         "3_trunc")
        bwd_log = DirLog(-1, cfg.effective_window, cfg.effective_error,
                         "5_trunc")

        end_out = self._extend(m.copy(), codes, quals, out, start_off, n, +1,
                               fwd_log)
        if end_out is None:
            return ReadResult(False, self._ext_error)
        start_out = self._extend(m.copy(), codes, quals, out,
                                 start_off - k - 1, -1, -1, bwd_log)
        if start_out is None:
            return ReadResult(False, self._ext_error)
        start_out += 1

        if cfg.do_homo_trim:
            end_out = self._homo_trim(out, start_out, end_out, fwd_log,
                                      bwd_log)
            if end_out is None:
                return ReadResult(False, ERROR_HOMOPOLYMER)

        corrected = "".join(_REV[c] for c in out[start_out:end_out])
        return ReadResult(True, "", corrected, fwd_log.render(),
                          bwd_log.render(), start_out, end_out)

    _ext_error = ""

    def _log_substitution(self, m: Kmer, d: int, log: DirLog, cpos: int,
                          frm: int, to: int):
        """log_substitution (error_correct_reads.cc:360-379).
        Returns ('ok'|'truncate'|'error', out_rewind)."""
        if frm == to:
            return "ok", 0
        m.replace0(d, to)
        if self.is_contaminant(m.canonical()):
            if self.cfg.trim_contaminant:
                log.truncation(cpos)
                return "truncate", 0
            self._ext_error = ERROR_CONTAMINANT
            return "error", 0
        frm_c = _REV[frm] if frm >= 0 else "N"
        to_c = _REV[to] if to >= 0 else "N"
        if log.substitution(cpos, frm_c, to_c):
            diff = log.remove_last_window()
            log.truncation(cpos - d * diff)
            return "truncate", diff
        return "ok", 0

    def _extend(self, m: Kmer, codes, quals, out, pos, end, d, log):
        """extend (error_correct_reads.cc:384-565). Returns the raw out
        position (one-past-last-written in direction d), or None with
        self._ext_error set."""
        cfg = self.cfg
        self._ext_error = ""
        prev_count = self.get_val(m.canonical())
        opos = pos  # out position; moves in lockstep with pos

        def in_range(p):
            return p < end if d == 1 else p > end

        while in_range(pos):
            base_code = codes[pos]
            cpos = pos
            pos += d

            ori = base_code
            m.shift(d, ori if ori >= 0 else 0)
            if ori >= 0 and self.is_contaminant(m.canonical()):
                if cfg.trim_contaminant:
                    log.truncation(cpos)
                    return opos
                self._ext_error = ERROR_CONTAMINANT
                return None

            counts, ucode, level, count = self.get_best_alternatives(m, d)

            if count == 0:
                self.counters["trunc_count0"] += 1
                log.truncation(cpos)
                return opos

            if count == 1:
                if ori != ucode:
                    self.counters["count1_sub"] += 1
                prev_count = counts[ucode]
                res, diff = self._log_substitution(m, d, log, cpos, ori, ucode)
                if res == "truncate":
                    if diff > 0:
                        self.counters["window_trip"] += 1
                    return opos - d * diff
                if res == "error":
                    return None
                out[opos] = m.base0(d)
                opos += d
                continue

            if ori >= 0:
                if counts[ori] > cfg.min_count:
                    if counts[ori] >= cfg.cutoff or quals[cpos] >= cfg.qual_cutoff:
                        self.counters["keep_cutoff_or_qual"] += 1
                        out[opos] = m.base0(d)
                        opos += d
                        continue
                    p = float(sum(counts)) * cfg.collision_prob
                    prob = self._poisson(p, counts[ori])
                    if prob < cfg.poisson_threshold:
                        self.counters["keep_poisson"] += 1
                        out[opos] = m.base0(d)
                        opos += d
                        continue
                    self.counters["poisson_rejected"] += 1
                elif level == 0 and counts[ori] == 0:
                    self.counters["trunc_lq_alts"] += 1
                    log.truncation(cpos)
                    return opos
            elif level == 0:
                self.counters["trunc_n_lq"] += 1
                log.truncation(cpos)
                return opos

            # multiple alternatives: find those with a continuation at
            # the same-or-better level (error_correct_reads.cc:473-507)
            self.counters["ambiguous"] += 1
            check_code = ori
            success = False
            cont_counts = [0, 0, 0, 0]
            cont_with_next = [False, False, False, False]
            read_nbase = codes[pos] if in_range(pos) else -1

            for i in range(4):
                if counts[i] <= cfg.min_count:
                    continue
                check_code = i
                nmer = m.copy()
                nmer.replace0(d, i)
                nmer.shift(d, 0)
                ncounts, _, nlevel, ncount = self.get_best_alternatives(nmer, d)
                if ncount > 0 and nlevel >= level:
                    cont_with_next[i] = read_nbase >= 0 and ncounts[read_nbase] > 0
                    success = True
                    cont_counts[i] = counts[i]

            if success:
                self.counters["ambig_success"] += 1
                check_code = -1
                _prev = (
                    _UINT32_MAX
                    if prev_count <= cfg.min_count
                    else prev_count
                )
                if prev_count <= cfg.min_count:
                    self.counters["tiebreak_overflow_deadcode"] += 1
                # Replicates the compiled reference exactly, including the
                # int overflow at error_correct_reads.cc:520: min_diff is
                # (int)std::abs((long)cont - (long)_prev_count), which for
                # _prev_count == UINT32_MAX wraps negative, so the
                # (un-cast long) comparison below never matches and no
                # substitution happens when prev_count <= min_count —
                # the source comment's "pick the largest count" intent is
                # dead code in the real binary.
                min_diff = 2**31 - 1
                candidates = [False] * 4
                ncand = 0
                for i in range(4):
                    if cont_counts[i] > 0:
                        min_diff = min(
                            min_diff, _wrap_int32(abs(cont_counts[i] - _prev))
                        )
                for i in range(4):
                    if abs(cont_counts[i] - _prev) == min_diff:
                        candidates[i] = True
                        ncand += 1
                        check_code = i
                if ncand > 1 and read_nbase >= 0:
                    self.counters["tiebreak_next_base"] += 1
                    for i in range(4):
                        if candidates[i]:
                            if not cont_with_next[i]:
                                ncand -= 1
                            else:
                                check_code = i
                if ncand != 1:
                    check_code = -1
                if check_code >= 0:
                    if check_code != ori:
                        self.counters["ambig_sub"] += 1
                    res, diff = self._log_substitution(
                        m, d, log, cpos, ori, check_code
                    )
                    if res == "truncate":
                        return opos - d * diff
                    if res == "error":
                        return None

            if ori < 0 and check_code < 0:
                self.counters["trunc_n_no_sub"] += 1
                log.truncation(cpos)
                return opos

            out[opos] = m.base0(d)
            opos += d

        return opos

    def _homo_trim(self, out, start_out, end_out, fwd_log, bwd_log):
        """homo_trim (error_correct_reads.cc:567-597). Returns new
        end_out or None (whole read is homopolymer)."""
        cfg = self.cfg
        max_score = _INT_MIN
        max_pos = None
        score = 0
        ptr = end_out - 1
        pbase = out[ptr]
        ptr -= 1
        while ptr >= start_out:
            cbase = out[ptr]
            # +1 if same as last, -1 if not (reference :577)
            score += (2 if pbase == cbase else 0) - 1
            pbase = cbase
            if score > max_score:
                max_score = score
                max_pos = ptr
            ptr -= 1
        if max_score < cfg.homo_trim:
            return end_out
        if max_pos is None or max_pos < start_out:
            return None
        fwd_log.force_truncate(max_pos)
        bwd_log.force_truncate(max_pos)
        fwd_log.truncation(max_pos)
        return max_pos
