"""Error-correction configuration, shared by the oracle and the batched
device corrector. Field names/defaults mirror the reference CLI
(src/error_correct_reads_cmdline.yaggo) and the accessor semantics of
error_correct_t (error_correct_reads.cc:197-216: window/error of 0 fall
back to k and k/2)."""

from __future__ import annotations

import dataclasses

# The stage-2 quality cutoff when no -q/-Q is given:
# numeric_limits<char>::max() (error_correct_reads_cmdline.yaggo), i.e.
# "no base is quality-protected". THE single definition — the EC CLI's
# default and the quorum driver's replay-cache packing both import it,
# so the cached qual>=cutoff plane can never drift from the cutoff the
# corrector resolves (ADVICE r5).
DEFAULT_QUAL_CUTOFF = 127


@dataclasses.dataclass(frozen=True)
class ECConfig:
    k: int
    skip: int = 1
    good: int = 2
    anchor_count: int = 3
    min_count: int = 1
    # No default in the reference CLI: unless -p is given the cutoff is
    # COMPUTED from the database (compute_poisson_cutoff,
    # error_correct_reads.cc:710-717) — models/error_correct.resolve_cutoff
    # does that; library users must pass a value explicitly.
    cutoff: int = dataclasses.field(default=None)  # type: ignore[assignment]
    qual_cutoff: int = DEFAULT_QUAL_CUTOFF  # ASCII code
    window: int = 10
    error: int = 3
    homo_trim: int | None = None
    trim_contaminant: bool = False
    no_discard: bool = False
    collision_prob: float = 0.01 / 3.0
    poisson_threshold: float = 1e-6
    # float dtype for the Poisson ambiguity test: the reference computes
    # in double; the device computes in float32. Tests set "float32" on
    # the oracle so both sides round identically at the threshold.
    poisson_dtype: str = "float64"

    def __post_init__(self):
        if self.cutoff is None:
            raise TypeError(
                "ECConfig.cutoff has no default: pass the -p value or the "
                "database-computed cutoff (models/error_correct."
                "resolve_cutoff)")

    @property
    def effective_window(self) -> int:
        return self.window if self.window else self.k

    @property
    def effective_error(self) -> int:
        return self.error if self.error else self.k // 2

    @property
    def do_homo_trim(self) -> bool:
        return self.homo_trim is not None


ERROR_CONTAMINANT = "Contaminated read"
ERROR_NO_STARTING_MER = "No high quality mer"
ERROR_HOMOPOLYMER = "Entire read is an homopolymer"
