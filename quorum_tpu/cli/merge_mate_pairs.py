"""merge_mate_pairs — interleave paired read files into one FASTQ stream.

Reference: src/merge_mate_pairs.cc. Files are taken pairwise (1st with
2nd, 3rd with 4th, ...); records are emitted alternately so a
downstream corrector run with --no-discard preserves pairing. FASTA
inputs get a fabricated quality string of '*' (merge_mate_pairs.cc:51-59).
Mismatched pair lengths abort with the reference's message
(merge_mate_pairs.cc:80-85).
"""

from __future__ import annotations

import argparse
import itertools
import sys
from typing import Iterator, Sequence

from ..io import fastq


def merge_records(files: Sequence[str]) -> Iterator[tuple[str, bytes, bytes]]:
    """Yield records alternating between each pair of files."""
    if len(files) % 2 != 0:
        raise ValueError("Must give a even number files")
    for f_even, f_odd in zip(files[0::2], files[1::2]):
        it_even = fastq.iter_records([f_even])
        it_odd = fastq.iter_records([f_odd])
        for r_even, r_odd in itertools.zip_longest(it_even, it_odd):
            if r_even is None or r_odd is None:
                raise RuntimeError("Input files are not paired reads.")
            yield r_even
            yield r_odd


def write_fastq_record(out, rec: tuple[str, bytes, bytes]) -> None:
    header, seq, qual = rec
    qual_s = qual.decode() if qual else "*" * len(seq)
    out.write(f"@{header}\n{seq.decode()}\n+\n{qual_s}\n")


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="merge_mate_pairs",
        description="Merge paired read files into one interleaved FASTQ "
                    "stream on stdout.",
    )
    p.add_argument("-o", "--output", default=None,
                   help="Output file (default stdout)")
    p.add_argument("file", nargs="+", help="Paired input files")
    return p


def main(argv=None) -> int:
    args = build_parser().parse_args(argv)
    # a streaming CLI output (stdout-equivalent), not a run artifact
    out = (sys.stdout if args.output is None
           else open(args.output, "w"))  # qlint: disable=raw-artifact-write
    try:
        for rec in merge_records(args.file):
            write_fastq_record(out, rec)
    except (ValueError, RuntimeError, OSError) as e:
        print(str(e), file=sys.stderr)
        return 1
    finally:
        out.flush()
        if out is not sys.stdout:
            out.close()
    return 0


if __name__ == "__main__":
    sys.exit(main())
