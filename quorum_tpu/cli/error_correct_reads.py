"""quorum_error_correct_reads — flag-compatible with the reference CLI
(src/error_correct_reads_cmdline.yaggo; main wiring
error_correct_reads.cc:676-742). Corrects reads from FASTQ files
against a stage-1 mer database on the TPU."""

from __future__ import annotations

import argparse
import sys

from ..io.fastq import BadReadPolicy
from ..models.ec_config import ECConfig  # noqa: F401 (re-export for users)
from ..models.error_correct import ECOptions, run_error_correct
from ..utils import faults
from ..utils import vlog as vlog_mod
from .observability import add_observability_args


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="quorum_error_correct_reads",
        description="Error correct reads from a fastq file based on the "
                    "k-mer frequencies.",
    )
    p.add_argument("-t", "--thread", type=int, default=1,
                   help="Number of threads (host I/O; device is parallel)")
    p.add_argument("-m", "--min-count", type=int, default=1,
                   help='Minimum count for a k-mer to be considered "good"')
    p.add_argument("-s", "--skip", type=int, default=1,
                   help="Number of bases to skip for start k-mer")
    p.add_argument("-g", "--good", type=int, default=2,
                   help="Number of good k-mer in a row for anchor")
    p.add_argument("-a", "--anchor-count", type=int, default=3,
                   help="Minimum count for an anchor k-mer")
    p.add_argument("-w", "--window", type=int, default=10,
                   help="Size of window")
    p.add_argument("-e", "--error", type=int, default=3,
                   help="Maximum number of error in a window")
    p.add_argument("-o", "--output", default=None, metavar="prefix",
                   help="Output file prefix (default: stdout/stderr)")
    p.add_argument("--contaminant", metavar="path",
                   help="Contaminant sequences (fasta/fastq) or k-mer "
                        "database")
    p.add_argument("--trim-contaminant", action="store_true",
                   help="Trim reads containing contaminated k-mers instead "
                        "of discarding")
    p.add_argument("--homo-trim", type=int, default=None,
                   help="Trim homo-polymer run at the 3' end")
    p.add_argument("--gzip", action="store_true", help="Gzip output file")
    p.add_argument("-M", "--no-mmap", action="store_true",
                   help="Do not memory map the input mer database")
    p.add_argument("--verify-db", choices=("full", "sample", "off"),
                   default="full",
                   help="Checksum verification of v5 databases at "
                        "load: full (default) checks every section "
                        "and the whole-file digest, sample scrubs a "
                        "random subset of entry chunks, off skips. "
                        "A bad digest refuses the load (rc 3, "
                        "integrity_errors_total)")
    p.add_argument("--presence-floor", type=int, default=0, metavar="N",
                   help="Treat mers with count < N as absent at DB "
                        "load (0 = auto: a prefiltered database "
                        "applies its declared floor, others keep "
                        "full presence). The floor is what makes a "
                        "--prefilter database byte-equivalent to the "
                        "unfiltered one (ISSUE 14)")
    p.add_argument("--apriori-error-rate", type=float, default=0.01,
                   help="Probability of a base being an error")
    p.add_argument("--poisson-threshold", type=float, default=1e-6,
                   help="Error probability threshold in Poisson test")
    p.add_argument("-p", "--cutoff", type=int, default=None,
                   help="Poisson cutoff when there are multiple choices")
    p.add_argument("-q", "--qual-cutoff-value", type=int, default=None,
                   help="Any base above with quality equal or greater is "
                        "untouched when there are multiple choices")
    p.add_argument("-Q", "--qual-cutoff-char", default=None,
                   help="Any base above with quality equal or greater is "
                        "untouched when there are multiple choices")
    p.add_argument("-d", "--no-discard", action="store_true",
                   help="Do not discard reads, output a single N")
    p.add_argument("-v", "--verbose", action="store_true", help="Be verbose")
    p.add_argument("--batch-size", type=int, default=8192,
                   help="Reads per device batch")
    p.add_argument("--devices", default="auto", metavar="N",
                   help="Correct data-parallel over N local devices "
                        "(power of two; 'all' = every local device, "
                        "'auto' = all on a real accelerator, 1 on "
                        "CPU). The table replicates per device below "
                        "the size threshold and stays row-sharded "
                        "with routed lookups above it; output is "
                        "byte-identical to --devices 1")
    p.add_argument("--render-workers", type=int, default=0, metavar="N",
                   help="Host finish/render workers behind a sequence-"
                        "numbered reorder stage (0 = auto, min(4, "
                        "cores)). Output is byte-identical for any N; "
                        "N > 1 hides the per-batch host tail behind "
                        "the device")
    p.add_argument("--profile", metavar="dir", default=None,
                   help="Write a jax.profiler trace to this directory")
    p.add_argument("--metrics", metavar="path", default=None,
                   help="Write a final metrics JSON (schema "
                        "quorum-tpu-metrics/1) to this path")
    p.add_argument("--metrics-interval", metavar="seconds", type=float,
                   default=0.0,
                   help="With --metrics: also write JSONL heartbeat "
                        "events at this period (0 = off)")
    add_observability_args(p)
    # fault tolerance (ISSUE 4)
    p.add_argument("--checkpoint-every", metavar="batches", type=int,
                   default=0,
                   help="Journal completed batches every N batches: "
                        "output streams to <prefix>.fa/.log.partial "
                        "and a kill -> --resume run is byte-identical "
                        "to an uninterrupted one (needs -o, no "
                        "--gzip; 0 = off)")
    p.add_argument("--resume", action="store_true",
                   help="Skip reads already journaled by an "
                        "interrupted --checkpoint-every run, then "
                        "finalize atomically (fresh start if no "
                        "journal)")
    p.add_argument("--on-bad-read",
                   choices=BadReadPolicy.MODES, default="abort",
                   help="Malformed-record policy: abort the run "
                        "(default), skip and count, or quarantine to "
                        "<prefix>.quarantine.fastq")
    faults.add_fault_args(p)
    from ..parallel import fleet as fleet_mod
    fleet_mod.add_fleet_args(p)
    p.add_argument("db", help="Mer database")
    p.add_argument("sequence", nargs="+", help="Input sequence")
    return p


def _run_fleet(args, opts, flt, ec_kwargs) -> None:
    """Fleet stage 2 (ISSUE 20): input files shard across hosts by
    the verified host plan; each host corrects its files one at a time
    into `<prefix>.fleet<NNNN>` segments (NNNN = the GLOBAL file
    index), and process 0 concatenates the segments in file order —
    so the merged `.fa`/`.log` are byte-identical to a single-process
    run (correction output is a pure per-read stream; batch
    composition cannot change a read's rendered bytes). Hosts with no
    files of their own still hit both barriers."""
    import dataclasses
    import os

    from ..models.error_correct import run_error_correct
    from ..parallel import fleet as fleet_mod
    from ..parallel import multihost

    owner = multihost.verified_host_plan(args.sequence)
    mine = [gi for gi, h in enumerate(owner) if h == flt.process_id]
    for gi in mine:
        seg_opts = {"output": fleet_mod.segment_prefix(args.output, gi)}
        if opts.metrics:
            # per-SEGMENT metrics file: segment indices are globally
            # disjoint (one owner per file), so no host marker needed
            root, ext = os.path.splitext(opts.metrics)
            seg_opts["metrics"] = f"{root}.seg{gi:04d}{ext}"
        with fleet_mod.host_run():
            run_error_correct(
                args.db, [args.sequence[gi]], None,
                dataclasses.replace(opts, **seg_opts), **ec_kwargs)
    flt.barrier("stage2_segments")
    if flt.process_id == 0:
        fleet_mod.fleet_merge(args.output, len(args.sequence))
    flt.barrier("stage2_merge")


def main(argv=None, db=None, prepacked=None) -> int:
    from ..utils.jaxcache import enable_cache
    enable_cache()
    args = build_parser().parse_args(argv)
    # OR, not assign: QUORUM_TPU_VERBOSE may have enabled it already
    vlog_mod.verbose = args.verbose or vlog_mod.verbose

    if args.qual_cutoff_char is not None and args.qual_cutoff_value is not None:
        print("Switches -q and -Q are conflicting.", file=sys.stderr)
        return 1
    if args.qual_cutoff_char is not None and (
            len(args.qual_cutoff_char) != 1
            or ord(args.qual_cutoff_char) > 127):
        print("The qual-cutoff-char must be one ASCII character.",
              file=sys.stderr)
        return 1
    if args.qual_cutoff_value is not None and not (
            0 <= args.qual_cutoff_value <= 127):
        print("The qual-cutoff-value must be in the range 0-127.",
              file=sys.stderr)
        return 1
    from ..models.ec_config import DEFAULT_QUAL_CUTOFF
    qual_cutoff = (
        ord(args.qual_cutoff_char) if args.qual_cutoff_char is not None
        else args.qual_cutoff_value if args.qual_cutoff_value is not None
        else DEFAULT_QUAL_CUTOFF  # numeric_limits<char>::max()
    )

    faults.setup(args.fault_plan)
    # fleet bring-up BEFORE any jax device use
    from ..parallel import fleet as fleet_mod
    try:
        flt = fleet_mod.ensure_initialized(args)
    except (RuntimeError, ValueError) as e:
        print(f"quorum_error_correct_reads: {e}", file=sys.stderr)
        return 1
    fleet_run = flt is not None and db is None and prepacked is None
    if fleet_run:
        if args.output is None:
            print("a fleet correction needs -o PREFIX (per-host "
                  "output segments merge under it)", file=sys.stderr)
            return 1
        if args.gzip:
            print("--gzip does not compose with a fleet run: "
                  "concatenated gzip members are not byte-identical "
                  "to a single-stream file", file=sys.stderr)
            return 1
    from ..parallel.tile_sharded import resolve_devices_and_batch
    try:
        devices, batch_size = resolve_devices_and_batch(
            args.devices, args.batch_size, "quorum_error_correct_reads")
    except ValueError as e:
        print(str(e), file=sys.stderr)
        return 1
    opts = ECOptions(
        output=args.output,
        gzip=args.gzip,
        contaminant=args.contaminant,
        cutoff=args.cutoff,
        apriori_error_rate=args.apriori_error_rate,
        poisson_threshold=args.poisson_threshold,
        batch_size=batch_size,
        threads=args.thread,
        devices=devices,
        render_workers=args.render_workers,
        no_mmap=args.no_mmap,
        profile=args.profile,
        metrics=args.metrics,
        metrics_interval=args.metrics_interval,
        metrics_port=args.metrics_port,
        metrics_textfile=args.metrics_textfile,
        metrics_force=args.metrics_live,
        trace_spans=args.trace_spans,
        metrics_push_url=args.metrics_push_url,
        metrics_push_interval=args.metrics_push_interval,
        alert_rules=args.alert_rules,
        checkpoint_every=args.checkpoint_every,
        resume=args.resume,
        on_bad_read=args.on_bad_read,
        verify_db=args.verify_db,
        presence_floor=args.presence_floor,
        preflight=args.preflight,
        stall_timeout_s=args.stall_timeout_s,
    )
    ec_kwargs = dict(
        qual_cutoff=qual_cutoff, skip=args.skip, good=args.good,
        anchor_count=args.anchor_count, min_count=args.min_count,
        window=args.window, error=args.error, homo_trim=args.homo_trim,
        trim_contaminant=args.trim_contaminant,
        no_discard=args.no_discard,
    )
    try:
        if fleet_run:
            _run_fleet(args, opts, flt, ec_kwargs)
        else:
            run_error_correct(
                args.db, args.sequence, None, opts,
                db=db, prepacked=prepacked, **ec_kwargs,
            )
    except (RuntimeError, ValueError, OSError) as e:
        print(str(e), file=sys.stderr)
        from ..io.checkpoint import CheckpointError, NON_RETRYABLE_RC
        from ..io.integrity import IntegrityError
        from ..utils import resources
        # resource-guard rcs (ISSUE 19): a full disk is NOT retried
        # (rc 4 — it does not empty itself between attempts); a
        # watchdog stall IS (rc 75, EX_TEMPFAIL — the next attempt
        # resumes from the journal)
        if isinstance(e, resources.ResourceExhausted):
            return resources.DISK_FULL_RC
        if isinstance(e, resources.StallError):
            return resources.STALL_RC
        # deterministic refusal (journal/config mismatch, or an
        # artifact that failed its digests): rc 3 so the driver's
        # retry loop fails fast instead of backing off
        return (NON_RETRYABLE_RC
                if isinstance(e, (CheckpointError, IntegrityError))
                else 1)
    return 0


if __name__ == "__main__":
    sys.exit(main())
