"""Shared live-observability CLI surface (ISSUE 2) and the one
startup/teardown shape every entry point runs it through (ISSUE 3).

All main CLIs expose the same four flags; one helper keeps the
surfaces (and their help text) from drifting apart. `--metrics` /
`--metrics-interval` stay per-CLI — their help genuinely differs
(the driver suffixes per-stage paths).

`observability()` is the context manager behind those flags: it
builds the registry and span tracer, starts the live exposition
(endpoint/textfile) INSIDE the error umbrella (a busy port must still
land the error document), and on exit guarantees — in order — that
the span file closes, the final metrics document lands with a status
stamp, and the endpoint port frees. Before it existed the quorum
driver, both stage CLIs, and run_error_correct each carried their own
slightly different copy of that teardown (the explicit ROADMAP gap);
`quorum-serve` is the fourth consumer.
"""

from __future__ import annotations

import argparse
import contextlib
import os


def add_observability_args(p: argparse.ArgumentParser,
                           driver: bool = False,
                           metrics: bool = False) -> None:
    """The live-exposition + span-tracing flag block. `driver=True`
    switches to the quorum driver's wording (one endpoint for all
    stages, per-stage span suffixes) and drops `--metrics-live`,
    which only the driver itself forwards to its children.
    `metrics=True` also owns the `--metrics`/`--metrics-interval`
    pair with the generic help text — the three main CLIs keep their
    own copies because their help genuinely differs (the driver
    suffixes per-stage paths); the simpler CLIs (query/histo/serve)
    share this one."""
    if metrics:
        p.add_argument("--metrics", metavar="path", default=None,
                       help="Write a final metrics JSON (schema "
                            "quorum-tpu-metrics/1) to this path")
        p.add_argument("--metrics-interval", metavar="seconds",
                       type=float, default=0.0,
                       help="With --metrics: also write JSONL "
                            "heartbeat events at this period "
                            "(0 = off)")
    p.add_argument("--metrics-port", metavar="port", type=int,
                   default=None,
                   help="Serve live Prometheus /metrics (+ /healthz) "
                        "on this port during the run; 0 = ephemeral"
                        + (". One endpoint carries the driver and "
                           "both stages under stage=... labels"
                           if driver else ""))
    p.add_argument("--metrics-textfile", metavar="path", default=None,
                   help="Atomically refresh a Prometheus textfile "
                        "here on each heartbeat"
                        + (" (shared by the driver and both stages)"
                           if driver else ""))
    p.add_argument("--metrics-push-url", metavar="url", default=None,
                   help="Periodically POST the Prometheus exposition "
                        "to this push-gateway URL (plus the final "
                        "metrics JSON at <url>/final on exit) — the "
                        "transport for fleets that cannot be scraped; "
                        "see tools/push_receiver.py"
                        + (". One stream carries the driver and both "
                           "stages" if driver else ""))
    p.add_argument("--metrics-push-interval", metavar="seconds",
                   type=float, default=0.0,
                   help="Push period for --metrics-push-url "
                        "(0 = default 5s); failed pushes back off "
                        "exponentially, capped at 30s")
    p.add_argument("--trace-spans", metavar="path", default=None,
                   help="Write hierarchical span JSONL here (plus a "
                        "Chrome trace_event twin, .trace.json)"
                        + (", suffixed .stage1/.stage2 per stage"
                           if driver else ""))
    p.add_argument("--alert-rules", metavar="path", default=None,
                   help="Alert rules JSON evaluated against the live "
                        "registry on the heartbeat cadence "
                        "(threshold / rate-over-window / absence / "
                        "SLO burn-rate; merged over the built-in "
                        "defaults by name). Firing rules land "
                        "structured 'alert' events and "
                        "alerts_firing{rule=} gauges"
                        + ("; forwarded to both stages" if driver
                           else ""))
    p.add_argument("--preflight", choices=("strict", "warn", "off"),
                   default="warn",
                   help="Disk preflight before work starts: compare "
                        "estimated output/checkpoint bytes against "
                        "free space on the target filesystems. "
                        "strict refuses (rc 4, not retried), warn "
                        "(default) prints one line per short "
                        "filesystem, off skips the check"
                        + ("; forwarded to both stages" if driver
                           else ""))
    p.add_argument("--stall-timeout-s", metavar="seconds", type=float,
                   default=0.0,
                   help="Offline stall watchdog: abort a stage whose "
                        "batch cursor stops advancing for this long "
                        "(flight dump kind 'stall', retryable rc 75 "
                        "so a driver retry resumes from checkpoint); "
                        "0 = off"
                        + ("; forwarded to both stages" if driver
                           else ""))
    if not driver:
        p.add_argument("--metrics-live", action="store_true",
                       help="Force a live metrics registry even with "
                            "no output path, so a parent process's "
                            "exposition endpoint sees this stage "
                            "(the quorum driver forwards this with "
                            "--metrics-port)")


class ObservabilitySession:
    """What `observability()` yields: the registry and tracer, plus
    the knobs a run uses to steer the final document.

    * `status` — the stamp written on a CLEAN exit ("ok" by default);
      entry points that report failure through a return code instead
      of an exception set it to "error" before leaving the block. An
      exception always stamps "error", whatever `status` says.
    * `at_exit(fn)` — `fn(registry)` runs right before the final
      write on EVERY exit path (success or error); the quorum driver
      derives its compile-cache-miss gauge here so a crashed run
      still reports it.
    """

    def __init__(self, registry, tracer):
        self.registry = registry
        self.tracer = tracer
        self.server = None  # exposition endpoint, once started
        self.pusher = None  # MetricsPusher, with --metrics-push-url
        self.alerts = None  # AlertEngine (telemetry/alerts.py)
        self.flight = None  # FlightRecorder (telemetry/flight.py)
        self.quality = None  # QualityScorecard (telemetry/quality.py)
        self.status: str | None = None
        self._at_exit: list = []
        self._profile: str | None = None

    def at_exit(self, fn) -> None:
        self._at_exit.append(fn)

    def _record_devtrace(self) -> bool:
        """Device-truth telemetry (ISSUE 10): parse the `--profile`
        directory the run just wrote (the jax.profiler trace exits
        with the body, so it is complete here) and land the
        device-kernel attribution in the registry. Returns True when
        metrics were recorded (the caller may need to re-write an
        already-written final document)."""
        if not self._profile or not self.registry.enabled:
            return False
        try:
            from ..telemetry import devtrace
            return devtrace.record_profile_metrics(self.registry,
                                                   self._profile)
        except Exception:  # noqa: BLE001 - telemetry never kills runs
            return False

    def _finalize(self, ok: bool) -> None:
        reg = self.registry
        if not reg.enabled:
            return
        if self.quality is not None:
            # close the last (possibly short) rate window BEFORE the
            # alert engine's final evaluate: a drift/contam firing
            # transition at close still lands its alert event and
            # dump: true flight capture while the sinks are open
            try:
                self.quality.tick(final=True)
            except Exception:  # noqa: BLE001 - telemetry never masks exits
                pass
        if self.alerts is not None:
            # stop the ticker BEFORE the final write: a closed engine
            # never lands another event, so nothing can reopen (and
            # truncate) the event sink after the registry closes it
            try:
                self.alerts.close()
            except Exception:  # noqa: BLE001 - alerts never mask exits
                pass
        for fn in self._at_exit:
            try:
                fn(reg)
            except Exception:  # noqa: BLE001 - exit hooks never mask exits
                pass
        if self.flight is not None:
            # land ring evictions in flight_events_dropped_total
            # BEFORE the final write, so the document carries them
            try:
                self.flight.flush_drop_counter()
            except Exception:  # noqa: BLE001 - forensics never mask exits
                pass
        recorded = self._record_devtrace()
        if not ok:
            reg.set_meta(status="error")
            reg.write()
        elif reg.meta.get("status") is None:
            # a run that already stamped + wrote (run_error_correct's
            # success path) is left alone — no second write...
            reg.set_meta(status=self.status or "ok")
            reg.write()
        elif recorded:
            # ...unless the post-run devtrace parse added metrics the
            # body's own write predates — refresh the document so the
            # device attribution lands in it (atomic replace)
            reg.write()


@contextlib.contextmanager
def observability(metrics: str | None = None, interval: float = 0.0,
                  port: int | None = None, textfile: str | None = None,
                  live: bool = False, trace_spans: str | None = None,
                  profile: str | None = None,
                  push_url: str | None = None,
                  push_interval: float = 0.0,
                  alert_rules: str | None = None,
                  watch_paths=(),
                  stall_timeout_s: float = 0.0,
                  **meta):
    """The one observability lifecycle (ISSUE 3 satellite): registry +
    tracer up front, exposition started inside the umbrella, and a
    teardown that runs on every exit — span close, status-stamped
    final write (skipped when the body already wrote), endpoint
    close. `meta` seeds `registry.set_meta` (stage=..., etc.).

    `profile` (the run's `--profile` trace directory): the span
    tracer's Chrome-trace twin is ALSO exported into it as
    `spans.trace.json` (one directory carries the XLA device timeline
    and the host span timeline side by side — load both in Perfetto),
    and on exit the trace is parsed for DEVICE-truth kernel
    attribution (telemetry/devtrace.py): `device_kernel_us` and
    friends land in the registry, with `meta.profile` declaring the
    surface for tools/metrics_check.py.

    `push_url` (`--metrics-push-url`): a MetricsPusher periodically
    POSTs the live exposition there and terminal-flushes the final
    document on exit (telemetry/push.py) — the transport for fleets
    that cannot be scraped.

    `alert_rules` (`--alert-rules`): every enabled registry gets an
    AlertEngine (telemetry/alerts.py) — built-in rules, plus the
    serve SLO set when meta declares stage="serve", plus the file's
    rules (a bad file is reported loudly and counted, never fatal) —
    attached at the heartbeat cadence and closed BEFORE the final
    write so the document carries the end-of-run alert state.

    `watch_paths` / `stall_timeout_s` (ISSUE 19): the resource-guard
    frame (utils/resources.py). Watch paths (the run's output /
    checkpoint / metrics targets) arm the disk/RSS monitor ticker —
    `disk_free_bytes{path=}` gauges plus the standing watermark alert
    rules (DEFAULT_RESOURCE_RULES, appended only when the monitor is
    live); a positive stall timeout arms the offline stall watchdog
    the stage loops beat via resources.watchdog_beat. The frame also
    routes the writer degradation ladder's counters to this registry;
    it stacks/restores exactly like the integrity registry below.

    Typical shape::

        with observability(args.metrics, args.metrics_interval,
                           port=args.metrics_port, ...) as obs:
            rc = run(obs.registry, obs.tracer)
            if rc != 0:
                obs.status = "error"
    """
    from ..io import integrity
    from ..telemetry import flight as flight_mod
    from ..telemetry import registry_for, tracer_for
    from ..telemetry import export as export_mod

    reg = registry_for(metrics, interval,
                       force=(port is not None or bool(textfile)
                              or live or bool(push_url)))
    if meta:
        reg.set_meta(**meta)
    if profile and reg.enabled:
        # declares the devtrace surface: metrics_check requires the
        # device-kernel names whenever a document carries this
        reg.set_meta(profile=profile)
    if reg.enabled:
        # which autotune profile steers this run's levers (ISSUE 11):
        # every document says where its defaults came from, and
        # metrics_check validates the stamp
        try:
            from ..ops import tuning
            ppath = tuning.active_profile_path()
            if ppath:
                reg.set_meta(autotune_profile=ppath)
        except Exception:  # noqa: BLE001 - telemetry never kills runs
            pass
    tracer = tracer_for(trace_spans)
    obs = ObservabilitySession(reg, tracer)
    obs._profile = profile
    # the flight recorder (ISSUE 16): always-on in every entry point.
    # Taps point the registry's event sink and the span tracer at the
    # ring (no new call sites); install() makes it the process-current
    # recorder so serve internals / alert rules / SIGUSR1 reach it.
    obs.flight = flight_mod.FlightRecorder(
        reg, out_path=flight_mod.default_out_path(metrics))
    flight_token = flight_mod.install(obs.flight)
    if obs.flight.enabled:
        if reg.enabled:
            reg.flight = obs.flight
            # declares the surface: metrics_check requires the
            # flight counters whenever a document carries this
            reg.set_meta(flight=True)
        if tracer.enabled:
            tracer.flight = obs.flight
    if reg.enabled:
        # the quality scorecard (telemetry/quality.py, ISSUE 17):
        # installed BEFORE the alert engine so its exporter runs
        # first on each heartbeat — the engine's evaluate sees the
        # freshly-closed window's gauges, not last window's. Hooks
        # reg.quality (the final document's `quality` section) and
        # pre-creates the quality_* gauges at quiet values, so the
        # drift rules below stay silent until a data window closes.
        from ..telemetry import quality as quality_mod
        obs.quality = quality_mod.QualityScorecard(reg)
        # the alert engine (telemetry/alerts.py): built-in rules plus
        # the input-drift set (quiet off the data plane), plus the
        # serve SLO set for serve registries, overridden by the
        # --alert-rules file. A bad file costs a loud stderr line and
        # a counted rule error, never the run — but the defaults keep
        # watching either way.
        from ..telemetry import alerts as alerts_mod
        rule_sets = [alerts_mod.DEFAULT_RULES,
                     alerts_mod.DEFAULT_QUALITY_RULES]
        if meta.get("stage") == "serve":
            rule_sets.append(alerts_mod.DEFAULT_SERVE_RULES)
        if watch_paths:
            # the resource watermark surface (ISSUE 19): only when
            # the monitor below will actually publish the gauges the
            # threshold rules read
            rule_sets.append(alerts_mod.DEFAULT_RESOURCE_RULES)
        if alert_rules:
            try:
                rule_sets.append(alerts_mod.load_rules(alert_rules))
                reg.set_meta(alert_rules_file=alert_rules)
            except (OSError, ValueError) as e:
                import sys as _sys
                print(f"quorum-tpu: ignoring --alert-rules "
                      f"{alert_rules}: {e}", file=_sys.stderr)
                reg.counter("alert_rule_errors_total").inc()
                reg.event("alert_rule_error", error=str(e))
        obs.alerts = alerts_mod.AlertEngine(
            reg, alerts_mod.merge_rules(*rule_sets))
        obs.alerts.attach(period_s=(interval if interval
                                    and interval > 0 else 5.0))
    # artifact loaders (db_format/checkpoint) run far below the entry
    # points, so the run's registry is installed ambiently for their
    # verification telemetry (integrity_errors_total / bytes-verified
    # counters + integrity_error events); nested observability()
    # blocks — the driver's stage children — stack and restore
    prev_integrity = integrity.install_registry(
        reg if reg.enabled else None)
    # the resource-guard frame (ISSUE 19): same stack/restore
    # discipline — the degradation ladder, disk/RSS monitor, and
    # stall watchdog are armed for exactly this lifecycle
    from ..utils import resources as resources_mod
    resources_token = resources_mod.install(
        reg, watch_paths=watch_paths, stall_timeout_s=stall_timeout_s,
        interval_s=(interval if interval and interval > 0 else 5.0))
    try:
        try:
            obs.server = export_mod.start_exposition(
                reg, port, textfile, period=interval)
            if push_url:
                from ..telemetry.push import DEFAULT_PERIOD_S
                from ..telemetry.push import MetricsPusher
                obs.pusher = MetricsPusher(
                    reg, push_url,
                    period_s=(push_interval if push_interval
                              and push_interval > 0
                              else DEFAULT_PERIOD_S))
            yield obs
        except BaseException as e:
            # the black box's primary trigger: the dump (ring, all-
            # thread stacks, levers, registry snapshot) lands BEFORE
            # the final write so flight_dumps_total rides the error
            # document; forensics must never mask the real failure
            try:
                obs.flight.dump("exception", detail=repr(e))
            except Exception:  # noqa: BLE001 - never mask the exit
                pass
            obs._finalize(ok=False)
            raise
        if obs.status == "error":
            # entry points report many failures through a return code
            # (their catch blocks map RuntimeError/OSError to rc 1) —
            # an error-status exit is a dying run all the same, and
            # the ring still holds the fault/exception events that
            # explain it
            try:
                obs.flight.dump("error", detail="run exited with "
                                                "status=error")
            except Exception:  # noqa: BLE001 - never mask the status
                pass
        obs._finalize(ok=True)
    finally:
        resources_mod.uninstall(resources_token)
        flight_mod.uninstall(flight_token)
        integrity.install_registry(prev_integrity)
        # span + endpoint teardown on EVERY exit: the Chrome trace of
        # an interrupted run is exactly when it's needed, and the
        # port must free for the next stage/run
        tracer.close()
        if profile and tracer.enabled:
            try:
                os.makedirs(profile, exist_ok=True)
                tracer.write_chrome_trace(
                    os.path.join(profile, "spans.trace.json"))
            except OSError:  # pragma: no cover - unwritable profile dir
                pass
        if obs.pusher is not None:
            # terminal flush AFTER the status-stamped final write, so
            # the pushed document is the one on disk; never raises
            try:
                obs.pusher.close(
                    final_doc=reg.as_dict() if reg.enabled else None)
            except Exception:  # noqa: BLE001 - push never kills runs
                pass
        if obs.server is not None:
            obs.server.close()
