"""Shared live-observability CLI flags (ISSUE 2).

All three main CLIs expose the same four flags; one helper keeps the
surfaces (and their help text) from drifting apart. `--metrics` /
`--metrics-interval` stay per-CLI — their help genuinely differs
(the driver suffixes per-stage paths).
"""

from __future__ import annotations

import argparse


def add_observability_args(p: argparse.ArgumentParser,
                           driver: bool = False) -> None:
    """The live-exposition + span-tracing flag block. `driver=True`
    switches to the quorum driver's wording (one endpoint for all
    stages, per-stage span suffixes) and drops `--metrics-live`,
    which only the driver itself forwards to its children."""
    p.add_argument("--metrics-port", metavar="port", type=int,
                   default=None,
                   help="Serve live Prometheus /metrics (+ /healthz) "
                        "on this port during the run; 0 = ephemeral"
                        + (". One endpoint carries the driver and "
                           "both stages under stage=... labels"
                           if driver else ""))
    p.add_argument("--metrics-textfile", metavar="path", default=None,
                   help="Atomically refresh a Prometheus textfile "
                        "here on each heartbeat"
                        + (" (shared by the driver and both stages)"
                           if driver else ""))
    p.add_argument("--trace-spans", metavar="path", default=None,
                   help="Write hierarchical span JSONL here (plus a "
                        "Chrome trace_event twin, .trace.json)"
                        + (", suffixed .stage1/.stage2 per stage"
                           if driver else ""))
    if not driver:
        p.add_argument("--metrics-live", action="store_true",
                       help="Force a live metrics registry even with "
                            "no output path, so a parent process's "
                            "exposition endpoint sees this stage "
                            "(the quorum driver forwards this with "
                            "--metrics-port)")
