"""quorum-serve — the persistent correction service (ISSUE 3).

Loads a stage-1 mer database once, warms the corrector, and serves
`POST /correct` with dynamic batching until drained (SIGTERM or
`POST /quiesce`). The correction flags mirror
`quorum_error_correct_reads` so a serve deployment and an offline run
of the same flags produce byte-identical corrections; the final
metrics document lands through the same observability() lifecycle as
every other CLI.
"""

from __future__ import annotations

import argparse
import signal
import sys

from ..utils import faults
from ..utils import vlog as vlog_mod
from ..utils.vlog import vlog
from .observability import add_observability_args, observability


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="quorum-serve",
        description="Serve quorum error correction over HTTP: POST "
                    "FASTQ text to /correct, scrape /metrics, drain "
                    "with SIGTERM or POST /quiesce.",
    )
    # correction surface (quorum_error_correct_reads parity)
    p.add_argument("-m", "--min-count", type=int, default=1,
                   help='Minimum count for a k-mer to be considered "good"')
    p.add_argument("-s", "--skip", type=int, default=1,
                   help="Number of bases to skip for start k-mer")
    p.add_argument("-g", "--good", type=int, default=2,
                   help="Number of good k-mer in a row for anchor")
    p.add_argument("-a", "--anchor-count", type=int, default=3,
                   help="Minimum count for an anchor k-mer")
    p.add_argument("-w", "--window", type=int, default=10,
                   help="Size of window")
    p.add_argument("-e", "--error", type=int, default=3,
                   help="Maximum number of error in a window")
    p.add_argument("--contaminant", metavar="path",
                   help="Contaminant sequences (fasta/fastq) or k-mer "
                        "database")
    p.add_argument("--trim-contaminant", action="store_true",
                   help="Trim reads containing contaminated k-mers "
                        "instead of discarding")
    p.add_argument("--homo-trim", type=int, default=None,
                   help="Trim homo-polymer run at the 3' end")
    p.add_argument("-M", "--no-mmap", action="store_true",
                   help="Do not memory map the input mer database")
    p.add_argument("--verify-db", choices=("full", "sample", "off"),
                   default="full",
                   help="Checksum verification when loading v5 "
                        "databases (boot, POST /reload, watchdog "
                        "rebuilds): full (default) checks every "
                        "section, sample scrubs a random subset of "
                        "entry chunks (latency-bounded reloads), off "
                        "skips. A bad digest fails the build — a "
                        "reload rolls back to the old engine")
    p.add_argument("--apriori-error-rate", type=float, default=0.01,
                   help="Probability of a base being an error")
    p.add_argument("--poisson-threshold", type=float, default=1e-6,
                   help="Error probability threshold in Poisson test")
    p.add_argument("-p", "--cutoff", type=int, default=None,
                   help="Poisson cutoff when there are multiple choices")
    p.add_argument("-q", "--qual-cutoff-value", type=int, default=None,
                   help="Any base above with quality equal or greater is "
                        "untouched when there are multiple choices")
    p.add_argument("-Q", "--qual-cutoff-char", default=None,
                   help="Any base above with quality equal or greater is "
                        "untouched when there are multiple choices")
    p.add_argument("-d", "--no-discard", action="store_true",
                   help="Do not discard reads, output a single N")
    p.add_argument("-v", "--verbose", action="store_true", help="Be verbose")
    # serving surface
    p.add_argument("--host", default="127.0.0.1",
                   help="Bind address (default loopback; 0.0.0.0 to "
                        "serve off-machine)")
    p.add_argument("--port", type=int, default=8100,
                   help="Listen port (default 8100; 0 = ephemeral)")
    p.add_argument("--max-batch", type=int, default=1024,
                   help="Reads per device batch; also the padded row "
                        "capacity every batch compiles at (default 1024)")
    p.add_argument("--max-wait-ms", type=float, default=5.0,
                   help="How long the dispatcher waits to coalesce "
                        "more requests into a batch (default 5)")
    p.add_argument("--queue-requests", type=int, default=64,
                   help="Bounded request-queue capacity; a full queue "
                        "answers 429 + Retry-After (default 64)")
    p.add_argument("--deadline-ms", type=float, default=None,
                   help="Default per-request deadline (overridable "
                        "per request); expired requests answer 504")
    p.add_argument("--drain-grace-s", type=float, default=30.0,
                   help="Max seconds a drain waits for in-flight "
                        "batches (default 30)")
    p.add_argument("--max-consecutive-failures", metavar="n", type=int,
                   default=5,
                   help="After n device-step failures in a row, "
                        "/healthz answers 503 (unhealthy) so load "
                        "balancers eject the replica; any success "
                        "resets the streak (default 5; 0 = never)")
    p.add_argument("--warmup-lengths", metavar="L1,L2,...", default=None,
                   help="Comma-separated read lengths to pre-compile "
                        "before listening (one device step per "
                        "length bucket)")
    # resilience surface (ISSUE 7)
    p.add_argument("--step-timeout-ms", metavar="ms", type=float,
                   default=0,
                   help="Engine-step watchdog: a device step "
                        "exceeding this budget fails only its batch "
                        "and the warm engine is rebuilt (DB reload + "
                        "per-bucket recompile, engine_restarts_total)"
                        " instead of wedging the process. Must "
                        "comfortably exceed the worst warm step AND "
                        "any cold compile not pre-paid by "
                        "--warmup-lengths (default 0 = off)")
    p.add_argument("--max-hedges", metavar="n", type=int, default=8,
                   help="When a failed batch bisects ambiguously, "
                        "re-run up to n surviving requests solo per "
                        "failed batch (hedges_total) so an innocent "
                        "batchmate never eats a 500 (default 8; "
                        "0 = off)")
    p.add_argument("--quota-rps", metavar="r", type=float, default=0,
                   help="Per-client token-bucket quota: r requests/s "
                        "per X-Quorum-Client identity, 429 + "
                        "Retry-After past it (quota_rejections_total)"
                        ". Requests without the header are not "
                        "quota-limited (default 0 = off)")
    p.add_argument("--quota-burst", metavar="n", type=float, default=0,
                   help="Token-bucket capacity per client (default "
                        "0 = max(1, --quota-rps))")
    p.add_argument("--interactive-weight", metavar="w", type=int,
                   default=4,
                   help="Priority lanes: pop w interactive requests "
                        "(X-Quorum-Priority: interactive, the "
                        "default lane) for every bulk one while both "
                        "lanes hold work (default 4)")
    p.add_argument("--no-reload", action="store_true",
                   help="Disable POST /reload (hot DB/contaminant/"
                        "config swap); it answers 501")
    # live ingestion tier (ISSUE 18). Geometry flags are long-only:
    # -m/-s/-q already mean min-count/skip/qual-cutoff-value on this
    # CLI (quorum_error_correct_reads parity), so the stage-1 short
    # spellings cannot be reused here.
    p.add_argument("--ingest", action="store_true",
                   help="Run the live ingestion tier: POST /ingest "
                        "streams FASTQ chunks into a mutable counting "
                        "table while /correct serves from the last "
                        "sealed epoch snapshot (the db positional is "
                        "omitted; the service boots on the live "
                        "table, resumed from --live-dir if a "
                        "checkpoint exists)")
    p.add_argument("--live-dir", metavar="dir", default=None,
                   help="Directory for epoch snapshots and the "
                        "live-table checkpoint (required with "
                        "--ingest)")
    p.add_argument("--ingest-mer-len", metavar="k", type=int,
                   default=24,
                   help="Live table mer length (default 24)")
    p.add_argument("--ingest-bits", metavar="b", type=int, default=7,
                   help="Live table counter bits (default 7)")
    p.add_argument("--ingest-size", metavar="size", default="16M",
                   help="Initial live table capacity in entries "
                        "(k/M/G suffixes; grows by doubling like the "
                        "offline build; default 16M)")
    p.add_argument("--ingest-qual-thresh", metavar="q", type=int,
                   default=None,
                   help="Quality threshold for a high-quality mer "
                        "(stage-1 --min-qual-value; required with "
                        "--ingest)")
    p.add_argument("--epoch-reads", metavar="n", type=int, default=0,
                   help="Seal + swap a new epoch snapshot after every "
                        "n ingested reads (0 = only --epoch-interval-s"
                        " and POST /epoch trigger epochs)")
    p.add_argument("--epoch-interval-s", metavar="s", type=float,
                   default=0.0,
                   help="Seal + swap a new epoch at most every s "
                        "seconds when new reads arrived (0 = off)")
    p.add_argument("--live-checkpoint-every", metavar="n", type=int,
                   default=0,
                   help="Commit a crash-safe live-table checkpoint "
                        "(table planes + ingest cursor) every n "
                        "chunks; a killed service resumes without "
                        "re-ingesting (default 0 = only at drain)")
    p.add_argument("--live-floor-initial", metavar="f", type=int,
                   default=1,
                   help="Presence floor applied to EARLY epoch "
                        "snapshots, when coverage is too thin to "
                        "trust once-seen mers (default 1 = off)")
    p.add_argument("--live-floor-final", metavar="f", type=int,
                   default=1,
                   help="Presence floor once coverage reaches "
                        "--live-floor-ramp (default 1)")
    p.add_argument("--live-floor-ramp", metavar="cov", type=float,
                   default=0.0,
                   help="Mean HQ coverage at which the epoch floor "
                        "finishes ramping from initial to final "
                        "(0 = floor pinned at final)")
    p.add_argument("--ingest-queue-chunks", metavar="n", type=int,
                   default=16,
                   help="Bounded ingest chunk queue; a full queue "
                        "answers 429 + Retry-After (default 16)")
    # observability (same surface as the other CLIs; --metrics
    # writes the final document on drain)
    add_observability_args(p, metrics=True)
    faults.add_fault_args(p)
    p.add_argument("db", nargs="?", default=None,
                   help="Mer database (omitted with --ingest: the "
                        "service boots on the live table)")
    return p


def main(argv=None) -> int:
    from ..utils.jaxcache import enable_cache
    enable_cache()
    args = build_parser().parse_args(argv)
    # OR, not assign: QUORUM_TPU_VERBOSE may have enabled it already
    vlog_mod.verbose = args.verbose or vlog_mod.verbose
    faults.setup(args.fault_plan)

    if args.ingest:
        if args.db is not None:
            print("--ingest boots on the live table; drop the db "
                  "argument (use POST /ingest to feed it).",
                  file=sys.stderr)
            return 1
        if not args.live_dir:
            print("--ingest requires --live-dir (epoch snapshots and "
                  "the live-table checkpoint live there).",
                  file=sys.stderr)
            return 1
        if args.ingest_qual_thresh is None:
            print("--ingest requires --ingest-qual-thresh (the "
                  "stage-1 min-qual-value).", file=sys.stderr)
            return 1
    elif args.db is None:
        print("A mer database is required (or --ingest).",
              file=sys.stderr)
        return 1
    if args.qual_cutoff_char is not None and args.qual_cutoff_value is not None:
        print("Switches -q and -Q are conflicting.", file=sys.stderr)
        return 1
    if args.qual_cutoff_char is not None and (
            len(args.qual_cutoff_char) != 1
            or ord(args.qual_cutoff_char) > 127):
        print("The qual-cutoff-char must be one ASCII character.",
              file=sys.stderr)
        return 1
    if args.qual_cutoff_value is not None and not (
            0 <= args.qual_cutoff_value <= 127):
        print("The qual-cutoff-value must be in the range 0-127.",
              file=sys.stderr)
        return 1
    qual_cutoff = (
        ord(args.qual_cutoff_char) if args.qual_cutoff_char is not None
        else args.qual_cutoff_value if args.qual_cutoff_value is not None
        else 127  # numeric_limits<char>::max()
    )
    warmup_lengths: list[int] = []
    if args.warmup_lengths:
        try:
            warmup_lengths = [int(x) for x in
                              args.warmup_lengths.split(",") if x]
        except ValueError:
            print(f"Bad --warmup-lengths {args.warmup_lengths!r}",
                  file=sys.stderr)
            return 1

    # the service is its own /metrics endpoint, so the registry must
    # be live even without --metrics (live=True); --metrics-port
    # additionally starts the standalone exposition endpoint the
    # other CLIs use, for scrapers that must not share the serving
    # port's queue
    # the resource-guard frame (ISSUE 19): watch the live-ingest
    # snapshot/checkpoint directory (the service's only durable
    # writes) for the watermark alerts
    watch = [p for p in (getattr(args, "live_dir", None),
                         args.metrics) if p]
    with observability(args.metrics, args.metrics_interval,
                       port=args.metrics_port,
                       textfile=args.metrics_textfile,
                       live=True, trace_spans=args.trace_spans,
                       push_url=args.metrics_push_url,
                       push_interval=args.metrics_push_interval,
                       alert_rules=args.alert_rules,
                       watch_paths=watch,
                       stage="serve") as obs:
        try:
            rc = _serve(args, qual_cutoff, warmup_lengths, obs)
        except (RuntimeError, ValueError, OSError) as e:
            print(str(e), file=sys.stderr)
            obs.status = "error"
            from ..utils import resources
            if isinstance(e, resources.ResourceExhausted):
                return resources.DISK_FULL_RC
            return 1
        if rc != 0:
            obs.status = "error"
        return rc


def _make_engine(args, qual_cutoff: int, reg, tracer,
                 db: str | None = None, verify: str | None = None,
                 **over):
    """Construct a CorrectionEngine from the CLI flags, with optional
    reload-time overrides (`db`, `contaminant`, `cutoff`) and an
    explicit `verify` mode (swap paths pin it; see _swap_verify).
    Looked up through the package attribute so tests can stub the
    engine."""
    from .. import serve as serve_pkg
    return serve_pkg.CorrectionEngine(
        db or args.db,
        cutoff=over.get("cutoff", args.cutoff),
        qual_cutoff=qual_cutoff,
        skip=args.skip, good=args.good, anchor_count=args.anchor_count,
        min_count=args.min_count, window=args.window, error=args.error,
        homo_trim=args.homo_trim, trim_contaminant=args.trim_contaminant,
        no_discard=args.no_discard,
        contaminant=over.get("contaminant", args.contaminant),
        apriori_error_rate=args.apriori_error_rate,
        poisson_threshold=args.poisson_threshold, no_mmap=args.no_mmap,
        rows=args.max_batch, verify_db=verify or args.verify_db,
        registry=reg, tracer=tracer)


def _swap_verify(args) -> str:
    """The verification mode for candidate tables about to SWAP into
    a running server (POST /reload, live-epoch swaps): a corrupted
    table must not replace a healthy serving one, so even
    --verify-db=off is raised to sampled scrubbing here (the ROADMAP
    verify-at-swap item) — boot keeps the user's choice."""
    return "sample" if args.verify_db == "off" else args.verify_db


def _serve(args, qual_cutoff: int, warmup_lengths: list[int], obs) -> int:
    from ..io import db_format
    from ..serve import (CorrectionServer, DynamicBatcher,
                         TokenBucketQuota)

    reg = obs.registry
    # a serve run that drains before its first request must still
    # write a gateable document (ingest-only warm-ups make that a
    # normal lifecycle, not an edge case)
    from ..telemetry.contract import precreate_serve_metrics
    precreate_serve_metrics(reg)

    # the config actually serving: starts at the boot flags, advanced
    # by every successful /reload (and, in --ingest mode, every epoch
    # swap) — the watchdog's rebuild must reproduce the SERVING
    # config, not silently revert to boot
    effective = {"db": args.db, "over": {}}

    dispatcher = None
    if args.ingest:
        import os

        from ..ops.poisson import compute_poisson_cutoff
        from ..serve.ingest import IngestDispatcher
        from ..serve.live_table import (LiveTableCheckpoint,
                                        load_or_create)
        from ..utils import sizes

        os.makedirs(args.live_dir, exist_ok=True)
        ckpt = LiveTableCheckpoint(args.live_dir)
        table, cursor = load_or_create(
            ckpt, args.ingest_mer_len, args.ingest_bits,
            sizes.parse_size(args.ingest_size),
            args.ingest_qual_thresh)
        if cursor >= 0:
            vlog("Resumed live table from checkpoint: cursor ",
                 cursor, " (", table.stats.reads, " reads)")

        def _epoch_engine(db_path: str, poisson: dict):
            """Build the engine for a freshly sealed epoch snapshot:
            re-resolve the cutoff from the ACCUMULATED stats (the
            same Poisson parameterization the offline pipeline uses,
            with -p still winning), sample-verify the candidate
            (_swap_verify), and warm it to the serving engine's
            length buckets so the swap costs no cold compile."""
            cutoff = args.cutoff
            if cutoff is None:
                cutoff = compute_poisson_cutoff(
                    int(poisson["distinct_hq"]),
                    int(poisson["total_hq"]),
                    args.apriori_error_rate / 3.0,
                    args.poisson_threshold / args.apriori_error_rate,
                ) or 1  # an empty/thin boot table still serves
            cur = (dispatcher.batcher.current_engine()
                   if dispatcher is not None
                   and dispatcher.batcher is not None else None)
            eng = _make_engine(args, qual_cutoff, reg, obs.tracer,
                               db=db_path, verify=_swap_verify(args),
                               cutoff=cutoff)
            eng.warmup(getattr(cur, "warm_lengths", ())
                       or warmup_lengths)
            # the watchdog's rebuild must reproduce THIS epoch
            effective["db"] = db_path
            effective["over"] = dict(effective["over"],
                                     cutoff=cutoff)
            return eng

        dispatcher = IngestDispatcher(
            table, ckpt, _epoch_engine, live_dir=args.live_dir,
            epoch_reads=args.epoch_reads,
            epoch_interval_s=args.epoch_interval_s,
            checkpoint_every=args.live_checkpoint_every,
            queue_chunks=args.ingest_queue_chunks,
            floor_initial=args.live_floor_initial,
            floor_final=args.live_floor_final,
            floor_ramp=args.live_floor_ramp,
            cursor=cursor, registry=reg, tracer=obs.tracer)
        # epoch 0: the boot engine is a sealed snapshot of whatever
        # the (possibly resumed) live table holds right now
        engine = dispatcher.boot_epoch()
    else:
        engine = _make_engine(args, qual_cutoff, reg, obs.tracer)
    if warmup_lengths:
        vlog("Warming ", len(warmup_lengths), " length buckets")
        engine.warmup(warmup_lengths)

    def _engine_factory(old):
        """Watchdog rebuild: the EFFECTIVE db/config (boot flags plus
        any /reload overrides), re-warmed to the hung engine's length
        buckets so the replacement answers the next request without a
        cold compile. `warm_lengths` is a lock-free snapshot — the
        hung step may hold the old engine's lock forever."""
        eng = _make_engine(args, qual_cutoff, reg, obs.tracer,
                           db=effective["db"], **effective["over"])
        eng.warmup(getattr(old, "warm_lengths", ()) or warmup_lengths)
        return eng

    batcher = DynamicBatcher(
        engine, max_batch=args.max_batch,
        max_wait_ms=args.max_wait_ms,
        queue_requests=args.queue_requests,
        max_consecutive_failures=args.max_consecutive_failures,
        step_timeout_ms=args.step_timeout_ms or None,
        engine_factory=_engine_factory,
        max_hedges=args.max_hedges,
        interactive_weight=args.interactive_weight,
        registry=reg)

    def _engine_builder(params: dict):
        """POST /reload: validate the new DB with the PR-4 header/
        k/bits reuse check BEFORE building, then return a warm engine
        for the batcher to swap in. Any raise here rolls the reload
        back (the server never swaps)."""
        cur = batcher.current_engine()
        db = params.get("db") or getattr(cur, "db_path", args.db)
        header = db_format.read_header(db)  # raises on corrupt/foreign
        cfg = getattr(cur, "cfg", None)
        meta = getattr(cur, "meta", None)
        if cfg is not None and meta is not None:
            if (header.get("key_len") != 2 * cfg.k
                    or header.get("bits") != meta.bits):
                raise ValueError(
                    f"reload refused: {db} is k="
                    f"{header.get('key_len', 0) // 2}/bits="
                    f"{header.get('bits')} but the serving engine is "
                    f"k={cfg.k}/bits={meta.bits}")
        over = dict(effective["over"])
        over.update({k: params[k] for k in ("contaminant", "cutoff")
                     if k in params})
        # candidate tables are verified BEFORE they can swap in, even
        # under --verify-db=off (the verify-at-swap fix)
        eng = _make_engine(args, qual_cutoff, reg, obs.tracer,
                           db=db, verify=_swap_verify(args), **over)
        eng.warmup(getattr(cur, "warm_lengths", ()) or warmup_lengths)
        # the build succeeded, so the server WILL swap it in (the
        # engine's rows always match --max-batch): a later watchdog
        # rebuild must reproduce this config
        effective["db"] = db
        effective["over"] = over
        return eng

    quota = None
    if args.quota_rps and args.quota_rps > 0:
        quota = TokenBucketQuota(args.quota_rps,
                                 burst=args.quota_burst or None)
    # meta declares the enabled resilience features so metrics_check
    # can require their counters in the final document
    reg.set_meta(max_hedges=args.max_hedges)
    if args.step_timeout_ms:
        reg.set_meta(step_timeout_ms=args.step_timeout_ms)
    if quota is not None:
        reg.set_meta(quota_rps=args.quota_rps)
    if not args.no_reload:
        reg.set_meta(reload=True)
    if dispatcher is not None:
        # metrics_check requires the ingest/epoch counter surface in
        # the final document once this is declared
        reg.set_meta(live_ingest=True,
                     ingest_k=args.ingest_mer_len,
                     epoch_reads=args.epoch_reads,
                     live_floor_initial=args.live_floor_initial,
                     live_floor_final=args.live_floor_final,
                     live_floor_ramp=args.live_floor_ramp)
    server = CorrectionServer(
        batcher, host=args.host, port=args.port,
        deadline_ms=args.deadline_ms, registry=reg,
        drain_grace_s=args.drain_grace_s, quota=quota,
        engine_builder=None if args.no_reload else _engine_builder,
        alerts=getattr(obs, "alerts", None), ingest=dispatcher)
    if dispatcher is not None:
        dispatcher.start(batcher)

    def _sigterm(_signum, _frame):
        vlog("SIGTERM: draining")
        server.initiate_drain()

    try:
        signal.signal(signal.SIGTERM, _sigterm)
    except ValueError:
        pass  # not the main thread (in-process embedding/tests)
    print(f"quorum-serve: listening on {args.host}:{server.port} "
          f"(max-batch {args.max_batch}, queue {args.queue_requests})",
          file=sys.stderr)
    reg.heartbeat(stage="serve", port=server.port)
    try:
        server.serve_until_drained()
    except BaseException:
        # an unexpected failure must still free the port; the
        # observability teardown stamps the error document
        server.close()
        if dispatcher is not None:
            dispatcher.drain(timeout=5.0)
        raise
    if dispatcher is not None:
        # finish queued chunks and commit the final live-table
        # checkpoint (cursor) so a restart resumes without
        # re-ingesting
        dispatcher.drain()
    vlog("Drained; writing final metrics")
    return 0


if __name__ == "__main__":
    sys.exit(main())
