"""quorum-serve — the persistent correction service (ISSUE 3).

Loads a stage-1 mer database once, warms the corrector, and serves
`POST /correct` with dynamic batching until drained (SIGTERM or
`POST /quiesce`). The correction flags mirror
`quorum_error_correct_reads` so a serve deployment and an offline run
of the same flags produce byte-identical corrections; the final
metrics document lands through the same observability() lifecycle as
every other CLI.
"""

from __future__ import annotations

import argparse
import signal
import sys

from ..utils import faults
from ..utils import vlog as vlog_mod
from ..utils.vlog import vlog
from .observability import add_observability_args, observability


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="quorum-serve",
        description="Serve quorum error correction over HTTP: POST "
                    "FASTQ text to /correct, scrape /metrics, drain "
                    "with SIGTERM or POST /quiesce.",
    )
    # correction surface (quorum_error_correct_reads parity)
    p.add_argument("-m", "--min-count", type=int, default=1,
                   help='Minimum count for a k-mer to be considered "good"')
    p.add_argument("-s", "--skip", type=int, default=1,
                   help="Number of bases to skip for start k-mer")
    p.add_argument("-g", "--good", type=int, default=2,
                   help="Number of good k-mer in a row for anchor")
    p.add_argument("-a", "--anchor-count", type=int, default=3,
                   help="Minimum count for an anchor k-mer")
    p.add_argument("-w", "--window", type=int, default=10,
                   help="Size of window")
    p.add_argument("-e", "--error", type=int, default=3,
                   help="Maximum number of error in a window")
    p.add_argument("--contaminant", metavar="path",
                   help="Contaminant sequences (fasta/fastq) or k-mer "
                        "database")
    p.add_argument("--trim-contaminant", action="store_true",
                   help="Trim reads containing contaminated k-mers "
                        "instead of discarding")
    p.add_argument("--homo-trim", type=int, default=None,
                   help="Trim homo-polymer run at the 3' end")
    p.add_argument("-M", "--no-mmap", action="store_true",
                   help="Do not memory map the input mer database")
    p.add_argument("--apriori-error-rate", type=float, default=0.01,
                   help="Probability of a base being an error")
    p.add_argument("--poisson-threshold", type=float, default=1e-6,
                   help="Error probability threshold in Poisson test")
    p.add_argument("-p", "--cutoff", type=int, default=None,
                   help="Poisson cutoff when there are multiple choices")
    p.add_argument("-q", "--qual-cutoff-value", type=int, default=None,
                   help="Any base above with quality equal or greater is "
                        "untouched when there are multiple choices")
    p.add_argument("-Q", "--qual-cutoff-char", default=None,
                   help="Any base above with quality equal or greater is "
                        "untouched when there are multiple choices")
    p.add_argument("-d", "--no-discard", action="store_true",
                   help="Do not discard reads, output a single N")
    p.add_argument("-v", "--verbose", action="store_true", help="Be verbose")
    # serving surface
    p.add_argument("--host", default="127.0.0.1",
                   help="Bind address (default loopback; 0.0.0.0 to "
                        "serve off-machine)")
    p.add_argument("--port", type=int, default=8100,
                   help="Listen port (default 8100; 0 = ephemeral)")
    p.add_argument("--max-batch", type=int, default=1024,
                   help="Reads per device batch; also the padded row "
                        "capacity every batch compiles at (default 1024)")
    p.add_argument("--max-wait-ms", type=float, default=5.0,
                   help="How long the dispatcher waits to coalesce "
                        "more requests into a batch (default 5)")
    p.add_argument("--queue-requests", type=int, default=64,
                   help="Bounded request-queue capacity; a full queue "
                        "answers 429 + Retry-After (default 64)")
    p.add_argument("--deadline-ms", type=float, default=None,
                   help="Default per-request deadline (overridable "
                        "per request); expired requests answer 504")
    p.add_argument("--drain-grace-s", type=float, default=30.0,
                   help="Max seconds a drain waits for in-flight "
                        "batches (default 30)")
    p.add_argument("--max-consecutive-failures", metavar="n", type=int,
                   default=5,
                   help="After n device-step failures in a row, "
                        "/healthz answers 503 (unhealthy) so load "
                        "balancers eject the replica; any success "
                        "resets the streak (default 5; 0 = never)")
    p.add_argument("--warmup-lengths", metavar="L1,L2,...", default=None,
                   help="Comma-separated read lengths to pre-compile "
                        "before listening (one device step per "
                        "length bucket)")
    # observability (same surface as the other CLIs; --metrics
    # writes the final document on drain)
    add_observability_args(p, metrics=True)
    faults.add_fault_args(p)
    p.add_argument("db", help="Mer database")
    return p


def main(argv=None) -> int:
    from ..utils.jaxcache import enable_cache
    enable_cache()
    args = build_parser().parse_args(argv)
    # OR, not assign: QUORUM_TPU_VERBOSE may have enabled it already
    vlog_mod.verbose = args.verbose or vlog_mod.verbose
    faults.setup(args.fault_plan)

    if args.qual_cutoff_char is not None and args.qual_cutoff_value is not None:
        print("Switches -q and -Q are conflicting.", file=sys.stderr)
        return 1
    if args.qual_cutoff_char is not None and (
            len(args.qual_cutoff_char) != 1
            or ord(args.qual_cutoff_char) > 127):
        print("The qual-cutoff-char must be one ASCII character.",
              file=sys.stderr)
        return 1
    if args.qual_cutoff_value is not None and not (
            0 <= args.qual_cutoff_value <= 127):
        print("The qual-cutoff-value must be in the range 0-127.",
              file=sys.stderr)
        return 1
    qual_cutoff = (
        ord(args.qual_cutoff_char) if args.qual_cutoff_char is not None
        else args.qual_cutoff_value if args.qual_cutoff_value is not None
        else 127  # numeric_limits<char>::max()
    )
    warmup_lengths: list[int] = []
    if args.warmup_lengths:
        try:
            warmup_lengths = [int(x) for x in
                              args.warmup_lengths.split(",") if x]
        except ValueError:
            print(f"Bad --warmup-lengths {args.warmup_lengths!r}",
                  file=sys.stderr)
            return 1

    # the service is its own /metrics endpoint, so the registry must
    # be live even without --metrics (live=True); --metrics-port
    # additionally starts the standalone exposition endpoint the
    # other CLIs use, for scrapers that must not share the serving
    # port's queue
    with observability(args.metrics, args.metrics_interval,
                       port=args.metrics_port,
                       textfile=args.metrics_textfile,
                       live=True, trace_spans=args.trace_spans,
                       stage="serve") as obs:
        try:
            rc = _serve(args, qual_cutoff, warmup_lengths, obs)
        except (RuntimeError, ValueError, OSError) as e:
            print(str(e), file=sys.stderr)
            obs.status = "error"
            return 1
        if rc != 0:
            obs.status = "error"
        return rc


def _serve(args, qual_cutoff: int, warmup_lengths: list[int], obs) -> int:
    from ..serve import CorrectionEngine, CorrectionServer, DynamicBatcher

    reg = obs.registry
    engine = CorrectionEngine(
        args.db, cutoff=args.cutoff, qual_cutoff=qual_cutoff,
        skip=args.skip, good=args.good, anchor_count=args.anchor_count,
        min_count=args.min_count, window=args.window, error=args.error,
        homo_trim=args.homo_trim, trim_contaminant=args.trim_contaminant,
        no_discard=args.no_discard, contaminant=args.contaminant,
        apriori_error_rate=args.apriori_error_rate,
        poisson_threshold=args.poisson_threshold, no_mmap=args.no_mmap,
        rows=args.max_batch, registry=reg, tracer=obs.tracer)
    if warmup_lengths:
        vlog("Warming ", len(warmup_lengths), " length buckets")
        engine.warmup(warmup_lengths)
    batcher = DynamicBatcher(
        engine, max_batch=args.max_batch,
        max_wait_ms=args.max_wait_ms,
        queue_requests=args.queue_requests,
        max_consecutive_failures=args.max_consecutive_failures,
        registry=reg)
    server = CorrectionServer(batcher, host=args.host, port=args.port,
                              deadline_ms=args.deadline_ms, registry=reg,
                              drain_grace_s=args.drain_grace_s)

    def _sigterm(_signum, _frame):
        vlog("SIGTERM: draining")
        server.initiate_drain()

    try:
        signal.signal(signal.SIGTERM, _sigterm)
    except ValueError:
        pass  # not the main thread (in-process embedding/tests)
    print(f"quorum-serve: listening on {args.host}:{server.port} "
          f"(max-batch {args.max_batch}, queue {args.queue_requests})",
          file=sys.stderr)
    reg.heartbeat(stage="serve", port=server.port)
    try:
        server.serve_until_drained()
    except BaseException:
        # an unexpected failure must still free the port; the
        # observability teardown stamps the error document
        server.close()
        raise
    vlog("Drained; writing final metrics")
    return 0


if __name__ == "__main__":
    sys.exit(main())
