"""split_mate_pairs — de-interleave a corrected FASTA stream into
<prefix>_1.fa / <prefix>_2.fa.

Reference: src/split_mate_pairs.cc — reads two-line records
(header + sequence) from stdin and writes them alternately to the two
output files. We additionally accept an input file argument (stdin
remains the default) so the driver can split an already-written .fa
without a shell pipe.
"""

from __future__ import annotations

import argparse
import sys


def split_stream(inp, prefix: str) -> None:
    file1 = prefix + "_1.fa"
    file2 = prefix + "_2.fa"
    # streaming CLI outputs, written in one pass per input record
    out1 = open(file1, "w")  # qlint: disable=raw-artifact-write
    out2 = open(file2, "w")  # qlint: disable=raw-artifact-write
    with out1, out2:
        outs = (out1, out2)
        first = True
        while True:
            header = inp.readline()
            if not header:
                break
            seq = inp.readline()
            outs[0 if first else 1].write(header.rstrip("\r\n") + "\n"
                                          + seq.rstrip("\r\n") + "\n")
            first = not first


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="split_mate_pairs",
        description="Split an interleaved corrected FASTA stream into "
                    "<prefix>_1.fa and <prefix>_2.fa.",
    )
    p.add_argument("-i", "--input", default=None,
                   help="Input file (default stdin)")
    p.add_argument("prefix", help="Output prefix")
    return p


def main(argv=None) -> int:
    args = build_parser().parse_args(argv)
    inp = sys.stdin if args.input is None else open(args.input, "r")
    try:
        split_stream(inp, args.prefix)
    except OSError as e:
        print(str(e), file=sys.stderr)
        return 1
    finally:
        if inp is not sys.stdin:
            inp.close()
    return 0


if __name__ == "__main__":
    sys.exit(main())
