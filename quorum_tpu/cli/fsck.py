"""quorum-fsck — offline integrity verifier for every artifact the
pipeline persists (ISSUE 8).

KMC 3 ships `kmc_tools` as a first-class verifier/manipulator for its
on-disk k-mer databases (PAPERS.md); this is quorum-tpu's equivalent
over the artifacts io/ writes:

* **Databases** — native v5 files get the full checksum walk (header
  digest, bucket index, every entry chunk, derived section and
  whole-file digests), reported PER SECTION with byte offsets so an
  operator knows which 4 MiB of a 10 GiB table rotted; v4/v3/v2/v1
  files get the structural host load (counts, bucket addresses,
  truncation); reference `binary/quorum_db` files get the geometry +
  full-decode check (the digest-less format's maximum). Sharded
  manifests (`--db-layout=sharded`, ISSUE 9) get the manifest seal,
  every shard file's own checksum walk, and the manifest's per-shard
  whole-file digests — problems name `shard-K/<section>` so the
  damaged shard file is pinpointed, not just "the database".
* **Checkpoint directories** — the stage-1 snapshot (header seal +
  payload digest), the sharded manifest + every shard payload, and
  the driver's replay capture (manifest seal + per-batch digests).
* **Stage-2 journals** (`PREFIX.resume.json`) — document seal,
  partial-output presence, committed-range digests, and torn-tail
  detection. `--repair` truncates a torn tail back to the last
  committed byte — the ONE safe repair (it is exactly what `--resume`
  does); everything else is refuse-loudly: damaged bytes cannot be
  reconstructed, only detected before they flow into corrections.

Exit status: 0 = every artifact clean (or repaired under `--repair`),
1 = damage found (or left unrepaired), 2 = a path that is no known
artifact kind.
"""

from __future__ import annotations

import argparse
import os
import sys

from ..io import checkpoint as ckpt_mod
from ..io import db_format, integrity, quorum_db


class _Report:
    """Collects per-section lines and the damage verdict."""

    def __init__(self, quiet: bool = False):
        self.quiet = quiet
        self.bad = 0
        self.repaired = 0
        self.checked = 0

    def ok(self, path: str, section: str, detail: str = "") -> None:
        self.checked += 1
        if not self.quiet:
            print(f"{path}: {section}: OK"
                  + (f" ({detail})" if detail else ""))

    def fail(self, path: str, section: str, detail: str,
             offset=None) -> None:
        self.checked += 1
        self.bad += 1
        at = f" @ offset {offset}" if offset is not None else ""
        print(f"{path}: {section}: BAD{at}: {detail}",
              file=sys.stderr)

    def fixed(self, path: str, section: str, detail: str) -> None:
        self.checked += 1
        self.repaired += 1
        print(f"{path}: {section}: REPAIRED: {detail}")


# ---------------------------------------------------------------------------
# Databases
# ---------------------------------------------------------------------------


def check_db(path: str, mode: str, rep: _Report) -> None:
    if quorum_db.is_ref_db(path):
        problems = quorum_db.verify_ref_db(path)
        if problems:
            for sec, off, msg in problems:
                rep.fail(path, f"ref-format {sec}", msg, off)
        else:
            rep.ok(path, "ref-format database",
                   "header geometry + full decode")
        return
    try:
        header, problems = db_format.verify_db_file(path, mode)
    except (OSError, ValueError) as e:
        rep.fail(path, "header", str(e))
        return
    version = header.get("version", 1)
    if problems:
        for sec, off, msg in problems:
            rep.fail(path, sec, msg, off)
        return
    if header.get("format") == db_format.MANIFEST_FORMAT:
        rep.ok(path, "sharded database manifest",
               f"{header.get('n_shards')} shard file(s), "
               f"{header.get('n_entries')} entries — manifest seal, "
               f"per-shard checksums + whole-file digests, {mode} "
               "mode")
        return
    if header.get("layout") == "shard":
        rep.ok(path, "database shard",
               f"shard {header.get('shard')} of "
               f"{header.get('n_shards')} ({header.get('n_entries')} "
               f"entries), v{version} checksums, {mode} mode — run "
               "fsck on the manifest to also check the shard set")
        return
    if version >= 5:
        n = header.get("n_entries", "?")
        rep.ok(path, "v5 checksums",
               f"header + bucket index + entries ({n} entries), "
               f"{mode} mode")
    else:
        rep.ok(path, f"v{version} structure",
               "no digests in this version — structural checks only; "
               "re-export with --db-version 5 for checksums")


# ---------------------------------------------------------------------------
# Checkpoint directories
# ---------------------------------------------------------------------------


def check_checkpoint_dir(d: str, rep: _Report) -> None:
    found = False
    single = os.path.join(d, "stage1.ckpt")
    if os.path.exists(single):
        found = True
        try:
            snap = ckpt_mod.Stage1Checkpoint(d).load()
            rep.ok(single, "stage-1 snapshot",
                   f"cursor {snap.cursor}, header seal + payload "
                   "digest")
        except ckpt_mod.CheckpointError as e:
            rep.fail(single, "stage-1 snapshot", str(e))
    manifest = os.path.join(d, ckpt_mod.Stage1ShardedCheckpoint.MANIFEST)
    if os.path.exists(manifest):
        found = True
        try:
            snap = ckpt_mod.Stage1ShardedCheckpoint(d).load()
            rep.ok(manifest, "sharded stage-1 snapshot",
                   f"{snap.n_shards} shards at cursor {snap.cursor}, "
                   "manifest seal + per-shard digests")
        except ckpt_mod.CheckpointError as e:
            rep.fail(manifest, "sharded stage-1 snapshot", str(e))
    replay = ckpt_mod.ReplayCache(d)
    if os.path.exists(replay.manifest_path):
        found = True
        _check_replay(replay, rep)
    if not found:
        rep.fail(d, "checkpoint directory",
                 "no stage-1 snapshot, sharded manifest, or replay "
                 "capture found")


def _check_replay(replay: ckpt_mod.ReplayCache, rep: _Report) -> None:
    path = replay.manifest_path
    try:
        doc = replay.manifest()
    except ckpt_mod.CheckpointError as e:
        rep.fail(path, "replay manifest", str(e))
        return
    if doc is None:
        rep.fail(path, "replay manifest", "unreadable or wrong format")
        return
    payloads = doc.get("payloads") or []
    n = int(doc.get("n_batches", 0))
    bad = 0
    for i in range(n):
        bp = replay._batch_path(i)
        if not os.path.exists(bp):
            rep.fail(bp, "replay batch", "missing")
            bad += 1
            continue
        if i < len(payloads):
            want = payloads[i]
            size = os.path.getsize(bp)
            if size != int(want.get("bytes", -1)):
                rep.fail(bp, "replay batch",
                         f"{size} bytes, manifest recorded "
                         f"{want.get('bytes')}")
                bad += 1
                continue
            got = integrity.crc32c_file(bp)
            if got != int(want.get("crc32c", -1)):
                rep.fail(bp, "replay batch",
                         f"digest mismatch (crc32c {got:#010x} != "
                         f"manifest {int(want.get('crc32c', -1)):#010x})")
                bad += 1
    if not bad:
        detail = (f"{n} batches, per-batch digests"
                  if payloads else f"{n} batches (no digests — "
                  "pre-ISSUE-8 capture)")
        rep.ok(path, "replay capture", detail)


# ---------------------------------------------------------------------------
# Stage-2 journals
# ---------------------------------------------------------------------------


def check_journal(path: str, rep: _Report, repair: bool = False) -> None:
    prefix = path[:-len(".resume.json")]
    j = ckpt_mod.Stage2Journal(prefix)
    try:
        st = j.load()
    except ckpt_mod.CheckpointError as e:
        rep.fail(path, "journal document", str(e))
        return
    if st is None:
        rep.ok(path, "journal",
               "no partial outputs (a fresh run starts over; nothing "
               "to verify)")
        return
    rep.ok(path, "journal document",
           f"seal OK, {st['batches']} batches committed")
    for p, committed, key in (
            (j.fa_partial, int(st["fa_bytes"]), "fa_crc32c"),
            (j.log_partial, int(st["log_bytes"]), "log_crc32c")):
        size = os.path.getsize(p)
        if size < committed:
            rep.fail(p, "committed range",
                     f"{size} bytes, journal committed {committed} — "
                     "the partial lost committed data")
            continue
        want = st.get(key)
        if want is not None:
            got = integrity.crc32c_file(p, 0, committed)
            if got != int(want):
                rep.fail(p, "committed range",
                         f"digest mismatch inside the committed "
                         f"{committed} bytes (crc32c {got:#010x} != "
                         f"journaled {int(want):#010x})")
                continue
            rep.ok(p, "committed range",
                   f"{committed} bytes, digest OK")
        else:
            rep.ok(p, "committed range",
                   f"{committed} bytes (no digest — pre-ISSUE-8 "
                   "journal)")
        if size > committed:
            if repair:
                with open(p, "r+b") as f:
                    f.truncate(committed)
                rep.fixed(p, "torn tail",
                          f"truncated {size - committed} bytes past "
                          f"the last committed record (what --resume "
                          "does)")
            else:
                rep.fail(p, "torn tail",
                         f"{size - committed} bytes past the commit "
                         "point (expected after a crash; --repair "
                         "truncates to the last valid record)")


# ---------------------------------------------------------------------------
# Entry point
# ---------------------------------------------------------------------------


def _looks_like_db(path: str) -> bool:
    try:
        with open(path, "rb") as f:
            head = f.read(1)
        return head == b"{"
    except OSError:
        return False


def main(argv=None) -> int:
    p = argparse.ArgumentParser(
        prog="quorum-fsck",
        description="Verify the integrity of quorum-tpu on-disk "
                    "artifacts: databases (native v1-v5 and reference "
                    "format), checkpoint directories, and stage-2 "
                    "resume journals. Exits non-zero on damage.")
    p.add_argument("paths", nargs="+", metavar="PATH",
                   help="Database files, checkpoint directories, or "
                        "PREFIX.resume.json journals")
    p.add_argument("--verify", choices=("full", "sample"),
                   default="full",
                   help="Database checksum depth: full (default) or "
                        "sample (random entry-chunk scrub)")
    p.add_argument("--repair", action="store_true",
                   help="Truncate torn journal tails back to the last "
                        "committed record — the only safe repair; "
                        "all other damage is report-only")
    p.add_argument("-q", "--quiet", action="store_true",
                   help="Suppress per-section OK lines")
    args = p.parse_args(argv)

    rep = _Report(quiet=args.quiet)
    unknown = 0
    for path in args.paths:
        if os.path.isdir(path):
            check_checkpoint_dir(path, rep)
        elif path.endswith(".resume.json") and os.path.exists(path):
            check_journal(path, rep, repair=args.repair)
        elif os.path.exists(path) and (_looks_like_db(path)
                                       or quorum_db.is_ref_db(path)):
            check_db(path, args.verify, rep)
        else:
            print(f"{path}: not a recognized quorum-tpu artifact "
                  "(database, checkpoint directory, or .resume.json)",
                  file=sys.stderr)
            unknown += 1
    if not args.quiet or rep.bad or rep.repaired:
        verdict = ("clean" if not rep.bad else
                   f"{rep.bad} damaged section(s)")
        extra = (f", {rep.repaired} repaired" if rep.repaired else "")
        print(f"quorum-fsck: {rep.checked} check(s): {verdict}{extra}")
    if unknown:
        return 2
    return 1 if rep.bad else 0


if __name__ == "__main__":
    sys.exit(main())
