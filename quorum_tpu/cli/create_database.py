"""quorum_create_database — flag-compatible with the reference CLI
(src/create_database_cmdline.yaggo): required -s/-m/-b, one of -q/-Q,
plus -t/-o/-p and read files."""

from __future__ import annotations

import argparse
import sys

from ..io.fastq import BadReadPolicy
from ..models.create_database import BuildConfig, create_database_main
from ..utils import faults
from ..utils import vlog as vlog_mod
from ..utils.sizes import parse_size
from .observability import add_observability_args


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="quorum_create_database",
        description="Create database of k-mers for quorum error corrector",
    )
    p.add_argument("-s", "--size", required=True,
                   help="Initial hash size (suffix k/M/G/T ok)")
    p.add_argument("-m", "--mer", required=True, type=int, help="Mer length")
    p.add_argument("-b", "--bits", required=True, type=int,
                   help="Bits for value field")
    p.add_argument("-q", "--min-qual-value", type=int,
                   help="Min quality as an int")
    p.add_argument("-Q", "--min-qual-char",
                   help="Min quality as a ASCII character")
    p.add_argument("-t", "--threads", type=int, default=1,
                   help="Number of threads (host I/O; device is parallel)")
    p.add_argument("-o", "--output", default="combined_database",
                   help="Output file")
    p.add_argument("-p", "--reprobe", type=int, default=126,
                   help="Maximum number of reprobes")
    p.add_argument("--batch-size", type=int, default=8192,
                   help="Reads per device batch")
    p.add_argument("--devices", default="auto", metavar="N",
                   help="Shard the counting table over N local "
                        "devices (power of two; 'all' = every local "
                        "device, 'auto' = all on a real accelerator, "
                        "1 on CPU; 1 = single-chip path). Output is "
                        "byte-identical to --devices 1")
    p.add_argument("--ref-format", action="store_true",
                   help="Write the reference's binary/quorum_db format "
                        "instead of the native format")
    p.add_argument("--db-version", type=int, choices=(4, 5), default=5,
                   help="Native export version: 5 (default) carries "
                        "per-section CRC32C digests and a whole-file "
                        "trailer digest so loaders and quorum-fsck "
                        "detect silent corruption; 4 is the bare "
                        "round-5 layout (same payload bytes)")
    p.add_argument("--db-layout", choices=("single", "sharded"),
                   default="single",
                   help="On-disk layout: single (default) gathers a "
                        "sharded table to one chip and writes one "
                        "file; sharded streams each shard D2H "
                        "independently into <output>.shard-K-of-S.qdb "
                        "files under a sealed manifest at <output> — "
                        "no cross-device gather, no single-chip "
                        "geometry cap, same payload bytes")
    p.add_argument("--prefilter", choices=("auto", "off", "two-pass",
                                           "inline"),
                   default="auto",
                   help="Singleton prefilter (ISSUE 14): two-pass "
                        "streams the input once into a count-min "
                        "sketch then inserts only mers seen >= 2 "
                        "times (exact); inline gates inserts behind "
                        "the online sketch, khmer-style "
                        "(approximate at the margin). Dropped "
                        "singletons shrink the table severalfold in "
                        "error-rich data; the database declares its "
                        "presence floor so stage 2 stays consistent. "
                        "auto = QUORUM_PREFILTER env > autotune "
                        "profile > off")
    p.add_argument("--partitions", type=int, default=1, metavar="P",
                   help="Build the table in P sequential passes over "
                        "the input (power of two <= 256), each "
                        "counting one disjoint leading-bit row range "
                        "at 1/P the table memory and exporting "
                        "straight into the sharded manifest "
                        "(--db-layout=sharded is implied). The "
                        "reassembled payload is byte-identical to a "
                        "single-pass build; kill->resume re-runs "
                        "only the torn partition")
    p.add_argument("--profile", metavar="dir", default=None,
                   help="Write a jax.profiler trace to this directory")
    p.add_argument("--metrics", metavar="path", default=None,
                   help="Write a final metrics JSON (schema "
                        "quorum-tpu-metrics/1) to this path")
    p.add_argument("--metrics-interval", metavar="seconds", type=float,
                   default=0.0,
                   help="With --metrics: also write JSONL heartbeat "
                        "events at this period (0 = off)")
    add_observability_args(p)
    # fault tolerance (ISSUE 4)
    p.add_argument("--checkpoint-dir", metavar="dir", default=None,
                   help="Write atomic snapshots of the counting table "
                        "(plus the input batch cursor) here; a killed "
                        "run restarted with --resume continues from "
                        "the last one")
    p.add_argument("--checkpoint-every", metavar="batches", type=int,
                   default=64,
                   help="Batches between snapshots (default 64; each "
                        "snapshot syncs the device)")
    p.add_argument("--resume", action="store_true",
                   help="Continue from the last valid checkpoint in "
                        "--checkpoint-dir (fresh start if none)")
    p.add_argument("--on-bad-read",
                   choices=BadReadPolicy.MODES, default="abort",
                   help="Malformed-record policy: abort the run "
                        "(default), skip and count, or quarantine to "
                        "<output>.quarantine.fastq")
    faults.add_fault_args(p)
    from ..parallel import fleet as fleet_mod
    fleet_mod.add_fleet_args(p)
    p.add_argument("-v", "--verbose", action="store_true")
    p.add_argument("reads", nargs="+", help="Read files")
    return p


def main(argv=None, handoff: dict | None = None, batches=None,
         batches_factory=None) -> int:
    from ..utils.jaxcache import enable_cache
    enable_cache()
    args = build_parser().parse_args(argv)
    # OR, not assign: QUORUM_TPU_VERBOSE may have enabled it already
    vlog_mod.verbose = args.verbose or vlog_mod.verbose
    if args.min_qual_value is None and args.min_qual_char is None:
        print("Either a min-qual-value or min-qual-char must be provided.",
              file=sys.stderr)
        return 1
    if args.min_qual_char is not None and len(args.min_qual_char) != 1:
        print("The min-qual-char should be one ASCII character.",
              file=sys.stderr)
        return 1
    # our value word is uint32: bit0 quality + up to 30 count bits
    if not (1 <= args.bits <= 30):
        print("The number of bits should be between 1 and 30",
              file=sys.stderr)
        return 1
    qual_thresh = (
        ord(args.min_qual_char) if args.min_qual_char is not None
        else args.min_qual_value
    )
    if args.mer < 1 or args.mer > 31:
        print("Mer length must be between 1 and 31", file=sys.stderr)
        return 1
    faults.setup(args.fault_plan)
    # fleet bring-up BEFORE any jax device use: jax.distributed must
    # initialize before the backend comes up
    from ..parallel import fleet as fleet_mod
    try:
        flt = fleet_mod.ensure_initialized(args)
    except (RuntimeError, ValueError) as e:
        print(f"quorum_create_database: {e}", file=sys.stderr)
        return 1
    from ..parallel.tile_sharded import resolve_devices_and_batch
    try:
        devices, batch_size = resolve_devices_and_batch(
            args.devices, args.batch_size, "quorum_create_database")
    except ValueError as e:
        print(str(e), file=sys.stderr)
        return 1
    # memory-frugal counting (ISSUE 14): resolve + validate the
    # prefilter mode and partition count before any device work
    from ..ops.sketch import prefilter_default
    auto = args.prefilter == "auto"
    prefilter = prefilter_default() if auto else args.prefilter
    P = args.partitions
    if P < 1 or P > 256 or (P & (P - 1)):
        print(f"--partitions must be a power of two in [1, 256], "
              f"got {P}", file=sys.stderr)
        return 1
    if flt is not None:
        # the fleet stage-1 is partition-binned: plan P up to a power
        # of two >= the process count so every host owns >= 1 pass
        P = fleet_mod.plan_partitions(P, flt.num_processes)
        if P != args.partitions:
            vlog_mod.vlog("Fleet build: raising --partitions to ", P,
                          " (", flt.num_processes, " processes)")
        if args.ref_format:
            print("--ref-format does not compose with a multi-host "
                  "fleet (no sharded manifest)", file=sys.stderr)
            return 1
    if prefilter != "off" and devices > 1:
        if auto:
            # an env/profile-resolved default the user never asked
            # for must DEGRADE on an unsupported combination, not
            # refuse the run (an explicit flag still refuses loudly)
            vlog_mod.vlog("Prefilter default ", prefilter,
                          " does not compose with --devices ", devices,
                          "; running unfiltered")
            prefilter = "off"
        else:
            print("--prefilter composes with --devices 1 today; use "
                  "--partitions for multi-pass capacity over a mesh",
                  file=sys.stderr)
            return 1
    if prefilter == "inline" and (P > 1 or args.checkpoint_dir):
        if auto:
            vlog_mod.vlog("Prefilter default inline does not compose "
                          "with --partitions/--checkpoint-dir; "
                          "running unfiltered")
            prefilter = "off"
        else:
            print("--prefilter=inline supports neither --partitions "
                  "nor --checkpoint-dir (the online sketch is "
                  "neither pass-stable nor snapshotted); use "
                  "--prefilter=two-pass", file=sys.stderr)
            return 1
    if args.ref_format and (P > 1 or prefilter != "off"):
        print("--ref-format supports neither --partitions nor "
              "--prefilter", file=sys.stderr)
        return 1
    db_layout = args.db_layout
    if P > 1:
        # the partitioned export IS the sharded manifest: each pass
        # streams its shard file as it completes
        db_layout = "sharded"
    cfg = BuildConfig(
        k=args.mer,
        bits=args.bits,
        qual_thresh=qual_thresh,
        initial_size=parse_size(args.size),
        max_reprobe=args.reprobe,
        batch_size=batch_size,
        threads=args.threads,
        devices=devices,
        profile=args.profile,
        checkpoint_dir=args.checkpoint_dir,
        checkpoint_every=args.checkpoint_every,
        resume=args.resume,
        on_bad_read=args.on_bad_read,
        db_version=args.db_version,
        db_layout=db_layout,
        prefilter=prefilter,
        partitions=P,
        quarantine_path=(args.output + ".quarantine.fastq"
                         if args.on_bad_read == "quarantine" else None),
    )
    from .observability import observability
    from ..utils import resources
    if flt is not None and args.metrics:
        # hosts share one filesystem in CI (and may on NFS pods):
        # per-host metrics documents get per-host paths
        args.metrics = fleet_mod.host_scoped_path(args.metrics,
                                                  flt.process_id)
    rc = 1  # flipped to 0 only on success: any exception leaves 1
    # the resource-guard frame (ISSUE 19): watch the output and
    # checkpoint filesystems for the watermark alerts
    watch = [p for p in (args.output, args.checkpoint_dir,
                         args.metrics) if p]
    # a failed run (hash-full, busy --metrics-port, or anything
    # uncaught) must still land its metrics document with
    # status=error — monitoring needs a run that FAILED, not one that
    # stopped reporting. The observability() teardown guarantees it.
    with observability(args.metrics, args.metrics_interval,
                       port=args.metrics_port,
                       textfile=args.metrics_textfile,
                       live=args.metrics_live,
                       trace_spans=args.trace_spans,
                       profile=args.profile,
                       push_url=args.metrics_push_url,
                       push_interval=args.metrics_push_interval,
                       alert_rules=args.alert_rules,
                       watch_paths=watch,
                       stall_timeout_s=args.stall_timeout_s) as obs:
        try:
            if flt is not None:
                obs.registry.set_meta(
                    host_process_count=flt.num_processes,
                    host_process_index=flt.process_id)
            # disk preflight BEFORE the parse/device work: an export
            # that cannot fit should refuse in seconds, not hours
            resources.preflight(
                args.preflight,
                resources.estimate_stage1_needs(
                    args.output, cfg.initial_size, cfg.k, cfg.bits,
                    checkpoint_dir=cfg.checkpoint_dir,
                    partitions=cfg.partitions))
            create_database_main(args.reads, args.output, cfg,
                                 cmdline=list(sys.argv),
                                 ref_format=args.ref_format,
                                 handoff=handoff, batches=batches,
                                 batches_factory=batches_factory,
                                 metrics=obs.registry, tracer=obs.tracer)
            rc = 0
            obs.registry.set_meta(output=args.output)
        except (RuntimeError, OSError, ValueError) as e:
            # RuntimeError: hash-full / checkpoint mismatch; OSError:
            # real (or injected) IO failures. A CheckpointError or
            # IntegrityError is deterministic — rc 3 tells the
            # driver's retry loop not to back off and re-run a doomed
            # attempt. ResourceExhausted (full disk / strict
            # preflight) is rc 4, also not retried; a watchdog
            # StallError is rc 75, which IS (resume from checkpoint).
            from ..io.checkpoint import (CheckpointError,
                                         NON_RETRYABLE_RC)
            from ..io.integrity import IntegrityError
            if isinstance(e, resources.ResourceExhausted):
                rc = resources.DISK_FULL_RC
            elif isinstance(e, resources.StallError):
                rc = resources.STALL_RC
            elif resources.is_enospc(e):
                # a bare ENOSPC escaping stage 1 is the DB export
                # (every optional writer degrades in place): required
                # — seal the dump naming the writer, do not retry
                resources.fail_required("db.payload", e,
                                        path=args.output)
                rc = resources.DISK_FULL_RC
            elif isinstance(e, (CheckpointError, IntegrityError)):
                rc = NON_RETRYABLE_RC
            print(str(e), file=sys.stderr)
            obs.status = "error"
    return rc


if __name__ == "__main__":
    sys.exit(main())
