"""quorum-autotune — derive the device-lever profile for this
backend by measurement (ISSUE 11, ROADMAP item 5).

Runs the round-7 in-process A/B probes (the same interleaved
discipline as `bench.py --ab`: tunnel throughput varies 2-3x BETWEEN
processes, so lever comparisons must happen within one) over a
synthetic batch at the requested geometry, picks the winning settings
for each lever, and persists them as a sealed JSON profile
(ops/tuning.py) that every later run's lever resolution loads by
default — explicit env vars still win. Parity is asserted in-process
exactly as the bench does: a variant that does not produce identical
output never becomes a default.

Typical use on new hardware::

    quorum-autotune                      # probe + write the backend
                                         # profile (~/.cache/...)
    quorum-autotune --out prof.json      # explicit path; apply with
                                         # QUORUM_AUTOTUNE_PROFILE=prof.json
    quorum-autotune --dry-run            # measure + report only

The probe results print as BENCH-style metric lines (and land in
`--metrics-lines PATH`), so `tools/metrics_check.py --require-metric
autotune_stage1 --require-metric autotune_stage2` re-validates a
freshly derived profile the same way CI validates the bench A/B
documents.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

from ..utils import levers


def _synth(n_reads: int, read_len: int, seed: int = 5,
           coverage: int = 40, err_rate: float = 0.01):
    """The bench generator's regime (bench.synth_reads, re-derived
    here because bench.py lives outside the package): reads sampled
    from one genome with substitution errors, so table load and
    branch mix match real Illumina input."""
    import numpy as np
    genome_size = max(2 * read_len, n_reads * read_len // coverage)
    rng = np.random.default_rng(seed)
    genome = rng.integers(0, 4, size=genome_size, dtype=np.int8)
    starts = rng.integers(0, genome_size - read_len, size=n_reads)
    idx = starts[:, None] + np.arange(read_len)[None, :]
    truth = genome[idx]
    errs = rng.random(truth.shape) < err_rate
    codes = np.where(errs,
                     (truth + rng.integers(1, 4, size=truth.shape)) % 4,
                     truth).astype(np.int8)
    quals = np.full(codes.shape, 70, np.uint8)
    quals[errs] = 68
    lengths = np.full((n_reads,), read_len, np.int32)
    return codes, quals, lengths


def _bench_pair(fn_a, fn_b, reps: int):
    """Interleaved min-of-reps timing (both warmed first so compiles
    land in the persistent cache, not the measurement)."""
    fn_a(), fn_b()
    ta, tb = [], []
    for _ in range(reps):
        t0 = time.perf_counter()
        fn_a()
        ta.append(time.perf_counter() - t0)
        t0 = time.perf_counter()
        fn_b()
        tb.append(time.perf_counter() - t0)
    return min(ta), min(tb)


def run_probes(n_reads: int, read_len: int, k: int,
               reps: int) -> dict:
    """Measure the three levers at this geometry. Returns the raw
    numbers (seconds, parity flags) — the caller decides winners.
    Raises RuntimeError when any variant breaks parity."""
    import numpy as np

    from ..io import packing
    from ..models import corrector
    from ..models.ec_config import ECConfig
    from ..ops import ctable

    codes, quals, lengths = _synth(n_reads, read_len)
    qt = 38
    pk1 = packing.pack_reads(codes, quals, lengths, thresholds=(qt,))
    pk1.to_wire()
    est = (codes.size // 40) + int(codes.size * 0.01 * k * 1.3)
    meta = ctable.TileMeta(
        k=k, bits=7, rb_log2=ctable.tile_rb_for(est, k, 7))

    # -- stage 1: per-observation vs pre-aggregated insert ------------
    import jax
    tables = {}

    def insert_once(agg: bool):
        # force the lever for the probe, then RESTORE the caller's
        # setting — an in-process embedder's explicit env override
        # must survive the probe (cli/observability + smoke run
        # autotune inside larger processes)
        prev = levers.raw("QUORUM_S1_AGGREGATE")
        os.environ["QUORUM_S1_AGGREGATE"] = "1" if agg else "0"
        try:
            bstate = ctable.make_tile_build(meta)
            bstate, full, _obs = ctable.tile_insert_reads_packed(
                bstate, meta, pk1, qt)
            if full:
                raise RuntimeError("probe table filled — geometry "
                                   "estimate too small")
            jax.block_until_ready(bstate.tag)
            tables[agg] = bstate
        finally:
            if prev is None:
                os.environ.pop("QUORUM_S1_AGGREGATE", None)
            else:
                os.environ["QUORUM_S1_AGGREGATE"] = prev

    s1_base_s, s1_agg_s = _bench_pair(lambda: insert_once(False),
                                      lambda: insert_once(True), reps)

    def _entries(bs):
        return sorted(zip(*(
            a.tolist() for a in ctable.tile_iterate(
                ctable.tile_finalize(bs, meta), meta))))

    s1_parity = _entries(tables[False]) == _entries(tables[True])
    if not s1_parity:
        raise RuntimeError("stage-1 aggregation parity FAILED — no "
                           "profile written")

    # -- stage 2: sweep compaction x loop draining --------------------
    state = ctable.tile_finalize(tables[True], meta)
    cfg = ECConfig(k=k, cutoff=4, poisson_dtype="float32")
    pk2 = packing.pack_reads(codes, quals, lengths,
                             thresholds=(cfg.qual_cutoff,))
    pk2.to_wire()
    outs = {}

    def correct_once(compact: bool, drain: int):
        _res, packed = corrector.correct_batch_packed(
            state, meta, pk2, cfg, pack_cap=4 * n_reads,
            compact_sweep=compact, drain_levels=drain)
        jax.block_until_ready(packed)
        outs[(compact, drain)] = np.asarray(packed)

    base_s, sweep_s = _bench_pair(lambda: correct_once(False, 0),
                                  lambda: correct_once(True, 0), reps)
    b2, drain_s = _bench_pair(lambda: correct_once(False, 0),
                              lambda: correct_once(True, 2), reps)
    base_s = min(base_s, b2)
    s2_parity = (np.array_equal(outs[(False, 0)], outs[(True, 0)])
                 and np.array_equal(outs[(False, 0)], outs[(True, 2)]))
    if not s2_parity:
        raise RuntimeError("stage-2 lever parity FAILED — no profile "
                           "written")
    return {
        "s1_base_s": s1_base_s, "s1_agg_s": s1_agg_s,
        "s2_base_s": base_s, "s2_sweep_s": sweep_s,
        "s2_sweep_drain_s": drain_s,
        "parity": True,
    }


# a lever must beat the incumbent by this margin to flip the default:
# min-of-reps absorbs most noise, the hysteresis absorbs the rest (a
# 1% "win" re-measured tomorrow is a coin flip)
WIN_MARGIN = 0.02


def decide(measured: dict) -> dict:
    """The winning lever settings from the probe numbers."""
    winners = {}
    winners["QUORUM_S1_AGGREGATE"] = (
        "1" if measured["s1_agg_s"]
        < measured["s1_base_s"] * (1.0 - WIN_MARGIN) else "0")
    variants = {
        ("0", "0"): measured["s2_base_s"],
        ("1", "0"): measured["s2_sweep_s"],
        ("1", "2"): measured["s2_sweep_drain_s"],
    }
    best = min(variants, key=variants.get)
    if variants[best] >= measured["s2_base_s"] * (1.0 - WIN_MARGIN):
        best = ("0", "0")  # not a real win: keep the plain loop
    winners["QUORUM_COMPACT_SWEEP"] = best[0]
    winners["QUORUM_DRAIN_LEVELS"] = best[1]
    return winners


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="quorum-autotune",
        description="Measure the device levers on this backend with "
                    "the in-process A/B probes and persist the "
                    "winners as a sealed profile that later runs "
                    "load by default (env vars still win).")
    p.add_argument("--out", metavar="path", default=None,
                   help="Profile path (default: the per-backend file "
                        "under QUORUM_AUTOTUNE_DIR, which lever "
                        "resolution finds automatically; an explicit "
                        "path is applied via "
                        "QUORUM_AUTOTUNE_PROFILE=path)")
    p.add_argument("--reads", type=int,
                   default=int(levers.raw("QUORUM_AB_READS",
                                          "16384")),
                   help="Probe batch rows (default 16384 or "
                        "$QUORUM_AB_READS — match the production "
                        "batch size: the levers trade width-"
                        "proportional work)")
    p.add_argument("--len", dest="read_len", type=int,
                   default=int(levers.raw("QUORUM_AB_LEN", "150")),
                   help="Probe read length (default 150 or "
                        "$QUORUM_AB_LEN)")
    p.add_argument("-k", "--kmer-len", type=int,
                   default=int(levers.raw("QUORUM_AB_K", "24")),
                   help="Probe mer length (default 24 or "
                        "$QUORUM_AB_K)")
    p.add_argument("--reps", type=int,
                   default=int(levers.raw("QUORUM_AB_REPS", "3")),
                   help="Timing repetitions, min taken (default 3 "
                        "or $QUORUM_AB_REPS)")
    p.add_argument("--metrics-lines", metavar="path", default=None,
                   help="Also write the probe metric lines here "
                        "(BENCH-style; gate with metrics_check "
                        "--require-metric autotune_stage1/_stage2)")
    p.add_argument("--dry-run", action="store_true",
                   help="Measure and report; write nothing")
    p.add_argument("-v", "--verbose", action="store_true")
    return p


def main(argv=None) -> int:
    from ..utils.jaxcache import enable_cache
    enable_cache()
    args = build_parser().parse_args(argv)
    from ..utils import vlog as vlog_mod
    vlog_mod.verbose = args.verbose or vlog_mod.verbose

    import jax

    from ..ops import tuning
    from ..telemetry import metric_line

    backend = tuning.backend_name()
    geometry = {"reads": args.reads, "read_len": args.read_len,
                "k": args.kmer_len}
    lines = [metric_line("autotune_env", backend=backend,
                         jax_backend=jax.default_backend(),
                         reps=args.reps, **geometry)]
    print(lines[-1], flush=True)
    try:
        measured = run_probes(args.reads, args.read_len,
                              args.kmer_len, args.reps)
    except RuntimeError as e:
        print(f"quorum-autotune: {e}", file=sys.stderr)
        return 1
    winners = decide(measured)
    lines.append(metric_line(
        "autotune_stage1",
        base_ms=round(measured["s1_base_s"] * 1e3, 1),
        aggregated_ms=round(measured["s1_agg_s"] * 1e3, 1),
        speedup=round(measured["s1_base_s"] / measured["s1_agg_s"], 3),
        winner=winners["QUORUM_S1_AGGREGATE"],
        parity="content-identical"))
    print(lines[-1], flush=True)
    lines.append(metric_line(
        "autotune_stage2",
        base_ms=round(measured["s2_base_s"] * 1e3, 1),
        compact_sweep_ms=round(measured["s2_sweep_s"] * 1e3, 1),
        compact_drain_ms=round(measured["s2_sweep_drain_s"] * 1e3, 1),
        speedup_sweep=round(
            measured["s2_base_s"] / measured["s2_sweep_s"], 3),
        speedup_sweep_drain=round(
            measured["s2_base_s"] / measured["s2_sweep_drain_s"], 3),
        winner_sweep=winners["QUORUM_COMPACT_SWEEP"],
        winner_drain=winners["QUORUM_DRAIN_LEVELS"],
        parity="byte-identical"))
    print(lines[-1], flush=True)

    out = args.out or tuning.default_profile_path(backend)
    if args.dry_run:
        lines.append(metric_line("autotune_profile", written=False,
                                 path=out, **winners))
        print(lines[-1], flush=True)
    else:
        measured_rounded = {kk: round(vv, 6) if isinstance(vv, float)
                            else vv for kk, vv in measured.items()}
        tuning.write_profile(out, backend, geometry, winners,
                             measured=measured_rounded)
        lines.append(metric_line("autotune_profile", written=True,
                                 path=out, **winners))
        print(lines[-1], flush=True)
    if args.metrics_lines:
        # atomic replace: metrics_check gates this document in CI — a
        # torn write must not look like a truncated probe run
        from ..telemetry.registry import atomic_write
        atomic_write(args.metrics_lines, "\n".join(lines) + "\n")
    return 0


if __name__ == "__main__":
    sys.exit(main())
