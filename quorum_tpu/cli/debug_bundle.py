"""quorum-debug-bundle — one-command postmortem collection
(ISSUE 16).

A wedged or dead run leaves its evidence scattered: the flight-
recorder dump next to the metrics document, the events/span JSONL
streams, the database the run was built against, the environment that
steered it. Attaching them to a bug report one-by-one loses half of
it. This tool collects everything into ONE tarball with a typed,
digest-stamped manifest (schema ``quorum-tpu-debug-bundle/1``,
telemetry/schema.validate_debug_bundle_manifest):

* every ARTIFACT path given — flight dumps, metrics JSON, events or
  span JSONL, Chrome traces — classified by content and validated
  through the shared schema validators (the manifest records each
  file's problem count, so a truncated artifact is flagged at
  collection time, not discovered on the other machine);
* ``--db`` paths get a ``quorum-fsck`` verdict (the full checksum
  walk), captured as ``fsck.txt`` with its exit status in the
  manifest;
* a generated ``config.json``: resolved ``QUORUM_*`` lever values
  (value vs catalog default), argv, cwd, and the Python version —
  the environment HALF of a postmortem that the artifacts alone
  cannot carry.

The manifest itself is sealed (io/integrity crc32c) and every entry
carries the file's own crc32c, so a bundle shipped across machines
self-describes what made it in and whether it survived the trip.
``tools/metrics_check.py`` accepts the manifest (and the flight dump
inside) by schema dispatch.
"""

from __future__ import annotations

import argparse
import contextlib
import io
import json
import os
import sys
import tarfile
import time

from ..io import integrity
from ..telemetry import schema as schema_mod
from ..utils import levers


def _classify(path: str) -> tuple[str, int]:
    """(kind, problem count) for one artifact, using the same
    content dispatch tools/metrics_check.py uses — so the manifest's
    `problems` field means exactly what the CI gate would say."""
    try:
        with open(path, encoding="utf-8", errors="strict") as f:
            text = f.read()
    except (OSError, UnicodeDecodeError):
        return "other", 0
    try:
        doc = json.loads(text)
    except ValueError:
        doc = None
    kind = "other"
    if isinstance(doc, dict):
        s = doc.get("schema")
        if s == schema_mod.FLIGHT_SCHEMA:
            kind = "flight"
        elif "traceEvents" in doc:
            kind = "trace"
        elif "counters" in doc or s == schema_mod.SCHEMA_VERSION:
            kind = "metrics"
    else:
        for line in text.splitlines():
            line = line.strip()
            if not line or line.startswith("#"):
                continue
            try:
                obj = json.loads(line)
            except ValueError:
                continue
            if isinstance(obj, dict) and "span" in obj:
                kind = "spans"
            elif isinstance(obj, dict) and "event" in obj:
                kind = "events"
            break
    if kind == "other":
        return kind, 0
    return kind, len(schema_mod.check_file(path))


def _fsck_verdict(paths: list[str]) -> tuple[str, int]:
    """Run quorum-fsck in-process over `paths`, capturing its full
    per-section report (stdout + stderr interleaved) and exit
    status."""
    from . import fsck as fsck_mod
    buf = io.StringIO()
    with contextlib.redirect_stdout(buf), \
            contextlib.redirect_stderr(buf):
        try:
            rc = fsck_mod.main(list(paths))
        except Exception as e:  # noqa: BLE001 - verdict, not crash
            print(f"quorum-fsck crashed: {e!r}", file=buf)
            rc = 2
    return buf.getvalue(), rc


def _config_doc() -> dict:
    """The environment half of the postmortem: every declared lever's
    resolved value next to its catalog default, plus the collection
    context."""
    vals = {}
    for name in levers.names():
        lv = levers.CATALOG[name]
        vals[name] = {"value": levers.raw(name),
                      "default": lv.default, "type": lv.type}
    return {
        "argv": list(sys.argv),
        "cwd": os.getcwd(),
        "python": sys.version,
        "collected_unix_s": int(time.time()),
        "levers": vals,
    }


def main(argv=None) -> int:
    p = argparse.ArgumentParser(
        prog="quorum-debug-bundle",
        description="Collect flight dumps, metrics/events/span "
                    "artifacts, quorum-fsck verdicts, and the "
                    "resolved configuration into one postmortem "
                    "tarball with a sealed, typed manifest "
                    "(quorum-tpu-debug-bundle/1)")
    p.add_argument("paths", nargs="*", metavar="ARTIFACT",
                   help="Artifacts to collect: flight dumps "
                        "(*.flight.json), metrics JSON, events/span "
                        "JSONL, Chrome traces — classified by "
                        "content and validated at collection time")
    p.add_argument("--db", action="append", default=[],
                   metavar="PATH",
                   help="Database file / checkpoint directory / "
                        ".resume.json journal to run quorum-fsck "
                        "on; the verdict text lands in the bundle "
                        "as fsck.txt (repeatable)")
    p.add_argument("--out", default="quorum-debug-bundle.tar.gz",
                   metavar="TARBALL",
                   help="Output tarball path (default "
                        "%(default)s)")
    p.add_argument("-q", "--quiet", action="store_true",
                   help="Suppress per-file collection lines")
    args = p.parse_args(argv)
    if not args.paths and not args.db:
        p.error("nothing to collect: give at least one ARTIFACT "
                "or --db PATH")

    files: list[dict] = []
    payload: list[tuple[str, bytes]] = []
    used: set[str] = set()

    def arcname(base: str) -> str:
        name, i = base, 1
        while name in used:
            name = f"{i}-{base}"
            i += 1
        used.add(name)
        return name

    def add(path_or_none, base, kind, data, problems,
            **extra) -> None:
        name = arcname(base)
        payload.append((name, data))
        entry = {"name": name, "kind": kind, "bytes": len(data),
                 "crc32c": integrity.crc32c(data),
                 "problems": problems}
        if path_or_none:
            entry["source"] = os.path.abspath(path_or_none)
        entry.update(extra)
        files.append(entry)
        if not args.quiet:
            flag = f", {problems} problem(s)" if problems else ""
            print(f"  + {name} ({kind}, {len(data)} bytes{flag})")

    missing = 0
    for path in args.paths:
        if not os.path.isfile(path):
            print(f"{path}: missing (skipped)", file=sys.stderr)
            missing += 1
            continue
        kind, problems = _classify(path)
        with open(path, "rb") as f:
            data = f.read()
        add(path, os.path.basename(path), kind, data, problems)
    if args.db:
        text, rc = _fsck_verdict(args.db)
        add(None, "fsck.txt", "fsck", text.encode(), rc,
            exit_status=rc, checked=[os.path.abspath(d)
                                     for d in args.db])
    cfg = json.dumps(_config_doc(), indent=1, sort_keys=True) + "\n"
    add(None, "config.json", "config", cfg.encode(), 0)

    if not files:
        print("quorum-debug-bundle: nothing collected",
              file=sys.stderr)
        return 2

    manifest = integrity.seal({
        "schema": schema_mod.DEBUG_BUNDLE_SCHEMA,
        "meta": {
            "tool": "quorum-debug-bundle",
            "pid": os.getpid(),
            "argv": list(sys.argv),
            "created_unix_s": int(time.time()),
            "missing": missing,
        },
        "files": files,
    })
    for err in schema_mod.validate_debug_bundle_manifest(manifest):
        # a self-check only: the validator and this writer live in
        # the same PR, so a disagreement is a bug, not bad input
        print(f"manifest self-check: {err}", file=sys.stderr)
    mdata = (json.dumps(manifest, indent=1) + "\n").encode()
    try:
        with tarfile.open(args.out, "w:gz") as tar:
            def addfile(nm: str, data: bytes) -> None:
                info = tarfile.TarInfo(nm)
                info.size = len(data)
                info.mtime = int(time.time())
                tar.addfile(info, io.BytesIO(data))
            addfile("MANIFEST.json", mdata)
            for nm, data in payload:
                addfile(nm, data)
    except OSError as e:
        print(f"{args.out}: {e}", file=sys.stderr)
        return 1
    total = sum(f["bytes"] for f in files)
    print(f"quorum-debug-bundle: {args.out}: {len(files)} file(s), "
          f"{total} bytes payload"
          + (f", {missing} missing" if missing else ""))
    return 0


if __name__ == "__main__":
    sys.exit(main())
