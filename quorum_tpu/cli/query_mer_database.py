"""query_mer_database — print count+quality for given mers
(reference: src/query_mer_database.cc:7-24; same output format).

Telemetry (ISSUE 3 satellite): the same observability surface as the
main CLIs — `--metrics` writes a final JSON with per-query counters
(`mers_queried`/`mers_found`/`mers_bad_length`), and the
`--metrics-port`/`--metrics-textfile`/`--trace-spans` block works
identically. Stdout stays reference-identical.
"""

from __future__ import annotations

import argparse
import sys

from ..io import db_format
from ..ops import mer
from .observability import add_observability_args, observability


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="query_mer_database",
        description="Print count and quality flag for the given mers.",
    )
    add_observability_args(p, metrics=True)
    p.add_argument("db", help="Mer database")
    p.add_argument("mers", nargs="+", metavar="mer",
                   help="Mers to look up")
    return p


def main(argv=None) -> int:
    from ..utils.jaxcache import enable_cache
    enable_cache()
    args = build_parser().parse_args(argv)
    with observability(args.metrics, args.metrics_interval,
                       port=args.metrics_port,
                       textfile=args.metrics_textfile,
                       live=args.metrics_live,
                       trace_spans=args.trace_spans,
                       push_url=args.metrics_push_url,
                       push_interval=args.metrics_push_interval,
                       alert_rules=args.alert_rules,
                       stage="query_mer_database") as obs:
        reg, tracer = obs.registry, obs.tracer
        try:
            with tracer.span("load_db"):
                state, meta, _ = db_format.read_db(args.db,
                                                   to_device=False)
        except (RuntimeError, ValueError, OSError) as e:
            print(str(e), file=sys.stderr)
            obs.status = "error"
            return 1
        k = meta.k
        reg.set_meta(db=args.db, k=k)
        print(k)
        for s in args.mers:
            if len(s) != k:
                print(f"{s}: wrong length (k={k})", file=sys.stderr)
                reg.counter("mers_bad_length").inc()
                continue
            with tracer.span("query"):
                hi, lo = mer.pack_kmer(s)
                chi, clo = mer.canonical_py(hi, lo, k)
                v = db_format.db_lookup_np(state, meta, chi, clo)
                canon = mer.unpack_kmer(chi, clo, k)
            print(f"{s}:{canon} val:{v >> 1} qual:{v & 1}")
            reg.counter("mers_queried").inc()
            if int(v) >> 1 > 0:
                reg.counter("mers_found").inc()
            reg.heartbeat(stage="query_mer_database")
    return 0


if __name__ == "__main__":
    sys.exit(main())
