"""query_mer_database — print count+quality for given mers
(reference: src/query_mer_database.cc:7-24; same output format)."""

from __future__ import annotations

import sys

from ..io import db_format
from ..ops import mer


def main(argv=None) -> int:
    from ..utils.jaxcache import enable_cache
    enable_cache()
    argv = sys.argv[1:] if argv is None else argv
    if len(argv) < 2:
        print(f"Usage: query_mer_database db mer ...", file=sys.stderr)
        return 1
    try:
        state, meta, _ = db_format.read_db(argv[0], to_device=False)
    except (RuntimeError, ValueError, OSError) as e:
        print(str(e), file=sys.stderr)
        return 1
    k = meta.k
    print(k)
    for s in argv[1:]:
        if len(s) != k:
            print(f"{s}: wrong length (k={k})", file=sys.stderr)
            continue
        hi, lo = mer.pack_kmer(s)
        chi, clo = mer.canonical_py(hi, lo, k)
        v = db_format.db_lookup_np(state, meta, chi, clo)
        canon = mer.unpack_kmer(chi, clo, k)
        print(f"{s}:{canon} val:{v >> 1} qual:{v & 1}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
