"""quorum — the top-level pipeline driver.

Reference: src/quorum.in (Perl). Orchestrates quality-base autodetect
(quorum.in:129-152), quorum_create_database (:154-160), and error
correction — single-file mode (:171-173) or paired mode, where the
reference forks a merge | correct | split process pipe (:172-231). We
run the same chain in-process: merge_mate_pairs.merge_records streams
interleaved pairs through run_error_correct (the prefetch thread gives
the reader/device overlap), and split_mate_pairs de-interleaves the
corrected .fa into <prefix>_1.fa / <prefix>_2.fa.
"""

from __future__ import annotations

import argparse
import os
import re
import sys
import time

import dataclasses

from ..io import checkpoint as ckpt_mod
from ..io import integrity as integrity_mod
from ..io import fastq, packing
from ..utils import faults, levers, resources
from ..models.error_correct import ECOptions, run_error_correct

# EC's default quality cutoff when the driver passes no -q/-Q to it —
# the SAME constant the EC CLI defaults to (models/ec_config), so the
# replay cache's packed qual>=cutoff plane can never drift from the
# cutoff stage 2 resolves (ADVICE r5). The reference driver likewise
# never forwards a qual cutoff (quorum.in:160-171).
from ..models.ec_config import DEFAULT_QUAL_CUTOFF as _EC_QUAL_CUTOFF

# Replay-cache budget: the driver keeps stage 1's decoded+packed
# batches in RAM so stage 2 skips the second parse (the reference gets
# this for free from the page cache, quorum.in:154-231). Beyond the
# budget the cache is dropped and stage 2 re-reads from disk.
# QUORUM_REPLAY_CACHE_BYTES accepts k/M/G/T suffixes (utils/sizes).
def _replay_cap() -> int:
    from ..utils.sizes import parse_size
    raw = levers.raw("QUORUM_REPLAY_CACHE_BYTES")
    if raw is None:
        return 6 * 1024 ** 3
    try:
        return parse_size(raw)
    except (ValueError, TypeError):
        print(f"Ignoring invalid QUORUM_REPLAY_CACHE_BYTES={raw!r}",
              file=sys.stderr)
        return 6 * 1024 ** 3
from ..utils import vlog as vlog_mod
from ..utils.vlog import vlog
from . import create_database as cdb_cli
from . import error_correct_reads as ec_cli
from .observability import add_observability_args
from .merge_mate_pairs import merge_records
from .split_mate_pairs import split_stream

from .. import __version__ as _PKG_VERSION

# The reference quorum is 1.x; wrappers gate on `quorum --version`, so
# the CLI reports a 1.x-compatible version with the package version as
# the local segment (PEP 440).
VERSION = f"1.1.1+tpu.{_PKG_VERSION}"

# Retry backoff ceiling: exponential growth stops doubling here — a
# flapping device should not push the next attempt out by hours.
_RETRY_BACKOFF_CAP_MS = 30_000.0

# module-level so tests mock the clock without touching time.sleep
# globally (chaos tests assert the exact backoff sequence)
_sleep = time.sleep


def _run_stage_with_retries(reg, stage: str, attempt_fn, retries: int,
                            backoff_ms: float, cursor_fn=None) -> int:
    """Run one pipeline stage under the driver's retry policy: on
    failure (nonzero rc OR an exception of the stages' failure
    shapes), wait with capped exponential backoff and try again, up
    to `retries` extra attempts. Every attempt is recorded — the
    manifest carries `<stage>_attempts`, the registry counts
    `stage_retries_total`, and each retry emits a `stage_retry` event
    (cause, attempt number, resumed-from cursor via `cursor_fn`).
    `attempt_fn(attempt)` returns the stage's rc; retried attempts are
    expected to pass --resume so the stage continues from its
    checkpoint instead of restarting."""
    attempt = 0
    while True:
        cause = None
        try:
            rc = attempt_fn(attempt)
            if rc != 0:
                cause = f"exit status {rc}"
        except (ckpt_mod.CheckpointError,
                integrity_mod.IntegrityError) as e:
            # deterministic refusal (config mismatch, corrupt or
            # digest-failing artifact): retrying with backoff just
            # re-raises it — surface immediately
            rc = ckpt_mod.NON_RETRYABLE_RC
            cause = f"{type(e).__name__}: {e}"
        except resources.ResourceExhausted as e:
            # a required writer hit ENOSPC (or strict preflight
            # refused) in an in-process stage: already laddered
            # (sealed flight dump, disk_full event) — map to the
            # non-retryable rc below
            rc = resources.DISK_FULL_RC
            cause = f"{type(e).__name__}: {e}"
        except resources.StallError as e:
            # the watchdog aborted a wedged attempt: retryable — the
            # stage resumes from its checkpoint
            rc = resources.STALL_RC
            cause = f"{type(e).__name__}: {e}"
        except (RuntimeError, ValueError, OSError) as e:
            rc = 1
            cause = f"{type(e).__name__}: {e}"
        if reg.enabled:
            reg.set_meta(**{f"{stage}_attempts": attempt + 1})
        if rc == 0:
            return 0
        # DISK_FULL_RC joins the non-retryable set: a full disk does
        # not empty itself between backoff attempts, and every retry
        # would re-run hours of compute into the same ENOSPC
        if (rc in (ckpt_mod.NON_RETRYABLE_RC, resources.DISK_FULL_RC)
                or attempt >= retries):
            if cause:
                print(f"quorum: {stage} failed: {cause}",
                      file=sys.stderr)
            return rc
        delay_ms = min(backoff_ms * (2 ** attempt),
                       _RETRY_BACKOFF_CAP_MS)
        cursor = cursor_fn() if cursor_fn is not None else None
        reg.counter("stage_retries_total").inc()
        reg.event("stage_retry", stage=stage, attempt=attempt + 1,
                  cause=cause, backoff_ms=delay_ms, resumed_from=cursor)
        print(f"quorum: {stage} failed ({cause}); retrying in "
              f"{delay_ms / 1000.0:.1f}s (attempt {attempt + 2} of "
              f"{retries + 1}"
              + (f", resuming from batch {cursor}" if cursor is not None
                 else "") + ")", file=sys.stderr)
        if delay_ms > 0:
            _sleep(delay_ms / 1000.0)
        attempt += 1


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="quorum",
        description="Run the quorum error corrector on the given fastq "
                    "files. With --paired-files, an even number of files "
                    "is expected and corrected pairs are written to "
                    "<prefix>_1.fa and <prefix>_2.fa.",
    )
    p.add_argument("-s", "--size", default="200M",
                   help="Mer database size (default 200M)")
    p.add_argument("-t", "--threads", type=int, default=None,
                   help="Number of threads (default number of cpus)")
    p.add_argument("-p", "--prefix", default="quorum_corrected",
                   help="Output prefix (default quorum_corrected)")
    p.add_argument("-k", "--kmer-len", type=int, default=24,
                   help="Kmer length (default 24)")
    p.add_argument("-q", "--min-q-char", type=int, default=None,
                   help="Minimum quality char. Usually 33 or 64 "
                        "(autodetect)")
    p.add_argument("-m", "--min-quality", type=int, default=5,
                   help="Minimum above -q for high quality base (5)")
    p.add_argument("-w", "--window", type=int, default=None,
                   help="Window size for trimming")
    p.add_argument("-e", "--error", type=int, default=None,
                   help="Maximum number of errors in a window")
    p.add_argument("--min-count", type=int, default=None,
                   help="Minimum count for a k-mer to be good")
    p.add_argument("--skip", type=int, default=None,
                   help="Number of bases to skip to find anchor kmer")
    p.add_argument("--anchor", type=int, default=None,
                   help="Number of good kmer in a row for anchor")
    p.add_argument("--anchor-count", type=int, default=None,
                   help="Minimum count for an anchor kmer")
    p.add_argument("--contaminant", default=None,
                   help="Contaminant sequences")
    p.add_argument("--trim-contaminant", "--contaminant-trim",
                   action="store_true",
                   help="Trim sequences with contaminant mers")
    p.add_argument("-d", "--no-discard", action="store_true",
                   help="Do not discard reads, output a single N (false)")
    p.add_argument("-P", "--paired-files", action="store_true",
                   help="Preserve mate pairs in two files")
    p.add_argument("--homo-trim", type=int, default=None,
                   help="Trim homo-polymer on 3' end")
    p.add_argument("--batch-size", type=int, default=8192,
                   help="Reads per device batch")
    p.add_argument("--devices", default="auto", metavar="N",
                   help="Scale out over N local devices (power of "
                        "two; 'all' = every local device, 'auto' = "
                        "all on a real accelerator, 1 on CPU): "
                        "stage 1 builds the table sharded by leading "
                        "row bits, stage 2 corrects data-parallel "
                        "(replicated or routed table by size). "
                        "Output is byte-identical to --devices 1")
    p.add_argument("--profile", metavar="dir", default=None,
                   help="Write jax.profiler traces (per-stage "
                        "subdirectories of this directory)")
    p.add_argument("--metrics", metavar="path", default=None,
                   help="Write a run-manifest metrics JSON here plus "
                        "per-stage files with .stage1/.stage2 suffixes")
    p.add_argument("--metrics-interval", metavar="seconds", type=float,
                   default=0.0,
                   help="With --metrics: JSONL heartbeat period for "
                        "the stages (0 = off)")
    add_observability_args(p, driver=True)
    # fault tolerance (ISSUE 4)
    p.add_argument("--checkpoint-dir", metavar="dir", default=None,
                   help="Enable crash-safe checkpoints: stage-1 table "
                        "snapshots land here; stage 2 journals beside "
                        "its output. A killed run restarted with "
                        "--resume continues instead of recounting")
    p.add_argument("--checkpoint-every", metavar="batches", type=int,
                   default=64,
                   help="Batches between stage checkpoints "
                        "(default 64)")
    p.add_argument("--resume", action="store_true",
                   help="Continue an interrupted run: a finished "
                        "stage-1 database is reused, otherwise each "
                        "stage resumes from its last checkpoint")
    p.add_argument("--stage-retries", metavar="n", type=int, default=0,
                   help="Retry a failed stage up to n times with "
                        "capped exponential backoff, resuming from "
                        "its checkpoint (default 0 = fail fast)")
    p.add_argument("--retry-backoff-ms", metavar="ms", type=float,
                   default=500.0,
                   help="Base retry backoff; doubles per attempt, "
                        "capped at 30s (default 500)")
    p.add_argument("--on-bad-read",
                   choices=fastq.BadReadPolicy.MODES, default="abort",
                   help="Malformed-record policy: abort (default), "
                        "skip and count, or quarantine to "
                        "<prefix>.quarantine.fastq")
    # data integrity (ISSUE 8)
    p.add_argument("--db-version", type=int, choices=(4, 5), default=5,
                   help="Mer-database export version: 5 (default) "
                        "carries per-section CRC32C digests + a "
                        "whole-file trailer digest; 4 is the bare "
                        "layout (same payload bytes)")
    p.add_argument("--db-layout", choices=("single", "sharded"),
                   default="single",
                   help="Mer-database on-disk layout: single (default) "
                        "writes one file (gathering a sharded table "
                        "to one chip); sharded streams per-shard "
                        "files under a sealed manifest — no "
                        "cross-device gather, no single-chip geometry "
                        "cap, same payload bytes")
    p.add_argument("--verify-db", choices=("full", "sample", "off"),
                   default="full",
                   help="Checksum verification when stage 2 loads a "
                        "v5 database: full (default), sample "
                        "(random chunk scrub), or off. A bad digest "
                        "refuses the run (rc 3)")
    # memory-frugal counting (ISSUE 14)
    p.add_argument("--prefilter", choices=("auto", "off", "two-pass",
                                           "inline"),
                   default="auto",
                   help="Stage-1 singleton prefilter: drop mers seen "
                        "once before they claim a table slot "
                        "(two-pass = exact via a sketch pass; inline "
                        "= khmer-style online). The database declares "
                        "its presence floor and stage 2 auto-applies "
                        "it — output equals an unfiltered run at the "
                        "same floor. auto = QUORUM_PREFILTER env > "
                        "autotune profile > off")
    p.add_argument("--partitions", type=int, default=1, metavar="P",
                   help="Build the mer database in P sequential "
                        "passes (power of two <= 256), each at 1/P "
                        "the table memory, exported straight into "
                        "the sharded manifest — byte-identical "
                        "payload, terabase-scale inputs on one HBM")
    p.add_argument("--render-workers", type=int, default=0, metavar="N",
                   help="Stage-2 host finish/render workers behind a "
                        "sequence-numbered reorder stage (0 = auto, "
                        "min(4, cores)); output is byte-identical for "
                        "any N")
    faults.add_fault_args(p)
    from ..parallel import fleet as fleet_mod
    fleet_mod.add_fleet_args(p)
    p.add_argument("--debug", action="store_true",
                   help="Display debugging information")
    p.add_argument("--version", action="version", version=VERSION)
    p.add_argument("reads", nargs="*", help="Input fastq files")
    return p


def _stage_path(base: str, tag: str) -> str:
    """Per-stage artifact path: out.json -> out.stage1.json (same for
    .jsonl); a path without a known extension just gets the suffix
    appended."""
    for ext in (".jsonl", ".json"):
        if base.endswith(ext):
            return f"{base[:-len(ext)]}.{tag}{ext}"
    return f"{base}.{tag}"


def detect_min_q_char(path: str, max_reads: int = 1000) -> int:
    """Scan up to `max_reads` records of `path` for the smallest quality
    character (quorum.in:129-152), with the reference's special Illumina
    adjustment (min char 35 or 66 -> subtract 2, quality values 0/1
    unseen) and the 33/59/64 sanity check."""
    min_q = 256
    for i, (_hdr, _seq, qual) in enumerate(fastq.iter_records([path])):
        if i >= max_reads:
            break
        if not qual:
            raise RuntimeError("Invalid fastq format")
        min_q = min(min_q, min(qual))
    if min_q in (35, 66):
        min_q -= 2
    if min_q not in (33, 59, 64):
        raise RuntimeError(
            f"Found an unusual minimum quality char of {min_q} "
            f"({chr(min_q) if 0 <= min_q < 256 else '?'}). Stopping now. "
            f"Use option -q to override")
    return min_q


def main(argv=None) -> int:
    from ..telemetry import track_jax_compile_cache
    from ..utils.jaxcache import enable_cache
    from .observability import observability
    cache_dir = enable_cache()
    args = build_parser().parse_args(argv)
    # OR, not assign: QUORUM_TPU_VERBOSE may have enabled it already
    vlog_mod.verbose = args.debug or vlog_mod.verbose
    # one in-process plan covers the driver AND both stages (their
    # mains run in this process); subprocess children would pick it up
    # from the QUORUM_FAULT_PLAN env var instead
    faults.setup(args.fault_plan)

    # fleet bring-up (ISSUE 20) BEFORE observability or any jax
    # device use: jax.distributed must initialize before the backend
    from ..parallel import fleet as fleet_mod
    try:
        flt = fleet_mod.ensure_initialized(args)
    except (RuntimeError, ValueError) as e:
        print(f"quorum: {e}", file=sys.stderr)
        return 1
    metrics_base = args.metrics
    if flt is not None and args.metrics:
        # hosts share one filesystem in CI (and may on NFS pods):
        # each host's own documents land under a per-host path; the
        # ONE aggregated fleet document keeps the original base
        args.metrics = fleet_mod.host_scoped_path(args.metrics,
                                                  flt.process_id)

    # driver telemetry: the run manifest (resolved config, jax
    # backend/devices, compile-cache hits) plus per-child timings;
    # the listener must attach BEFORE the stages compile anything.
    # Live exposition (--metrics-port/--metrics-textfile) forces a
    # real registry even without --metrics; the in-process stage
    # registries self-register with the same live set, so one
    # endpoint/textfile carries driver + stage1 + stage2 under
    # stage=... labels. observability() keeps everything from the
    # live-endpoint start on under one umbrella: an UNCAUGHT
    # exception (the stage CLIs only catch RuntimeError; a busy
    # --metrics-port raises OSError here) still frees the /metrics
    # port and stamps the manifest status=error before propagating.
    # The driver's own span file covers work done in the DRIVER
    # process (the shared read/pack producer) — the stages'
    # in-device loops land in the forwarded .stage1/.stage2 files.
    # --metrics-push-url rides the DRIVER's pusher only: the stage
    # registries live in this process, so the pushed exposition
    # (render_live) already carries driver + stage1 + stage2 — a
    # per-stage pusher would triple-post the same series
    # the driver's own resource-guard frame watches the filesystems
    # its artifacts land on (the in-process stages nest their own
    # frames over the same paths); the stall watchdog is per-STAGE —
    # only the stage loops beat, so arming one here would misfire
    watch = [p for p in (args.prefix + "_mer_database.jf",
                         args.checkpoint_dir, args.metrics) if p]
    with observability(args.metrics, args.metrics_interval,
                       port=args.metrics_port,
                       textfile=args.metrics_textfile,
                       trace_spans=(_stage_path(args.trace_spans, "driver")
                                    if args.trace_spans else None),
                       profile=args.profile,
                       push_url=args.metrics_push_url,
                       push_interval=args.metrics_push_interval,
                       alert_rules=args.alert_rules,
                       watch_paths=watch) as obs:
        reg = obs.registry
        track_jax_compile_cache(reg)

        def _cache_gauges(reg_) -> None:
            hits = reg_.counter("jax_cache_hits").value
            reqs = reg_.counter("jax_cache_requests").value
            reg_.gauge("jax_cache_misses").set(max(0, reqs - hits))

        obs.at_exit(_cache_gauges)
        if flt is not None and reg.enabled:
            reg.set_meta(host_process_count=flt.num_processes,
                         host_process_index=flt.process_id)
        rc = _main_inner(args, reg, obs.tracer, cache_dir, flt)
        if rc != 0:
            obs.status = "error"
        elif reg.enabled:
            # the "real driver entry point" for aggregate_metrics the
            # telemetry ROADMAP item has wanted since PR 2: every run
            # lands ONE job-level aggregated document (per-host shards
            # under `hosts`; a single host on a local --devices mesh is
            # simply a one-shard reduce). Collective + symmetric: on a
            # fleet every host calls it, and process 0 writes the one
            # document at the ORIGINAL --metrics base.
            try:
                from ..parallel import multihost
                hosts_path = (_stage_path(metrics_base, "hosts")
                              if metrics_base else None)
                reg.set_meta(metrics_hosts=hosts_path)
                multihost.aggregate_metrics(reg, path=hosts_path)
            except Exception as e:  # noqa: BLE001 - reporting only
                print(f"quorum: metrics aggregation failed: {e}",
                      file=sys.stderr)
    return rc


def _main_inner(args, reg, driver_tracer, cache_dir, flt=None) -> int:
    if not re.match(r"^\d+[kMGT]?$", args.size):
        print(f"Invalid size '{args.size}'. It must be a number, maybe "
              "followed by a suffix (like k, M, G for thousand, million "
              "and billion).", file=sys.stderr)
        return 1
    if not args.reads:
        print("No sequence files. See quorum --help.", file=sys.stderr)
        return 1
    if args.paired_files and len(args.reads) % 2 != 0:
        print("With --paired-files an even number of input files is "
              "required.", file=sys.stderr)
        return 1

    import jax
    from ..parallel import fleet as fleet_mod
    if flt is None:
        flt = fleet_mod.active()
    if jax.process_count() > 1 and flt is None:
        # multi-host without the fleet bring-up: per-host driver runs
        # would race on one output path. The fleet tier (ISSUE 20)
        # owns the orchestration — require its flags.
        print("quorum: multi-host runs need the fleet flags "
              "(--coordinator/--num-processes/--process-id, or the "
              "QUORUM_FLEET_* levers) so the driver can shard input "
              "and merge per-host outputs", file=sys.stderr)
        return 1
    if flt is not None and args.paired_files:
        # paired mode streams ONE interleaved record stream through
        # correction — there is no per-file decomposition to shard
        print("quorum: --paired-files does not compose with a "
              "multi-host fleet yet; run unpaired or drop the fleet "
              "flags", file=sys.stderr)
        return 1

    # --devices: resolve once, forward the RESOLVED count to both
    # stages (their own 'auto' could disagree if device enumeration
    # races a plugin registration), and shape the shared producer's
    # batches to whole per-device slices
    from ..parallel.tile_sharded import resolve_devices_and_batch
    try:
        n_devices, args.batch_size = resolve_devices_and_batch(
            args.devices, args.batch_size, "quorum")
    except ValueError as e:
        print(str(e), file=sys.stderr)
        return 1
    vlog("Using ", n_devices, " device(s)")

    # ISSUE 14 validations, mirrored here so the operator gets the
    # refusal directly instead of "Creating the mer database failed"
    P = args.partitions
    if P < 1 or P > 256 or (P & (P - 1)):
        print(f"quorum: --partitions must be a power of two in "
              f"[1, 256], got {P}", file=sys.stderr)
        return 1
    if flt is not None:
        # fleet stage 1 is partition-binned: plan P up to a power of
        # two >= the process count so every host owns >= 1 pass
        planned = fleet_mod.plan_partitions(P, flt.num_processes)
        if planned != P:
            vlog("Fleet run: raising --partitions to ", planned,
                 " (", flt.num_processes, " processes)")
        P = args.partitions = planned
    if args.prefilter not in ("auto", "off") and n_devices > 1:
        print("quorum: --prefilter composes with --devices 1 today; "
              "use --partitions for multi-pass capacity over a mesh",
              file=sys.stderr)
        return 1
    if args.prefilter == "inline" and (P > 1 or args.checkpoint_dir):
        print("quorum: --prefilter=inline supports neither "
              "--partitions nor --checkpoint-dir; use "
              "--prefilter=two-pass", file=sys.stderr)
        return 1

    # per-stage observability paths (forward --metrics, --profile and
    # --trace-spans consistently to both children, suffixed per
    # stage; --metrics-textfile is shared — each stage's heartbeats
    # atomically re-render the ONE file from all live registries)
    m1 = _stage_path(args.metrics, "stage1") if args.metrics else None
    m2 = _stage_path(args.metrics, "stage2") if args.metrics else None
    p1 = os.path.join(args.profile, "stage1") if args.profile else None
    p2 = os.path.join(args.profile, "stage2") if args.profile else None
    ts1 = (_stage_path(args.trace_spans, "stage1")
           if args.trace_spans else None)
    ts2 = (_stage_path(args.trace_spans, "stage2")
           if args.trace_spans else None)
    if reg.enabled:
        devs = jax.devices()
        reg.set_meta(
            driver="quorum", version=VERSION,
            config={k: "" if v is None else str(v)
                    for k, v in vars(args).items()},
            jax_backend=jax.default_backend(),
            device_count=len(devs),
            devices_resolved=n_devices,
            device_kinds=sorted({d.device_kind for d in devs}),
            process_count=jax.process_count(),
            compile_cache_dir=str(cache_dir),
            metrics_stage1=m1, metrics_stage2=m2,
        )

    min_q_char = args.min_q_char
    if min_q_char is None:
        try:
            min_q_char = detect_min_q_char(args.reads[0])
        except (RuntimeError, ValueError, OSError) as e:
            print(str(e), file=sys.stderr)
            return 1
    vlog("Using min quality char ", min_q_char, " (+", args.min_quality, ")")

    # CPU-count autodetect, like the reference driver's /proc/cpuinfo
    # scan (quorum.in:110-120); forwarded to both stages' host decode
    threads = args.threads if args.threads else (os.cpu_count() or 1)
    vlog("Using ", threads, " threads for host decode")

    # Stage 1: quorum_create_database -s SIZE -m K -q char+qual -t N
    # -b 7 (quorum.in:154-160)
    db_file = args.prefix + "_mer_database.jf"
    cdb_argv = ["-s", args.size, "-m", str(args.kmer_len),
                "-q", str(min_q_char + args.min_quality), "-b", "7",
                "-t", str(threads),
                "-o", db_file, "--batch-size", str(args.batch_size),
                "--devices", str(n_devices),
                "--db-version", str(args.db_version),
                "--db-layout", args.db_layout,
                "--preflight", args.preflight]
    if args.stall_timeout_s and args.stall_timeout_s > 0:
        cdb_argv.extend(["--stall-timeout-s",
                         str(args.stall_timeout_s)])
    if args.prefilter != "auto":
        cdb_argv.extend(["--prefilter", args.prefilter])
    if args.partitions != 1:
        cdb_argv.extend(["--partitions", str(args.partitions)])
    if args.checkpoint_dir:
        cdb_argv.extend(["--checkpoint-dir", args.checkpoint_dir,
                         "--checkpoint-every",
                         str(args.checkpoint_every)])
    if args.on_bad_read != "abort":
        # matters for the stage's own read path (it normally consumes
        # the driver's shared producer, which applies the policy
        # itself below)
        cdb_argv.extend(["--on-bad-read", args.on_bad_read])
    if m1 is not None:
        cdb_argv.extend(["--metrics", m1,
                         "--metrics-interval", str(args.metrics_interval)])
    if p1 is not None:
        cdb_argv.extend(["--profile", p1])
    if ts1 is not None:
        cdb_argv.extend(["--trace-spans", ts1])
    if args.metrics_textfile:
        cdb_argv.extend(["--metrics-textfile", args.metrics_textfile])
    if args.alert_rules:
        # each stage registry evaluates the same rule set (the
        # driver's own registry too — its engine watches the
        # stage_retries/push counters that live driver-side)
        cdb_argv.extend(["--alert-rules", args.alert_rules])
    if args.metrics_port is not None:
        # the driver owns the endpoint; the stage must still run a
        # real registry so its counters appear on it
        cdb_argv.append("--metrics-live")
    if args.debug:
        cdb_argv.append("-v")
        print("+ quorum_create_database " + " ".join(cdb_argv)
              + " " + " ".join(args.reads), file=sys.stderr)

    # Parse + pack the reads ONCE for both stages (unpaired mode):
    # stage 1 consumes this generator; every yielded (batch, packed)
    # pair is retained (packed with both stages' quality thresholds)
    # and replayed into stage 2, sparing the second disk parse + H2D
    # re-pack that the two-process reference gets from the page cache.
    reads_cache: list = []
    # "complete" flips True only when the caching producer is consumed
    # to exhaustion: a multi-pass stage 1 that abandons its first
    # iterator mid-stream (a partition-geometry restart) must never
    # leave a TRUNCATED cache that stage 2 would silently replay as
    # the whole input (ISSUE 14 review)
    # on a fleet the RAM replay cache is off: stage 2 corrects
    # PER-FILE segments (each host re-reads only its own files), so a
    # full-input replay would feed every host every read
    cache_ok = not args.paired_files and flt is None
    cache_state = {"bytes": 0, "ok": cache_ok,
                   "writer": None, "complete": False}
    # with --checkpoint-dir the replay cache ALSO streams to disk
    # (io/checkpoint.ReplayCache), so a later --resume run feeds
    # stage 2 from the capture instead of re-parsing the FASTQ —
    # before round 7 only the stage OUTPUTS resumed
    replay_identity = {
        "inputs": list(args.reads),
        "batch_size": int(args.batch_size),
        "qual_cutoff": int(_EC_QUAL_CUTOFF),
        "on_bad_read": args.on_bad_read,
    }
    replay_store = (ckpt_mod.ReplayCache(args.checkpoint_dir)
                    if args.checkpoint_dir and not args.paired_files
                    and flt is None
                    else None)

    def _cached_batches():
        from ..utils.pipeline import prefetch
        t1 = min_q_char + args.min_quality
        policy = None
        if args.on_bad_read != "abort":
            # the driver parses ONCE for both stages, so the bad-read
            # policy lives on ITS reader; the quarantine lands beside
            # the corrected output
            policy = fastq.BadReadPolicy(
                args.on_bad_read, args.prefix + ".quarantine.fastq",
                reg if reg.enabled else None)
            reg.counter("bad_reads_total")
            reg.set_meta(on_bad_read=args.on_bad_read)
        src = fastq.read_batches(args.reads, args.batch_size,
                                 threads=threads, policy=policy)

        def _pack_and_keep(it):
            import numpy as _np
            cap_bytes = _replay_cap()  # resolve once, not per batch
            writer = cache_state["writer"]
            for b in it:
                # SEPARATE single-plane wires per stage: a combined
                # two-plane wire would give the driver's executables
                # different jit keys (the threshold tuple is static)
                # than the standalone stage CLIs compile — measured
                # as minutes of needless recompile per driver run.
                pk1 = packing.pack_reads(b.codes, b.quals, b.lengths,
                                         thresholds=(t1,))
                item = (dataclasses.replace(b, quals=None),
                        pk1.compact())
                if cache_state["ok"]:
                    # the cached stage-2 wire shares pk1's code/N
                    # planes and adds only the EC qual plane; stage 2
                    # never touches host quals, so the cached batch
                    # drops them. Count retained headers too (~90 B
                    # of str + list-slot overhead each).
                    pk2 = packing.PackedReads(
                        pcodes=pk1.pcodes, nmask=pk1.nmask,
                        hq={_EC_QUAL_CUTOFF: _np.packbits(
                            _np.asarray(b.quals, _np.uint8)
                            >= _EC_QUAL_CUTOFF,
                            axis=1, bitorder="little")},
                        lengths=pk1.lengths,
                        length=pk1.length).compact()
                    cached = (item[0], pk2)
                    cache_state["bytes"] += (
                        b.codes.nbytes + pk2.nbytes
                        + sum(len(h) + 90 for h in b.headers))
                    if cache_state["bytes"] > cap_bytes:
                        cache_state["ok"] = False
                        reads_cache.clear()
                        if writer is not None:
                            writer.abort()
                    else:
                        reads_cache.append(cached)
                        if writer is not None:
                            writer.add(cached[0], cached[1])
                yield item
            # every batch landed: the RAM cache is the full input now
            # (an abandoned iterator never reaches this line), and the
            # on-disk capture commits (the manifest is the atomic
            # commit point — a kill before this line just means the
            # next resume re-parses)
            cache_state["complete"] = True
            if writer is not None and cache_state["ok"]:
                writer.finish()
        return prefetch(_pack_and_keep(src),
                        metrics=reg if reg.enabled else None,
                        name="reads_producer",
                        tracer=driver_tracer)

    def _plain_batches():
        # repeat passes of a multi-pass stage 1 (ISSUE 14): a fresh
        # quiet re-parse — deterministic batching identical to the
        # caching producer (a quarantine/skip policy skips the same
        # records), no cache side effects, no double-counted
        # telemetry. The span-parallel single-file reader (PR 9)
        # keeps these re-reads cheap.
        from ..utils.pipeline import prefetch
        t1 = min_q_char + args.min_quality
        policy = (fastq.BadReadPolicy("skip", None, None)
                  if args.on_bad_read != "abort" else None)
        src = fastq.read_batches(args.reads, args.batch_size,
                                 threads=threads, policy=policy)

        def _pack(it):
            for b in it:
                pk1 = packing.pack_reads(b.codes, b.quals, b.lengths,
                                         thresholds=(t1,))
                yield dataclasses.replace(b, quals=None), pk1.compact()
        return prefetch(_pack(src))

    handoff: dict = {}
    if reg.enabled:
        reg.counter("stage_retries_total")  # lands even at 0

    def _stage1_cursor():
        if not args.checkpoint_dir:
            return None
        # on a fleet, stage 1 scopes its checkpoint artifacts per
        # host (models/create_database); peek at THIS host's cursor
        ck_dir = (flt.host_scoped_dir(args.checkpoint_dir)
                  if flt is not None else args.checkpoint_dir)
        if args.partitions > 1:
            return ckpt_mod.Stage1PartitionCursor(ck_dir).cursor()
        cls = (ckpt_mod.Stage1ShardedCheckpoint if n_devices > 1
               else ckpt_mod.Stage1Checkpoint)
        return cls(ck_dir).cursor()

    def _stage1_attempt(attempt: int) -> int:
        # every attempt gets a FRESH shared producer and replay cache
        # (a failed attempt consumed part of the previous generator).
        # The producer is handed over as a FACTORY: pass 1 of a
        # multi-pass build consumes the caching producer (populating
        # the stage-2 replay cache exactly once), repeat passes
        # re-parse quietly.
        handoff.clear()
        reads_cache.clear()
        cache_state["bytes"] = 0
        cache_state["ok"] = cache_ok
        cache_state["complete"] = False
        cache_state["writer"] = (
            replay_store.start(replay_identity, _replay_cap())
            if replay_store is not None else None)
        argv = list(cdb_argv)
        if args.checkpoint_dir and (args.resume or attempt > 0):
            argv.append("--resume")
        calls = {"n": 0}

        def factory():
            first = calls["n"] == 0
            calls["n"] += 1
            return _cached_batches() if first else _plain_batches()
        return cdb_cli.main(argv + list(args.reads), handoff=handoff,
                            batches_factory=factory)

    def _stage1_db_reusable() -> bool:
        """The reuse bar: a readable database header whose geometry
        matches THIS run's flags. write_db is atomic (tmp-then-
        rename) so a torn file shouldn't exist, but a foreign file,
        or a database built at a different k, must trigger a rebuild,
        not feed stage 2 the wrong table. (The header doesn't record
        the input set — resuming over changed inputs is the
        operator's assertion, as with any --resume.)"""
        from ..io import db_format as _dbf
        try:
            h = _dbf.read_header(db_file)
        except (OSError, ValueError):
            return False
        if (h.get("key_len") != 2 * args.kmer_len
                or h.get("bits") != 7):
            print(f"quorum: --resume found {db_file} built with "
                  f"k={h.get('key_len', 0) // 2}/bits={h.get('bits')}"
                  f" (this run: k={args.kmer_len}/bits=7); rebuilding",
                  file=sys.stderr)
            return False
        if h.get("version", 1) >= 5 and args.verify_db != "off":
            # the reuse decision is the one place a corrupt database
            # can be CURED instead of refused: verify its digests per
            # --verify-db and rebuild on damage rather than handing
            # stage 2 a file it will refuse (ISSUE 8)
            try:
                _, problems = _dbf.verify_db_file(db_file,
                                                  args.verify_db)
            except (OSError, ValueError) as e:
                problems = [("file", None, str(e))]
            if problems:
                sec, _off, msg = problems[0]
                print(f"quorum: --resume found {db_file} but it "
                      f"failed verification ({sec}: {msg}); "
                      "rebuilding", file=sys.stderr)
                reg.counter("integrity_errors_total").inc()
                reg.event("integrity_error", file=db_file,
                          section=sec, detail=msg)
                return False
        return True

    # driver --resume with stage 1 already durable (its database file
    # exists and validates, and no partial checkpoint is pending):
    # reuse it instead of recounting — the point of resuming. Stage 2
    # then reloads the table and re-reads the inputs from disk.
    skip_s1 = (args.resume and os.path.exists(db_file)
               and _stage1_cursor() is None and _stage1_db_reusable())
    if flt is not None and args.resume:
        # the skip decision must be COLLECTIVE: one host skipping
        # stage 1 while another rebuilds would deadlock the rebuild's
        # record exchange. Any host that can't reuse forces a rebuild
        # everywhere (the database file lives on the shared prefix,
        # but partial per-host checkpoints may not agree).
        votes = flt.exchange_json("stage1_skip", bool(skip_s1))
        skip_s1 = all(votes)
    if skip_s1:
        vlog("Resume: reusing existing mer database ", db_file)
        reg.event("stage_skipped", stage="create_database",
                  reason="resume: database exists")
        reg.set_meta(stage1_resumed_db=db_file)
    else:
        t_s1 = time.perf_counter()
        s1_rc = _run_stage_with_retries(reg, "create_database",
                                        _stage1_attempt,
                                        args.stage_retries,
                                        args.retry_backoff_ms,
                                        cursor_fn=_stage1_cursor)
        if s1_rc != 0:
            if s1_rc in (resources.DISK_FULL_RC, resources.STALL_RC):
                # disk-full / stall rcs carry retry semantics for
                # OUTER supervisors (cluster schedulers) — propagate
                print("Creating the mer database failed (out of disk "
                      "space or stalled).", file=sys.stderr)
                return s1_rc
            print("Creating the mer database failed. Most likely the "
                  "size passed to the -s switch is too small.",
                  file=sys.stderr)
            return 1
        if reg.enabled:
            s1_s = round(time.perf_counter() - t_s1, 3)
            reg.gauge("stage1_seconds").set(s1_s)
            reg.event("stage_done", stage="create_database",
                      seconds=s1_s)
    prepacked = (reads_cache if cache_state["ok"]
                 and cache_state["complete"] and reads_cache else None)
    prepacked_factory = (lambda: prepacked) if prepacked else None
    if prepacked_factory is None and replay_store is not None:
        # resumed run with stage 1 skipped (or its RAM cache lost):
        # replay the on-disk capture instead of re-parsing the FASTQ.
        # A capture that EXISTS but fails its digests is a loud
        # refusal (rc 3) — silently replaying corrupted reads would
        # corrupt the output while looking clean (ISSUE 8).
        try:
            replay = replay_store.load(replay_identity)
        except ckpt_mod.CheckpointError as e:
            print(f"quorum: {e}", file=sys.stderr)
            return ckpt_mod.NON_RETRYABLE_RC
        if replay is not None:
            vlog("Resume: replaying ", replay.n_batches,
                 " cached batches from ", replay_store.dir,
                 " (no FASTQ re-parse)")
            reg.event("replay_cache_resume",
                      n_batches=replay.n_batches)
            reg.set_meta(replay_cache_resumed=True)
            prepacked_factory = replay.batches

    # Stage 2: error correction (quorum.in:162-231)
    ec_common = ["--batch-size", str(args.batch_size),
                 "-t", str(threads), "--devices", str(n_devices),
                 "--verify-db", args.verify_db,
                 "--render-workers", str(args.render_workers),
                 "--preflight", args.preflight]
    if args.stall_timeout_s and args.stall_timeout_s > 0:
        ec_common.extend(["--stall-timeout-s",
                          str(args.stall_timeout_s)])
    for flag, val in (("--min-count", args.min_count),
                      ("--skip", args.skip),
                      ("--good", args.anchor),
                      ("--anchor-count", args.anchor_count),
                      ("--window", args.window),
                      ("--error", args.error),
                      ("--homo-trim", args.homo_trim),
                      ("--contaminant", args.contaminant)):
        if val is not None:
            ec_common.extend([flag, str(val)])
    if args.trim_contaminant:
        ec_common.append("--trim-contaminant")
    if args.checkpoint_dir:
        ec_common.extend(["--checkpoint-every",
                          str(args.checkpoint_every)])
    if args.on_bad_read != "abort":
        ec_common.extend(["--on-bad-read", args.on_bad_read])
    no_discard = args.no_discard or args.paired_files
    if no_discard:
        ec_common.append("--no-discard")
    if args.debug:
        ec_common.append("-v")
    if m2 is not None:
        ec_common.extend(["--metrics", m2,
                          "--metrics-interval", str(args.metrics_interval)])
    if p2 is not None:
        ec_common.extend(["--profile", p2])
    if ts2 is not None:
        ec_common.extend(["--trace-spans", ts2])
    if args.metrics_textfile:
        ec_common.extend(["--metrics-textfile", args.metrics_textfile])
    if args.alert_rules:
        ec_common.extend(["--alert-rules", args.alert_rules])
    if args.metrics_port is not None:
        ec_common.append("--metrics-live")

    def record_stage2(t0: float) -> None:
        if reg.enabled:
            s2_s = round(time.perf_counter() - t0, 3)
            reg.gauge("stage2_seconds").set(s2_s)
            reg.event("stage_done", stage="error_correct", seconds=s2_s)

    def _stage2_cursor():
        if not args.checkpoint_dir:
            return None
        return ckpt_mod.Stage2Journal(args.prefix).batches_done()

    def _stage2_resume(attempt: int) -> bool:
        return bool(args.checkpoint_dir
                    and (args.resume or attempt > 0))

    if not args.paired_files:
        ec_argv = ec_common + ["-o", args.prefix, db_file] + list(args.reads)
        if args.debug:
            print("+ quorum_error_correct_reads " + " ".join(ec_argv),
                  file=sys.stderr)

        def _stage2_attempt(attempt: int) -> int:
            argv = list(ec_argv)
            if _stage2_resume(attempt):
                argv.append("--resume")
            return ec_cli.main(argv, db=handoff.get("db"),
                               prepacked=(prepacked_factory()
                                          if prepacked_factory else None))

        t_s2 = time.perf_counter()
        s2_rc = _run_stage_with_retries(reg, "error_correct",
                                        _stage2_attempt,
                                        args.stage_retries,
                                        args.retry_backoff_ms,
                                        cursor_fn=_stage2_cursor)
        if s2_rc != 0:
            print("Error correction failed", file=sys.stderr)
            return (s2_rc if s2_rc in (resources.DISK_FULL_RC,
                                       resources.STALL_RC) else 1)
        record_stage2(t_s2)
        if replay_store is not None:
            # the corrected output is final — the capture is garbage
            # now (and sizeable); a finished stage-1 checkpoint clears
            # the same way
            replay_store.clear()
        return 0

    # Paired mode: merge | correct | split, in-process
    # (quorum.in:172-231). --no-discard is forced so every input read
    # yields exactly one output record and pairing survives the split.
    if args.debug:
        print(f"+ merge_mate_pairs {' '.join(args.reads)} | "
              f"quorum_error_correct_reads {' '.join(ec_common)} "
              f"{db_file} /dev/fd/0 | split_mate_pairs {args.prefix}",
              file=sys.stderr)
    opts = ECOptions(output=args.prefix, contaminant=args.contaminant,
                     batch_size=args.batch_size, threads=threads,
                     devices=n_devices, verify_db=args.verify_db,
                     render_workers=args.render_workers,
                     profile=p2, metrics=m2,
                     metrics_interval=args.metrics_interval,
                     metrics_textfile=args.metrics_textfile,
                     metrics_force=args.metrics_port is not None,
                     trace_spans=ts2, alert_rules=args.alert_rules,
                     preflight=args.preflight,
                     stall_timeout_s=args.stall_timeout_s)
    kwargs = dict(no_discard=True,
                  trim_contaminant=args.trim_contaminant)
    for key, val in (("min_count", args.min_count), ("skip", args.skip),
                     ("good", args.anchor),
                     ("anchor_count", args.anchor_count),
                     ("window", args.window), ("error", args.error),
                     ("homo_trim", args.homo_trim)):
        if val is not None:
            kwargs[key] = val
    def _stage2_paired_attempt(attempt: int) -> int:
        o = opts
        if args.checkpoint_dir:
            o = dataclasses.replace(
                opts, checkpoint_every=args.checkpoint_every,
                resume=_stage2_resume(attempt))
        run_error_correct(db_file, [], None, o,
                          records=merge_records(args.reads),
                          db=handoff.get("db"), **kwargs)
        return 0

    t_s2 = time.perf_counter()
    s2_rc = _run_stage_with_retries(reg, "error_correct",
                                    _stage2_paired_attempt,
                                    args.stage_retries,
                                    args.retry_backoff_ms,
                                    cursor_fn=_stage2_cursor)
    if s2_rc != 0:
        print("Error correction failed", file=sys.stderr)
        return (s2_rc if s2_rc in (resources.DISK_FULL_RC,
                                   resources.STALL_RC) else 1)
    record_stage2(t_s2)
    fa_path = args.prefix + ".fa"
    try:
        with open(fa_path, "r") as inp:
            split_stream(inp, args.prefix)
    except OSError as e:
        print(str(e), file=sys.stderr)
        return 1
    os.remove(fa_path)
    return 0


if __name__ == "__main__":
    sys.exit(main())
