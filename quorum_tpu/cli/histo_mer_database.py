"""histo_mer_database — count histogram split by quality bit, capped at
1000 (reference: src/histo_mer_database.cc:8-28; identical output:
"<count> <n_lowqual> <n_highqual>" for each non-empty bin). The primary
DB-equivalence check — one bincount reduce over the value array."""

from __future__ import annotations

import sys

import numpy as np

from ..io import db_format

HLEN = 1001


def histo(vals: np.ndarray) -> np.ndarray:
    v = np.asarray(vals)
    v = v[v != 0]
    counts = np.minimum(v >> 1, HLEN - 1).astype(np.int64)
    quals = (v & 1).astype(np.int64)
    out = np.zeros((HLEN, 2), dtype=np.int64)
    np.add.at(out, (counts, quals), 1)
    return out


def main(argv=None) -> int:
    from ..utils.jaxcache import enable_cache
    enable_cache()
    argv = sys.argv[1:] if argv is None else argv
    if len(argv) != 1:
        print(f"Usage: histo_mer_database db", file=sys.stderr)
        return 1
    try:
        state, meta, _ = db_format.read_db(argv[0], to_device=False)
    except (RuntimeError, ValueError, OSError) as e:
        print(str(e), file=sys.stderr)
        return 1
    _, _, vals = db_format.db_iterate(state, meta)
    out = histo(vals)
    for i in range(HLEN):
        if out[i, 0] or out[i, 1]:
            print(f"{i} {out[i, 0]} {out[i, 1]}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
