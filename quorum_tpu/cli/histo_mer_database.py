"""histo_mer_database — count histogram split by quality bit, capped at
1000 (reference: src/histo_mer_database.cc:8-28; identical output:
"<count> <n_lowqual> <n_highqual>" for each non-empty bin). The primary
DB-equivalence check — one bincount reduce over the value array.

Telemetry (ISSUE 3 satellite): same observability surface as the main
CLIs — `--metrics` records a `distinct_mers` counter and
`max_count` / `nonempty_bins` gauges; stdout stays
reference-identical.

`--json PATH` (ISSUE 17 satellite): a schema-versioned sidecar
(`quorum-tpu-histo/1`) carrying the same bins as machine-readable
rows plus summary stats — including the coverage-mode fit the
quality scorecard's coverage model uses
(telemetry/quality.coverage_from_histo) — so operators and tools
consume the spectrum without parsing stdout.
"""

from __future__ import annotations

import argparse
import json
import sys

import numpy as np

from ..io import db_format
from ..telemetry import quality
from ..telemetry.registry import atomic_write
from ..telemetry.schema import HISTO_SCHEMA
from .observability import add_observability_args, observability

HLEN = 1001


def histo(vals: np.ndarray) -> np.ndarray:
    v = np.asarray(vals)
    v = v[v != 0]
    counts = np.minimum(v >> 1, HLEN - 1).astype(np.int64)
    quals = (v & 1).astype(np.int64)
    out = np.zeros((HLEN, 2), dtype=np.int64)
    np.add.at(out, (counts, quals), 1)
    return out


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="histo_mer_database",
        description="Histogram of mer counts split by the quality bit.",
    )
    add_observability_args(p, metrics=True)
    p.add_argument("--json", metavar="path", default=None,
                   help="Also write the histogram as a "
                        "schema-versioned JSON sidecar "
                        "(quorum-tpu-histo/1): bins as [count, "
                        "n_lowqual, n_highqual] rows plus summary "
                        "stats including the fitted coverage mode")
    p.add_argument("db", help="Mer database")
    return p


def histo_doc(out: np.ndarray) -> dict:
    """The `--json` sidecar document for one computed histogram:
    non-empty bins as rows (count ascending, mirroring stdout), and
    the summary stats computed UNCONDITIONALLY — unlike the registry
    telemetry, the sidecar is its own artifact, not gated on
    --metrics."""
    bins = [[int(i), int(out[i, 0]), int(out[i, 1])]
            for i in range(out.shape[0]) if out[i, 0] or out[i, 1]]
    occupied = [row[0] for row in bins]
    return {
        "schema": HISTO_SCHEMA,
        "bins": bins,
        "stats": {
            "distinct_total": int(out.sum()),
            "distinct_nonempty": len(bins),
            "max_count": max(occupied) if occupied else 0,
            "coverage_mode": quality.coverage_from_histo(bins),
        },
    }


def main(argv=None) -> int:
    from ..utils.jaxcache import enable_cache
    enable_cache()
    args = build_parser().parse_args(argv)
    with observability(args.metrics, args.metrics_interval,
                       port=args.metrics_port,
                       textfile=args.metrics_textfile,
                       live=args.metrics_live,
                       trace_spans=args.trace_spans,
                       push_url=args.metrics_push_url,
                       push_interval=args.metrics_push_interval,
                       alert_rules=args.alert_rules,
                       stage="histo_mer_database") as obs:
        reg, tracer = obs.registry, obs.tracer
        try:
            with tracer.span("load_db"):
                state, meta, _ = db_format.read_db(args.db,
                                                   to_device=False)
        except (RuntimeError, ValueError, OSError) as e:
            print(str(e), file=sys.stderr)
            obs.status = "error"
            return 1
        reg.set_meta(db=args.db, k=meta.k)
        with tracer.span("histogram"):
            _, _, vals = db_format.db_iterate(state, meta)
            out = histo(vals)
        nonempty = 0
        for i in range(HLEN):
            if out[i, 0] or out[i, 1]:
                print(f"{i} {out[i, 0]} {out[i, 1]}")
                nonempty += 1
        if args.json:
            doc = histo_doc(out)
            atomic_write(args.json,
                         json.dumps(doc, indent=1) + "\n")
            if reg.enabled:
                reg.set_meta(histo_json=args.json)
                reg.gauge("coverage_mode").set(
                    doc["stats"]["coverage_mode"])
        if reg.enabled:
            total = int(out.sum())
            reg.counter("distinct_mers").inc(total)
            reg.gauge("nonempty_bins").set(nonempty)
            occupied = np.nonzero(out.sum(axis=1))[0]
            reg.gauge("max_count").set(
                int(occupied.max()) if occupied.size else 0)
    return 0


if __name__ == "__main__":
    sys.exit(main())
