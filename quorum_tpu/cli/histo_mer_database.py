"""histo_mer_database — count histogram split by quality bit, capped at
1000 (reference: src/histo_mer_database.cc:8-28; identical output:
"<count> <n_lowqual> <n_highqual>" for each non-empty bin). The primary
DB-equivalence check — one bincount reduce over the value array.

Telemetry (ISSUE 3 satellite): same observability surface as the main
CLIs — `--metrics` records a `distinct_mers` counter and
`max_count` / `nonempty_bins` gauges; stdout stays
reference-identical.
"""

from __future__ import annotations

import argparse
import sys

import numpy as np

from ..io import db_format
from .observability import add_observability_args, observability

HLEN = 1001


def histo(vals: np.ndarray) -> np.ndarray:
    v = np.asarray(vals)
    v = v[v != 0]
    counts = np.minimum(v >> 1, HLEN - 1).astype(np.int64)
    quals = (v & 1).astype(np.int64)
    out = np.zeros((HLEN, 2), dtype=np.int64)
    np.add.at(out, (counts, quals), 1)
    return out


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="histo_mer_database",
        description="Histogram of mer counts split by the quality bit.",
    )
    add_observability_args(p, metrics=True)
    p.add_argument("db", help="Mer database")
    return p


def main(argv=None) -> int:
    from ..utils.jaxcache import enable_cache
    enable_cache()
    args = build_parser().parse_args(argv)
    with observability(args.metrics, args.metrics_interval,
                       port=args.metrics_port,
                       textfile=args.metrics_textfile,
                       live=args.metrics_live,
                       trace_spans=args.trace_spans,
                       push_url=args.metrics_push_url,
                       push_interval=args.metrics_push_interval,
                       alert_rules=args.alert_rules,
                       stage="histo_mer_database") as obs:
        reg, tracer = obs.registry, obs.tracer
        try:
            with tracer.span("load_db"):
                state, meta, _ = db_format.read_db(args.db,
                                                   to_device=False)
        except (RuntimeError, ValueError, OSError) as e:
            print(str(e), file=sys.stderr)
            obs.status = "error"
            return 1
        reg.set_meta(db=args.db, k=meta.k)
        with tracer.span("histogram"):
            _, _, vals = db_format.db_iterate(state, meta)
            out = histo(vals)
        nonempty = 0
        for i in range(HLEN):
            if out[i, 0] or out[i, 1]:
                print(f"{i} {out[i, 0]} {out[i, 1]}")
                nonempty += 1
        if reg.enabled:
            total = int(out.sum())
            reg.counter("distinct_mers").inc(total)
            reg.gauge("nonempty_bins").set(nonempty)
            occupied = np.nonzero(out.sum(axis=1))[0]
            reg.gauge("max_count").set(
                int(occupied.max()) if occupied.size else 0)
    return 0


if __name__ == "__main__":
    sys.exit(main())
