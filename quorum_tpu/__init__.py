"""quorum_tpu — a TPU-native k-mer based Illumina error-correction framework.

A ground-up rebuild of the capabilities of Quorum (alekseyzimin/Quorum
v1.1.1) designed for TPU hardware: the two hot loops (k-mer database
construction and batched read correction) run as JAX/XLA programs over
HBM-resident hash tables, with multi-chip scaling via `jax.sharding.Mesh`
and XLA collectives instead of shared-memory pthreads.

Layer map (mirrors SURVEY.md §1, re-architected TPU-first):

  ops/       — device primitives: 2-bit k-mer arithmetic, the HBM hash
               table (build/query kernels), Poisson terms.
  models/    — the two pipeline stages as jittable programs
               (create_database, error_correct) plus a pure-Python
               oracle transcription of the reference semantics used as
               a test oracle.
  parallel/  — device-mesh sharding: hash-prefix sharded tables,
               all-to-all mer routing, data-parallel read streams.
  io/        — FASTQ/FASTA ingestion, 2-bit batch encoding, the
               self-describing on-disk database (checkpoint) format.
  cli/       — the user surface: `quorum` driver plus the per-stage
               tools, flag-compatible with the reference binaries.
  native/    — C++ host runtime (FASTQ parsing / encoding) bound via
               ctypes, with a pure-Python fallback.
  data/      — built-in Illumina adapter contaminant set (the
               reference's data/adapter.fa as a generator).
  tools/     — (repo root) analysis utilities, e.g. the multi-chip
               communication model.
"""

__version__ = "0.5.0"
