"""quorum_tpu — a TPU-native k-mer based Illumina error-correction framework.

A ground-up rebuild of the capabilities of Quorum (alekseyzimin/Quorum
v1.1.1) designed for TPU hardware: the two hot loops (k-mer database
construction and batched read correction) run as JAX/XLA programs over
HBM-resident hash tables, with multi-chip scaling via `jax.sharding.Mesh`
and XLA collectives instead of shared-memory pthreads.

Layer map (mirrors SURVEY.md §1, re-architected TPU-first):

  ops/       — device primitives: 2-bit k-mer arithmetic, the HBM hash
               table (build/query kernels), Poisson terms.
  models/    — the two pipeline stages as jittable programs
               (create_database, error_correct) plus a pure-Python
               oracle transcription of the reference semantics used as
               a test oracle.
  parallel/  — device-mesh sharding: hash-prefix sharded tables,
               all-to-all mer routing, data-parallel read streams.
  io/        — FASTQ/FASTA ingestion, 2-bit batch encoding, the
               self-describing on-disk database (checkpoint) format.
  cli/       — the user surface: `quorum` driver plus the per-stage
               tools, flag-compatible with the reference binaries.
  native/    — C++ host runtime (FASTQ parsing / encoding) bound via
               ctypes, with a pure-Python fallback.
  data/      — built-in Illumina adapter contaminant set (the
               reference's data/adapter.fa as a generator).
  tools/     — (repo root) analysis utilities, e.g. the multi-chip
               communication model.
"""

__version__ = "0.5.0"

# Runtime compile sentinel opt-in (ISSUE 15): QUORUM_COMPILE_SENTINEL=1
# — on in ci/tier1.sh — must wrap jax.jit BEFORE any jit-bearing
# submodule binds it in a module-level functools.partial decorator,
# and package import is the one point that precedes them all (the
# tests' conftest and every CLI entry route through here). Costs one
# env read when the lever is unset; installs the recording factory
# (analysis/compile_sentinel.py) when set.


def _maybe_install_compile_sentinel() -> None:
    from .utils import levers
    if levers.get_bool("QUORUM_COMPILE_SENTINEL"):
        from .analysis import compile_sentinel
        compile_sentinel.install()


_maybe_install_compile_sentinel()
