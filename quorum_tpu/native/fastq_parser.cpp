// Native FASTQ chunk parser + 2-bit encoder.
//
// The host-side analogue of the reference's C++ parsing layer
// (Jellyfish stream_manager + whole_sequence_parser, used at
// src/create_database.cc:27-28 and src/error_correct_reads.cc:127):
// the Python reader feeds decompressed byte chunks; this scanner
// consumes complete strict 4-line FASTQ records, encoding bases to
// 2-bit codes (-1 for non-ACGT) and copying raw quality bytes into
// caller-allocated fixed-stride arrays. Multi-line FASTQ and FASTA
// fall back to the pure-Python parser (io/fastq.py) — this is the fast
// path for the dominant format, not a second grammar implementation.
//
// Build: g++ -O2 -shared -fPIC fastq_parser.cpp -o libqtfastq.so
// (done on demand by quorum_tpu/native/binding.py, cached in
// ~/.cache/quorum_tpu).

#include <cstdint>
#include <cstring>

namespace {

inline const char* find_nl(const char* p, const char* end) {
    return static_cast<const char*>(memchr(p, '\n', end - p));
}

signed char CODE[256];

struct CodeInit {
    CodeInit() {
        memset(CODE, -1, sizeof(CODE));
        CODE[(unsigned)'A'] = 0; CODE[(unsigned)'a'] = 0;
        CODE[(unsigned)'C'] = 1; CODE[(unsigned)'c'] = 1;
        CODE[(unsigned)'G'] = 2; CODE[(unsigned)'g'] = 2;
        CODE[(unsigned)'T'] = 3; CODE[(unsigned)'t'] = 3;
    }
} code_init;

}  // namespace

extern "C" {

// Parse complete 4-line FASTQ records from buf[0:len).
//
// Outputs (caller-allocated):
//   codes  [cap_reads * stride] int8: 2-bit codes, -1 non-ACGT,
//          -2 padding beyond each read's length
//   quals  [cap_reads * stride] uint8: raw quality bytes, 0 padding
//   lengths[cap_reads] int32
//   hdr_off/hdr_len: header byte ranges within buf (after '@')
//
// Returns the number of records parsed (<= cap_reads), or:
//   -1  malformed input (not strict 4-line FASTQ) -> caller falls back
//   -2  a read longer than `stride`
// *consumed is set to the number of bytes of buf fully processed; the
// caller carries the remainder into the next chunk. With eof set, a
// trailing partial record is malformed (-1).
long qt_parse(const char* buf, long len, int eof,
              signed char* codes, unsigned char* quals,
              int32_t* lengths, int64_t* hdr_off, int32_t* hdr_len,
              int32_t cap_reads, int32_t stride, int64_t* consumed) {
    const char* p = buf;
    const char* end = buf + len;
    long n = 0;
    *consumed = 0;
    while (n < cap_reads) {
        const char* rec = p;
        if (rec == end) break;
        if (*rec != '@') return -1;
        const char* h_end = find_nl(rec, end);
        if (!h_end) { if (eof) return -1; break; }
        const char* seq = h_end + 1;
        const char* s_end = find_nl(seq, end);
        if (!s_end) { if (eof) return -1; break; }
        const char* plus = s_end + 1;
        const char* p_end = find_nl(plus, end);
        if (!p_end) { if (eof) return -1; break; }
        if (plus == p_end || *plus != '+') return -1;
        const char* qual = p_end + 1;
        const char* q_end = find_nl(qual, end);
        if (!q_end) {
            if (!eof) break;
            q_end = end;  // final record may lack trailing newline
            if (q_end == qual) return -1;
        }
        long slen = s_end - seq;
        long qlen = q_end - qual;
        if (slen != qlen) return -1;  // multi-line or corrupt -> fallback
        if (slen > stride) return -2;
        // strip possible '\r'
        if (slen > 0 && seq[slen - 1] == '\r') { --slen; --qlen; }
        signed char* crow = codes + (int64_t)n * stride;
        unsigned char* qrow = quals + (int64_t)n * stride;
        for (long i = 0; i < slen; ++i)
            crow[i] = CODE[(unsigned char)seq[i]];
        memset(crow + slen, -2, stride - slen);
        memcpy(qrow, qual, qlen);
        memset(qrow + qlen, 0, stride - qlen);
        lengths[n] = (int32_t)slen;
        long hl = h_end - rec - 1;
        if (hl > 0 && rec[hl] == '\r') --hl;
        hdr_off[n] = (rec + 1) - buf;
        hdr_len[n] = (int32_t)hl;
        ++n;
        p = (q_end == end) ? end : q_end + 1;
        *consumed = p - buf;
    }
    return n;
}

}  // extern "C"
