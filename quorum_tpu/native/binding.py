"""ctypes binding for the native FASTQ parser (fastq_parser.cpp).

Builds the shared library on first use with the system g++ (cached in
~/.cache/quorum_tpu), per the no-pybind11 environment; any failure —
no compiler, unwritable cache, malformed/multi-line input — falls back
to the pure-Python parser in io/fastq.py. Strict 4-line FASTQ only by
design (see the .cpp header comment).
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import sys
from typing import Iterator, Sequence

import numpy as np

_HERE = os.path.dirname(__file__)
_CACHE = os.path.expanduser("~/.cache/quorum_tpu")
_LIB = None
_TRIED = False

CHUNK = 8 << 20


def _build() -> str | None:
    src = os.path.join(_HERE, "fastq_parser.cpp")
    out = os.path.join(_CACHE, "libqtfastq.so")
    try:
        if (os.path.exists(out)
                and os.path.getmtime(out) >= os.path.getmtime(src)):
            return out
        os.makedirs(_CACHE, exist_ok=True)
        subprocess.run(
            ["g++", "-O2", "-shared", "-fPIC", src, "-o", out + ".tmp"],
            check=True, capture_output=True, timeout=120)
        os.replace(out + ".tmp", out)
        return out
    except (OSError, subprocess.SubprocessError):
        return None


def _load():
    global _LIB, _TRIED
    if _TRIED:
        return _LIB
    _TRIED = True
    path = _build()
    if path is None:
        return None
    try:
        lib = ctypes.CDLL(path)
        lib.qt_parse.restype = ctypes.c_long
        lib.qt_parse.argtypes = [
            ctypes.c_char_p, ctypes.c_long, ctypes.c_int,
            ctypes.POINTER(ctypes.c_int8),
            ctypes.POINTER(ctypes.c_uint8),
            ctypes.POINTER(ctypes.c_int32),
            ctypes.POINTER(ctypes.c_int64),
            ctypes.POINTER(ctypes.c_int32),
            ctypes.c_int32, ctypes.c_int32,
            ctypes.POINTER(ctypes.c_int64),
        ]
        _LIB = lib
    except OSError:
        _LIB = None
    return _LIB


def available() -> bool:
    return _load() is not None


class Fallback(Exception):
    """Input isn't strict 4-line FASTQ — use the Python parser."""


def _parse_stream(f, batch_size: int, stride: int = 4096):
    """Yield raw (codes, quals, lengths, headers, n) tuples from one
    binary stream via the native parser. Raises Fallback on grammar
    mismatch with no records consumed from the CURRENT buffer."""
    lib = _load()
    assert lib is not None
    codes = np.empty((batch_size, stride), dtype=np.int8)
    quals = np.empty((batch_size, stride), dtype=np.uint8)
    lengths = np.empty((batch_size,), dtype=np.int32)
    hdr_off = np.empty((batch_size,), dtype=np.int64)
    hdr_len = np.empty((batch_size,), dtype=np.int32)
    consumed = ctypes.c_int64(0)
    buf = b""
    eof = False
    first = True
    while not eof or buf:
        while not eof and len(buf) < CHUNK:
            chunk = f.read(CHUNK)
            if not chunk:
                eof = True
                break
            buf += chunk
        if not buf:
            break
        n = lib.qt_parse(
            buf, len(buf), int(eof),
            codes.ctypes.data_as(ctypes.POINTER(ctypes.c_int8)),
            quals.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8)),
            lengths.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
            hdr_off.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
            hdr_len.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
            batch_size, stride, ctypes.byref(consumed))
        if n == -1:
            if first:
                raise Fallback()
            raise ValueError("malformed FASTQ record (native parser)")
        if n == -2:
            # oversized read: grow the row stride and re-parse the same
            # buffer — nothing yielded is lost
            stride = min(stride * 2, 1 << 22)
            codes = np.empty((batch_size, stride), dtype=np.int8)
            quals = np.empty((batch_size, stride), dtype=np.uint8)
            continue
        if n == 0 and eof:
            break
        if n == 0:
            # need more bytes for one record
            chunk = f.read(CHUNK)
            if not chunk:
                eof = True
            else:
                buf += chunk
            continue
        first = False
        headers = [
            buf[hdr_off[i]:hdr_off[i] + hdr_len[i]].decode()
            for i in range(n)
        ]
        yield codes, quals, lengths, headers, int(n)
        buf = buf[consumed.value:]


def read_batches(paths: Sequence[str], batch_size: int = 8192
                 ) -> Iterator["object"]:
    """ReadBatch iterator via the native parser, falling back per-file
    to the Python parser for FASTA/multi-line/oversized inputs.

    Fault-plan coverage: the `fastq.read` injection site fires once
    per parsed record here too (batch-granular: all of a batch's
    records fire before the batch yields, so an `at=N` fault lands on
    the same record count as the pure-Python parser and a raising
    action still precedes any consumption of that record downstream).
    Before round 7 an active plan silently bypassed the native path;
    now chaos tests exercise the production parser."""
    from ..io import fastq
    from ..utils import faults

    for path in paths:
        if path in ("-", "/dev/fd/0", "/dev/stdin"):
            # stdin can't be re-opened for the grammar fallback
            yield from fastq.batch_records(fastq.iter_records([path]),
                                           batch_size)
            continue
        f = fastq._open(path)
        try:
            try:
                for codes, quals, lengths, headers, n in _parse_stream(
                        f, batch_size):
                    if faults.active():
                        for _ in range(int(n)):
                            faults.inject("fastq.read")
                    if n < batch_size:  # inert padding rows
                        codes[n:] = -2
                        quals[n:] = 0
                        lengths[n:] = 0
                    maxlen = int(lengths[:n].max()) if n else 1
                    L = fastq.bucket_for(maxlen)
                    yield fastq.ReadBatch(
                        codes=codes[:, :L].copy(),
                        quals=quals[:, :L].copy(),
                        lengths=lengths.copy(),
                        headers=headers, n=n)
            except Fallback:
                f.close()
                f = fastq._open(path)
                yield from fastq.batch_records(
                    fastq.iter_records([path]), batch_size)
        finally:
            if f is not sys.stdin.buffer:
                f.close()
