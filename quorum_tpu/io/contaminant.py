"""Contaminant k-mer set loading.

The reference loads a Jellyfish `binary/binary_dumper` database into an
in-memory mer set (contaminant_database, error_correct_reads.cc:66-99,
:693-708) that the driver builds from a FASTA at compile time via
`jellyfish count` (Makefile.am:50-56). The TPU build accepts:

* a FASTA/FASTQ file of contaminant sequences — counted directly into a
  small device table (membership only), covering both the driver's
  documented `--contaminant FILE` surface (README.md "fasta or fastq
  file of contaminant sequences") and removing the build-time jellyfish
  dependency;
* one of our own `binary/quorum_tpu_db` database files.

Either way the result is a (TableState, TableMeta) whose value words
are nonzero exactly for member k-mers; the device corrector fuses the
membership probe into its lookup rounds. The k-match validation of
error_correct_reads.cc:703-705 is enforced by the caller (correct_batch
raises on mismatch) and double-checked here for DB files.
"""

from __future__ import annotations

import json

from . import db_format


def _is_quorum_db(path: str) -> bool:
    try:
        with open(path, "rb") as f:
            line = f.readline(1 << 16)
        header = json.loads(line)
        return header.get("format") == db_format.FORMAT
    except (OSError, ValueError, UnicodeDecodeError):
        return False


def build_kmer_set(paths, k: int, size_log2: int = 16):
    """Count every canonical k-mer of the given sequence files into a
    membership table (value word nonzero for members): stage 1's own
    build pipeline with bits=1 and qual_thresh=0 (every base "high
    quality" — only window validity matters for membership)."""
    from ..models.create_database import BuildConfig, build_database

    cfg = BuildConfig(k=k, bits=1, qual_thresh=0,
                      initial_size=1 << size_log2, batch_size=512)
    state, meta, _stats = build_database(list(paths), cfg)
    return state, meta


def load_contaminant(path: str, k: int):
    """Load a contaminant k-mer set for correction at mer length k.
    Returns (TableState, TableMeta). Raises ValueError on k mismatch
    (reference message, error_correct_reads.cc:703-705)."""
    from . import jf_binary, quorum_db

    if _is_quorum_db(path) or quorum_db.is_ref_db(path):
        state, meta, _hdr = db_format.read_db(path, to_device=True)
        if meta.k != k:
            raise ValueError(
                f"Contaminant mer length ({meta.k}) different than "
                f"correction mer length ({k})")
        return state, meta
    if jf_binary.is_jf_binary(path):
        # the reference's own surface: a `jellyfish count` adapter DB
        # (error_correct_reads.cc:693-708)
        import numpy as np

        from ..ops import ctable

        khi, klo, counts, kk = jf_binary.read_jf_binary(path)
        if kk != k:
            raise ValueError(
                f"Contaminant mer length ({kk}) different than "
                f"correction mer length ({k})")
        vals = np.where(counts > 0, 2, 0).astype(np.uint32)  # member bit
        return ctable.tile_from_entries(khi, klo, vals, k, bits=7)
    return build_kmer_set([path], k)
