"""Best-effort reader for reference `binary/quorum_db` file headers.

The reference's database files are written by Jellyfish's
`file_header` (JSON text, then binary payload): database_header adds
`bits`, `key_bytes`, `value_bytes` and the `binary/quorum_db` format
tag (/root/reference/src/mer_database.hpp:43-63), and
`hash_with_quality::write` appends the raw `large_hash::array` +
`atomic_bits_array` planes (:115-126).

What we can and cannot do in this environment:

* The JSON header is self-describing — this module parses it (a
  brace-matching scan, since the document is multi-line and followed
  immediately by binary data) and reports the full geometry: hash
  size, key length, value bits, reprobe limit, payload byte counts.
  `db_format.read_header` uses it to give a precise diagnostic when a
  reference-built file is passed to our tools.
* The payload is Jellyfish's offsets-packed hash-array memory dump —
  slot words interleave partial keys and reprobe offsets at bit
  granularity. io/quorum_db implements a full encoder/decoder for that
  design (round 4): the geometry comes entirely from the header, the
  matrix is inverted to recover partially-stored keys, and
  db_format.read_db routes `binary/quorum_db` files through it, so the
  inspection tools and the corrector accept reference-format files and
  `quorum_create_database --ref-format` produces them. Jellyfish
  itself is still not buildable here (external pkg-config dep,
  configure.ac:28; no network), so the codec is validated by
  round-trip and header byte-count consistency, NOT by diffing against
  a Jellyfish-produced file — that residual risk is the documented
  boundary, and this module keeps giving precise diagnostics for
  files whose geometry the codec rejects.
"""

from __future__ import annotations

import json

REF_FORMAT = "binary/quorum_db"
JF_FORMATS = (REF_FORMAT, "binary/jellyfish", "binary/sorted")


class RefHeaderError(ValueError):
    """File does not carry a parseable Jellyfish-style JSON header."""


def parse_jf_header(data: bytes) -> tuple[dict, int]:
    """Parse a Jellyfish-style JSON header from the start of `data`.

    The document is arbitrary formatted JSON followed immediately by
    binary payload, so the end is found by brace matching (tracking
    strings and escapes), not by line structure. Returns
    (header_dict, end_offset) where end_offset is one past the closing
    brace."""
    i = 0
    while i < len(data) and data[i:i + 1].isspace():
        i += 1
    if i >= len(data) or data[i] != ord("{"):
        raise RefHeaderError("no JSON object at start of file")
    depth = 0
    in_str = False
    esc = False
    for j in range(i, len(data)):
        c = data[j]
        if in_str:
            if esc:
                esc = False
            elif c == ord("\\"):
                esc = True
            elif c == ord('"'):
                in_str = False
        elif c == ord('"'):
            in_str = True
        elif c == ord("{"):
            depth += 1
        elif c == ord("}"):
            depth -= 1
            if depth == 0:
                try:
                    return json.loads(data[i:j + 1]), j + 1
                except json.JSONDecodeError as e:
                    raise RefHeaderError(f"malformed JSON header: {e}") from e
    raise RefHeaderError("unterminated JSON header")


def read_ref_header(path: str, max_header: int = 1 << 20
                    ) -> tuple[dict, int]:
    """Read and parse the header of a reference-format database file.

    Returns (header, payload_offset). payload_offset is the aligned
    position after the JSON document (the `alignment` root field when
    present, Jellyfish's generic_file_header convention; 8 otherwise)
    — best-effort, since no reference-built file can be generated
    in-environment to pin the padding byte-for-byte."""
    with open(path, "rb") as f:
        data = f.read(max_header)
    header, end = parse_jf_header(data)
    align = int(header.get("alignment", 8) or 8)
    payload = -(-end // align) * align
    return header, payload


def describe(header: dict) -> str:
    """One-line geometry summary for diagnostics."""
    fields = []
    for key in ("format", "key_len", "bits", "size", "max_reprobe",
                "key_bytes", "value_bytes", "alignment"):
        if key in header:
            fields.append(f"{key}={header[key]}")
    return ", ".join(fields) if fields else "no geometry fields"


def ref_db_error(path: str, header: dict) -> RuntimeError:
    """The diagnostic raised when a reference-built DB is passed to a
    tool of ours."""
    return RuntimeError(
        f"'{path}' is a reference-format quorum database "
        f"({describe(header)}). Its payload is a Jellyfish "
        "offsets-packed hash-array dump, which this framework does not "
        "decode (Jellyfish is not available to validate the bit "
        "layout). Re-create the database with quorum_create_database "
        "from the original reads."
    )
