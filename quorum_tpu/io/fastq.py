"""FASTQ/FASTA ingestion: streaming, multi-file, fixed-shape batches.

Host-side replacement for Jellyfish's `stream_manager` +
`whole_sequence_parser` (used at src/create_database.cc:27-28,52 and
src/error_correct_reads.cc:127): a chunked reader that yields
fixed-shape numpy batches ready for `jax.device_put`. A C++ fast path
(quorum_tpu.native) parses and 2-bit-encodes large inputs; this module
is the always-available pure-Python implementation and the common
batching logic.

Handles 4-line and multi-line FASTQ, FASTA (quality treated as absent),
gzip-compressed inputs (by extension or magic), and '-' for stdin.
"""

from __future__ import annotations

import dataclasses
import gzip
import io as _io
import sys
import threading
from typing import Iterable, Iterator, Sequence

import numpy as np

from ..ops import mer
from ..utils import faults
from ..utils.vlog import vlog

# Read-length buckets: batches are padded to the smallest bucket that
# fits the longest read in the batch, so jit specializations stay few.
LENGTH_BUCKETS = (64, 128, 160, 192, 256, 384, 512, 1024, 2048, 4096)


@dataclasses.dataclass
class ReadBatch:
    """A fixed-shape batch of reads.

    codes: int8[B, L] 2-bit base codes, -1 for non-ACGT, -2 beyond length.
    quals: uint8[B, L] ASCII quality codes (0 beyond length / FASTA).
    lengths: int32[B]
    headers: list[str] (without the @/> marker)
    n: number of real reads (rows beyond n are padding)
    """

    codes: np.ndarray
    quals: np.ndarray
    lengths: np.ndarray
    headers: list
    n: int


class BadReadPolicy:
    """What to do with a malformed record mid-stream (`--on-bad-read`).

    * ``abort`` (the default, and the only behavior before ISSUE 4):
      raise — one bad record kills the run.
    * ``skip``: drop the record, count it (`bad_reads_total`), keep
      streaming.
    * ``quarantine``: like skip, but the offending record's raw bytes
      are appended to `quarantine_path` (a `.quarantine.fastq`) so the
      operator can triage what the instrument produced instead of
      grepping a Gbase input for it.

    Thread-safe (the multi-file reader parses on worker threads);
    shared by stage 1, stage 2, and the quorum driver's one-parse
    path. `registry` (an enabled telemetry registry, or None) carries
    the counter."""

    MODES = ("abort", "skip", "quarantine")

    def __init__(self, mode: str = "abort",
                 quarantine_path: str | None = None, registry=None):
        if mode not in self.MODES:
            raise ValueError(f"bad on-bad-read mode {mode!r} "
                             f"(one of {self.MODES})")
        if mode == "quarantine" and not quarantine_path:
            raise ValueError("quarantine mode needs a quarantine path")
        self.mode = mode
        self.quarantine_path = quarantine_path
        self.registry = registry
        self.bad = 0
        self._lock = threading.Lock()
        self._f = None
        self._closed = False

    @property
    def wants_raw(self) -> bool:
        return self.mode == "quarantine"

    def handle(self, path: str, err: Exception, raw_lines) -> None:
        """One malformed record: raise (abort) or record and
        continue."""
        if self.mode == "abort":
            raise err
        with self._lock:
            self.bad += 1
            if self.registry is not None:
                self.registry.counter("bad_reads_total").inc()
            if (self.mode == "quarantine" and raw_lines
                    and not self._closed):
                if self._f is None:
                    self._f = open(self.quarantine_path, "wb")
                for ln in raw_lines:
                    self._f.write(ln)
                self._f.flush()
        vlog("Bad read in ", path, ": ", err)

    def close(self) -> None:
        """Idempotent; a straggler worker hitting a bad record after
        close still counts it but writes nothing (reopening would
        truncate the quarantine)."""
        with self._lock:
            self._closed = True
            if self._f is not None:
                self._f.close()
                self._f = None


def _open(path: str):
    if path == "-" or path == "/dev/fd/0" or path == "/dev/stdin":
        return sys.stdin.buffer
    if path.endswith(".gz"):
        return gzip.open(path, "rb")
    f = open(path, "rb")
    magic = f.read(2)
    f.seek(0)
    if magic == b"\x1f\x8b":
        f.close()
        return gzip.open(path, "rb")
    return f


def iter_records(paths: Sequence[str],
                 policy: BadReadPolicy | None = None,
                 ) -> Iterator[tuple[str, bytes, bytes]]:
    """Yield (header, seq, qual) byte records across files. qual is b''
    for FASTA records (Jellyfish's parser does the same; merge_mate_pairs
    then fabricates '*' quals, src/merge_mate_pairs.cc:51-59).

    `policy` (a BadReadPolicy, or None = abort) decides what happens
    to malformed records mid-stream."""
    for path in paths:
        f = _open(path)
        try:
            yield from _iter_one(f, path, policy)
        finally:
            if f is not sys.stdin.buffer:
                f.close()


def _iter_one(f, path: str, policy: BadReadPolicy | None = None,
              ) -> Iterator[tuple[str, bytes, bytes]]:
    # raw-line capture (for quarantine) only when someone wants it —
    # the common abort/skip paths never build the list
    capture = policy is not None and policy.wants_raw
    line = f.readline()
    while line:
        stripped = line.rstrip(b"\r\n")
        if not stripped:
            line = f.readline()
            continue
        if stripped.startswith(b">"):
            raw = [line] if capture else None
            header_b = stripped[1:]
            seq_parts = []
            line = f.readline()
            while line and not line.startswith(b">") and not line.startswith(b"@"):
                if capture:
                    raw.append(line)
                seq_parts.append(line.rstrip(b"\r\n"))
                line = f.readline()
            try:
                header = header_b.decode()
            except UnicodeDecodeError as err:
                # a corrupt header byte is a malformed record like any
                # other — the policy decides, after the record's lines
                # are consumed so the stream resyncs cleanly
                if policy is None:
                    raise
                policy.handle(path, err, raw or [])
                continue
            faults.inject("fastq.read")
            yield header, b"".join(seq_parts), b""
        elif stripped.startswith(b"@"):
            raw = [line] if capture else None
            header_b = stripped[1:]
            seq_parts = []
            line = f.readline()
            while line and not line.startswith(b"+"):
                if capture:
                    raw.append(line)
                seq_parts.append(line.rstrip(b"\r\n"))
                line = f.readline()
            seq = b"".join(seq_parts)
            # line is the '+' separator; read quals until length matches
            if capture and line:
                raw.append(line)
            qual_parts = []
            qlen = 0
            line = f.readline()
            while line and qlen < len(seq):
                if capture:
                    raw.append(line)
                q = line.rstrip(b"\r\n")
                qual_parts.append(q)
                qlen += len(q)
                line = f.readline()
            qual = b"".join(qual_parts)
            try:
                header = header_b.decode()
            except UnicodeDecodeError as err:
                if policy is None:
                    raise
                policy.handle(path, err, raw or [])
                continue
            if len(qual) != len(seq):
                err = ValueError(
                    f"{path}: quality length {len(qual)} != sequence length "
                    f"{len(seq)} for read '{header}'"
                )
                if policy is None:
                    raise err
                # `line` already holds the first unconsumed line, so
                # the stream resyncs at the next record boundary
                policy.handle(path, err, raw or [])
                continue
            faults.inject("fastq.read")
            yield header, seq, qual
        else:
            err = ValueError(
                f"{path}: unrecognized record start: {stripped[:40]!r}")
            if policy is None:
                raise err
            policy.handle(path, err, [line] if capture else [])
            line = f.readline()


def bucket_for(length: int) -> int:
    for b in LENGTH_BUCKETS:
        if length <= b:
            return b
    return length  # oversized: one-off shape


def batch_records(
    records: Iterable[tuple[str, bytes, bytes]],
    batch_size: int = 8192,
) -> Iterator[ReadBatch]:
    """Group records into fixed-shape ReadBatches of `batch_size` rows."""
    buf: list[tuple[str, bytes, bytes]] = []
    for rec in records:
        buf.append(rec)
        if len(buf) == batch_size:
            yield _make_batch(buf, batch_size)
            buf = []
    if buf:
        yield _make_batch(buf, batch_size)


def _make_batch(buf, batch_size) -> ReadBatch:
    n = len(buf)
    maxlen = max((len(seq) for _, seq, _ in buf), default=1)
    L = bucket_for(max(maxlen, 1))
    codes = np.full((batch_size, L), -2, dtype=np.int8)
    quals = np.zeros((batch_size, L), dtype=np.uint8)
    lengths = np.zeros((batch_size,), dtype=np.int32)
    headers = []
    for i, (hdr, seq, qual) in enumerate(buf):
        headers.append(hdr)
        m = len(seq)
        lengths[i] = m
        codes[i, :m] = mer.seq_to_codes(seq)
        if qual:
            quals[i, :m] = np.frombuffer(qual, dtype=np.uint8)
    return ReadBatch(codes=codes, quals=quals, lengths=lengths,
                     headers=headers, n=n)


def _read_batches_one(paths: Sequence[str], batch_size: int,
                      policy: BadReadPolicy | None = None,
                      ) -> Iterator[ReadBatch]:
    use_native = False
    # a non-abort bad-read policy needs the pure-Python parser (the
    # C++ fast path has no record-recovery hooks). Fault plans no
    # longer force the bypass: the native reader carries its own
    # per-record `fastq.read` injection point (native/binding.py), so
    # chaos tests exercise the production parser too.
    if policy is None or policy.mode == "abort":
        try:  # C++ fast path, if the shared library is built
            from ..native import binding as _nb
            use_native = _nb.available()
        except Exception:
            use_native = False
    if use_native:
        from ..native import binding as _nb
        yield from _nb.read_batches(paths, batch_size)
    else:
        yield from batch_records(iter_records(paths, policy), batch_size)


def read_batches(paths: Sequence[str], batch_size: int = 8192,
                 threads: int = 1,
                 policy: BadReadPolicy | None = None,
                 ) -> Iterator[ReadBatch]:
    """Batched reads from FASTQ/FASTA files.

    With threads > 1 and multiple input files, up to `threads` files
    decode concurrently (each worker feeds a bounded queue; batches
    still yield in file order, so output record order matches the
    reference's). This is the real host parallelism behind the CLIs'
    `-t` — the decode (gzip inflation especially) overlaps the device
    pipeline the way the reference's N parser threads do
    (create_database.cc:122, error_correct_reads.cc:738). Single-file
    inputs decode on one worker regardless (gzip is inherently
    serial); the prefetch thread still overlaps it with device work."""
    if threads <= 1 or len(paths) <= 1:
        try:
            yield from _read_batches_one(paths, batch_size, policy)
        finally:
            # the reader owns the policy lifecycle: the quarantine
            # stream closes however this generator ends (exhausted,
            # abandoned, or errored) — callers don't have to remember
            if policy is not None:
                policy.close()
        return
    import itertools
    import queue

    from ..utils.pipeline import put_or_stop as _put_or_stop

    qs = [queue.Queue(maxsize=4) for _ in paths]
    stop = threading.Event()
    # workers CLAIM file indices in order (not one pre-pinned file
    # each): with fewer permits than files, pre-pinning could hand
    # every permit to later files while the consumer blocks on file
    # 0's queue — an unbreakable cycle
    claim = itertools.count()
    claim_lock = threading.Lock()

    def put_or_stop(i, item) -> bool:
        """Stop-aware bounded put (the shared pipeline helper); False
        if the consumer went away — an unbounded put here would
        strand the worker forever on a full queue after the generator
        is abandoned."""
        return _put_or_stop(qs[i], item, stop)

    def worker():
        while not stop.is_set():
            with claim_lock:
                i = next(claim)
            if i >= len(paths):
                return
            try:
                for b in _read_batches_one([paths[i]], batch_size,
                                           policy):
                    if not put_or_stop(i, b):
                        return
                if not put_or_stop(i, None):
                    return
            except BaseException as e:  # noqa: BLE001 - forwarded
                put_or_stop(i, ("__err__", e))
                return

    ts = [threading.Thread(target=worker, daemon=True)
          for _ in range(min(max(1, threads), len(paths)))]
    for t in ts:
        t.start()
    try:
        for i in range(len(paths)):
            while True:
                item = qs[i].get()
                if item is None:
                    break
                if isinstance(item, tuple) and len(item) == 2 \
                        and item[0] == "__err__":
                    raise item[1]
                yield item
    finally:
        stop.set()
        if policy is not None:
            policy.close()
