"""FASTQ/FASTA ingestion: streaming, multi-file, fixed-shape batches.

Host-side replacement for Jellyfish's `stream_manager` +
`whole_sequence_parser` (used at src/create_database.cc:27-28,52 and
src/error_correct_reads.cc:127): a chunked reader that yields
fixed-shape numpy batches ready for `jax.device_put`. A C++ fast path
(quorum_tpu.native) parses and 2-bit-encodes large inputs; this module
is the always-available pure-Python implementation and the common
batching logic.

Handles 4-line and multi-line FASTQ, FASTA (quality treated as absent),
gzip-compressed inputs (by extension or magic), and '-' for stdin.
"""

from __future__ import annotations

import dataclasses
import gzip
import io as _io
import os
import sys
import threading
from typing import Iterable, Iterator, Sequence

import numpy as np

from ..ops import mer
from ..utils import faults, resources
from ..utils.vlog import vlog

# Read-length buckets: batches are padded to the smallest bucket that
# fits the longest read in the batch, so jit specializations stay few.
LENGTH_BUCKETS = (64, 128, 160, 192, 256, 384, 512, 1024, 2048, 4096)


@dataclasses.dataclass
class ReadBatch:
    """A fixed-shape batch of reads.

    codes: int8[B, L] 2-bit base codes, -1 for non-ACGT, -2 beyond length.
    quals: uint8[B, L] ASCII quality codes (0 beyond length / FASTA).
    lengths: int32[B]
    headers: list[str] (without the @/> marker)
    n: number of real reads (rows beyond n are padding)
    """

    codes: np.ndarray
    quals: np.ndarray
    lengths: np.ndarray
    headers: list
    n: int


class BadReadPolicy:
    """What to do with a malformed record mid-stream (`--on-bad-read`).

    * ``abort`` (the default, and the only behavior before ISSUE 4):
      raise — one bad record kills the run.
    * ``skip``: drop the record, count it (`bad_reads_total`), keep
      streaming.
    * ``quarantine``: like skip, but the offending record's raw bytes
      are appended to `quarantine_path` (a `.quarantine.fastq`) so the
      operator can triage what the instrument produced instead of
      grepping a Gbase input for it.

    Thread-safe (the multi-file reader parses on worker threads);
    shared by stage 1, stage 2, and the quorum driver's one-parse
    path. `registry` (an enabled telemetry registry, or None) carries
    the counter."""

    MODES = ("abort", "skip", "quarantine")

    def __init__(self, mode: str = "abort",
                 quarantine_path: str | None = None, registry=None):
        if mode not in self.MODES:
            raise ValueError(f"bad on-bad-read mode {mode!r} "
                             f"(one of {self.MODES})")
        if mode == "quarantine" and not quarantine_path:
            raise ValueError("quarantine mode needs a quarantine path")
        self.mode = mode
        self.quarantine_path = quarantine_path
        self.registry = registry
        self.bad = 0
        self._lock = threading.Lock()
        self._f = None
        self._closed = False

    @property
    def wants_raw(self) -> bool:
        return self.mode == "quarantine"

    def handle(self, path: str, err: Exception, raw_lines) -> None:
        """One malformed record: raise (abort) or record and
        continue. The quarantine stream is an *optional* writer on
        the ISSUE 19 degradation ladder: before this fix a full disk
        here propagated out of bad-read handling and killed the run —
        precisely while it was already limping — so now an ENOSPC
        degrades the stream (writer_degraded_total{writer=
        quarantine.stream}) and the run keeps its `bad_reads_total`
        accounting and its primary output."""
        if self.mode == "abort":
            raise err
        with self._lock:
            # count BEFORE the quarantine write: accounting must
            # survive a degraded stream
            self.bad += 1
            if self.registry is not None:
                self.registry.counter("bad_reads_total").inc()
            if (self.mode == "quarantine" and raw_lines
                    and not self._closed
                    and not resources.degraded("quarantine.stream")):
                with resources.guard("quarantine.stream",
                                     path=self.quarantine_path):
                    faults.inject("quarantine.write",
                                  path=self.quarantine_path)
                    if self._f is None:
                        self._f = open(self.quarantine_path, "wb")
                    for ln in raw_lines:
                        self._f.write(ln)
                    self._f.flush()
        vlog("Bad read in ", path, ": ", err)

    def close(self) -> None:
        """Idempotent; a straggler worker hitting a bad record after
        close still counts it but writes nothing (reopening would
        truncate the quarantine)."""
        with self._lock:
            self._closed = True
            if self._f is not None:
                f, self._f = self._f, None
                # a degraded stream may still hold buffered bytes a
                # full disk will refuse: closing is quarantine work,
                # so it degrades rather than killing the teardown
                with resources.guard("quarantine.stream",
                                     path=self.quarantine_path):
                    f.close()


def _open(path: str):
    if path == "-" or path == "/dev/fd/0" or path == "/dev/stdin":
        return sys.stdin.buffer
    if path.endswith(".gz"):
        return gzip.open(path, "rb")
    f = open(path, "rb")
    magic = f.read(2)
    f.seek(0)
    if magic == b"\x1f\x8b":
        f.close()
        return gzip.open(path, "rb")
    return f


def iter_records(paths: Sequence[str],
                 policy: BadReadPolicy | None = None,
                 ) -> Iterator[tuple[str, bytes, bytes]]:
    """Yield (header, seq, qual) byte records across files. qual is b''
    for FASTA records (Jellyfish's parser does the same; merge_mate_pairs
    then fabricates '*' quals, src/merge_mate_pairs.cc:51-59).

    `policy` (a BadReadPolicy, or None = abort) decides what happens
    to malformed records mid-stream."""
    for path in paths:
        f = _open(path)
        try:
            yield from _iter_one(f, path, policy)
        finally:
            if f is not sys.stdin.buffer:
                f.close()


def _iter_one(f, path: str, policy: BadReadPolicy | None = None,
              ) -> Iterator[tuple[str, bytes, bytes]]:
    # raw-line capture (for quarantine) only when someone wants it —
    # the common abort/skip paths never build the list
    capture = policy is not None and policy.wants_raw
    line = f.readline()
    while line:
        stripped = line.rstrip(b"\r\n")
        if not stripped:
            line = f.readline()
            continue
        if stripped.startswith(b">"):
            raw = [line] if capture else None
            header_b = stripped[1:]
            seq_parts = []
            line = f.readline()
            while line and not line.startswith(b">") and not line.startswith(b"@"):
                if capture:
                    raw.append(line)
                seq_parts.append(line.rstrip(b"\r\n"))
                line = f.readline()
            try:
                header = header_b.decode()
            except UnicodeDecodeError as err:
                # a corrupt header byte is a malformed record like any
                # other — the policy decides, after the record's lines
                # are consumed so the stream resyncs cleanly
                if policy is None:
                    raise
                policy.handle(path, err, raw or [])
                continue
            faults.inject("fastq.read")
            yield header, b"".join(seq_parts), b""
        elif stripped.startswith(b"@"):
            raw = [line] if capture else None
            header_b = stripped[1:]
            seq_parts = []
            line = f.readline()
            while line and not line.startswith(b"+"):
                if capture:
                    raw.append(line)
                seq_parts.append(line.rstrip(b"\r\n"))
                line = f.readline()
            seq = b"".join(seq_parts)
            # line is the '+' separator; read quals until length matches
            if capture and line:
                raw.append(line)
            qual_parts = []
            qlen = 0
            line = f.readline()
            while line and qlen < len(seq):
                if capture:
                    raw.append(line)
                q = line.rstrip(b"\r\n")
                qual_parts.append(q)
                qlen += len(q)
                line = f.readline()
            qual = b"".join(qual_parts)
            try:
                header = header_b.decode()
            except UnicodeDecodeError as err:
                if policy is None:
                    raise
                policy.handle(path, err, raw or [])
                continue
            if len(qual) != len(seq):
                err = ValueError(
                    f"{path}: quality length {len(qual)} != sequence length "
                    f"{len(seq)} for read '{header}'"
                )
                if policy is None:
                    raise err
                # `line` already holds the first unconsumed line, so
                # the stream resyncs at the next record boundary
                policy.handle(path, err, raw or [])
                continue
            faults.inject("fastq.read")
            yield header, seq, qual
        else:
            err = ValueError(
                f"{path}: unrecognized record start: {stripped[:40]!r}")
            if policy is None:
                raise err
            policy.handle(path, err, [line] if capture else [])
            line = f.readline()


def bucket_for(length: int) -> int:
    for b in LENGTH_BUCKETS:
        if length <= b:
            return b
    return length  # oversized: one-off shape


def batch_records(
    records: Iterable[tuple[str, bytes, bytes]],
    batch_size: int = 8192,
) -> Iterator[ReadBatch]:
    """Group records into fixed-shape ReadBatches of `batch_size` rows."""
    buf: list[tuple[str, bytes, bytes]] = []
    for rec in records:
        buf.append(rec)
        if len(buf) == batch_size:
            yield _make_batch(buf, batch_size)
            buf = []
    if buf:
        yield _make_batch(buf, batch_size)


def _make_batch(buf, batch_size) -> ReadBatch:
    n = len(buf)
    maxlen = max((len(seq) for _, seq, _ in buf), default=1)
    L = bucket_for(max(maxlen, 1))
    codes = np.full((batch_size, L), -2, dtype=np.int8)
    quals = np.zeros((batch_size, L), dtype=np.uint8)
    lengths = np.zeros((batch_size,), dtype=np.int32)
    headers = []
    for i, (hdr, seq, qual) in enumerate(buf):
        headers.append(hdr)
        m = len(seq)
        lengths[i] = m
        codes[i, :m] = mer.seq_to_codes(seq)
        if qual:
            quals[i, :m] = np.frombuffer(qual, dtype=np.uint8)
    return ReadBatch(codes=codes, quals=quals, lengths=lengths,
                     headers=headers, n=n)


# ---------------------------------------------------------------------------
# Single-file span-parallel parse (ISSUE 9)
# ---------------------------------------------------------------------------

# below this size the span probing + worker setup costs more than the
# serial parse; tests lower it to exercise the path on tiny inputs
PARALLEL_SPAN_MIN_BYTES = 4 << 20

# bases a sequence line may contain (IUPAC + lowercase); quality
# strings essentially never pass this filter, which is what
# disambiguates '@'-starting quality lines from record headers
_SEQ_CHARS = frozenset(b"ACGTUNRYSWKMBDHVacgtunryswkmbdhv.-")


def _is_seq_line(line: bytes) -> bool:
    s = line.rstrip(b"\r\n")
    return bool(s) and all(c in _SEQ_CHARS for c in s)


def _rec4_at(lines, i: int) -> bool:
    """lines[i:i+4] look like one strict 4-line FASTQ record."""
    return (i + 3 < len(lines)
            and lines[i].startswith(b"@")
            and _is_seq_line(lines[i + 1])
            and lines[i + 2].startswith(b"+")
            and len(lines[i + 3].rstrip(b"\r\n"))
            == len(lines[i + 1].rstrip(b"\r\n")))


def _probe_record_start(f, offset: int, window: int = 64) -> int | None:
    """Scan forward from `offset` for a confident 4-line-FASTQ record
    start: TWO consecutive strict 4-line records (header/'@', sequence,
    '+', length-matched quality). One record alone is not confident —
    a WRAPPED (multi-line) FASTQ's quality chunks can impersonate it
    (an '@'-leading quality chunk + an all-IUPAC chunk + a '+'-leading
    chunk of matching wrap width), and a cut there would silently
    corrupt records; two in lockstep closes that. Returns the byte
    offset of the first header line, or None when no confident
    boundary lies within `window` lines."""
    f.seek(offset)
    if offset:
        f.readline()  # discard the partial line the cut landed in
    positions, lines = [], []
    for _ in range(window):
        pos = f.tell()
        line = f.readline()
        if not line:
            break
        positions.append(pos)
        lines.append(line)
    for i in range(len(lines) - 7):
        if _rec4_at(lines, i) and _rec4_at(lines, i + 4):
            return positions[i]
    return None


def _single_file_spans(path: str, n: int) -> list[tuple[int, int]] | None:
    """Record-aligned [start, end) spans of ONE uncompressed 4-line
    FASTQ file, or None when the file can't be split safely (stdin,
    gzip, FASTA, multi-line records, too small). Span boundaries land
    exactly between records, so each span parses independently and
    their record streams concatenate to the serial parse's order."""
    if path in ("-", "/dev/fd/0", "/dev/stdin") or path.endswith(".gz"):
        return None
    try:
        size = os.path.getsize(path)
    except OSError:
        return None
    if n <= 1 or size < max(PARALLEL_SPAN_MIN_BYTES, 4 * n):
        return None
    with open(path, "rb") as f:
        if f.read(2) == b"\x1f\x8b":  # gzip by magic, not extension
            return None
        f.seek(0)
        if not f.readline().startswith(b"@"):
            return None  # FASTA (or junk): the serial parser handles it
        # the head must itself be strict 4-line FASTQ: a wrapped
        # (multi-line) file — which _iter_one supports — has no
        # record-aligned byte cuts, so it stays on the serial parser
        if _probe_record_start(f, 0) != 0:
            return None
        cuts = [0]
        for i in range(1, n):
            target = size * i // n
            pos = _probe_record_start(f, target)
            if pos is None or pos <= cuts[-1] or pos >= size:
                continue  # fold this span into its neighbor
            cuts.append(pos)
    cuts.append(size)
    spans = [(cuts[i], cuts[i + 1]) for i in range(len(cuts) - 1)
             if cuts[i + 1] > cuts[i]]
    return spans if len(spans) > 1 else None


class _SpanReader:
    """readline()-only view of [start, end) of a binary file. Span
    boundaries are record starts, so the parser sees a clean EOF
    exactly between records."""

    def __init__(self, f, start: int, end: int):
        f.seek(start)
        self._f = f
        self._end = end
        self._pos = start

    def readline(self) -> bytes:
        if self._pos >= self._end:
            return b""
        line = self._f.readline()
        self._pos += len(line)
        return line


def _iter_sources_pooled(n: int, threads: int, produce) -> Iterator:
    """The items of `produce(0)`, `produce(1)`, … `produce(n-1)`
    concatenated in SOURCE ORDER, with the producers running
    concurrently on a worker pool — the one ordered fan-in protocol
    behind both the multi-file reader and the single-file span parse.
    Workers CLAIM source indices in order (not one pre-pinned source
    each): with fewer workers than sources, pre-pinning could hand
    every worker a later source while the consumer blocks on source
    0's queue — an unbreakable cycle. A producer exception is
    forwarded and re-raised at the consumer in order; abandoning the
    generator stops the workers (stop-aware bounded puts)."""
    import itertools
    import queue

    from ..utils.pipeline import put_or_stop as _put_or_stop

    qs = [queue.Queue(maxsize=4) for _ in range(n)]
    stop = threading.Event()
    claim = itertools.count()
    claim_lock = threading.Lock()

    def worker():
        while not stop.is_set():
            with claim_lock:
                i = next(claim)
            if i >= n:
                return
            try:
                for item in produce(i):
                    # 1-tuple wrap: data can never be mistaken for the
                    # error sentinel or the end-of-source None
                    if not _put_or_stop(qs[i], (item,), stop):
                        return
                if not _put_or_stop(qs[i], None, stop):
                    return
            except BaseException as e:  # noqa: BLE001 - forwarded
                _put_or_stop(qs[i], ("__err__", e), stop)
                return

    ts = [threading.Thread(target=worker, daemon=True)
          for _ in range(min(max(1, threads), n))]
    for t in ts:
        t.start()
    try:
        for i in range(n):
            while True:
                item = qs[i].get()
                if item is None:
                    break
                if len(item) == 2:
                    raise item[1]
                yield item[0]
    finally:
        stop.set()


def _iter_records_spans(path: str, spans: list, threads: int,
                        policy: BadReadPolicy | None,
                        ) -> Iterator[tuple[str, bytes, bytes]]:
    """Parse one file's record-aligned spans on a worker pool, yielding
    records in FILE ORDER (span streams are stitched back in span
    order, so downstream batching — and therefore batch cursors,
    resume journals, and output bytes — match the serial parse
    exactly). Only reached with policy None/abort (read_batches
    gates): a malformed record aborts the run from whichever worker
    hits it."""
    CHUNK = 512  # records per queue item: amortize queue overhead

    def produce(i):
        with open(path, "rb") as f:
            rdr = _SpanReader(f, *spans[i])
            chunk: list = []
            for rec in _iter_one(rdr, path, policy):
                chunk.append(rec)
                if len(chunk) >= CHUNK:
                    yield chunk
                    chunk = []
            if chunk:
                yield chunk

    for chunk in _iter_sources_pooled(len(spans), threads, produce):
        yield from chunk


def _read_batches_one(paths: Sequence[str], batch_size: int,
                      policy: BadReadPolicy | None = None,
                      ) -> Iterator[ReadBatch]:
    use_native = False
    # a non-abort bad-read policy needs the pure-Python parser (the
    # C++ fast path has no record-recovery hooks). Fault plans no
    # longer force the bypass: the native reader carries its own
    # per-record `fastq.read` injection point (native/binding.py), so
    # chaos tests exercise the production parser too.
    if policy is None or policy.mode == "abort":
        try:  # C++ fast path, if the shared library is built
            from ..native import binding as _nb
            use_native = _nb.available()
        except Exception:
            use_native = False
    if use_native:
        from ..native import binding as _nb
        yield from _nb.read_batches(paths, batch_size)
    else:
        yield from batch_records(iter_records(paths, policy), batch_size)


def read_batches(paths: Sequence[str], batch_size: int = 8192,
                 threads: int = 1,
                 policy: BadReadPolicy | None = None,
                 ) -> Iterator[ReadBatch]:
    """Batched reads from FASTQ/FASTA files.

    With threads > 1 and multiple input files, up to `threads` files
    decode concurrently (each worker feeds a bounded queue; batches
    still yield in file order, so output record order matches the
    reference's). This is the real host parallelism behind the CLIs'
    `-t` — the decode (gzip inflation especially) overlaps the device
    pipeline the way the reference's N parser threads do
    (create_database.cc:122, error_correct_reads.cc:738).

    A SINGLE uncompressed strict-4-line-FASTQ file also parses in
    parallel (ISSUE 9): the file splits into record-aligned spans
    (`_single_file_spans`) that the same worker pool parses
    concurrently, records stitched back in file order before batching
    — so batch boundaries are identical to the serial parse. gzip
    (inherently serial), stdin, FASTA, wrapped multi-line records,
    and small files fall back to one worker; so do skip/quarantine
    bad-read policies and active fault plans, whose exact record
    semantics only the serial parser reproduces (see the gate below);
    so does the native C++ fast path, which is quicker still."""
    if threads > 1 and len(paths) == 1:
        use_native = False
        if policy is None or policy.mode == "abort":
            try:
                from ..native import binding as _nb
                use_native = _nb.available()
            except Exception:
                use_native = False
        # two callers depend on the SERIAL parser's exact record
        # semantics, so they opt out of span parallelism: an active
        # fault plan (`fastq.read` `at=`/`count=` hit indices must be
        # reproducible, not scheduler-dependent), and any non-abort
        # bad-read policy — on a damaged file, WHICH records a
        # skip/quarantine resync swallows depends on parser state
        # carried across the damage, which a span cut truncates; the
        # survivor stream (and the quarantine file's order) must match
        # the serial parse, so triage modes stay serial. Under abort
        # the first malformed record kills the run either way.
        deterministic_only = (faults.active()
                              or (policy is not None
                                  and policy.mode != "abort"))
        spans = (None if use_native or deterministic_only
                 else _single_file_spans(paths[0], threads))
        if spans:
            try:
                yield from batch_records(
                    _iter_records_spans(paths[0], spans, threads,
                                        policy), batch_size)
            finally:
                if policy is not None:
                    policy.close()
            return
    if threads <= 1 or len(paths) <= 1:
        try:
            yield from _read_batches_one(paths, batch_size, policy)
        finally:
            # the reader owns the policy lifecycle: the quarantine
            # stream closes however this generator ends (exhausted,
            # abandoned, or errored) — callers don't have to remember
            if policy is not None:
                policy.close()
        return
    try:
        yield from _iter_sources_pooled(
            len(paths), threads,
            lambda i: _read_batches_one([paths[i]], batch_size, policy))
    finally:
        if policy is not None:
            policy.close()
