from . import fastq, db_format  # noqa: F401
