"""`binary/quorum_db` payload codec — the reference's on-disk database.

The reference writes its stage-1 database as a Jellyfish `file_header`
(JSON) followed by two raw planes (`hash_with_quality::write`,
/root/reference/src/mer_database.hpp:115-126) and reads it back by
binding raw array views over the mmap
(`database_query`, :270-278):

* keys: `large_hash::array` memory — an offsets-packed open-addressing
  table whose stored field per slot combines the un-addressed high
  bits of the GF(2)-hashed key with the reprobe offset (so keys are
  stored PARTIALLY and recovered by inverting the hash matrix);
* vals: `atomic_bits_array` — (bits+1)-bit fields packed into 64-bit
  words without crossing word boundaries.

Header fields written/consumed (mer_database.hpp:43-63, :270-278):
`format` ("binary/quorum_db"), `size`, `key_len` (bits, = 2k),
`val_len`, `max_reprobe`, `reprobes`, `matrix`, `bits`, `key_bytes`,
`value_bytes`, plus Jellyfish's standard provenance fields.

VALIDATION BOUNDARY (io/ref_db.py documents the history): Jellyfish
itself is not buildable in this environment and no reference-produced
file exists to diff against, so the bit layout below is derived from
the reference's usage plus Jellyfish 2's documented design, and is
validated by self round-trip and by header byte-count consistency —
byte-level parity against a real Jellyfish build is explicitly
unverified. The reader derives everything (field widths, reprobe
sequence, matrix) from the header rather than assuming our writer's
choices, so it extends as far as the header is honest.

Layout specifics (all little-endian):
* lsize = log2(table size), obits = bitlen(max_reprobe+1),
  field width kb = key_len - lsize + obits.
* slot field = ((M.key >> lsize) << obits) | (reprobe_index + 1);
  0 = empty. Slot's home = (slot - reprobes[reprobe_index]) mod size;
  full hashed key = (high << lsize) | home; key = M^-1 . hashed.
* key plane bytes = ceil(size * kb / 64) * 8 (fields packed
  consecutively across words); value plane: floor(64/(bits+1)) fields
  per word, value_bytes = ceil(size / per_word) * 8.
"""

from __future__ import annotations

import json
import math
import os

import numpy as np

from . import integrity, ref_db

REF_FORMAT = ref_db.REF_FORMAT  # "binary/quorum_db"

# the reference's default reprobe limit (create_database yaggo default;
# quadratic probing offsets, triangular numbers like Jellyfish's)
DEFAULT_MAX_REPROBE = 126


def _reprobes(max_reprobe: int) -> list[int]:
    return [i * (i + 1) // 2 for i in range(max_reprobe + 1)]


# ---------------------------------------------------------------------------
# GF(2) square invertible matrix (the hash; RectangularBinaryMatrix role)
# ---------------------------------------------------------------------------

def _gf2_invert(rows: list[int], n: int) -> list[int] | None:
    """Invert an n x n GF(2) matrix given as n row bitmasks (bit j =
    column j). Returns inverse rows or None if singular."""
    a = list(rows)
    inv = [1 << i for i in range(n)]
    for col in range(n):
        piv = None
        for r in range(col, n):
            if (a[r] >> col) & 1:
                piv = r
                break
        if piv is None:
            return None
        a[col], a[piv] = a[piv], a[col]
        inv[col], inv[piv] = inv[piv], inv[col]
        for r in range(n):
            if r != col and ((a[r] >> col) & 1):
                a[r] ^= a[col]
                inv[r] ^= inv[col]
    return inv


def make_matrix(key_len: int, seed: int = 0x5EED) -> tuple[list[int],
                                                           list[int]]:
    """A random invertible key_len x key_len GF(2) matrix (rows as
    ints) and its inverse. Deterministic per (key_len, seed)."""
    rng = np.random.default_rng(seed + key_len)
    while True:
        rows = [int.from_bytes(rng.bytes(8), "little")
                & ((1 << key_len) - 1) for _ in range(key_len)]
        inv = _gf2_invert(rows, key_len)
        if inv is not None:
            return rows, inv


def _apply_matrix_np(rows: list[int], keys: np.ndarray) -> np.ndarray:
    """M . key over GF(2) for a uint64 key vector: output bit r =
    parity(popcount(key & rows[r]))."""
    out = np.zeros_like(keys)
    for r, row in enumerate(rows):
        par = np.bitwise_count(keys & np.uint64(row)).astype(np.uint64) \
            & np.uint64(1)
        out |= par << np.uint64(r)
    return out


# ---------------------------------------------------------------------------
# Bit-plane packing helpers
# ---------------------------------------------------------------------------

def _pack_fields(fields: np.ndarray, width: int) -> np.ndarray:
    """Pack uint64 `fields` of `width` bits consecutively into
    little-endian uint64 words (fields may straddle words)."""
    n = len(fields)
    nwords = -(-n * width // 64)
    words = np.zeros(nwords + 1, np.uint64)  # +1: straddle spill room
    bitpos = np.arange(n, dtype=np.uint64) * np.uint64(width)
    wi = (bitpos >> np.uint64(6)).astype(np.int64)
    sh = bitpos & np.uint64(63)
    np.bitwise_or.at(words, wi, fields << sh)
    # spill in [1, 64]; shift in two steps so a 64-bit shift (UB on
    # uint64) never happens
    spill = np.uint64(64) - sh
    hi = (fields >> np.uint64(1)) >> (spill - np.uint64(1))
    np.bitwise_or.at(words, wi + 1, hi)
    return words[:nwords]


def _unpack_fields(words: np.ndarray, n: int, width: int) -> np.ndarray:
    words = np.concatenate([words, np.zeros(1, np.uint64)])
    bitpos = np.arange(n, dtype=np.uint64) * np.uint64(width)
    wi = (bitpos >> np.uint64(6)).astype(np.int64)
    sh = bitpos & np.uint64(63)
    lo = words[wi] >> sh
    spill = np.uint64(64) - sh
    hi = (words[wi + 1] << np.uint64(1)) << (spill - np.uint64(1))
    mask = np.uint64((1 << width) - 1)
    return (lo | hi) & mask


def _key_bytes(size: int, kb: int) -> int:
    return (-(-size * kb // 64)) * 8


def _val_geometry(size: int, vbits: int) -> tuple[int, int]:
    per_word = 64 // vbits
    return per_word, -(-size // per_word) * 8


# ---------------------------------------------------------------------------
# Writer
# ---------------------------------------------------------------------------

def write_ref_db(path: str, khi, klo, vals, k: int, bits: int,
                 max_reprobe: int = DEFAULT_MAX_REPROBE,
                 cmdline=None, min_fill: float = 0.8) -> None:
    """Write (canonical key, value-word) entries as a binary/quorum_db
    file. Keys are placed by quadratic probing on the GF(2)-hashed
    address exactly as the format prescribes; the table size doubles
    until every key places within the reprobe limit."""
    khi = np.asarray(khi, np.uint64)
    klo = np.asarray(klo, np.uint64)
    vals = np.asarray(vals, np.uint64)
    keys = (khi << np.uint64(32)) | klo
    n = len(keys)
    key_len = 2 * k
    rows, _inv = make_matrix(key_len)
    reprobes = _reprobes(max_reprobe)
    hashed = _apply_matrix_np(rows, keys)

    lsize = max(4, math.ceil(math.log2(max(1, n) / min_fill)))
    while True:
        size = 1 << lsize
        mask = np.uint64(size - 1)
        home = (hashed & mask).astype(np.int64)
        slot_of = np.full(n, -1, np.int64)
        o_of = np.zeros(n, np.int64)
        occupied = np.zeros(size, bool)
        pending = np.arange(n)
        for o, rp in enumerate(reprobes):
            if not len(pending):
                break
            s = (home[pending] + rp) % size
            free = ~occupied[s]
            cand = pending[free]
            cs = s[free]
            # first-come within the round: first index claiming a slot
            uniq, first = np.unique(cs, return_index=True)
            winners = cand[first]
            occupied[uniq] = True
            slot_of[winners] = uniq
            o_of[winners] = o
            pending = pending[slot_of[pending] < 0]
        if not len(pending):
            break
        lsize += 1  # couldn't place within the reprobe limit: double

    obits = (max_reprobe + 1).bit_length()
    kb = key_len - lsize + obits
    if kb <= 0:
        raise ValueError("table size exceeds key information content")
    fields = np.zeros(size, np.uint64)
    stored_hi = hashed >> np.uint64(lsize)
    fields[slot_of] = (stored_hi << np.uint64(obits)) \
        | (o_of.astype(np.uint64) + np.uint64(1))
    key_words = _pack_fields(fields, kb)
    kbytes = _key_bytes(size, kb)

    vbits = bits + 1
    per_word, vbytes = _val_geometry(size, vbits)
    vfields = np.zeros(size, np.uint64)
    vfields[slot_of] = vals & np.uint64((1 << vbits) - 1)
    vwi = np.arange(size) // per_word
    vsh = (np.arange(size) % per_word * vbits).astype(np.uint64)
    val_words = np.zeros(vbytes // 8, np.uint64)
    np.bitwise_or.at(val_words, vwi, vfields << vsh)

    header = {
        "format": REF_FORMAT,
        "size": size,
        "key_len": key_len,
        "val_len": 0,
        "max_reprobe": max_reprobe,
        "reprobes": reprobes,
        "matrix": {"r": key_len, "c": key_len, "rows": rows},
        "bits": bits,
        "key_bytes": kbytes,
        "value_bytes": vbytes,
        "alignment": 8,
        "cmdline": list(cmdline) if cmdline else [],
        "hostname": os.uname().nodename,
    }
    blob = json.dumps(header).encode()
    kw = key_words.tobytes()
    # atomic replace (quorum-lint raw-artifact-write): a crashed
    # export must never leave a torn reference DB for a later
    # loader. Streamed into a sibling tmp — the word arrays can be
    # GBs, so no concatenated copy of the payload is ever built.
    tmp = path + ".tmp"
    with open(tmp, "wb") as f:
        f.write(blob)
        f.write(kw)
        f.write(b"\0" * (kbytes - len(kw)))
        f.write(val_words.tobytes())
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, path)
    # renames are only durable once the directory entry is down
    # (ISSUE 8) — same contract as _atomic_db_write
    integrity.fsync_dir(path)


# ---------------------------------------------------------------------------
# Reader
# ---------------------------------------------------------------------------

def read_ref_db(path: str):
    """Decode a binary/quorum_db file (geometry entirely from its
    header). Returns (khi u32[N], klo u32[N], vals u32[N], k, bits)."""
    with open(path, "rb") as f:
        data = f.read()
    header, off = ref_db.parse_jf_header(data)
    if header.get("format") != REF_FORMAT:
        raise ValueError(
            f"'{path}': format '{header.get('format')}' is not "
            f"'{REF_FORMAT}'")
    size = int(header["size"])
    key_len = int(header["key_len"])
    bits = int(header["bits"])
    max_reprobe = int(header.get("max_reprobe", DEFAULT_MAX_REPROBE))
    reprobes = [int(x) for x in header.get(
        "reprobes", _reprobes(max_reprobe))]
    kbytes = int(header["key_bytes"])
    vbytes = int(header["value_bytes"])
    mat = header.get("matrix") or {}
    rows = [int(r) for r in mat.get("rows", [])]
    if len(rows) != key_len:
        raise ValueError(
            f"'{path}': matrix is {len(rows)} rows, need {key_len} "
            "(a Jellyfish-built file may use a layout this decoder "
            "cannot verify; see io/ref_db.py)")
    inv = _gf2_invert(rows, key_len)
    if inv is None:
        raise ValueError(f"'{path}': hash matrix is singular")
    lsize = size.bit_length() - 1
    if (1 << lsize) != size:
        raise ValueError(f"'{path}': size {size} is not a power of two")
    obits = (max_reprobe + 1).bit_length()
    kb = key_len - lsize + obits
    exp_kbytes = _key_bytes(size, kb)
    per_word, exp_vbytes = _val_geometry(size, bits + 1)
    if kbytes != exp_kbytes or vbytes != exp_vbytes:
        raise ValueError(
            f"'{path}': payload geometry mismatch (key {kbytes} vs "
            f"{exp_kbytes} expected, value {vbytes} vs {exp_vbytes}) — "
            "not this codec's layout (see io/ref_db.py)")
    if len(data) < off + kbytes + vbytes:
        # a short ref-format payload is corruption (bit rot can't be
        # caught — the format carries no digests — but truncation can)
        raise integrity.record_error(
            f"'{path}': truncated payload ({len(data) - off} of "
            f"{kbytes + vbytes} payload bytes)", path=path,
            section="payload", offset=off)

    key_words = np.frombuffer(data, np.uint64, kbytes // 8, off)
    fields = _unpack_fields(key_words, size, kb)
    occ = np.nonzero(fields != 0)[0]
    fld = fields[occ]
    o_of = (fld & np.uint64((1 << obits) - 1)).astype(np.int64) - 1
    if o_of.size and (o_of.max() >= len(reprobes)):
        raise ValueError(f"'{path}': reprobe index out of range")
    rp = np.asarray(reprobes, np.int64)[o_of]
    home = (occ - rp) % size
    hashed = ((fld >> np.uint64(obits)) << np.uint64(lsize)) \
        | home.astype(np.uint64)
    keys = _apply_matrix_np(inv, hashed)

    val_words = np.frombuffer(data, np.uint64, vbytes // 8, off + kbytes)
    vwi = occ // per_word
    vsh = (occ % per_word * (bits + 1)).astype(np.uint64)
    vals = (val_words[vwi] >> vsh) & np.uint64((1 << (bits + 1)) - 1)

    khi = (keys >> np.uint64(32)).astype(np.uint32)
    klo = (keys & np.uint64(0xFFFFFFFF)).astype(np.uint32)
    return khi, klo, vals.astype(np.uint32), key_len // 2, bits


def verify_ref_db(path: str) -> list[tuple]:
    """Offline verification for quorum-fsck: header geometry
    consistency plus a full decode (the payload's reprobe indices and
    occupancy are the only structure the digest-less reference format
    lets us check). Returns (section, offset, message) problems; empty
    = as clean as the format can prove."""
    problems: list[tuple] = []
    try:
        read_ref_db(path)
    except integrity.IntegrityError as e:
        problems.append((e.section or "payload", e.offset, str(e)))
    except (ValueError, ref_db.RefHeaderError, OSError) as e:
        problems.append(("header", None, str(e)))
    return problems


def is_ref_db(path: str) -> bool:
    try:
        with open(path, "rb") as f:
            head = f.read(1 << 16)
        header, _ = ref_db.parse_jf_header(head)
        return header.get("format") == REF_FORMAT
    except (OSError, ref_db.RefHeaderError):
        return False
