"""Bit-packed read transport for the host->device link.

The corrector consumes quality ONLY as the predicate
``qual >= qual_cutoff`` (models/corrector.py: the three uses) and the
database builder only as ``qual < qual_thresh``
(ops/ctable.extract_observations_impl); the reference does the same —
quality chars are compared against one threshold in both binaries
(src/create_database.cc:80-84 `*q++ >= args.min_qual_arg`,
src/error_correct_reads.cc:440-444 `qual >= qual_cutoff`). So the
wire format between host parser and device needs only:

  * 2 bits/base of sequence (A/C/G/T),
  * 1 bit/base "this position is a non-ACGT base" (N mask),
  * 1 bit/base per quality THRESHOLD in play (the predicate itself,
    computed host-side).

= 0.5 B/base with one threshold vs the 2 B/base of int8 codes +
uint8 quals — a 4x cut to the dominant per-batch cost on the
tunneled TPU (H2D measured ~0.1-0.17 s/MB, PERF_NOTES.md). On device
the planes widen back to the exact int32 codes (-1 for N, -2 beyond
length) and a SYNTHETIC qual plane (threshold where the predicate
held, 0 where not) that makes every downstream comparison bit-identical.

Packing is plain numpy on the host (runs in the decode/prefetch
thread); unpacking is elementwise [B, L] work fused into the head of
the device executables (near-free per the measured cost model).
"""

from __future__ import annotations

import dataclasses

import numpy as np


@dataclasses.dataclass
class PackedReads:
    """Wire-format read batch. `hq[t]` is the 1-bit plane of
    ``qual >= t`` for each threshold t requested at pack time."""

    pcodes: np.ndarray | None  # uint8 [B, ceil(L/4)], base i at 2*(i%4)
    nmask: np.ndarray | None   # uint8 [B, ceil(L/8)], bit: code < 0
    hq: dict            # {threshold: uint8 [B, ceil(L/8)] | None}
    lengths: np.ndarray  # int32 [B]
    length: int          # L (unpacked row width)
    _wire: np.ndarray | None = dataclasses.field(
        default=None, repr=False, compare=False)
    _b: int | None = dataclasses.field(default=None, repr=False,
                                       compare=False)

    @property
    def n_reads(self) -> int:
        return self.pcodes.shape[0] if self.pcodes is not None else self._b

    @property
    def nbytes(self) -> int:
        # once the wire exists it CONTAINS every plane (codes, masks,
        # hq, lengths); counting the standalone arrays alongside it
        # would double the figure ~2x and overstate the driver's
        # replay-cache budget (ADVICE r5). The standalone planes only
        # count while no wire has been built yet.
        if self._wire is not None:
            return self._wire.nbytes
        arrs = [self.pcodes, self.nmask, self.lengths,
                *self.hq.values()]
        return sum(a.nbytes for a in arrs if a is not None)

    def require_plane(self, threshold: int) -> None:
        """Raise unless the batch was packed with the qual>=threshold
        plane (shared guard of both stages' packed entry points; the
        plane itself rides the wire buffer)."""
        if int(threshold) not in self.hq:
            raise KeyError(
                f"packed batch lacks the qual>={threshold} plane "
                f"(has {sorted(self.hq)})")

    def compact(self) -> "PackedReads":
        """A replay-cache-friendly copy holding ONLY the fused wire
        buffer plus geometry — the standalone plane arrays duplicate
        the wire's bytes and nothing reads them after to_wire()."""
        wire = self.to_wire()
        return PackedReads(
            pcodes=None, nmask=None,
            hq={t: None for t in self.hq}, lengths=self.lengths,
            length=self.length, _wire=wire,
            _b=self.n_reads)

    @property
    def thresholds(self) -> tuple:
        return tuple(sorted(self.hq))

    def to_wire(self) -> np.ndarray:
        """Concatenate every plane into ONE flat u8 buffer. The
        tunnel's H2D pays a large FIXED cost per transfer (measured
        ~60-120 ms regardless of size, PERF_NOTES.md round 5), so one
        fused buffer beats four small arrays even at identical bytes.
        Layout (canonical, all row-major): pcodes | nmask | hq planes
        in ascending threshold order | lengths as little-endian u8x4.
        The device side (ops/mer.wire_parts_device) slices it back by
        the same static layout. Cached — the CLIs warm it from the
        decode/prefetch thread so the main thread only does H2D."""
        if self._wire is None:
            if self.pcodes is None:
                raise ValueError("compacted PackedReads lost its planes "
                                 "before the wire was built")
            if self.lengths.dtype != np.int32:
                raise TypeError(
                    "lengths must be int32 for the wire layout")
            parts = [self.pcodes.reshape(-1), self.nmask.reshape(-1)]
            parts += [self.hq[t].reshape(-1) for t in self.thresholds]
            parts.append(np.ascontiguousarray(self.lengths)
                         .view(np.uint8))
            self._wire = np.concatenate(parts)
        return self._wire


def pack_reads(codes: np.ndarray, quals: np.ndarray, lengths: np.ndarray,
               thresholds=()) -> PackedReads:
    """Pack int8 codes [B, L] (-1 non-ACGT, -2 pad) + uint8 quals
    [B, L] into the wire format. `thresholds` lists every quality
    threshold the device side will need as a predicate plane."""
    codes = np.asarray(codes, np.int8)
    B, L = codes.shape
    pad4 = (-L) % 4
    c = np.clip(codes, 0, 3).astype(np.uint8)
    if pad4:
        c = np.pad(c, ((0, 0), (0, pad4)))
    c = c.reshape(B, -1, 4)
    pcodes = (c[:, :, 0] | (c[:, :, 1] << 2) | (c[:, :, 2] << 4)
              | (c[:, :, 3] << 6)).astype(np.uint8)
    nmask = np.packbits(codes < 0, axis=1, bitorder="little")
    hq = {
        int(t): np.packbits(np.asarray(quals, np.uint8) >= t, axis=1,
                            bitorder="little")
        for t in thresholds
    }
    return PackedReads(pcodes=pcodes, nmask=nmask, hq=hq,
                       lengths=np.asarray(lengths, np.int32), length=L)


# Device-side widening lives in ops/mer.py (ops must not import io —
# io/db_format imports ops.ctable); re-exported here so transport
# callers see one module.
from ..ops.mer import (  # noqa: E402,F401
    synth_quals_device,
    unpack_bits_device,
    unpack_codes_device,
)
