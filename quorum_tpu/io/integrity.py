"""Data-integrity primitives: CRC32C digests, sealed JSON headers,
and the verification telemetry hook (ISSUE 8).

Every artifact the pipeline persists — the mer database, stage-1
snapshots, the stage-2 resume journal, the driver's replay cache — was
trusted byte-for-byte after at most a header/geometry check, so silent
corruption (torn writes the rename race can't catch, bit rot on
long-lived DBs, truncated shard payloads) flowed straight into wrong
corrections or undefined resume behavior. KMC 3 ships `kmc_tools` as a
first-class verifier for its on-disk databases (PAPERS.md); this
module is the primitive layer under quorum-tpu's equivalent:

* `crc32c()` — CRC-32C (Castagnoli), the checksum every artifact
  carries. Pure numpy software implementation: small inputs take a
  scalar table loop, larger ones are split into equal chunks whose
  CRCs are computed column-vectorized (every chunk advances one byte
  per numpy step) and folded with the GF(2) combine operator — the
  classic zlib `crc32_combine` construction — so throughput scales
  with numpy, not the Python interpreter. Chains like `zlib.crc32`:
  ``crc32c(b, crc32c(a)) == crc32c(a + b)``.
* `crc32c_combine()` — CRC of a concatenation from the parts' CRCs,
  used to derive section/whole-file digests from chunk digests in one
  data pass (db_format's v5 writer) and to fold the vectorized chunks.
* `seal()` / `check_seal()` — self-digesting JSON headers: the
  document's CRC over its own canonical serialization (sort_keys,
  minus the digest field) rides in a `crc32c` field, so a flipped
  digit in a cursor or byte count is caught even when the mutation
  still parses as valid JSON.
* `IntegrityError` — the refusal. A ValueError subclass (existing
  corrupt-artifact handlers keep working) that the CLIs map to the
  non-retryable rc 3: resuming or serving from damaged bytes must
  fail loudly, never silently reuse them.
* the registry hook — loaders report what they verified
  (`integrity_bytes_verified_total`) and what they refused
  (`integrity_errors_total`, plus a structured `integrity_error`
  event naming file/section/offset) into the run's ambient metrics
  registry, installed by cli/observability.observability().
* `fsync_dir()` — directory durability for the atomic-rename commit
  protocol: a rename is only power-loss-durable once the parent
  directory entry is synced.
"""

from __future__ import annotations

import json
import os

import numpy as np

# CRC-32C (Castagnoli), reflected polynomial — the variant iSCSI/ext4
# use and SSE4.2/ARMv8 implement in hardware.
_POLY = np.uint32(0x82F63B78)

# below this many bytes the scalar loop beats the vectorized setup
_VECTOR_MIN = 1 << 12


def _make_table() -> np.ndarray:
    crc = np.arange(256, dtype=np.uint32)
    for _ in range(8):
        crc = np.where(crc & 1, (crc >> 1) ^ _POLY, crc >> 1)
    return crc


_TABLE = _make_table()
_TABLE_PY = [int(x) for x in _TABLE]  # scalar loop avoids numpy boxing


class IntegrityError(ValueError):
    """An artifact failed checksum/digest verification. A ValueError
    so existing corrupt-file handlers still catch it; the CLIs map it
    (like CheckpointError) to the non-retryable rc 3 — a damaged
    artifact is deterministic, retrying cannot help."""

    def __init__(self, message: str, path: str | None = None,
                 section: str | None = None, offset: int | None = None):
        super().__init__(message)
        self.path = path
        self.section = section
        self.offset = offset


def _as_bytes_view(data) -> np.ndarray:
    """A uint8 view of bytes/bytearray/memoryview/ndarray without
    copying (C-contiguous input) or with one copy (non-contiguous)."""
    if isinstance(data, np.ndarray):
        return np.frombuffer(
            memoryview(np.ascontiguousarray(data)).cast("B"), np.uint8)
    return np.frombuffer(memoryview(data).cast("B"), np.uint8)


def _crc_scalar(buf: np.ndarray, c: int) -> int:
    t = _TABLE_PY
    for b in buf.tolist():
        c = t[(c ^ b) & 0xFF] ^ (c >> 8)
    return c


# -- GF(2) combine (zlib crc32_combine, ported to the C polynomial) --------

def _gf2_times(mat: list[int], vec: int) -> int:
    out = 0
    i = 0
    while vec:
        if vec & 1:
            out ^= mat[i]
        vec >>= 1
        i += 1
    return out


def _gf2_square(mat: list[int]) -> list[int]:
    return [_gf2_times(mat, mat[n]) for n in range(32)]


def _zero_operator(nbytes: int) -> list[int]:
    """The 32x32 GF(2) matrix advancing a (raw) CRC register past
    `nbytes` zero bytes — multiplication by x^(8*nbytes) mod P in the
    reflected domain."""
    odd = [int(_POLY)] + [1 << (n - 1) for n in range(1, 32)]
    even = _gf2_square(odd)   # x^2
    odd = _gf2_square(even)   # x^4
    # even/odd now alternate as squares; accumulate the bits of 8*n
    op = None
    mat = _gf2_square(odd)    # x^8: one zero byte
    n = nbytes
    while n:
        if n & 1:
            op = mat if op is None else [_gf2_times(mat, r) for r in op]
        n >>= 1
        if n:
            mat = _gf2_square(mat)
    return op if op is not None else [1 << i for i in range(32)]


_OP_CACHE: dict[int, list[int]] = {}


def _zero_operator_cached(nbytes: int) -> list[int]:
    op = _OP_CACHE.get(nbytes)
    if op is None:
        if len(_OP_CACHE) > 64:  # bounded: lengths are few in practice
            _OP_CACHE.clear()
        op = _OP_CACHE[nbytes] = _zero_operator(nbytes)
    return op


def crc32c_combine(crc1: int, crc2: int, len2: int) -> int:
    """CRC32C of A+B given crc32c(A), crc32c(B), and len(B). Same
    construction as zlib's crc32_combine: the pre/post-conditioning
    XORs cancel, leaving one matrix application plus an XOR."""
    if len2 == 0:
        return crc1
    return _gf2_times(_zero_operator_cached(len2), crc1) ^ crc2


def crc32c(data, crc: int = 0) -> int:
    """CRC-32C of `data` (bytes-like or ndarray), chained from `crc`
    (the finalized CRC of everything before it, like zlib.crc32)."""
    buf = _as_bytes_view(data)
    n = buf.shape[0]
    c = (crc ^ 0xFFFFFFFF) & 0xFFFFFFFF
    if n < _VECTOR_MIN:
        return _crc_scalar(buf, c) ^ 0xFFFFFFFF
    # vectorized: K equal chunks of L bytes advance in lockstep, one
    # byte per numpy step; per-chunk CRCs fold left-to-right with the
    # combine operator for L. L ~ sqrt(n) balances the Python loop
    # (L iterations) against the fold (K applications), rounded to a
    # power of two so the operator cache hits across calls.
    L = 1 << max(11, min(24, int(n).bit_length() // 2))
    K = n // L
    body = K * L
    cols = np.ascontiguousarray(buf[:body].reshape(K, L).T)
    crcs = np.full((K,), 0xFFFFFFFF, np.uint32)
    for j in range(L):
        crcs = _TABLE[(crcs ^ cols[j]) & np.uint32(0xFF)] ^ (crcs >> 8)
    crcs ^= np.uint32(0xFFFFFFFF)
    total = c ^ 0xFFFFFFFF  # back to finalized form for combine
    op = _zero_operator_cached(L)
    for chunk_crc in crcs.tolist():
        total = _gf2_times(op, total) ^ chunk_crc
    if body < n:
        total = _crc_scalar(buf[body:],
                            (total ^ 0xFFFFFFFF)) ^ 0xFFFFFFFF
    return total


def crc32c_file(path: str, start: int = 0, length: int | None = None,
                crc: int = 0, block: int = 8 << 20) -> int:
    """Streaming CRC32C of `length` bytes of `path` from `start`
    (None = to EOF), chained from `crc`."""
    with open(path, "rb") as f:
        f.seek(start)
        remaining = length
        while remaining is None or remaining > 0:
            want = block if remaining is None else min(block, remaining)
            chunk = f.read(want)
            if not chunk:
                if remaining is not None:
                    raise IntegrityError(
                        f"'{path}' ends {remaining} bytes short of the "
                        f"digested range", path=path, offset=start)
                break
            crc = crc32c(chunk, crc)
            if remaining is not None:
                remaining -= len(chunk)
    return crc


# -- sealed JSON headers ---------------------------------------------------

SEAL_FIELD = "crc32c"


def seal(doc: dict) -> dict:
    """Return `doc` plus its self-digest: the CRC32C of the canonical
    (sort_keys) serialization minus the digest field itself."""
    body = json.dumps({k: v for k, v in doc.items() if k != SEAL_FIELD},
                      sort_keys=True).encode()
    return {**doc, SEAL_FIELD: crc32c(body)}


def check_seal(doc: dict, what: str, path: str) -> None:
    """Verify a sealed document's self-digest. A document without the
    field passes (pre-v5 artifacts stay loadable); a mismatch raises
    IntegrityError and records the detection."""
    want = doc.get(SEAL_FIELD)
    if want is None:
        return
    body = json.dumps({k: v for k, v in doc.items() if k != SEAL_FIELD},
                      sort_keys=True).encode()
    got = crc32c(body)
    if got != int(want):
        raise record_error(
            f"{what} '{path}' failed its header self-digest "
            f"(crc32c {got:#010x} != recorded {int(want):#010x}) — "
            "the document was altered after it was written",
            path=path, section="header", offset=0)
    record_verified(len(body))


# -- verification telemetry hook -------------------------------------------
# Loaders run deep below the CLIs, so the active run's registry is
# installed ambiently (cli/observability.observability() does it, the
# same pattern utils/faults.py uses for the fault plan). NULL-safe:
# with no registry installed every record call is a no-op.

_REG = None

COUNTER_ERRORS = "integrity_errors_total"
COUNTER_BYTES = "integrity_bytes_verified_total"


def install_registry(reg):
    """Install the ambient metrics registry for verification
    telemetry; returns the previous one (nest/restore discipline)."""
    global _REG
    prev = _REG
    _REG = reg
    return prev


def _registry():
    reg = _REG
    return reg if reg is not None and getattr(reg, "enabled", False) \
        else None


def record_verified(nbytes: int, **meta) -> None:
    """Count `nbytes` of artifact bytes that passed verification;
    `meta` (db_version=..., verify_db=...) declares the feature in the
    run's document so metrics_check can require these counters."""
    reg = _registry()
    if reg is None:
        return
    reg.counter(COUNTER_ERRORS)  # lands even at 0
    reg.counter(COUNTER_BYTES).inc(int(nbytes))
    if meta:
        reg.set_meta(**meta)


def record_error(message: str, path: str | None = None,
                 section: str | None = None,
                 offset: int | None = None) -> IntegrityError:
    """Count one detection, emit the structured event naming
    file/section/offset, and RETURN the IntegrityError for the caller
    to raise — `raise record_error(...)` keeps the telemetry and the
    refusal in one place."""
    reg = _registry()
    if reg is not None:
        reg.counter(COUNTER_BYTES)  # lands even at 0
        reg.counter(COUNTER_ERRORS).inc()
        reg.event("integrity_error", file=path, section=section,
                  offset=offset, detail=message)
    return IntegrityError(message, path=path, section=section,
                          offset=offset)


# -- directory durability --------------------------------------------------

def fsync_dir(path: str) -> None:
    """fsync the directory containing `path` (or `path` itself when it
    is a directory): the tmp-then-rename commit protocol is only
    power-loss-durable once the new directory entry is on disk, not
    just the renamed file's data. Best-effort — filesystems that
    cannot open directories (or don't need this) are not an error."""
    d = path if os.path.isdir(path) else (os.path.dirname(path) or ".")
    try:
        fd = os.open(d, os.O_RDONLY)
    except OSError:
        return
    try:
        os.fsync(fd)
    except OSError:
        pass
    finally:
        os.close(fd)
